"""Weight-update sharding (ZeRO-1) — the optimizer step, data-parallel.

Plain S-SGD makes every replica apply the identical optimizer update to
the full parameter set: n copies of the update FLOPs, n copies of the
optimizer state in HBM.  Weight-update sharding (the "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training"
technique from the TPU MLPerf submissions; ZeRO stage 1 elsewhere)
splits the update instead:

    reduce-scatter(grads) → each replica owns 1/n of the flat gradient
    inner update on the owned shard (momentum/Adam state: 1/n per chip)
    all-gather(updated params) → everyone replicated again

For any ELEMENTWISE inner transform (sgd, momentum, adam, adamw,
rmsprop, …) the sharded update is exactly the full update restricted to
the shard, so the result matches
:func:`~kungfu_tpu.optimizers.synchronous_sgd` to float tolerance — the
win is n× less optimizer-state memory and n× fewer update FLOPs, paid
with an all-gather of params instead of an all-reduce of grads (the
same bytes on the wire: reduce-scatter + all-gather IS the
bandwidth-optimal all-reduce decomposition, cf.
:mod:`kungfu_tpu.ops.schedules`).

Non-elementwise transforms (``clip_by_global_norm``, anything that
mixes statistics across parameters) are NOT shard-equivalent — compose
them on the gradient side before this wrapper if needed.

Structure note: the scatter + shard update run inside ``shard_map``
(their outputs are genuinely sharded, declared ``P(axes)``); the param
re-gather is left to the enclosing jit — ``defuse`` of the sharded flat
buffer makes XLA's partitioner insert the all-gather, which also keeps
shard_map's varying-manual-axes checking fully on (an in-body
``all_gather`` result cannot be declared replicated without disabling
the check).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from kungfu_tpu.utils.jaxcompat import axis_size, shard_map
from jax.sharding import PartitionSpec as P

from kungfu_tpu.ops.fuse import defuse, fuse


def zero1_train_step(loss_fn, inner: optax.GradientTransformation, comm,
                     average: bool = True, donate: bool = False):
    """Build a ZeRO-1 data-parallel training step over ``comm``'s mesh.

    ``loss_fn(params, batch) -> scalar`` runs per device on its batch
    shard (same contract as
    :func:`~kungfu_tpu.parallel.train.dp_train_step`); ``inner`` is any
    elementwise optax transform.

    Returns ``(step, init_opt)``:

    * ``init_opt(params) -> opt_shard`` — the optimizer state over the
      mesh-sharded flat parameter buffer (each device holds 1/n; build
      once per mesh epoch).
    * ``step(params, opt_shard, batch) -> (params, opt_shard, loss)`` —
      jitted over the mesh; params replicated in/out, ``batch`` leading
      axis divisible by ``comm.size``.
    """
    mesh, axes = comm.mesh, comm.axis
    axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
    n = comm.size

    def build(params):
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        buf, spec = fuse(zeros)
        total = int(buf.shape[-1])
        chunk = math.ceil(total / n)
        padded = chunk * n
        flat_dtype = spec.fused_dtype
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        # OUTER-axis-first scatter: the chunk device (i_h, i_l) ends up
        # owning then sits at flat offset (i_h*n_l + i_l)*chunk — the
        # same mesh-major order P(axes) uses to assemble the global
        # buffer, so the enclosing jit's defuse reads chunks back in
        # place (inner-first scattering produces local-major content and
        # a permuted parameter tree on hierarchical meshes)
        scatter_axes = [ax for ax in axes_t if sizes[ax] > 1]

        # optimizer-state pytree structure over one shard: vector leaves
        # are sharded over the mesh, scalar leaves (e.g. Adam's count)
        # are replicated
        state_shapes = jax.eval_shape(
            inner.init, jax.ShapeDtypeStruct((chunk,), flat_dtype)
        )
        state_specs = jax.tree_util.tree_map(
            lambda s: P(axes) if s.ndim else P(), state_shapes
        )

        def my_offset():
            off, seg = jnp.int32(0), padded
            for ax in scatter_axes:
                seg = seg // axis_size(ax)
                off = off + lax.axis_index(ax) * seg
            return off

        def flat_of(tree):
            b, _ = fuse(tree)
            pad = padded - total
            if pad:
                b = jnp.concatenate([b, jnp.zeros((pad,), b.dtype)])
            return b.astype(flat_dtype)

        def init_body(params):
            shard = lax.dynamic_slice(
                flat_of(params), (my_offset(),), (chunk,)
            )
            return inner.init(shard)

        init_opt = jax.jit(shard_map(
            init_body, mesh=mesh, in_specs=(P(),), out_specs=state_specs,
        ))

        def step_body(params, opt_shard, batch):
            # differentiate w.r.t. a per-device VARYING view of the
            # params: against the replicated view, autodiff inserts a
            # full cotangent psum (an all-reduce — the exact collective
            # this technique replaces), and the scatter below would
            # re-sum the already-summed gradients on top (measured n^2)
            from kungfu_tpu.ops.pallas._sharding import match_vma

            p_var = jax.tree_util.tree_map(
                lambda a: match_vma(a, frozenset(axes_t)), params
            )
            loss, grads = jax.value_and_grad(loss_fn)(p_var, batch)
            g = flat_of(grads)
            for ax in scatter_axes:
                g = lax.psum_scatter(g, ax, scatter_dimension=0, tiled=True)
            if average:
                g = g / n
            p_shard = lax.dynamic_slice(
                flat_of(params), (my_offset(),), (chunk,)
            )
            updates, opt_shard = inner.update(g, opt_shard, p_shard)
            p_shard = optax.apply_updates(p_shard, updates)
            loss = lax.pmean(loss, axes)
            return p_shard, opt_shard, loss

        inner_step = shard_map(
            step_body, mesh=mesh,
            in_specs=(P(), state_specs, P(axes)),
            out_specs=(P(axes), state_specs, P()),
        )

        def outer(params, opt_shard, batch):
            p_flat, opt_shard, loss = inner_step(params, opt_shard, batch)
            # p_flat is the sharded [padded] buffer; defuse's slices make
            # the partitioner insert the all-gather back to replicated —
            # PINNED, not left to compiler choice: a sharded params
            # output would poison every replicated-convention consumer
            # (resync, host snapshots) on multi-controller meshes
            from jax.sharding import NamedSharding

            rep = NamedSharding(mesh, P())
            new_params = jax.tree_util.tree_map(
                lambda a: jax.lax.with_sharding_constraint(a, rep),
                defuse(p_flat[:total], spec),
            )
            return new_params, opt_shard, loss

        return (
            jax.jit(outer, donate_argnums=(0, 1) if donate else ()),
            init_opt,
        )

    # the flat geometry depends on the param structure AND leaf
    # shapes/dtypes (the fuse spec bakes both in); build lazily on first
    # use and cache per full abstract signature
    cache = {}

    def _get(params):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        key = (treedef,
               tuple((tuple(l.shape), str(l.dtype)) for l in leaves))
        if key not in cache:
            cache[key] = build(params)
        return cache[key]

    def init_opt(params):
        return _get(params)[1](params)

    def step(params, opt_shard, batch):
        return _get(params)[0](params, opt_shard, batch)

    return step, init_opt


def zero1_reshard(opt_shard, params, new_comm, peer=None, snapshot=None):
    """Re-place a ZeRO-1 optimizer shard onto a NEW mesh epoch.

    The sharded state's geometry (chunk = ceil(total/n), mesh-major
    scatter order) is baked into each vector leaf, so an elastic resize
    cannot just keep training — the state must be re-chunked for the
    new world size.  Each vector leaf is unpadded to the true parameter
    count (recovered from ``params``), re-padded to the NEW chunk
    geometry, and placed sharded over the new mesh; scalar leaves (e.g.
    Adam's step count) are re-placed replicated.  Values are exactly
    preserved, so training continues as if the optimizer had always run
    at the new size — the same guarantee the elementwise-equivalence of
    the step itself gives.

    Two modes:

    * **Single-controller** (simulated peers / one host), no
      ``snapshot``: every old chunk is addressable — direct runtime
      re-placement, no host channel involved.
    * **Multi-controller** (or an explicit ``snapshot``): the old
      chunks live in other processes — some of which a shrink just
      retired — so the state must have been captured with
      :func:`zero1_snapshot` over the OLD epoch's membership *before*
      the resize (rank 0 holds the blob; the chunk owners may no longer
      be reachable afterwards).  Rank 0 passes it as ``snapshot``;
      everyone else passes ``None`` and receives it over ``peer``'s
      host channel.  ``opt_shard`` supplies only the state STRUCTURE
      here (a joiner passes its fresh ``init_opt(params)``) — vector
      geometry is synthesized for the new mesh, values come from the
      snapshot.  This folds the former snapshot→restore detour under
      the one reshard entry point (reference elastic-state contract:
      ``peer/peer.go:236-276``).
    """
    from jax.sharding import NamedSharding

    total = int(np.sum([int(np.prod(l.shape)) for l in
                        jax.tree_util.tree_leaves(params)]))
    n = new_comm.size
    chunk = math.ceil(total / n)
    padded = chunk * n

    if new_comm._multiproc or snapshot is not None:
        # host-plane path: structure from opt_shard, geometry synthesized
        # for the new mesh, values from the (broadcast) snapshot
        fresh = jax.tree_util.tree_map(
            lambda a: (a if getattr(a, "ndim", 0) == 0
                       else jax.ShapeDtypeStruct((padded,), a.dtype)),
            opt_shard,
        )
        return zero1_restore(snapshot, fresh, params, peer, new_comm)

    sharded = NamedSharding(new_comm.mesh, P(new_comm.axis))
    replicated = new_comm.replicated_sharding()

    def leaf(a):
        if getattr(a, "ndim", 0) == 0:
            return jax.device_put(jnp.asarray(a), replicated)
        return jax.device_put(_repad(np.asarray(a), total, padded), sharded)

    return jax.tree_util.tree_map(leaf, opt_shard)


def _repad(full: np.ndarray, total: int, new_padded: int) -> np.ndarray:
    """Unpad a flat state vector to the true parameter count and re-pad
    for a new chunk geometry — shared by reshard and restore so their
    geometry (and its misuse diagnostic) cannot drift."""
    if full.shape[0] < total:
        # the state was built for MORE parameters than ``params`` holds
        # (e.g. a trainable-only subtree was passed): truncating would
        # silently corrupt the optimizer state
        raise ValueError(
            f"optimizer state vector has {full.shape[0]} elements but "
            f"params fuse to {total} — zero1 reshard/restore needs the "
            "SAME param tree the state was built from"
        )
    buf = np.zeros((new_padded,), full.dtype)
    buf[:total] = full[:total]
    return buf


def zero1_snapshot(opt_shard, peer=None):
    """End-of-epoch HOST snapshot of the sharded optimizer state.

    Each member contributes its addressable chunks over the host channel
    (state_bytes/n each — no HBM spike; only rank 0's HOST RAM holds the
    assembled state on the snapshot side.  :func:`zero1_restore` then
    broadcasts the blob, so each member transiently holds ~state_bytes
    in host RAM while re-chunking — host RAM, not HBM, so the 1/n HBM
    contract is untouched; a per-range scatter is the future
    optimization).  Rank 0 returns the blob, everyone else ``None``.
    The elastic contract is the coordinator's: **rank 0 must survive
    the resize** (it is the peer proposing it).

    Without a channel (single-process / simulated peers) every chunk is
    addressable locally and the blob is assembled in place.
    """
    import io

    chan = getattr(peer, "channel", None) if peer is not None else None
    leaves, _ = jax.tree_util.tree_flatten(opt_shard)
    parts = {}
    scalars = {}
    for i, leaf in enumerate(leaves):
        if getattr(leaf, "ndim", 0) == 0:
            scalars[f"s{i}"] = np.asarray(leaf)
            continue
        if chan is None and not leaf.is_fully_addressable:
            # mirror zero1_reshard's misuse guard: packing only the
            # local 1/n without a channel to gather the rest would
            # build a silently incomplete snapshot
            raise ValueError(
                "zero1_snapshot without a host channel needs fully "
                "addressable state (multi-controller meshes must pass "
                "the peer)"
            )
        for s in leaf.addressable_shards:
            start = s.index[0].start or 0
            parts[f"l{i}_o{start}"] = np.asarray(s.data)

    def pack(d):
        bio = io.BytesIO()
        np.savez(bio, **d)
        return bio.getvalue()

    if chan is None:
        merged = dict(parts)
        merged.update(scalars)
        return pack(merged)
    rank = peer.rank()
    name = f"kf.z1snap.v{peer.cluster_version}"
    gathered = chan.gather_bytes(pack(parts), peer.cluster.workers, name)
    if rank != 0:
        return None
    merged = {}
    for blob in gathered:
        with np.load(io.BytesIO(blob)) as z:
            for k in z.files:
                merged[k] = z[k]
    merged.update(scalars)  # replicated: rank 0's copy is everyone's
    return pack(merged)


def zero1_restore(snapshot, fresh_opt_shard, params, peer=None,
                  new_comm=None):
    """Rebuild the sharded optimizer state on a NEW mesh epoch from a
    :func:`zero1_snapshot` blob.

    ``fresh_opt_shard`` is ``init_opt(params)`` from the NEW epoch's
    :func:`zero1_train_step` — it supplies the state STRUCTURE and the
    new chunk geometry (joiners have no old state to supply either);
    its values are overwritten.  Rank 0 passes the blob; other members
    pass ``None`` and receive it over the host channel."""
    import io

    chan = getattr(peer, "channel", None) if peer is not None else None
    if chan is not None:
        if peer.rank() == 0 and snapshot is None:
            # fail HERE, before the broadcast: a bare assert inside
            # broadcast_bytes would kill rank 0 and leave every other
            # member stalling in recv until its timeout
            raise ValueError(
                "zero1_restore: rank 0 must supply the snapshot blob"
            )
        name = f"kf.z1rest.v{peer.cluster_version}"
        snapshot = chan.broadcast_bytes(snapshot, peer.cluster.workers, name)
    if snapshot is None:
        raise ValueError("zero1_restore: no snapshot (rank 0 must supply it)")
    total = int(np.sum([int(np.prod(l.shape)) for l in
                        jax.tree_util.tree_leaves(params)]))
    leaves, treedef = jax.tree_util.tree_flatten(fresh_opt_shard)
    with np.load(io.BytesIO(snapshot)) as z:
        by_leaf = {}
        for k in z.files:
            if k.startswith("s"):
                by_leaf[("s", int(k[1:]))] = z[k]
            else:
                li, off = k[1:].split("_o")
                by_leaf.setdefault(("l", int(li)), []).append(
                    (int(off), z[k]))

    sharded = None
    if new_comm is not None:
        from jax.sharding import NamedSharding

        sharded = NamedSharding(new_comm.mesh, P(new_comm.axis))
    out = []
    for i, leaf in enumerate(leaves):
        if getattr(leaf, "ndim", 0) == 0:
            val = by_leaf.get(("s", i))
            if val is None:
                out.append(leaf)
            elif new_comm is not None:
                out.append(jax.device_put(jnp.asarray(val),
                                          new_comm.replicated_sharding()))
            else:
                out.append(jnp.asarray(val))
            continue
        chunks = sorted(by_leaf.get(("l", i), []))
        if not chunks:
            raise ValueError(f"snapshot holds no chunks for state leaf {i}")
        # chunks must tile [0, covered) with no interior gap: a
        # count-based check misses a hole whenever the old padding is at
        # least one chunk wide, silently restoring zeros into momentum
        expected = 0
        for off, c in chunks:
            if off != expected:
                raise ValueError(
                    f"snapshot leaf {i}: chunk gap at offset {expected} "
                    f"(next chunk starts at {off}) — a contributing "
                    "member's chunks are missing"
                )
            expected = off + c.shape[0]
        full = np.concatenate([c for _, c in chunks])
        buf = _repad(full, total, int(leaf.shape[0]))  # NEW padded size
        out.append(jax.device_put(buf, sharded) if sharded is not None
                   else jnp.asarray(buf))
    return jax.tree_util.tree_unflatten(treedef, out)


def opt_state_bytes(opt_state) -> int:
    """Total bytes across an optimizer-state pytree (for the memory
    assertion in tests/benchmarks)."""
    return sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(opt_state)
        if hasattr(l, "shape") and hasattr(l, "dtype")
    )
