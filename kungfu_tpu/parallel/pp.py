"""Pipeline parallelism over the host plane: 1F1B microbatch schedules
driven by async collective handles, with elastic stage re-carving.

The in-mesh pipeline (:class:`~kungfu_tpu.parallel.train.ShardedTrainer`)
runs GPipe ticks as ``lax.scan`` + ``ppermute`` inside ONE ``shard_map``
— right for a single XLA mesh, useless across the DCN where each slice
is its own process world.  This module is the cross-DCN pipeline axis:

* **stages** — contiguous layer ranges of the flagship transformer
  (:func:`stage_partition`); stage 0 owns the embedding, the last stage
  owns ``ln_f`` + the LM head and computes the loss.
* **activation hops** — point-to-point sends/recvs on the collective
  engine's async plane (:meth:`~kungfu_tpu.comm.engine.CollectiveEngine.
  send_async` / ``recv_async``): every hop is a PR-10
  :class:`~kungfu_tpu.comm.engine.CollectiveHandle` whose tag is fixed
  at issue time, so the ``handle-discipline`` lint polices its lifetime
  and the prefetched recv hides the DCN latency under stage compute.
* **schedule** — :func:`schedule_1f1b` (one-forward-one-backward: the
  steady state holds ≤ ``warmup+1`` live activations instead of all
  ``n_micro``), :func:`schedule_interleaved` (each stage owns ``v``
  non-adjacent layer chunks — the virtual-stage schedule is derived by
  a greedy dependency simulation, so any ``v`` is deadlock-free by
  construction), and :func:`schedule_sequential` (the naive baseline
  ``bench.py --pp`` measures 1F1B against).
* **ZeRO composition** — gradients reduce-scatter over the stage's DP
  group in buckets issued as async handles the moment that stage's last
  backward retires; the PP drain (the bubble) hides the DP wire exactly
  the way PR 10's depth-k pipeline hides bucket latency.  Sum order is
  fixed (dp-member order) so the composition stays bitwise against the
  replicated reference.
* **elastic re-carve** — :class:`StageBoundary` commits the stage's
  params + ZeRO opt chunks at the step boundary and ring-mirrors them
  one stage back (same dp lane: ``stride = dp`` ranks — on a multislice
  pod that is exactly one SLICE back, so a whole dead slice's stage
  survives on its predecessor).  On slice loss the survivors re-balance
  layers over the remaining stages via the pure
  :func:`stage_recarve_plan` every rank computes identically (the
  ``reshard_plan`` pattern) instead of aborting — wired into the
  recovery ladder as rung 10 (``elastic/shrink.py``,
  docs/fault_tolerance.md).

Mapping: PP runs across the DCN (slice) axis, TP within the ICI — a
stage rank with ``plan.tp > 1`` shard_maps its layer math over its own
local device mesh (Megatron column/row via :mod:`kungfu_tpu.parallel.
tp`), so the host world is ``pp × dp`` ranks and tensor parallelism
never crosses a slice (docs/pipeline.md).
"""

from __future__ import annotations

import io
import math
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kungfu_tpu.monitor import timeline
from kungfu_tpu.utils.log import get_logger

_log = get_logger("pp")

#: schedule vocabulary (KF_PP_SCHEDULE / ParallelPlan.pp_schedule)
SCHEDULES = ("1f1b", "interleaved", "sequential")

#: outstanding async p2p handles the pipeline keeps in flight; must stay
#: below the engine async pool (8 workers) or queued sends could starve
#: behind blocked recvs (see CollectiveEngine.recv_async)
_MAX_INFLIGHT_SENDS = 4
_PREFETCH = 2


# -- pure stage / schedule math --------------------------------------------
def stage_partition(n_layers: int, n_stages: int) -> List[Tuple[int, int]]:
    """Contiguous ``[lo, hi)`` layer range per stage, balanced with the
    remainder spread over the EARLIEST stages (they do not carry the
    LM-head loss work).  Pure and deterministic — every rank computes
    the identical map, like ``reshard_plan``."""
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    if n_layers < n_stages:
        raise ValueError(
            f"{n_layers} layers cannot fill {n_stages} stages "
            "(a stage with no layers would forward its input unchanged "
            "— shrink the stage count instead)")
    base, rem = divmod(n_layers, n_stages)
    out, lo = [], 0
    for s in range(n_stages):
        hi = lo + base + (1 if s < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


def interleaved_partition(n_layers: int, n_stages: int,
                          v: int) -> List[List[Tuple[int, int]]]:
    """Layer ranges for the interleaved schedule: ``n_stages * v``
    contiguous groups; stage ``s`` owns groups ``[s, s + S, s + 2S, …]``
    (chunk ``c`` of stage ``s`` is virtual stage ``c * S + s``).
    Returns ``[stage][chunk] -> (lo, hi)``."""
    if v < 1:
        raise ValueError(f"interleave must be >= 1, got {v}")
    groups = stage_partition(n_layers, n_stages * v)
    return [[groups[c * n_stages + s] for c in range(v)]
            for s in range(n_stages)]


def schedule_1f1b(n_micro: int, n_stages: int, stage: int
                  ) -> List[Tuple[str, int, int]]:
    """The classic one-forward-one-backward op list for ``stage``:
    ``[(kind, microbatch, chunk=0)]`` with kinds ``"F"``/``"B"``.
    Warmup ``min(S - 1 - stage, m)`` forwards, steady-state F/B pairs,
    backward drain.  Backwards retire in microbatch order on every
    stage — the property that keeps gradient accumulation bitwise
    against the sequential reference."""
    if not 0 <= stage < n_stages:
        raise ValueError(f"stage {stage} outside {n_stages} stages")
    warm = min(n_stages - 1 - stage, n_micro)
    ops: List[Tuple[str, int, int]] = []
    for m in range(warm):
        ops.append(("F", m, 0))
    for k in range(n_micro - warm):
        ops.append(("F", warm + k, 0))
        ops.append(("B", k, 0))
    for m in range(n_micro - warm, n_micro):
        ops.append(("B", m, 0))
    return ops


def schedule_sequential(n_micro: int, n_stages: int, stage: int
                        ) -> List[Tuple[str, int, int]]:
    """Naive sequential microbatching — each microbatch runs its full
    forward AND backward through the whole pipe before the next starts,
    so every DCN hop sits on the critical path.  The baseline the
    ``bench.py --pp`` gate measures 1F1B against."""
    del n_stages, stage
    ops: List[Tuple[str, int, int]] = []
    for m in range(n_micro):
        ops.append(("F", m, 0))
        ops.append(("B", m, 0))
    return ops


def schedule_interleaved(n_micro: int, n_stages: int, stage: int,
                         v: int) -> List[Tuple[str, int, int]]:
    """Interleaved (virtual-stage) schedule: stage ``s`` executes ops
    for its ``v`` chunks, ordered by a greedy global simulation of the
    ``S*v``-virtual-stage dependency DAG (each physical stage runs one
    ready op per tick, preferring backwards — the 1F1B shape emerges).
    Simulated, not formula'd: the op order is then consistent with a
    valid global schedule by construction, so the blocking recvs of a
    real run can never deadlock, for any ``(m, S, v)``."""
    if v == 1:
        return schedule_1f1b(n_micro, n_stages, stage)
    V = n_stages * v
    f_done = [[False] * n_micro for _ in range(V)]
    b_done = [[False] * n_micro for _ in range(V)]
    per_stage: List[List[Tuple[str, int, int]]] = [
        [] for _ in range(n_stages)]
    remaining = 2 * V * n_micro

    def ready(phys: int):
        """Best ready op for a physical stage: prefer B (drain memory),
        then the lowest (chunk, microbatch) F — deterministic.  Both
        kinds advance strictly in microbatch order per chunk, so
        gradient accumulation order matches the sequential reference
        (the bitwise contract)."""
        best = None
        for c in range(v):
            vs = c * n_stages + phys
            mb_b = next((m for m in range(n_micro)
                         if not b_done[vs][m]), None)
            if mb_b is not None and f_done[vs][mb_b] and (
                    vs == V - 1 or b_done[vs + 1][mb_b]):
                return ("B", mb_b, c)
            if best is None:
                mb_f = next((m for m in range(n_micro)
                             if not f_done[vs][m]), None)
                if mb_f is not None and (
                        vs == 0 or f_done[vs - 1][mb_f]):
                    best = ("F", mb_f, c)
        return best

    while remaining:
        progressed = False
        for phys in range(n_stages):
            op = ready(phys)
            if op is None:
                continue
            kind, m, c = op
            vs = c * n_stages + phys
            (f_done if kind == "F" else b_done)[vs][m] = True
            per_stage[phys].append(op)
            remaining -= 1
            progressed = True
        if not progressed:  # pragma: no cover - the DAG always has a root
            raise AssertionError("interleaved schedule wedged")
    return per_stage[stage]


def build_schedule(name: str, n_micro: int, n_stages: int, stage: int,
                   v: int = 1) -> List[Tuple[str, int, int]]:
    if name not in SCHEDULES:
        raise ValueError(f"unknown pp schedule {name!r}; one of {SCHEDULES}")
    if name == "interleaved":
        return schedule_interleaved(n_micro, n_stages, stage, v)
    if name == "sequential":
        return schedule_sequential(n_micro, n_stages, stage)
    if v != 1:
        raise ValueError("interleave > 1 requires the interleaved schedule")
    return schedule_1f1b(n_micro, n_stages, stage)


# -- pure re-carve planning -------------------------------------------------
#: pseudo-layer ids for the edge-owned params in recarve plans
_UNIT_EMBED = -1
_UNIT_FINAL = -2


def stage_recarve_plan(n_layers: int, old_n: int, new_n: int
                       ) -> List[Tuple[int, int, int]]:
    """Pure unit-move plan for an ``old_n -> new_n`` stage re-balance:
    ``[(unit, old_stage, new_stage)]`` where unit is a layer index, or
    ``-1`` (embedding block, stage 0's) / ``-2`` (ln_f + head, the last
    stage's).  Every rank computes the identical plan — the
    ``reshard_plan`` pattern at stage granularity.  Units whose owner
    does not change are omitted only when old and new stage indices
    AND maps coincide; callers move exactly what the plan lists."""
    old_map = stage_partition(n_layers, old_n)
    new_map = stage_partition(n_layers, new_n)

    def old_owner(layer: int) -> int:
        for s, (lo, hi) in enumerate(old_map):
            if lo <= layer < hi:
                return s
        raise AssertionError(layer)

    def new_owner(layer: int) -> int:
        for s, (lo, hi) in enumerate(new_map):
            if lo <= layer < hi:
                return s
        raise AssertionError(layer)

    plan = [(_UNIT_EMBED, 0, 0), (_UNIT_FINAL, old_n - 1, new_n - 1)]
    plan += [(l, old_owner(l), new_owner(l)) for l in range(n_layers)]
    return plan


def _chunk_splits(old_off: int, new_off: int, length: int,
                  oc: int, nc: int):
    """Split one contiguous flat segment by the chunk boundaries of BOTH
    the old geometry (chunk width ``oc``) and the new (``nc``):
    yields ``(old_member, new_member, old_off, new_off, len)``."""
    done = 0
    while done < length:
        oo, no = old_off + done, new_off + done
        jo, jn = oo // oc, no // nc
        lim = min(length - done,
                  (jo + 1) * oc - oo,
                  (jn + 1) * nc - no)
        yield (jo, jn, oo, no, lim)
        done += lim


# -- per-stage transformer compute ------------------------------------------
def stacked_from_transformer(cfg, tparams) -> dict:
    """Pack per-layer :meth:`Transformer.init` params into the stacked
    layout the pipeline carves stages from (same layout as
    :meth:`ShardedTrainer.from_transformer_params`, host-side)."""
    import jax
    import jax.numpy as jnp

    L = cfg.n_layers
    stacked = {
        "embed": tparams["embed"],
        "layers": jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[tparams[f"layer_{i}"]
                                         for i in range(L)]),
        "ln_f": tparams["ln_f"],
        "head": tparams["head"],
    }
    if cfg.pos == "learned":
        stacked["pos_embed"] = tparams["pos_embed"]
    return stacked


def init_stacked_params(cfg, key) -> dict:
    """Fresh stacked full-model params (flagship transformer init)."""
    from kungfu_tpu.models.transformer import Transformer

    return stacked_from_transformer(cfg, Transformer(cfg).init(key))


def slice_stage_params(cfg, full_stacked, lo: int, hi: int,
                       first: bool, last: bool) -> dict:
    """This stage's param subtree out of the full stacked tree."""
    import jax

    out = {"layers": jax.tree_util.tree_map(
        lambda a: a[lo:hi], full_stacked["layers"])}
    if first:
        out["embed"] = full_stacked["embed"]
        if cfg.pos == "learned":
            out["pos_embed"] = full_stacked["pos_embed"]
    if last:
        out["ln_f"] = full_stacked["ln_f"]
        out["head"] = full_stacked["head"]
    return out


def stage_param_shapes(cfg, lo: int, hi: int, first: bool,
                       last: bool) -> dict:
    """Shape/dtype skeleton of a stage's param subtree — pure (derived
    from the config alone), so EVERY rank can compute EVERY stage's
    flat layout for the re-carve plan without holding its data."""
    import jax
    import jax.numpy as jnp

    D, F, V = cfg.d_model, cfg.d_ff, cfg.vocab_size
    n = hi - lo
    f32 = jnp.float32

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, f32)

    layer = {
        "ln1": {"scale": s(n, D), "bias": s(n, D)},
        "ln2": {"scale": s(n, D), "bias": s(n, D)},
        "wq": {"w": s(n, D, D), "b": s(n, D)},
        "wk": {"w": s(n, D, D), "b": s(n, D)},
        "wv": {"w": s(n, D, D), "b": s(n, D)},
        "wo": {"w": s(n, D, D), "b": s(n, D)},
        "ffn_in": {"w": s(n, D, F), "b": s(n, F)},
        "ffn_out": {"w": s(n, F, D), "b": s(n, D)},
    }
    out = {"layers": layer}
    if first:
        out["embed"] = {"table": s(V, D)}
        if cfg.pos == "learned":
            out["pos_embed"] = {"table": s(cfg.max_seq, D)}
    if last:
        out["ln_f"] = {"scale": s(D), "bias": s(D)}
        out["head"] = {"w": s(D, V)}
    return out


def _flat_layout(shapes_tree, lo: int):
    """Flat-offset layout of a stage param tree in ``tree_flatten``
    order: ``[(key, global_row0, rows, rowsize, offset)]``.  ``key`` is
    the path tuple with the layer dimension factored out (a "layers"
    leaf's rows are GLOBAL layer indices ``[lo, hi)``); edge leaves are
    single rows keyed by their path."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten_with_path(shapes_tree)
    out = []
    off = 0
    for path, leaf in leaves:
        key = tuple(getattr(p, "key", str(p)) for p in path)
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        if key and key[0] == "layers":
            rows = int(leaf.shape[0])
            out.append((key, lo, rows, size // max(rows, 1), off))
        else:
            out.append((key, 0, 1, size, off))
        off += size
    return out, off


def stage_flat_layouts(cfg, stage_map: Sequence[Tuple[int, int]]):
    """``([layout_per_stage], [total_per_stage])`` for a stage map —
    the pure geometry the re-carve segment plan is computed from."""
    layouts, totals = [], []
    n = len(stage_map)
    for s, (lo, hi) in enumerate(stage_map):
        lay, total = _flat_layout(
            stage_param_shapes(cfg, lo, hi, s == 0, s == n - 1), lo)
        layouts.append(lay)
        totals.append(total)
    return layouts, totals


def flat_recarve_segments(cfg, old_map, new_map):
    """Pure flat-segment plan between two stage maps:
    ``[(old_stage, old_off, new_stage, new_off, length)]`` — for every
    leaf row range of every NEW stage, the contiguous span of the OLD
    stage flat holding the same values.  Segments tile every new stage
    flat exactly (property-tested).  Unit ownership comes from
    :func:`stage_recarve_plan` — ONE computation of "who owns layer l /
    the edges", shared by the unit-level plan and this transport
    plan."""
    old_lay, _ = stage_flat_layouts(cfg, old_map)
    new_lay, _ = stage_flat_layouts(cfg, new_map)
    S_old, S_new = len(old_map), len(new_map)
    if old_map != stage_partition(cfg.n_layers, S_old) \
            or new_map != stage_partition(cfg.n_layers, S_new):
        raise ValueError(
            "stage maps must be stage_partition outputs (the canonical "
            "balanced carve every rank derives identically)")
    unit_plan = stage_recarve_plan(cfg.n_layers, S_old, S_new)
    owner = {u: os_ for (u, os_, _) in unit_plan}

    def old_home(key, grow):
        """(old_stage, offset) of one row of leaf ``key``."""
        if key[0] == "layers":
            s = owner[grow]
            for k, gr0, rows, rowsize, off in old_lay[s]:
                if k == key:
                    return s, off + (grow - gr0) * rowsize, rowsize
            raise AssertionError((key, grow))
        s = owner[_UNIT_EMBED if key[0] in ("embed", "pos_embed")
                  else _UNIT_FINAL]
        for k, _, _, rowsize, off in old_lay[s]:
            if k == key:
                return s, off, rowsize
        raise AssertionError(key)

    segs = []
    for ns in range(S_new):
        for key, gr0, rows, rowsize, noff in new_lay[ns]:
            r = 0
            while r < rows:
                os_, ooff, rs = old_home(key, gr0 + r)
                assert rs == rowsize, (key, rs, rowsize)
                # extend over consecutive rows living contiguously in
                # the SAME old stage
                lo, hi = old_map[os_] if key[0] == "layers" else (0, 0)
                if key[0] == "layers":
                    run = min(rows - r, hi - (gr0 + r))
                else:
                    run = rows - r
                segs.append((os_, ooff, ns, noff + r * rowsize,
                             run * rowsize))
                r += run
    return segs


# -- the per-stage compute module -------------------------------------------
class StageModule:
    """One pipeline stage's transformer math: the layer range
    ``[lo, hi)`` (+ embedding on the first stage, final norm + LM head
    + loss on the last), with forward, recompute-backward
    (activation recomputation — the 1F1B memory contract), and optional
    tensor parallelism over a LOCAL device mesh (TP stays within the
    ICI; only activations cross the DCN)."""

    def __init__(self, cfg, lo: int, hi: int, *, first: bool, last: bool,
                 tp: int = 1, devices=None):
        import jax

        self.cfg, self.lo, self.hi = cfg, int(lo), int(hi)
        self.first, self.last = bool(first), bool(last)
        self.tp = int(tp)
        self.mesh = None
        if self.tp > 1:
            from jax.sharding import Mesh

            if cfg.n_heads % self.tp or cfg.d_ff % self.tp:
                raise ValueError(
                    f"tp={self.tp} must divide n_heads ({cfg.n_heads}) "
                    f"and d_ff ({cfg.d_ff})")
            devs = list(devices) if devices is not None else jax.devices()
            if len(devs) < self.tp:
                raise ValueError(
                    f"tp={self.tp} needs {self.tp} local devices, "
                    f"have {len(devs)}")
            self.mesh = Mesh(np.asarray(devs[: self.tp]), ("tp",))
        self._jit_fwd = jax.jit(self._fwd)
        self._jit_bwd = jax.jit(self._bwd)
        self._jit_loss_bwd = jax.jit(self._loss_bwd)

    # -- parameter layout -------------------------------------------------
    def param_specs(self):
        """PartitionSpecs over the local tp mesh (None when tp == 1)."""
        from jax.sharding import PartitionSpec as P

        if self.mesh is None:
            return None
        col = {"w": P(None, None, "tp"), "b": P(None, "tp")}
        layer = {
            "ln1": {"scale": P(None, None), "bias": P(None, None)},
            "ln2": {"scale": P(None, None), "bias": P(None, None)},
            "wq": dict(col), "wk": dict(col), "wv": dict(col),
            "wo": {"w": P(None, "tp", None), "b": P(None, None)},
            "ffn_in": dict(col),
            "ffn_out": {"w": P(None, "tp", None), "b": P(None, None)},
        }
        out = {"layers": layer}
        if self.first:
            out["embed"] = {"table": P(None, None)}
            if self.cfg.pos == "learned":
                out["pos_embed"] = {"table": P(None, None)}
        if self.last:
            out["ln_f"] = {"scale": P(None), "bias": P(None)}
            out["head"] = {"w": P(None, None)}
        return out

    def place(self, params):
        """Put a host stage-param tree onto this module's device layout
        (tp-sharded over the local mesh when tp > 1)."""
        import jax

        if self.mesh is None:
            return jax.tree_util.tree_map(jax.numpy.asarray, params)
        from jax.sharding import NamedSharding

        return jax.tree_util.tree_map(
            lambda x, spec: jax.device_put(
                x, NamedSharding(self.mesh, spec)),
            params, self.param_specs())

    # -- math --------------------------------------------------------------
    def _positions(self, B: int, S: int):
        import jax.numpy as jnp

        return jnp.broadcast_to(jnp.arange(S), (B, S))

    def _embed(self, params, ids):
        from kungfu_tpu.models import nn

        cfg = self.cfg
        h = nn.embedding_apply(params["embed"], ids,
                               dtype=cfg.compute_dtype)
        if cfg.pos == "learned":
            h = h + nn.embedding_apply(
                params["pos_embed"], self._positions(*ids.shape),
                dtype=cfg.compute_dtype)
        return h

    def _layers_dense(self, params, h, positions):
        """The tp == 1 layer loop — byte-for-byte the flagship
        :meth:`Transformer.hidden` block math."""
        import jax
        import jax.numpy as jnp

        from kungfu_tpu.models import nn
        from kungfu_tpu.models.transformer import _rope, default_attention

        cfg = self.cfg
        dt = cfg.compute_dtype
        H, Hd = cfg.n_heads, cfg.head_dim

        def heads(t):
            B, S, _ = t.shape
            return t.reshape(B, S, H, Hd).transpose(0, 2, 1, 3)

        def merge(t):
            B, Hn, S, D = t.shape
            return t.transpose(0, 2, 1, 3).reshape(B, S, Hn * D)

        for i in range(self.hi - self.lo):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["layers"])
            x = nn.layernorm_apply(lp["ln1"], h)
            q = heads(nn.dense_apply(lp["wq"], x, dtype=dt))
            k = heads(nn.dense_apply(lp["wk"], x, dtype=dt))
            v = heads(nn.dense_apply(lp["wv"], x, dtype=dt))
            if cfg.pos == "rope":
                q, k = _rope(q, k, positions)
            o = default_attention(q, k, v, cfg.causal)
            h = h + nn.dense_apply(lp["wo"], merge(o), dtype=dt)
            x = nn.layernorm_apply(lp["ln2"], h)
            y = nn.gelu(nn.dense_apply(lp["ffn_in"], x, dtype=dt))
            h = h + nn.dense_apply(lp["ffn_out"], y, dtype=dt)
        return h

    def _layers_tp(self, params, h, positions):
        """The tp > 1 layer loop under shard_map over the local mesh:
        Megatron column/row matmuls with the paired psum vjps
        (:mod:`kungfu_tpu.parallel.tp`), attention over the local
        head shard."""
        import jax

        from kungfu_tpu.models import nn
        from kungfu_tpu.models.transformer import _rope, default_attention
        from kungfu_tpu.parallel import tp as tpmod
        from kungfu_tpu.utils.jaxcompat import shard_map

        cfg = self.cfg
        dt = cfg.compute_dtype
        H_loc, Hd = cfg.n_heads // self.tp, cfg.head_dim

        def per_device(lparams, h, positions):
            def heads(t):
                B, S, _ = t.shape
                return t.reshape(B, S, H_loc, Hd).transpose(0, 2, 1, 3)

            def merge(t):
                B, Hn, S, D = t.shape
                return t.transpose(0, 2, 1, 3).reshape(B, S, Hn * D)

            for i in range(self.hi - self.lo):
                lp = jax.tree_util.tree_map(
                    lambda a: a[i], lparams["layers"])
                x = nn.layernorm_apply(lp["ln1"], h)
                x = tpmod.tp_region_enter(x, "tp")
                q = heads(tpmod.column_dense(lp["wq"], x, dtype=dt))
                k = heads(tpmod.column_dense(lp["wk"], x, dtype=dt))
                v = heads(tpmod.column_dense(lp["wv"], x, dtype=dt))
                if cfg.pos == "rope":
                    q, k = _rope(q, k, positions)
                o = default_attention(q, k, v, cfg.causal)
                h = h + tpmod.row_dense(lp["wo"], merge(o), "tp", dtype=dt)
                x = nn.layernorm_apply(lp["ln2"], h)
                x = tpmod.tp_region_enter(x, "tp")
                y = nn.gelu(tpmod.column_dense(lp["ffn_in"], x, dtype=dt))
                h = h + tpmod.row_dense(lp["ffn_out"], y, "tp", dtype=dt)
            return h

        from jax.sharding import PartitionSpec as P

        lay_specs = {"layers": self.param_specs()["layers"]}
        f = shard_map(
            per_device, mesh=self.mesh,
            in_specs=(lay_specs, P(), P()), out_specs=P(),
            check_vma=False,
        )
        return f({"layers": params["layers"]}, h, positions)

    def _hidden(self, params, x):
        import jax.numpy as jnp

        if self.first:
            positions = self._positions(*x.shape)
            h = self._embed(params, x)
        else:
            B, S = x.shape[0], x.shape[1]
            positions = self._positions(B, S)
            h = jnp.asarray(x, self.cfg.compute_dtype)
        if self.mesh is not None:
            return self._layers_tp(params, h, positions)
        return self._layers_dense(params, h, positions)

    def _fwd(self, params, x):
        return self._hidden(params, x)

    def _loss(self, params, x, targets):
        import jax.numpy as jnp

        from kungfu_tpu.models import nn
        from kungfu_tpu.ops.pallas.xent import token_nll

        h = self._hidden(params, x)
        hf = nn.layernorm_apply(params["ln_f"], h)
        logits = nn.dense_apply(params["head"], hf).astype(jnp.float32)
        return token_nll(logits, targets)

    def _bwd(self, params, x, dout):
        import jax

        if self.first:
            _, vjpf = jax.vjp(lambda p: self._fwd(p, x), params)
            (dparams,) = vjpf(dout)
            return dparams, None
        _, vjpf = jax.vjp(self._fwd, params, x)
        return vjpf(dout)

    def _loss_bwd(self, params, x, targets):
        import jax
        import jax.numpy as jnp

        if self.first:
            loss, vjpf = jax.vjp(lambda p: self._loss(p, x, targets),
                                 params)
            (dparams,) = vjpf(jnp.ones((), jnp.float32))
            return loss, dparams, None
        loss, vjpf = jax.vjp(
            lambda p, xx: self._loss(p, xx, targets), params, x)
        dparams, dx = vjpf(jnp.ones((), jnp.float32))
        return loss, dparams, dx

    # -- public ------------------------------------------------------------
    def forward(self, params, x):
        """Stage forward; ``x`` is int ids on the first stage, the
        incoming activation elsewhere."""
        return self._jit_fwd(params, x)

    def backward(self, params, x, dout):
        """Recompute-backward: ``(dparams, dx)`` (``dx`` None on the
        first stage — token ids have no cotangent)."""
        return self._jit_bwd(params, x, dout)

    def loss_backward(self, params, x, targets):
        """Last stage only: ``(loss, dparams, dx)`` — the loss forward
        and its vjp in one jitted call (seed 1.0)."""
        if not self.last:
            raise ValueError("loss_backward belongs to the last stage")
        return self._jit_loss_bwd(params, x, targets)


# -- elastic stage boundary -------------------------------------------------
class StageBoundary:
    """Committed step boundary of ONE rank's pipeline stage: the stage
    params as a flat host vector (+ treedef/shapes for restore) and the
    ZeRO-2 optimizer chunk, with a ring-buddy mirror one stage back in
    the SAME dp lane (``stride = dp`` ranks = one slice on a multislice
    pod) so a whole dead stage re-carves from its predecessor — the
    :class:`~kungfu_tpu.elastic.reshard.ZeroBoundary` discipline
    applied to the pipeline axis."""

    def __init__(self):
        self._lock = threading.Lock()
        self._step: Optional[int] = None
        self._cfg = None
        self._stage: Optional[int] = None
        self._n_stages: Optional[int] = None
        self._dp: int = 1
        self._dp_index: int = 0
        self._zero: int = 0
        #: flat stage params [total_s] f32 (params are replicated
        #: within the stage's dp group, so every member holds the full
        #: stage flat)
        self._pflat: Optional[np.ndarray] = None
        #: ZeRO-2 optimizer vec leaves: {leaf_idx: [chunk] np}
        self._opt_vec: Dict[int, np.ndarray] = {}
        self._opt_scal: Dict[int, np.ndarray] = {}
        self._opt_treedef = None
        self._opt_dtypes: Dict[int, np.dtype] = {}
        #: mirror of the successor stage (same dp lane)
        self._buddy: Optional[dict] = None
        self._buddy_stage: Optional[int] = None

    # -- commit ------------------------------------------------------------
    def commit(self, step: int, cfg, stage: int, n_stages: int, dp: int,
               dp_index: int, params, opt_state, zero_stage: int) -> None:
        """Host-copy this rank's stage state as of completed step
        ``step``.  ``opt_state`` is the ZeRO-2 chunk tree (leaves are
        ``[ceil(total/dp)]`` vectors or scalars); a replicated
        (``zero_stage == 0``) optimizer must be stateless — its
        vector leaves have no flat-chunk geometry to re-carve."""
        import jax

        leaves = jax.tree_util.tree_leaves(params)
        pflat = np.concatenate([np.asarray(l).ravel().astype(np.float32)
                                for l in leaves]) if leaves else np.zeros(0)
        oleaves, otd = jax.tree_util.tree_flatten(opt_state)
        vec, scal = {}, {}
        for i, l in enumerate(oleaves):
            a = np.array(l)
            if a.ndim >= 1:
                if zero_stage != 2:
                    raise ValueError(
                        "StageBoundary carries optimizer state through a "
                        "stage re-carve only in the ZeRO-2 flat-chunk "
                        "geometry — use zero_stage=2 or a stateless inner")
                vec[i] = a
            else:
                scal[i] = a
        with self._lock:
            self._step = int(step)
            self._cfg = cfg
            self._stage, self._n_stages = int(stage), int(n_stages)
            self._dp, self._dp_index = int(dp), int(dp_index)
            self._zero = int(zero_stage)
            self._pflat = pflat
            self._opt_vec, self._opt_scal = vec, scal
            self._opt_treedef = otd
            self._opt_dtypes = {i: a.dtype for i, a in vec.items()}
            self._buddy = None
            self._buddy_stage = None

    def step(self) -> Optional[int]:
        with self._lock:
            return self._step

    @property
    def stage(self) -> Optional[int]:
        with self._lock:
            return self._stage

    # -- ring-buddy mirror --------------------------------------------------
    def _blob(self) -> bytes:
        bio = io.BytesIO()
        np.savez(
            bio, pflat=self._pflat,
            meta=np.array([self._step, self._stage, self._n_stages,
                           self._dp, self._dp_index, self._zero], np.int64),
            **{f"v{i}": a for i, a in self._opt_vec.items()},
        )
        return bio.getvalue()

    def replicate_ring(self, chan, workers, tag: str) -> None:
        """Mirror this rank's committed stage onto the same dp lane of
        the PREDECESSOR stage (``stride = dp`` ranks back, ring-wrapped)
        and adopt the successor's — after this, a whole dead stage's
        params and opt chunks survive one stage (= one slice) earlier.
        ``tag`` must be identical on every rank."""
        with self._lock:
            if self._step is None:
                raise ValueError("replicate_ring before any commit")
            blob = self._blob()
            dp, stage, n_stages = self._dp, self._stage, self._n_stages
            dp_index = self._dp_index
        if n_stages < 2:
            return
        world = n_stages * dp
        me = stage * dp + dp_index
        pred = workers[(me - dp) % world]
        succ = workers[(me + dp) % world]
        name = f"kf.ppbuddy.{tag}"
        timeline.event("pp", "buddy-replicate", rank=me,
                       nbytes=len(blob), stage=stage)
        chan.send(pred, name, blob)
        from kungfu_tpu.elastic.reshard import _recv_or_fail

        raw = _recv_or_fail(chan, succ, (me + dp) % world,
                            "pp-buddy", name)
        with np.load(io.BytesIO(raw)) as z:
            buddy = {
                "pflat": z["pflat"],
                "meta": z["meta"],
                "vec": {int(k[1:]): z[k] for k in z.files
                        if k.startswith("v")},
            }
        with self._lock:
            self._buddy = buddy
            self._buddy_stage = (stage + 1) % n_stages

    # -- re-carve -----------------------------------------------------------
    def recarve(self, new_n_stages: int, peer=None, old_workers=None,
                new_workers=None, tag: str = "0",
                dead: Optional[Sequence[int]] = None,
                expect_step: Optional[int] = None) -> None:
        """Re-balance the committed stage state for a
        ``new_n_stages``-stage world (same dp width).  Leaderless: every
        participant computes the same :func:`flat_recarve_segments`
        plan and moves only the spans it owns or will own; dead stages'
        spans are served from the ring-buddy mirror on their
        predecessor (same dp lane).  ``dead`` is the confirmed dead set
        of OLD ranks; whole stages only (the slice ladder excludes
        slices whole).  ``expect_step`` gates against survivors whose
        boundaries committed different steps (the ZeroBoundary
        policy)."""
        with self._lock:
            if self._step is None:
                raise ValueError("recarve before any commit")
            step = self._step
            cfg = self._cfg
            old_n, dp = self._n_stages, self._dp
            my_stage, my_dp = self._stage, self._dp_index
            pflat = self._pflat
            opt_vec = dict(self._opt_vec)
            buddy, buddy_stage = self._buddy, self._buddy_stage
            zero = self._zero
        if expect_step is not None and step >= 0 and step != int(expect_step):
            raise ValueError(
                f"stage boundary committed at step {step} but the cluster "
                f"agreed to replay from step {expect_step} — a re-carve "
                "would blend states from different steps; escalate to the "
                "checkpoint restart")
        if not 1 <= new_n_stages:
            raise ValueError(f"new_n_stages must be >= 1, {new_n_stages}")
        old_map = stage_partition(cfg.n_layers, old_n)
        new_map = stage_partition(cfg.n_layers, new_n_stages)
        dead = {int(d) for d in (dead or ())}
        dead_stages = sorted({d // dp for d in dead})
        for s in dead_stages:
            members = set(range(s * dp, (s + 1) * dp))
            if not members <= dead:
                raise ValueError(
                    f"stage {s} is partially dead ({sorted(dead & members)}"
                    f" of {sorted(members)}) — the recovery ladder excludes "
                    "failure domains whole; re-run the slice verdict")
        alive_stages = [s for s in range(old_n) if s not in dead_stages]

        def server_stage(os_: int) -> Tuple[int, bool]:
            """(old stage whose ranks serve ``os_``'s spans, via_buddy)."""
            if os_ not in dead_stages:
                return os_, False
            pred = (os_ - 1) % old_n
            if pred in dead_stages:
                raise ValueError(
                    f"stage {os_} is dead and so is its buddy predecessor "
                    f"{pred} — stage unrecoverable (mirror redundancy "
                    "covers one failure domain; escalate to the "
                    "checkpoint restart)")
            return pred, True

        # recoverability first, BEFORE anything moves (and before the
        # wiring checks — data loss outranks a missing argument): every
        # dead stage must have an alive buddy predecessor, and when
        # THIS rank is that predecessor it must actually hold the
        # mirror — committed at THIS boundary's step.  The step check
        # matters: replicate_ring runs off the step path, so a rank one
        # commit ahead can mirror a NEWER successor state; serving a
        # dead stage from a different step would silently blend two
        # optimizer states — the exact failure the expect_step gate
        # exists to prevent (own step is already gated against it above)
        for s in dead_stages:
            serv0, _ = server_stage(s)
            if serv0 == my_stage:
                if buddy is None or buddy_stage != s:
                    raise ValueError(
                        f"stage {s} is dead and this rank holds no "
                        "mirror of it (replicate_ring was never run on "
                        "this boundary) — stage unrecoverable")
                bstep = int(buddy["meta"][0])
                if bstep != step:
                    raise ValueError(
                        f"stage {s}'s mirror was replicated at step "
                        f"{bstep} but this boundary committed step "
                        f"{step} — serving it would blend states from "
                        "different steps; escalate to the checkpoint "
                        "restart")
        if (old_n > 1 or new_n_stages > 1) and (
                peer is None or old_workers is None or new_workers is None):
            # all three or none: a missing worker list would silently
            # skip the remote sends in phase 1 and then crash the
            # receiving rank with a raw TypeError in phase 2
            raise ValueError(
                "multi-stage recarve needs peer + old_workers + "
                "new_workers (the typed configuration contract of the "
                "recovery path)")
        # staying = alive stages whose ranks are members of the NEW
        # world; alive-but-leaving stages (a planned resize's leavers)
        # still SERVE their spans before detaching, exactly like
        # ZeroBoundary's leavers
        if old_workers is not None and new_workers is not None:
            staying = [s for s in alive_stages
                       if new_workers.rank(old_workers[s * dp]) is not None]
        else:
            staying = alive_stages
        if len(staying) != new_n_stages:
            raise ValueError(
                f"{len(staying)} staying stages cannot carve "
                f"{new_n_stages} new stages (dp width is fixed)")
        # old-stage index -> new-stage index over the stayers
        new_of_old = {os_: ns for ns, os_ in enumerate(staying)}
        my_new_stage = new_of_old.get(my_stage)
        segs = flat_recarve_segments(cfg, old_map, new_map)
        timeline.event("pp", "stage-recarve", old_n=old_n,
                       new_n=new_n_stages, dead=dead_stages,
                       segments=len(segs))

        _, old_totals = stage_flat_layouts(cfg, old_map)
        _, new_totals = stage_flat_layouts(cfg, new_map)

        def old_rank(os_: int, j: int) -> int:
            return os_ * dp + j

        def new_rank(ns: int, j: int) -> int:
            return ns * dp + j

        chan = peer.channel if peer is not None else None
        me_addr = peer.config.self_id if peer is not None else None

        def local_flat(os_: int, via_buddy: bool) -> np.ndarray:
            if via_buddy:
                if buddy is None or buddy_stage != os_:
                    raise ValueError(
                        f"stage {os_} is dead and this rank holds no "
                        "mirror of it (replicate_ring was never run on "
                        "this boundary) — stage unrecoverable")
                return buddy["pflat"]
            return pflat

        def local_vec(os_: int, via_buddy: bool) -> Dict[int, np.ndarray]:
            if via_buddy:
                return buddy["vec"]
            return opt_vec

        # --- params: replicated within the stage, so the server for a
        # span toward (ns, j) is (server_stage, j) — same lane, zero
        # cross-lane traffic, and the whole-dead-stage case is LOCAL
        # (the mirror lives exactly where the data is needed).
        from kungfu_tpu.elastic.reshard import _recv_or_fail

        def seg_name(kind: str, i: int) -> str:
            return f"kf.pprc.{tag}.{kind}{i}"

        new_pflat = (np.zeros(new_totals[my_new_stage], np.float32)
                     if my_new_stage is not None else None)
        oc = {s: max(1, math.ceil(old_totals[s] / dp))
              for s in range(old_n)}
        nc = {s: max(1, math.ceil(new_totals[s] / dp))
              for s in range(new_n_stages)}
        new_vec: Dict[int, np.ndarray] = {}
        if zero == 2 and self._opt_dtypes and my_new_stage is not None:
            new_vec = {i: np.zeros(nc[my_new_stage], dt)
                       for i, dt in self._opt_dtypes.items()}

        # PHASE 1 — serve: every span this rank hosts that lands on
        # another rank is sent BEFORE any receive (the channel buffers
        # frames, so serve-all-then-assemble cannot deadlock — two
        # ranks that interleaved send/recv in plan order could each
        # block on a recv the other only reaches later).  Local spans
        # copy in place here too.
        for i, (os_, ooff, ns, noff, ln) in enumerate(segs):
            serv, via_buddy = server_stage(os_)
            if serv == my_stage:
                dst = new_rank(ns, my_dp)
                src_flat = local_flat(os_, via_buddy)
                if my_new_stage is not None and ns == my_new_stage:
                    new_pflat[noff:noff + ln] = src_flat[ooff:ooff + ln]
                elif new_workers is not None \
                        and new_workers[dst] != me_addr:
                    chan.send(new_workers[dst], seg_name("p", i),
                              np.ascontiguousarray(
                                  src_flat[ooff:ooff + ln]))
            if zero == 2 and self._opt_dtypes:
                for (jo, jn, oo, no, l) in _chunk_splits(
                        ooff, noff, ln, oc[os_], nc[ns]):
                    if not (serv == my_stage and jo == my_dp):
                        continue
                    vecs = local_vec(os_, via_buddy)
                    base = jo * oc[os_]
                    dst_is_me = (my_new_stage is not None
                                 and ns == my_new_stage and jn == my_dp)
                    if dst_is_me:
                        for k, arr in vecs.items():
                            new_vec[k][no - jn * nc[ns]:
                                       no - jn * nc[ns] + l] = \
                                arr[oo - base:oo - base + l]
                    else:
                        dst = new_rank(ns, jn)
                        for k, arr in vecs.items():
                            chan.send(
                                new_workers[dst],
                                seg_name(f"z{k}.", i) + f".{oo}",
                                np.ascontiguousarray(
                                    arr[oo - base:oo - base + l]))

        # PHASE 2 — assemble: receive every remote span of my new stage
        for i, (os_, ooff, ns, noff, ln) in enumerate(segs):
            serv, via_buddy = server_stage(os_)
            if my_new_stage is not None and ns == my_new_stage \
                    and serv != my_stage:
                raw = _recv_or_fail(
                    chan, old_workers[old_rank(serv, my_dp)],
                    old_rank(serv, my_dp), "pp-recarve",
                    seg_name("p", i))
                got = np.frombuffer(raw, np.float32)
                if got.shape[0] != ln:
                    raise ValueError(
                        f"recarve segment p{i}: expected {ln} "
                        f"elements, got {got.shape[0]}")
                new_pflat[noff:noff + ln] = got
            if zero == 2 and self._opt_dtypes:
                for (jo, jn, oo, no, l) in _chunk_splits(
                        ooff, noff, ln, oc[os_], nc[ns]):
                    dst_is_me = (my_new_stage is not None
                                 and ns == my_new_stage and jn == my_dp)
                    if not dst_is_me or (serv == my_stage and jo == my_dp):
                        continue
                    src = old_rank(serv, jo)
                    for k in new_vec:
                        raw = _recv_or_fail(
                            chan, old_workers[src], src, "pp-recarve",
                            seg_name(f"z{k}.", i) + f".{oo}")
                        got = np.frombuffer(raw, self._opt_dtypes[k])
                        if got.shape[0] != l:
                            raise ValueError(
                                f"recarve opt segment {i}@{oo}: "
                                f"expected {l}, got {got.shape[0]}")
                        new_vec[k][no - jn * nc[ns]:
                                   no - jn * nc[ns] + l] = got

        with self._lock:
            if my_new_stage is None:
                # leaver/dead lane: served its spans; drop stale state
                self._pflat = None
                self._opt_vec = {}
                return
            self._stage = my_new_stage
            self._n_stages = int(new_n_stages)
            self._pflat = new_pflat
            self._opt_vec = new_vec
            self._buddy = None
            self._buddy_stage = None

    # -- restore ------------------------------------------------------------
    def restore(self):
        """``(stage, n_stages, params_tree, opt_state)`` from the
        (re-carved) boundary — the new :class:`HostPipeline` epoch's
        starting state."""
        import jax

        with self._lock:
            if self._pflat is None:
                raise ValueError("restore before commit (or on a leaver)")
            cfg, stage, n = self._cfg, self._stage, self._n_stages
            pflat = self._pflat
            vec, scal = dict(self._opt_vec), dict(self._opt_scal)
            otd = self._opt_treedef
        lo, hi = stage_partition(cfg.n_layers, n)[stage]
        shapes = stage_param_shapes(cfg, lo, hi, stage == 0, stage == n - 1)
        leaves, treedef = jax.tree_util.tree_flatten(shapes)
        out, off = [], 0
        for leaf in leaves:
            size = int(np.prod(leaf.shape)) if leaf.shape else 1
            out.append(pflat[off:off + size].reshape(leaf.shape))
            off += size
        params = jax.tree_util.tree_unflatten(treedef, out)
        opt = None
        if otd is not None:
            n_leaves = otd.num_leaves
            oleaves = []
            for i in range(n_leaves):
                if i in vec:
                    oleaves.append(jax.numpy.asarray(vec[i]))
                else:
                    oleaves.append(jax.numpy.asarray(scal[i]))
            opt = jax.tree_util.tree_unflatten(otd, oleaves)
        return stage, n, params, opt


def recarve_stages_after_shrink(peer, boundary: StageBoundary,
                                old_workers,
                                expect_step: Optional[int] = None) -> None:
    """Shrink-recovery rung 10: re-balance pipeline stages across the
    survivors.  Call AFTER ``shrink_to_survivors`` succeeded
    (``peer.cluster.workers`` is the shrunk list); ``old_workers`` is
    the pre-shrink membership the boundary was committed under.  The
    dead set is derived the same way the ZeRO re-carve derives it:
    every old rank absent from the survivor list is confirmed dead."""
    new_workers = peer.cluster.workers
    dead = [r for r, w in enumerate(old_workers)
            if new_workers.rank(w) is None]
    dp = max(1, boundary._dp)
    if len(new_workers) % dp:
        raise ValueError(
            f"surviving world of {len(new_workers)} does not tile the "
            f"dp width {dp} — stage re-carve needs whole dp groups")
    boundary.recarve(
        len(new_workers) // dp, peer=peer, old_workers=old_workers,
        new_workers=new_workers, tag=f"v{peer.cluster_version}",
        dead=dead, expect_step=expect_step,
    )


# -- the host-plane pipeline runner ----------------------------------------
@dataclass
class _PendingRecv:
    handle: object
    dtype: object
    shape: tuple


class HostPipeline:
    """Runs one rank's side of the cross-DCN pipeline: the 1F1B (or
    interleaved / sequential) schedule over async p2p handles, with the
    stage's DP gradient sync — replicated or ZeRO-2 bucketed
    reduce-scatter — overlapped into the drain.

    World layout is stage-major (= slice-major, PR 8): rank ``r`` is
    stage ``r // dp``, dp lane ``r % dp``; activations flow within a
    lane, gradients reduce within a stage.  ``plan`` is a
    :class:`~kungfu_tpu.parallel.train.ParallelPlan` with
    ``pp * dp == len(engine.peers)``; ``tp`` shards the stage math over
    this rank's LOCAL devices (TP never crosses the DCN)."""

    def __init__(self, engine, plan, cfg, *, full_params=None,
                 stage_params=None, inner=None, devices=None, peer=None,
                 n_buckets: int = 2, prefetch: int = _PREFETCH):
        import jax
        import optax

        self.engine = engine
        self.plan = plan
        self.cfg = cfg
        self.peer = peer
        world = len(engine.peers)
        if plan.pp * plan.dp != world:
            raise ValueError(
                f"plan pp={plan.pp} x dp={plan.dp} does not tile the "
                f"{world}-rank world")
        if plan.zero_stage not in (0, 2):
            raise ValueError(
                "HostPipeline composes ZeRO-2 (bucketed reduce-scatter) "
                f"or replicated DP — zero_stage={plan.zero_stage}")
        if peer is not None:
            topo = peer.slice_topology()
            if topo is not None and (topo.num_slices != plan.pp
                                     or topo.ranks_per_slice != plan.dp):
                raise ValueError(
                    f"plan (pp={plan.pp}, dp={plan.dp}) disagrees with "
                    f"the slice topology {topo} — PP maps across the DCN "
                    "slice axis (one stage per slice)")
        self.rank = engine.rank
        self.stage = self.rank // plan.dp
        self.dp_index = self.rank % plan.dp
        self.v = plan.interleave if plan.pp_schedule == "interleaved" else 1
        self.n_micro = plan.n_micro or plan.pp
        self._S, self._V = plan.pp, plan.pp * self.v
        part = interleaved_partition(cfg.n_layers, plan.pp, self.v)
        self.mods: List[StageModule] = []
        self.params: List[dict] = []
        for c in range(self.v):
            lo, hi = part[self.stage][c]
            vs = c * self._S + self.stage
            mod = StageModule(cfg, lo, hi, first=vs == 0,
                              last=vs == self._V - 1, tp=plan.tp,
                              devices=devices)
            self.mods.append(mod)
            if stage_params is not None:
                sp = stage_params if self.v == 1 else stage_params[c]
            elif full_params is not None:
                sp = slice_stage_params(cfg, full_params, lo, hi,
                                        vs == 0, vs == self._V - 1)
            else:
                raise ValueError("need full_params or stage_params")
            self.params.append(mod.place(sp))
        self.inner = inner if inner is not None else optax.sgd(0.01)
        self._n_buckets = max(1, int(n_buckets))
        self._prefetch = max(0, int(prefetch))
        # ZeRO-2 opt state: one flat chunk per chunk-module; replicated:
        # full tree per module
        self.opt_state: List[object] = []
        self._flat_shapes: List[list] = []
        for c in range(self.v):
            leaves = jax.tree_util.tree_leaves(self.params[c])
            total = sum(int(np.prod(np.shape(l))) for l in leaves)
            self._flat_shapes.append(total)
            if plan.zero_stage == 2:
                chunk = max(1, math.ceil(total / plan.dp))
                self.opt_state.append(
                    self.inner.init(jax.numpy.zeros((chunk,),
                                                    jax.numpy.float32)))
            else:
                self.opt_state.append(self.inner.init(self.params[c]))
        self._step = 0
        #: the op list is a pure function of (schedule, m, S, stage, v)
        #: — all fixed at construction; the interleaved variant's
        #: greedy DAG simulation is O(S·v·m²) and must not re-run on
        #: the per-step hot path
        self._ops = build_schedule(self.plan.pp_schedule, self.n_micro,
                                   self._S, self.stage, self.v)
        # the steady-state in-flight set — prefetched activation recvs,
        # bounded sends, and the act+grad pair the current op touches —
        # must fit the engine's async worker pool, or a full pool stalls
        # submission mid-schedule while every peer waits on the frame we
        # never sent: a distributed deadlock, not a slowdown.  Validate
        # at construction (proto-verify pins the same bound statically).
        from kungfu_tpu.comm.engine import ASYNC_POOL_WORKERS
        window = self._prefetch + _MAX_INFLIGHT_SENDS + 2
        if window > ASYNC_POOL_WORKERS:
            raise ValueError(
                f"pipeline in-flight window {window} (prefetch="
                f"{self._prefetch} + max sends {_MAX_INFLIGHT_SENDS} + 2)"
                f" exceeds the async pool ({ASYNC_POOL_WORKERS} workers);"
                f" lower prefetch= or widen ASYNC_POOL_WORKERS")
        #: tag namespace keyed by the channel epoch token: a rebuilt
        #: post-shrink engine gets a fresh token, so a replayed step's
        #: tags can never collide with the dead epoch's stragglers
        self._tagbase = f"pp.e{getattr(engine.channel, 'token', 0)}"
        # the schedule needs warmup+drain handles in flight; widen the
        # engine window (local backpressure knob, kf-overlap)
        engine.set_overlap_depth(
            max(engine.overlap_depth, self._prefetch + _MAX_INFLIGHT_SENDS
                + 2))

    # -- geometry ----------------------------------------------------------
    def _phys(self, vs: int) -> int:
        return vs % self._S

    def _peer_rank(self, stage: int) -> int:
        return stage * self.plan.dp + self.dp_index

    def _dp_rank(self, j: int) -> int:
        return self.stage * self.plan.dp + j

    def _act_tag(self, mb: int, vs: int) -> str:
        return f"{self._tagbase}.t{self._step}.f{mb}.v{vs}"

    def _grad_tag(self, mb: int, vs: int) -> str:
        return f"{self._tagbase}.t{self._step}.b{mb}.v{vs}"

    def _op_dep(self, op) -> Optional[Tuple[str, int, tuple]]:
        """(tag, src_rank, (dtype, shape)) this op blocks on, or None."""
        kind, mb, c = op
        vs = c * self._S + self.stage
        B_mb = self._B_mb
        S = self._seq
        act_shape = (B_mb, S, self.cfg.d_model)
        dt = np.dtype(self.cfg.compute_dtype)
        if kind == "F":
            if vs == 0:
                return None
            return (self._act_tag(mb, vs), self._peer_rank(
                self._phys(vs - 1)), (dt, act_shape))
        if vs == self._V - 1:
            return None
        return (self._grad_tag(mb, vs), self._peer_rank(
            self._phys(vs + 1)), (dt, act_shape))

    def warmup(self, B_loc: int, seq: int) -> None:
        """Compile every stage's jitted entry points on dummy shapes —
        locally, with NO wire traffic.  A cold jit (multi-second under
        the tp shard_map vjps) sitting inside the first step's recv
        window would read as a dead peer to the per-peer deadline, the
        same reason the serve engine warms every prefill bucket."""
        m = self.n_micro
        if B_loc % m:
            raise ValueError(f"batch {B_loc} % n_micro {m} != 0")
        B_mb = B_loc // m
        dt = np.dtype(self.cfg.compute_dtype)
        ids = np.zeros((B_mb, seq), np.int32)
        act = np.zeros((B_mb, seq, self.cfg.d_model), dt)
        tgt = np.zeros((B_mb, seq), np.int32)
        for c, mod in enumerate(self.mods):
            p = self.params[c]
            x = ids if mod.first else act
            if mod.last:
                mod.loss_backward(p, x, tgt)
            else:
                mod.forward(p, x)
                mod.backward(p, x, act)

    # -- the step ----------------------------------------------------------
    def train_step(self, ids, targets) -> Optional[float]:
        """One full training step over this rank's dp-lane batch shard
        ``(ids, targets)`` of shape ``[B_loc, S]``; returns the mean
        microbatch loss on last-stage ranks, None elsewhere."""
        import jax

        ids = np.asarray(ids)
        targets = np.asarray(targets)
        m = self.n_micro
        B_loc, S = ids.shape
        if B_loc % m:
            raise ValueError(f"batch {B_loc} % n_micro {m} != 0")
        self._B_mb, self._seq = B_loc // m, S
        ids_mb = ids.reshape(m, self._B_mb, S)
        tgt_mb = targets.reshape(m, self._B_mb, S)
        ops = self._ops
        grads = [None] * self.v
        b_done = [0] * self.v
        losses: List[float] = []
        x_in: Dict[Tuple[int, int], object] = {}
        recvs: Dict[str, _PendingRecv] = {}
        sends: List[object] = []
        dp_pending: List[tuple] = []
        prefetch_on = self.plan.pp_schedule != "sequential"

        def ensure_recv(idx: int) -> None:
            if not prefetch_on:
                return
            for op in ops[idx: idx + 1 + self._prefetch]:
                dep = self._op_dep(op)
                if dep is None or dep[0] in recvs:
                    continue
                tag, src, (dt, shape) = dep
                recvs[tag] = _PendingRecv(
                    self.engine.recv_async(src, tag, dtype=dt,
                                           shape=shape), dt, shape)

        def wait_dep(op):
            dep = self._op_dep(op)
            if dep is None:
                return None
            tag, src, (dt, shape) = dep
            pr = recvs.pop(tag, None)
            kind, mb, c = op
            with timeline.span("pp", "bubble", rank=self.rank,
                               stage=self.stage, mb=mb, tag=tag):
                if pr is not None:
                    return pr.handle.wait()
                return self.engine.recv_from(src, tag, dtype=dt,
                                             shape=shape)

        def push_send(rank: int, arr, tag: str) -> None:
            h = self.engine.send_async(rank, np.ascontiguousarray(arr),
                                       tag)
            sends.append(h)
            while len(sends) > _MAX_INFLIGHT_SENDS:
                sends.pop(0).wait()

        ensure_recv(0)
        for idx, op in enumerate(ops):
            ensure_recv(idx + 1)
            kind, mb, c = op
            vs = c * self._S + self.stage
            mod, params = self.mods[c], self.params[c]
            if kind == "F":
                x = ids_mb[mb] if vs == 0 else wait_dep(op)
                x_in[(mb, c)] = x
                if vs < self._V - 1:
                    with timeline.span("pp", "fwd", rank=self.rank,
                                       stage=self.stage, mb=mb, chunk=c):
                        out = mod.forward(params, x)
                    push_send(self._peer_rank(self._phys(vs + 1)),
                              np.asarray(out), self._act_tag(mb, vs + 1))
                # last virtual stage: forward work happens fused into
                # the loss vjp at B — the schedule's B follows at once
                continue
            # backward
            x = x_in.pop((mb, c))
            if vs == self._V - 1:
                with timeline.span("pp", "bwd", rank=self.rank,
                                   stage=self.stage, mb=mb, chunk=c):
                    loss, dparams, dx = mod.loss_backward(
                        params, x, tgt_mb[mb])
                losses.append(float(loss))
            else:
                dout = wait_dep(op)
                with timeline.span("pp", "bwd", rank=self.rank,
                                   stage=self.stage, mb=mb, chunk=c):
                    dparams, dx = mod.backward(params, x, dout)
            if vs > 0:
                push_send(self._peer_rank(self._phys(vs - 1)),
                          np.asarray(dx), self._grad_tag(mb, vs - 1))
            grads[c] = dparams if grads[c] is None else \
                jax.tree_util.tree_map(jax.numpy.add, grads[c], dparams)
            b_done[c] += 1
            if b_done[c] == m:
                # this chunk's gradient is final: issue its DP
                # reduce-scatter NOW — the send rides the remaining
                # drain (the bubble hides the DP wire)
                dp_pending.append(self._dp_sync_begin(c, grads[c]))

        for h in sends:
            h.wait()
        for pend in dp_pending:
            self._dp_sync_finish(pend)
        self._step += 1
        return float(np.mean(losses)) if losses else None

    # -- DP gradient sync ---------------------------------------------------
    def _bucket_spans(self, width: int) -> List[Tuple[int, int]]:
        nb = min(self._n_buckets, max(1, width))
        base, rem = divmod(width, nb)
        spans, off = [], 0
        for b in range(nb):
            w = base + (1 if b < rem else 0)
            if w:
                spans.append((off, w))
            off += w
        return spans

    def _dp_sync_begin(self, c: int, gtree):
        """Flatten chunk ``c``'s grads and ISSUE the per-bucket
        reduce-scatter sends as async handles; returns the pending
        state ``_dp_sync_finish`` completes.  With dp == 1 there is no
        wire — the pending state is just the local flat."""
        import jax

        dp = self.plan.dp
        leaves = jax.tree_util.tree_leaves(gtree)
        flat = np.concatenate(
            [np.asarray(l, np.float32).ravel() for l in leaves]) \
            if leaves else np.zeros(0, np.float32)
        chunkw = max(1, math.ceil(max(flat.shape[0], 1) / dp))
        padded = np.zeros(dp * chunkw, np.float32)
        padded[: flat.shape[0]] = flat
        view = padded.reshape(dp, chunkw)
        spans = self._bucket_spans(chunkw)
        handles: List[object] = []
        tb = f"{self._tagbase}.t{self._step}.rs.c{c}"
        for b, (off, w) in enumerate(spans):
            for j in range(dp):
                if j == self.dp_index:
                    continue
                h = self.engine.send_async(
                    self._dp_rank(j),
                    np.ascontiguousarray(view[j, off:off + w]),
                    f"{tb}.b{b}.o{self.dp_index}")
                handles.append(h)
                while len(handles) > _MAX_INFLIGHT_SENDS:
                    handles.pop(0).wait()
        return (c, view, spans, handles)

    def _dp_sync_finish(self, pend) -> None:
        """Receive the peers' contributions bucket by bucket (summed in
        dp-member order — the bitwise contract), normalize by
        ``m * dp``, run the optimizer (ZeRO-2: on this member's chunk
        only, then all-gather the updated param chunks; replicated:
        all-gather the reduced grad and update locally).  Bucket b+1's
        recvs are posted before bucket b is summed — the depth-k
        bucket pipeline shape."""
        c, view, spans, handles = pend
        dp, m = self.plan.dp, self.n_micro
        chunkw = view.shape[1]
        tb = f"{self._tagbase}.t{self._step}.rs.c{c}"
        rhs: Dict[Tuple[int, int], object] = {}

        def post(b: int) -> None:
            if b >= len(spans):
                return
            _, w = spans[b]
            for j in range(dp):
                if j != self.dp_index:
                    rhs[(b, j)] = self.engine.recv_async(
                        self._dp_rank(j), f"{tb}.b{b}.o{j}",
                        dtype=np.float32, shape=(w,))

        acc = np.zeros(chunkw, np.float32)
        post(0)
        for b, (off, w) in enumerate(spans):
            post(b + 1)
            parts = [view[self.dp_index, off:off + w] if j == self.dp_index
                     else rhs.pop((b, j)).wait() for j in range(dp)]
            s = parts[0].copy()
            for p in parts[1:]:
                s += p
            acc[off:off + w] = s
        for h in handles:
            h.wait()
        acc /= (m * dp)
        self._apply_update(c, acc, chunkw)

    def _apply_update(self, c: int, grad_chunk: np.ndarray,
                      chunkw: int) -> None:
        """Optimizer step from MY reduced gradient chunk.  ZeRO-2:
        elementwise update on the chunk, all-gather the updated param
        chunks (each member's optimizer state never exceeds 1/dp of the
        stage).  Replicated: all-gather the reduced grad chunks to the
        full gradient and update the whole tree locally."""
        import jax
        import jax.numpy as jnp
        import optax

        dp = self.plan.dp
        total = self._flat_shapes[c]
        leaves, treedef = jax.tree_util.tree_flatten(self.params[c])
        sizes = [int(np.prod(np.shape(l))) for l in leaves]

        def unflatten(flat: np.ndarray):
            out, off = [], 0
            for l, sz in zip(leaves, sizes):
                out.append(jnp.asarray(
                    flat[off:off + sz]).reshape(np.shape(l)))
                off += sz
            return jax.tree_util.tree_unflatten(treedef, out)

        def exchange_chunks(mine: np.ndarray, what: str) -> np.ndarray:
            """All-gather equal chunks over the dp group (member order);
            returns the concatenated [dp*chunkw] flat."""
            tb = f"{self._tagbase}.t{self._step}.{what}.c{c}"
            hs, pending = [], {}
            for j in range(dp):
                if j == self.dp_index:
                    continue
                hs.append(self.engine.send_async(
                    self._dp_rank(j), np.ascontiguousarray(mine),
                    f"{tb}.o{self.dp_index}"))
                pending[j] = self.engine.recv_async(
                    self._dp_rank(j), f"{tb}.o{j}", dtype=np.float32,
                    shape=(chunkw,))
            full = np.zeros(dp * chunkw, np.float32)
            for j in range(dp):
                full[j * chunkw:(j + 1) * chunkw] = (
                    mine if j == self.dp_index else pending[j].wait())
            for h in hs:
                h.wait()
            return full

        if self.plan.zero_stage == 2:
            pflat = np.concatenate(
                [np.asarray(l, np.float32).ravel() for l in leaves]) \
                if leaves else np.zeros(0, np.float32)
            padded = np.zeros(dp * chunkw, np.float32)
            padded[:total] = pflat
            mine = jnp.asarray(
                padded[self.dp_index * chunkw:
                       (self.dp_index + 1) * chunkw])
            upd, self.opt_state[c] = self.inner.update(
                jnp.asarray(grad_chunk), self.opt_state[c], mine)
            new_mine = np.asarray(optax.apply_updates(mine, upd),
                                  dtype=np.float32)
            new_flat = (exchange_chunks(new_mine, "ag") if dp > 1
                        else new_mine)[:total]
            self.params[c] = self.mods[c].place(unflatten(new_flat))
            return
        gfull = (exchange_chunks(np.asarray(grad_chunk, np.float32), "gg")
                 if dp > 1 else grad_chunk)[:total]
        gtree = unflatten(gfull)
        upd, self.opt_state[c] = self.inner.update(
            gtree, self.opt_state[c], self.params[c])
        self.params[c] = self.mods[c].place(
            optax.apply_updates(self.params[c], upd))

    # -- elastic boundary ---------------------------------------------------
    def commit_boundary(self, boundary: StageBoundary) -> None:
        """Commit this rank's stage state at the CURRENT step (call
        right after a completed ``train_step``).  v == 1 only: the
        interleaved variant's chunks have no single contiguous stage
        flat to re-carve (schedule-level feature, not an elastic one)."""
        if self.v != 1:
            raise ValueError(
                "stage boundaries support the non-interleaved pipeline "
                "(one chunk per stage)")
        boundary.commit(
            self._step, self.cfg, self.stage, self._S, self.plan.dp,
            self.dp_index, self.params[0], self.opt_state[0],
            self.plan.zero_stage)

    @classmethod
    def from_boundary(cls, engine, plan, cfg, boundary: StageBoundary,
                      *, inner=None, devices=None, peer=None,
                      n_buckets: int = 2) -> "HostPipeline":
        """Rebuild a pipeline for the post-re-carve world from a
        re-carved :class:`StageBoundary` (params AND ZeRO-2 optimizer
        chunks restored bitwise)."""
        stage, n, params, opt = boundary.restore()
        if plan.pp != n:
            raise ValueError(
                f"plan.pp={plan.pp} but the boundary is carved for {n} "
                "stages — recarve first")
        pipe = cls(engine, plan, cfg, stage_params=params, inner=inner,
                   devices=devices, peer=peer, n_buckets=n_buckets)
        if opt is not None:
            pipe.opt_state[0] = opt
        pipe._step = boundary.step() or 0
        return pipe

    # -- reporting ----------------------------------------------------------
    @property
    def step_count(self) -> int:
        return self._step

    def stage_layers(self, c: int = 0) -> Tuple[int, int]:
        return self.mods[c].lo, self.mods[c].hi


def merge_stage_trees(cfg, n_stages: int, v: int, trees) -> dict:
    """Reassemble per-virtual-stage param-SHAPED trees (params, or any
    tree mirroring them — an optimizer trace, a gradient) into the full
    stacked tree.  ``trees[vs]`` must have the stage-subtree structure
    of virtual stage ``vs`` (:func:`slice_stage_params`)."""
    import jax
    import jax.numpy as jnp

    S, V = n_stages, n_stages * v
    part = interleaved_partition(cfg.n_layers, S, v)
    full: dict = {}
    layer_rows: List[object] = [None] * cfg.n_layers
    for vs in range(V):
        c, s = vs // S, vs % S
        lo, hi = part[s][c]
        for i, l in enumerate(range(lo, hi)):
            layer_rows[l] = jax.tree_util.tree_map(
                lambda a, ii=i: a[ii], trees[vs]["layers"])
        if vs == 0:
            full["embed"] = trees[vs]["embed"]
            if cfg.pos == "learned":
                full["pos_embed"] = trees[vs]["pos_embed"]
        if vs == V - 1:
            full["ln_f"] = trees[vs]["ln_f"]
            full["head"] = trees[vs]["head"]
    full["layers"] = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *layer_rows)
    return full


def reference_pipeline_step(cfg, plan, full_params, shards, inner,
                            opt_states=None):
    """Single-process fixed-world reference: the SAME stage modules and
    the SAME dp-member numpy reductions run sequentially — the bitwise
    yardstick the 1F1B tests pin the distributed run against.

    ``shards`` is ``[(ids, targets)]`` per dp lane; returns
    ``(new_full_params, losses_per_lane_mean, opt_states)`` where
    ``opt_states`` round-trips for multi-step references."""
    import jax
    import jax.numpy as jnp
    import optax

    S, v = plan.pp, (plan.interleave
                     if plan.pp_schedule == "interleaved" else 1)
    V = S * v
    m = plan.n_micro or S
    dp = plan.dp
    part = interleaved_partition(cfg.n_layers, S, v)
    mods, params = [], []
    for vs in range(V):
        c, s = vs // S, vs % S
        lo, hi = part[s][c]
        mod = StageModule(cfg, lo, hi, first=vs == 0, last=vs == V - 1,
                          tp=plan.tp)
        mods.append(mod)
        params.append(mod.place(slice_stage_params(
            cfg, full_params, lo, hi, vs == 0, vs == V - 1)))
    lane_grads: List[List[object]] = []
    losses = []
    for d in range(dp):
        ids, targets = shards[d]
        ids_mb = np.asarray(ids).reshape(m, -1, np.asarray(ids).shape[-1])
        tgt_mb = np.asarray(targets).reshape(m, -1,
                                             np.asarray(targets).shape[-1])
        acts: Dict[Tuple[int, int], object] = {}
        grads: List[object] = [None] * V
        lane_loss = []
        for mb in range(m):
            x = ids_mb[mb]
            for vs in range(V):
                acts[(vs, mb)] = x
                if vs < V - 1:
                    x = np.asarray(mods[vs].forward(params[vs], x))
        for mb in range(m):
            loss, dparams, dx = mods[V - 1].loss_backward(
                params[V - 1], acts[(V - 1, mb)], tgt_mb[mb])
            lane_loss.append(float(loss))
            grads[V - 1] = dparams if grads[V - 1] is None else \
                jax.tree_util.tree_map(jnp.add, grads[V - 1], dparams)
            for vs in range(V - 2, -1, -1):
                dparams, dx2 = mods[vs].backward(
                    params[vs], acts[(vs, mb)], np.asarray(dx))
                grads[vs] = dparams if grads[vs] is None else \
                    jax.tree_util.tree_map(jnp.add, grads[vs], dparams)
                dx = dx2
        lane_grads.append(grads)
        losses.append(float(np.mean(lane_loss)))
    # dp reduction in member order, then one normalize — the exact
    # numpy math of HostPipeline._dp_sync_finish
    new_states = []
    opt_states = opt_states or [None] * V
    for vs in range(V):
        flats = []
        for d in range(dp):
            leaves = jax.tree_util.tree_leaves(lane_grads[d][vs])
            flats.append(np.concatenate(
                [np.asarray(l, np.float32).ravel() for l in leaves]))
        acc = flats[0].copy()
        for f in flats[1:]:
            acc += f
        acc /= (m * dp)
        pleaves, ptd = jax.tree_util.tree_flatten(params[vs])
        sizes = [int(np.prod(np.shape(l))) for l in pleaves]
        gl, off = [], 0
        for l, sz in zip(pleaves, sizes):
            gl.append(jnp.asarray(acc[off:off + sz]).reshape(np.shape(l)))
            off += sz
        gtree = jax.tree_util.tree_unflatten(ptd, gl)
        st = opt_states[vs] if opt_states[vs] is not None \
            else inner.init(params[vs])
        upd, st = inner.update(gtree, st, params[vs])
        params[vs] = optax.apply_updates(params[vs], upd)
        new_states.append(st)
    full = merge_stage_trees(cfg, S, v, params)
    return full, losses, new_states
