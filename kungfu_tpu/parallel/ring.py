"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context support the TPU way (the reference has no attention at all,
SURVEY §5.7; this is the framework's sequence/context-parallel subsystem):
Q stays local, K/V blocks rotate around the ``sp`` ring via
``lax.ppermute`` while a streaming (online-softmax) accumulator folds each
block in — memory per device is O(S/sp), traffic rides the ICI ring, and
compute/communication overlap is XLA's job (each round's matmul hides the
next block's permute).

Differentiable: the backward pass is autodiff through the scan — ppermute
transposes to the inverse rotation, so cotangents counter-rotate around the
same ring (this *is* the ring-attention backward schedule).

Must run inside ``shard_map`` with ``axis`` a live mesh axis name; with
``sp == 1`` it degenerates to one masked flash-style block and is the
single-device attention path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def ring_attention(q, k, v, causal: bool = True, axis: str = "sp"):
    """q, k, v: [B, H, S_local, D] (sequence axis sharded over ``axis``).

    Returns [B, H, S_local, D] — the exact softmax attention output as if
    the full sequence were on one device.
    """
    n_sp = jax.lax.axis_size(axis)
    my_blk = jax.lax.axis_index(axis)
    B, H, S, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    qf = q.astype(jnp.float32) * scale

    q_pos = my_blk * S + jnp.arange(S)  # global positions of local queries

    def fold(carry, _):
        kv, blk, m, l, acc = carry
        kb, vb = kv
        logits = jnp.einsum("bhqd,bhkd->bhqk", qf, kb.astype(jnp.float32))
        if causal:
            k_pos = blk * S + jnp.arange(S)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((S, S), dtype=bool)
        m_new = jnp.maximum(m, jnp.max(jnp.where(mask, logits, -jnp.inf), axis=-1))
        # clamp so fully-masked rounds (future blocks under causal) keep
        # m finite and contribute exactly zero
        m_new = jnp.maximum(m_new, -1e30)
        p = jnp.where(mask, jnp.exp(logits - m_new[..., None]), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32)
        )
        # rotate K/V: receive the next block from the ring neighbour
        perm = [((j + 1) % n_sp, j) for j in range(n_sp)]
        kv = jax.tree_util.tree_map(
            lambda t: jax.lax.ppermute(t, axis, perm), (kb, vb)
        )
        return (kv, (blk + 1) % n_sp, m_new, l, acc), None

    def vary(x):
        # mark the accumulators as varying over the ring axis so the scan
        # carry type matches (jax>=0.9 varying-manual-axes typing)
        return jax.lax.pcast(x, (axis,), to="varying")

    m0 = vary(jnp.full((B, H, S), -jnp.inf, jnp.float32))
    l0 = vary(jnp.zeros((B, H, S), jnp.float32))
    acc0 = vary(jnp.zeros((B, H, S, D), jnp.float32))
    (_, _, _, l, acc), _ = jax.lax.scan(
        fold, ((k, v), my_blk, m0, l0, acc0), None, length=n_sp
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def make_ring_attn(axis: str = "sp"):
    """Adapter matching the ``attn_fn(q, k, v, causal)`` slot of
    :meth:`kungfu_tpu.models.transformer.Transformer.apply`."""

    def attn(q, k, v, causal):
        return ring_attention(q, k, v, causal=causal, axis=axis)

    return attn
