"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context support the TPU way (the reference has no attention at all,
SURVEY §5.7; this is the framework's sequence/context-parallel subsystem):
Q stays local, K/V blocks rotate around the ``sp`` ring via
``lax.ppermute`` while a streaming (online-softmax) accumulator folds each
block in — traffic rides the ICI ring, and compute/communication overlap
is XLA's job (each round's matmul hides the next block's permute).  On
TPU each round's block runs through the Pallas flash kernel and rounds
merge by lse (``block_impl`` below), taking per-device attention memory
from O((S/sp)²) scores to O(kernel block); off-TPU a jnp online-softmax
fold computes the same thing.

Differentiable: the backward pass is autodiff through the scan — ppermute
transposes to the inverse rotation, so cotangents counter-rotate around the
same ring (this *is* the ring-attention backward schedule).

Must run inside ``shard_map`` with ``axis`` a live mesh axis name; with
``sp == 1`` it degenerates to one masked flash-style block and is the
single-device attention path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from kungfu_tpu.utils.jaxcompat import axis_size


def ring_attention(q, k, v, causal: bool = True, axis: str = "sp",
                   block_impl: str = "auto",
                   kv_gather: Optional[str] = None):
    """q, k, v: [B, H, S_local, D] (sequence axis sharded over ``axis``).

    Returns [B, H, S_local, D] — the exact softmax attention output as if
    the full sequence were on one device.

    ``block_impl`` picks the per-round block computation:

    * ``einsum`` — jnp online-softmax fold (materializes one [S_local,
      S_local] f32 logits tile per round);
    * ``flash`` — the Pallas kernel via
      :func:`~kungfu_tpu.ops.pallas.attention.flash_attention_with_lse`,
      merged across rounds by lse: per-device memory drops to O(block)
      and causal runs *skip* fully-masked rounds' compute entirely
      (``lax.switch`` — the einsum path pays for them and discards);
    * ``auto`` — flash on TPU, einsum elsewhere (interpret-mode Pallas
      is too slow for the CPU test cluster).

    ``kv_gather`` swaps the n-round K/V *rotation* for ONE ring
    all-gather up front (:func:`kungfu_tpu.ops.schedules.
    all_gather_flat` — pass ``"pallas_ring"`` to ride the ICI kernels of
    :mod:`kungfu_tpu.ops.pallas.collectives`, or ``"lax"`` for the
    primitive): n ppermute program points collapse into one collective
    whose backward is the matching reduce-scatter of dK/dV (the gather
    kernel's custom vjp).  Trades the rotation's O(S_local²) working set
    for the gathered O(S_local · S_global) block — the short-sequence /
    bandwidth-rich regime; ``None`` (default) keeps the rotation.
    """
    if block_impl not in ("auto", "flash", "einsum"):
        raise ValueError(f"unknown block_impl {block_impl!r}")
    if kv_gather is not None:
        if block_impl == "flash":
            # the gathered path computes one masked einsum block — an
            # explicit flash request would be silently downgraded to the
            # O(S_local * S_global) logits tile the kernel exists to
            # avoid; refuse instead (auto/einsum opt in knowingly)
            raise ValueError(
                "kv_gather is einsum-block attention and cannot honor "
                "block_impl='flash'; use the ppermute rotation "
                "(kv_gather=None) for the flash path")
        from kungfu_tpu.ops.schedules import FLAT_SCHEDULES

        if kv_gather not in FLAT_SCHEDULES:
            raise ValueError(
                f"unknown kv_gather {kv_gather!r}; one of {FLAT_SCHEDULES}"
                " (or None for the ppermute rotation)")
        return _ring_kv_gather(q, k, v, causal, axis, kv_gather)
    if block_impl == "flash" or (
        block_impl == "auto" and jax.default_backend() == "tpu"
    ):
        return _ring_flash(q, k, v, causal, axis)
    return _ring_einsum(q, k, v, causal, axis)


def _ring_kv_gather(q, k, v, causal: bool, axis: str, schedule: str):
    """Gathered-K/V block attention: one ring all-gather of K and V over
    ``axis``, then a single masked online-softmax block per device.
    Exact — global causal positions mask the logits — and
    differentiable: the gather's transpose reduce-scatters dK/dV back to
    their owners (with ``schedule="pallas_ring"`` that is the ring
    kernel's custom vjp)."""
    from kungfu_tpu.ops.schedules import all_gather_flat

    n_sp = axis_size(axis)
    my_blk = jax.lax.axis_index(axis)
    B, H, S, D = q.shape

    def gather(t):
        flat = all_gather_flat(t.reshape(-1), [axis], schedule=schedule)
        # mesh-major rows = ring order: device j's [B, H, S, D] block
        return jnp.moveaxis(
            flat.reshape((n_sp,) + t.shape), 0, 2
        ).reshape(B, H, n_sp * S, D)

    kf = gather(k).astype(jnp.float32)
    vf = gather(v).astype(jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32) * scale, kf)
    if causal:
        q_pos = my_blk * S + jnp.arange(S)
        k_pos = jnp.arange(n_sp * S)
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask, logits, -jnp.inf)
    m = jnp.max(logits, axis=-1, keepdims=True)
    m = jnp.maximum(m, -1e30)  # fully-masked rows stay finite
    p = jnp.exp(logits - m)
    if causal:
        p = jnp.where(mask, p, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
    denom = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    return (out / denom).astype(q.dtype)


def _ring_flash(q, k, v, causal: bool, axis: str):
    """Flash-block ring: each round folds one rotating K/V block through
    the Pallas kernel; blocks merge by the standard online-softmax
    combine over (out, lse)."""
    from kungfu_tpu.ops.pallas._sharding import match_vma
    from kungfu_tpu.ops.pallas.attention import flash_attention_with_lse

    n_sp = axis_size(axis)
    my_blk = jax.lax.axis_index(axis)
    B, H, S, D = q.shape
    q3 = q.reshape(B * H, S, D)
    ring_vma = frozenset({axis})

    def _full(kb, vb):
        return flash_attention_with_lse(q3, kb, vb, causal=False)

    def _diag(kb, vb):
        return flash_attention_with_lse(q3, kb, vb, causal=True)

    def _masked(kb, vb):
        # future block under causal: zero contribution (lse = -inf);
        # match_vma gives all switch branches one output type
        return (
            match_vma(jnp.zeros_like(q3), ring_vma),
            match_vma(jnp.full((B * H, S), -jnp.inf, jnp.float32), ring_vma),
        )

    def fold(carry, _):
        kv, blk, m, l, acc = carry
        kb, vb = kv
        kb3 = kb.reshape(B * H, S, D)
        vb3 = vb.reshape(B * H, S, D)
        if causal:
            branch = jnp.where(
                blk > my_blk, 0, jnp.where(blk == my_blk, 2, 1)
            )
        else:
            branch = jnp.int32(1)
        out_i, lse_i = jax.lax.switch(
            branch, [_masked, _full, _diag], kb3, vb3
        )
        m_new = jnp.maximum(m, lse_i)
        # -inf - -inf is NaN: m is -inf before the first contributing
        # round (and m_new stays -inf if that round is masked too, which
        # a start-offset refactor could produce), so guard the operands,
        # not the result — a masked/virgin term must contribute exactly 0
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - jnp.where(jnp.isneginf(m_new), 0.0, m_new)))
        w = jnp.where(jnp.isneginf(lse_i), 0.0, jnp.exp(lse_i - jnp.where(jnp.isneginf(m_new), 0.0, m_new)))
        l = l * corr + w
        acc = acc * corr[..., None] + out_i.astype(jnp.float32) * w[..., None]
        perm = [((j + 1) % n_sp, j) for j in range(n_sp)]
        kv = jax.tree_util.tree_map(
            lambda t: jax.lax.ppermute(t, axis, perm), (kb, vb)
        )
        return (kv, (blk + 1) % n_sp, m_new, l, acc), None

    m0 = match_vma(jnp.full((B * H, S), -jnp.inf, jnp.float32), ring_vma)
    l0 = match_vma(jnp.zeros((B * H, S), jnp.float32), ring_vma)
    acc0 = match_vma(jnp.zeros((B * H, S, D), jnp.float32), ring_vma)
    (_, _, _, l, acc), _ = jax.lax.scan(
        fold, ((k, v), my_blk, m0, l0, acc0), None, length=n_sp
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype).reshape(B, H, S, D)


def _ring_einsum(q, k, v, causal: bool, axis: str):
    """jnp online-softmax ring fold (the original implementation)."""
    n_sp = axis_size(axis)
    my_blk = jax.lax.axis_index(axis)
    B, H, S, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    qf = q.astype(jnp.float32) * scale

    q_pos = my_blk * S + jnp.arange(S)  # global positions of local queries

    def fold(carry, _):
        kv, blk, m, l, acc = carry
        kb, vb = kv
        logits = jnp.einsum("bhqd,bhkd->bhqk", qf, kb.astype(jnp.float32))
        if causal:
            k_pos = blk * S + jnp.arange(S)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((S, S), dtype=bool)
        m_new = jnp.maximum(m, jnp.max(jnp.where(mask, logits, -jnp.inf), axis=-1))
        # clamp so fully-masked rounds (future blocks under causal) keep
        # m finite and contribute exactly zero
        m_new = jnp.maximum(m_new, -1e30)
        p = jnp.where(mask, jnp.exp(logits - m_new[..., None]), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32)
        )
        # rotate K/V: receive the next block from the ring neighbour
        perm = [((j + 1) % n_sp, j) for j in range(n_sp)]
        kv = jax.tree_util.tree_map(
            lambda t: jax.lax.ppermute(t, axis, perm), (kb, vb)
        )
        return (kv, (blk + 1) % n_sp, m_new, l, acc), None

    def vary(x):
        # mark the accumulators as varying over the ring axis so the scan
        # carry type matches (jax>=0.9 varying-manual-axes typing;
        # identity on 0.4.x, which has no vma types to match)
        from kungfu_tpu.utils.jaxcompat import pcast_varying

        return pcast_varying(x, (axis,))

    m0 = vary(jnp.full((B, H, S), -jnp.inf, jnp.float32))
    l0 = vary(jnp.zeros((B, H, S), jnp.float32))
    acc0 = vary(jnp.zeros((B, H, S, D), jnp.float32))
    (_, _, _, l, acc), _ = jax.lax.scan(
        fold, ((k, v), my_blk, m0, l0, acc0), None, length=n_sp
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def make_ring_attn(axis: str = "sp", block_impl: str = "auto"):
    """Adapter matching the ``attn_fn(q, k, v, causal)`` slot of
    :meth:`kungfu_tpu.models.transformer.Transformer.apply`."""

    def attn(q, k, v, causal):
        return ring_attention(
            q, k, v, causal=causal, axis=axis, block_impl=block_impl
        )

    return attn
