"""The 4-D sharded training step: dp x pp x sp x tp (+ EP over dp).

One ``shard_map`` over the :class:`~kungfu_tpu.parallel.mesh.MeshPlan`
mesh computes per-device gradients with every cross-device flow explicit:

* **dp** — gradient psum (the reference's allreduce, done as one XLA
  collective instead of the Go graph engine);
* **pp** — GPipe-style microbatch pipeline: a ``lax.scan`` over
  ``n_micro + pp - 1`` ticks, activations hopping stages via ``ppermute``
  (autodiff reverses the hops, giving the backward pipeline for free);
* **sp** — sequence sharding with ring attention
  (:mod:`kungfu_tpu.parallel.ring`);
* **tp** — Megatron column/row matmuls (:mod:`kungfu_tpu.parallel.tp`);
* **ep=dp** — optional switch-MoE FFNs with ``all_to_all`` token exchange
  (:mod:`kungfu_tpu.parallel.moe`).

Gradient synchronization is explicit and per-parameter-kind (see
:func:`sync_grads`): autodiff inside ``shard_map`` yields each rank's
d(own loss term)/d(own shard); collective transposes (ppermute, all_to_all,
and the tp custom-vjp pair) already route *sharded*-param flows, while
*replicated* params need the trailing psum — exactly the split the
reference handles with its group allreduce after local backprop
(``sync_sgd.py:58-109``), generalized to four axes.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass, replace as _dc_replace
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from kungfu_tpu.utils.jaxcompat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from kungfu_tpu.models import nn
from kungfu_tpu.models.transformer import TransformerConfig, _rope
from kungfu_tpu.parallel import tp as tpmod
from kungfu_tpu.parallel.mesh import AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP, MeshPlan
from kungfu_tpu.parallel.moe import moe_apply
from kungfu_tpu.parallel.ring import ring_attention
from kungfu_tpu.utils import envs

MOE_AUX_COEF = 0.01


@dataclass(frozen=True)
class ParallelPlan:
    """THE parallelism configuration: every axis degree, the ZeRO stage,
    and the pipeline schedule in one value, consumed by every
    entrypoint instead of each hand-wiring its own axis combination —
    :class:`ShardedTrainer` (in-mesh dp/pp/sp/tp), :func:`dp_train_step`
    / :func:`~kungfu_tpu.parallel.zero.zero_train_step` (host/device DP
    + ZeRO), :class:`~kungfu_tpu.parallel.pp.HostPipeline` (cross-DCN
    pipeline), and the serving fleet
    (:class:`kungfu_tpu.serve.scale.ServeFleet`).

    Axis mapping follows the slice-major hierarchy (PR 8): **pp across
    the DCN** (one stage per slice — ``pp`` ≡ ``MEGASCALE_NUM_SLICES``
    on a multislice pod), **tp within the ICI** (never crosses a
    slice), **dp/ZeRO across the replicas inside a slice** (host world
    is ``pp × dp`` ranks).  ``to_slice_topology()`` exposes exactly
    that correspondence; :meth:`HostPipeline.__init__` validates the
    plan against the peer's live topology.
    """

    dp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    #: 0 = replicated optimizer; 1/2/3 route the ZeRO family
    zero_stage: int = 0
    #: pipeline microbatches (None -> pp, the minimum that fills it)
    n_micro: Optional[int] = None
    #: pipeline schedule: "1f1b" | "interleaved" | "sequential"
    pp_schedule: str = "1f1b"
    #: model chunks per stage for the interleaved schedule
    interleave: int = 1
    #: allreduce decomposition arm (ops.schedules.ALLREDUCE_SCHEDULES)
    collective_schedule: str = "psum"

    def __post_init__(self):
        from kungfu_tpu.parallel.pp import SCHEDULES

        for name, v in (("dp", self.dp), ("tp", self.tp),
                        ("pp", self.pp), ("sp", self.sp)):
            if v < 1:
                raise ValueError(f"{name}={v} must be >= 1")
        if self.zero_stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_stage={self.zero_stage} not in 0..3")
        if self.pp_schedule not in SCHEDULES:
            raise ValueError(
                f"pp_schedule={self.pp_schedule!r}; one of {SCHEDULES}")
        if self.interleave < 1:
            raise ValueError(f"interleave={self.interleave} must be >= 1")
        if self.interleave > 1 and self.pp_schedule != "interleaved":
            raise ValueError(
                "interleave > 1 requires pp_schedule='interleaved'")
        if self.n_micro is not None and self.n_micro < 1:
            raise ValueError(f"n_micro={self.n_micro} must be >= 1")

    # -- shape -------------------------------------------------------------
    @property
    def size(self) -> int:
        """Device count of the in-mesh form (dp*pp*sp*tp)."""
        return self.dp * self.pp * self.sp * self.tp

    @property
    def host_size(self) -> int:
        """Host-plane world size of the cross-DCN form: one rank per
        (stage, dp lane); tp/sp ride each rank's LOCAL device mesh."""
        return self.dp * self.pp

    def mesh_plan(self) -> MeshPlan:
        return MeshPlan(dp=self.dp, pp=self.pp, sp=self.sp, tp=self.tp)

    def build_mesh(self, devices=None):
        return self.mesh_plan().build_mesh(devices)

    # -- pipeline geometry (stage-major = slice-major rank layout) ---------
    def stage_map(self, n_layers: int) -> List[Tuple[int, int]]:
        from kungfu_tpu.parallel.pp import stage_partition

        return stage_partition(n_layers, self.pp)

    def stage_of(self, rank: int) -> int:
        return rank // self.dp

    def dp_index(self, rank: int) -> int:
        return rank % self.dp

    def stage_ranks(self, stage: int) -> List[int]:
        return list(range(stage * self.dp, (stage + 1) * self.dp))

    def to_slice_topology(self):
        """The multislice topology this plan maps onto (PP across DCN
        slices, dp lanes within each), or None when single-stage."""
        if self.pp <= 1:
            return None
        from kungfu_tpu.elastic.slices import SliceTopology

        return SliceTopology(self.pp, self.dp)

    def with_stages(self, pp: int) -> "ParallelPlan":
        """The post-re-carve plan: same axes, ``pp`` stages (the
        elastic stage re-carve shrinks this, never dp/tp)."""
        return _dc_replace(self, pp=pp)

    # -- env contract ------------------------------------------------------
    @classmethod
    def from_env(cls, **overrides) -> "ParallelPlan":
        """Plan from the launch contract: ``KF_PP_STAGES``,
        ``KF_PP_MICROBATCHES`` (0 -> pp), ``KF_PP_SCHEDULE``
        (1f1b | interleaved | sequential); explicit kwargs win."""
        import os

        vals = dict(
            pp=envs.parse_int_env(envs.PP_STAGES, 1),
            n_micro=envs.parse_int_env(envs.PP_MICROBATCHES, 0) or None,
            pp_schedule=(os.environ.get(envs.PP_SCHEDULE, "")
                         or "1f1b").strip().lower(),
        )
        vals.update(overrides)
        return cls(**vals)

# parameter kinds → (psum axes, replication denominator axes)
_KIND_AXES = {
    # embed / ln_f / head: replicated everywhere; grads live on one pp stage
    "replicated": ((AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP), (AXIS_DP, AXIS_SP, AXIS_TP)),
    # per-layer params replicated over dp/sp/tp (layernorms, gate)
    "dense_layer": ((AXIS_DP, AXIS_SP, AXIS_TP), (AXIS_DP, AXIS_SP, AXIS_TP)),
    # tp-sharded weights: tp flows handled by the custom-vjp pair
    "tp_sharded": ((AXIS_DP, AXIS_SP), (AXIS_DP, AXIS_SP)),
    # expert weights: dp flows handled by all_to_all transpose
    "expert": ((AXIS_SP, AXIS_TP), (AXIS_DP, AXIS_SP, AXIS_TP)),
}


def _axis_prod(plan: MeshPlan, axes) -> int:
    sizes = {AXIS_DP: plan.dp, AXIS_PP: plan.pp, AXIS_SP: plan.sp, AXIS_TP: plan.tp}
    out = 1
    for a in axes:
        out *= sizes[a]
    return out


class ShardedTrainer:
    """Owns the mesh, the sharded parameter layout, and the jitted step."""

    def __init__(
        self,
        cfg: TransformerConfig,
        plan: Union[MeshPlan, "ParallelPlan"],
        n_experts: int = 0,
        n_micro: Optional[int] = None,
        tx: Optional[optax.GradientTransformation] = None,
        devices=None,
        capacity_factor: float = 1.25,
        schedule: str = "psum",
        fuse_grads: bool = False,
    ):
        if isinstance(plan, ParallelPlan):
            # the unified plan: axis degrees, microbatching, and the
            # collective schedule all come from one value
            if plan.zero_stage:
                raise ValueError(
                    "ShardedTrainer holds one replicated optimizer over "
                    "the mesh — ZeRO stages route through dp_train_step/"
                    "zero_train_step (device DP) or HostPipeline "
                    "(cross-DCN pp)")
            n_micro = n_micro or plan.n_micro
            # same disagreement contract as dp_train_step/zero_train_step:
            # an explicit non-default schedule kwarg must not be silently
            # clobbered by the plan (nor silently win over it)
            if schedule != "psum" and schedule != plan.collective_schedule:
                raise ValueError(
                    f"schedule={schedule!r} disagrees with "
                    f"plan.collective_schedule="
                    f"{plan.collective_schedule!r} — set it in the plan")
            schedule = plan.collective_schedule
            plan = plan.mesh_plan()
        if cfg.pos not in ("rope", "learned"):
            raise ValueError(f"unknown position mode {cfg.pos!r}")
        if cfg.n_layers % plan.pp:
            raise ValueError(f"n_layers {cfg.n_layers} % pp {plan.pp} != 0")
        if cfg.n_heads % plan.tp:
            raise ValueError(f"n_heads {cfg.n_heads} % tp {plan.tp} != 0")
        if cfg.d_ff % plan.tp:
            raise ValueError(f"d_ff {cfg.d_ff} % tp {plan.tp} != 0")
        if n_experts and n_experts % plan.ep:
            raise ValueError(f"n_experts {n_experts} % ep {plan.ep} != 0")
        from kungfu_tpu.ops.schedules import ALLREDUCE_SCHEDULES

        if schedule not in ALLREDUCE_SCHEDULES:
            raise ValueError(
                f"unknown schedule {schedule!r}; one of {ALLREDUCE_SCHEDULES}"
            )
        self.cfg = cfg
        self.plan = plan
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.n_micro = n_micro or plan.pp
        self.tx = tx or optax.sgd(0.01)
        #: allreduce decomposition compiled into sync_grads
        #: (kungfu_tpu.ops.schedules; pass comm.strategy to honor an
        #: installed/autotuned choice)
        self.schedule = schedule
        #: bucket the gradient sync: one collective per sync-kind
        #: (exact — leaves of a kind share axes and denominator)
        self.fuse_grads = fuse_grads
        self.mesh = plan.build_mesh(devices)
        self.param_specs, self.param_kinds = self._layout()
        self._step_fn = None
        self._pulse_fn = None
        from kungfu_tpu.monitor import pulse as pulselib
        #: kf-pulse gradient-signal monitor (None when KF_PULSE_EVERY=0)
        self.pulse = pulselib.PulseMonitor.from_env()

    # -- parameter layout -------------------------------------------------
    def _layout(self):
        """(PartitionSpec tree, kind tree) for the stacked param pytree."""
        cfg, moe = self.cfg, self.n_experts > 0

        def dup(spec_kind):
            return spec_kind

        layer = {
            "ln1": {"scale": (P(AXIS_PP, None), "dense_layer"),
                    "bias": (P(AXIS_PP, None), "dense_layer")},
            "ln2": {"scale": (P(AXIS_PP, None), "dense_layer"),
                    "bias": (P(AXIS_PP, None), "dense_layer")},
            "wq": {"w": (P(AXIS_PP, None, AXIS_TP), "tp_sharded"),
                   "b": (P(AXIS_PP, AXIS_TP), "tp_sharded")},
            "wk": {"w": (P(AXIS_PP, None, AXIS_TP), "tp_sharded"),
                   "b": (P(AXIS_PP, AXIS_TP), "tp_sharded")},
            "wv": {"w": (P(AXIS_PP, None, AXIS_TP), "tp_sharded"),
                   "b": (P(AXIS_PP, AXIS_TP), "tp_sharded")},
            "wo": {"w": (P(AXIS_PP, AXIS_TP, None), "tp_sharded"),
                   "b": (P(AXIS_PP, None), "dense_layer")},
        }
        if moe:
            layer["gate"] = {"w": (P(AXIS_PP, None, None), "dense_layer")}
            layer["w_in"] = (P(AXIS_PP, AXIS_DP, None, None), "expert")
            layer["w_out"] = (P(AXIS_PP, AXIS_DP, None, None), "expert")
        else:
            layer["ffn_in"] = {"w": (P(AXIS_PP, None, AXIS_TP), "tp_sharded"),
                               "b": (P(AXIS_PP, AXIS_TP), "tp_sharded")}
            layer["ffn_out"] = {"w": (P(AXIS_PP, AXIS_TP, None), "tp_sharded"),
                                "b": (P(AXIS_PP, None), "dense_layer")}
        tree = {
            "embed": {"table": (P(None, None), "replicated")},
            "layers": layer,
            "ln_f": {"scale": (P(None), "replicated"), "bias": (P(None), "replicated")},
            "head": {"w": (P(None, None), "replicated")},
        }
        if cfg.pos == "learned":
            tree["pos_embed"] = {"table": (P(None, None), "replicated")}
        is_leaf = lambda x: isinstance(x, tuple) and len(x) == 2 and isinstance(x[1], str)
        specs = jax.tree_util.tree_map(lambda t: t[0], tree, is_leaf=is_leaf)
        kinds = jax.tree_util.tree_map(lambda t: t[1], tree, is_leaf=is_leaf)
        return specs, kinds

    # -- init --------------------------------------------------------------
    def init(self, key) -> Dict[str, Any]:
        cfg = self.cfg
        params: Dict[str, Any] = {}
        key, k = jax.random.split(key)
        params["embed"] = nn.embedding_init(k, cfg.vocab_size, cfg.d_model)
        if cfg.pos == "learned":
            key, k = jax.random.split(key)
            params["pos_embed"] = nn.embedding_init(k, cfg.max_seq, cfg.d_model)
        per_layer = []
        for _ in range(cfg.n_layers):
            key, *ks = jax.random.split(key, 8)
            lp = {
                "ln1": nn.layernorm_init(cfg.d_model),
                "wq": nn.dense_init(ks[0], cfg.d_model, cfg.d_model),
                "wk": nn.dense_init(ks[1], cfg.d_model, cfg.d_model),
                "wv": nn.dense_init(ks[2], cfg.d_model, cfg.d_model),
                "wo": nn.dense_init(ks[3], cfg.d_model, cfg.d_model),
                "ln2": nn.layernorm_init(cfg.d_model),
            }
            if self.n_experts:
                lp["gate"] = {"w": nn.normal(ks[4], (cfg.d_model, self.n_experts))}
                lp["w_in"] = nn.glorot_uniform(ks[5], (self.n_experts, cfg.d_model, cfg.d_ff))
                lp["w_out"] = nn.glorot_uniform(ks[6], (self.n_experts, cfg.d_ff, cfg.d_model))
            else:
                lp["ffn_in"] = nn.dense_init(ks[4], cfg.d_model, cfg.d_ff)
                lp["ffn_out"] = nn.dense_init(ks[5], cfg.d_ff, cfg.d_model)
            per_layer.append(lp)
        params["layers"] = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_layer
        )
        params["ln_f"] = nn.layernorm_init(cfg.d_model)
        key, k = jax.random.split(key)
        params["head"] = nn.dense_init(k, cfg.d_model, cfg.vocab_size, use_bias=False)
        params = self.shard_params(params)
        opt_state = self.tx.init(params)
        return {"params": params, "opt_state": opt_state, "step": jnp.zeros((), jnp.int32)}

    def shard_params(self, params):
        """Place a (replicated/host) param pytree onto the mesh layout."""
        return jax.tree_util.tree_map(
            lambda x, spec: jax.device_put(x, NamedSharding(self.mesh, spec)),
            params,
            self.param_specs,
        )

    def from_transformer_params(self, tparams):
        """Pack per-layer ``Transformer.init`` params (dense FFN only) into
        the stacked sharded layout — used to cross-check against the
        unsharded model."""
        assert not self.n_experts
        L = self.cfg.n_layers
        stacked = {
            "embed": tparams["embed"],
            "layers": jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *[tparams[f"layer_{i}"] for i in range(L)]
            ),
            "ln_f": tparams["ln_f"],
            "head": tparams["head"],
        }
        if self.cfg.pos == "learned":
            stacked["pos_embed"] = tparams["pos_embed"]
        return self.shard_params(stacked)

    # -- the per-device math ----------------------------------------------
    def _block(self, lp, h, positions):
        """One transformer layer on local shards.  h: [B_mb, S_loc, D]
        replicated over tp; returns (h', aux)."""
        cfg, plan = self.cfg, self.plan
        dt = cfg.compute_dtype
        H_loc = cfg.n_heads // plan.tp

        x = nn.layernorm_apply(lp["ln1"], h)
        x = tpmod.tp_region_enter(x, AXIS_TP)
        q = tpmod.column_dense(lp["wq"], x, dtype=dt)
        k = tpmod.column_dense(lp["wk"], x, dtype=dt)
        v = tpmod.column_dense(lp["wv"], x, dtype=dt)

        def heads(t):
            B, S, _ = t.shape
            return t.reshape(B, S, H_loc, cfg.head_dim).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if cfg.pos == "rope":
            q, k = _rope(q, k, positions)
        o = ring_attention(q, k, v, causal=cfg.causal, axis=AXIS_SP)
        B, _, S, _ = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(B, S, H_loc * cfg.head_dim)
        h = h + tpmod.row_dense(lp["wo"], o, AXIS_TP, dtype=dt)

        x = nn.layernorm_apply(lp["ln2"], h)
        if self.n_experts:
            y, aux = moe_apply(
                {"gate": lp["gate"], "w_in": lp["w_in"], "w_out": lp["w_out"]},
                x,
                axis=AXIS_DP if plan.ep > 1 else None,
                n_experts_global=self.n_experts,
                capacity_factor=self.capacity_factor,
                dtype=dt,
            )
        else:
            x = tpmod.tp_region_enter(x, AXIS_TP)
            y = nn.gelu(tpmod.column_dense(lp["ffn_in"], x, dtype=dt))
            y = tpmod.row_dense(lp["ffn_out"], y, AXIS_TP, dtype=dt)
            aux = jnp.zeros((), jnp.float32)
        return h + y, aux

    def _local_loss(self, lparams, ids, targets):
        """Per-device loss term.  ids/targets: [B_loc, S_loc] local shards.
        Returns (own_term, nll_for_report, aux_for_report)."""
        cfg, plan = self.cfg, self.plan
        n_micro = self.n_micro
        Pp = plan.pp
        B_loc, S_loc = ids.shape
        assert B_loc % n_micro == 0, (B_loc, n_micro)
        B_mb = B_loc // n_micro

        sp_idx = jax.lax.axis_index(AXIS_SP)
        pp_idx = jax.lax.axis_index(AXIS_PP)
        pos = sp_idx * S_loc + jnp.arange(S_loc)
        positions = jnp.broadcast_to(pos, (B_mb, S_loc))

        ids_mb = ids.reshape(n_micro, B_mb, S_loc)
        tgt_mb = targets.reshape(n_micro, B_mb, S_loc)
        h0 = nn.embedding_apply(lparams["embed"], ids_mb, dtype=cfg.compute_dtype)
        if cfg.pos == "learned":
            # positions carry the sp-global offsets, so the learned table
            # lookup is shard-correct under sequence parallelism too
            pe = nn.embedding_apply(lparams["pos_embed"], positions,
                                    dtype=cfg.compute_dtype)
            h0 = h0 + pe[None]

        T = n_micro + Pp - 1
        if T > n_micro:
            pad = jnp.zeros((Pp - 1,) + h0.shape[1:], h0.dtype)
            h0 = jnp.concatenate([h0, pad], axis=0)

        def stage_fn(x):
            def layer_step(h, lp):
                h2, aux = self._block(lp, h, positions)
                return h2, aux

            h, auxs = jax.lax.scan(layer_step, x, lparams["layers"])
            return h, jnp.sum(auxs)

        perm = [(j, j + 1) for j in range(Pp - 1)]

        def tick(buf, x_t):
            inp = jnp.where(pp_idx == 0, x_t, buf)
            out, aux = stage_fn(inp)
            nxt = jax.lax.ppermute(out, AXIS_PP, perm) if Pp > 1 else out
            return nxt, (out, aux)

        buf0 = jnp.zeros(h0.shape[1:], h0.dtype)
        _, (outs, auxs) = jax.lax.scan(tick, buf0, h0)

        # microbatch m leaves the last stage at tick m + Pp - 1
        valid_outs = outs[Pp - 1 : Pp - 1 + n_micro]
        hf = nn.layernorm_apply(lparams["ln_f"], valid_outs)
        logits = nn.dense_apply(lparams["head"], hf).astype(jnp.float32)
        # fused-or-plain NLL: on the sharded path the [n_micro, B_mb,
        # S_loc, V] logits are the largest live tensor per device
        from kungfu_tpu.ops.pallas.xent import token_nll

        nll = token_nll(logits, tgt_mb)
        nll_term = jnp.where(pp_idx == Pp - 1, nll, 0.0)

        # aux from ticks where this stage processed a real microbatch
        t_idx = jnp.arange(T)
        valid = (t_idx >= pp_idx) & (t_idx < pp_idx + n_micro)
        aux_term = jnp.sum(auxs * valid) / n_micro

        own = nll_term + MOE_AUX_COEF * aux_term
        return own, (nll_term, aux_term)

    def sync_grads(self, grads):
        plan = self.plan
        from kungfu_tpu.ops.schedules import all_reduce_scheduled

        if not self.fuse_grads:
            def f(g, kind):
                axes, denom_axes = _KIND_AXES[kind]
                g = all_reduce_scheduled(g, axes, op="sum",
                                         schedule=self.schedule)
                return g / _axis_prod(plan, denom_axes)

            return jax.tree_util.tree_map(f, grads, self.param_kinds)

        # bucketed: ONE collective per sync-kind (leaves of a kind share
        # reduce axes and denominator, so fusing them is exact) — the
        # reference's fuse/defuse bucketing, per mesh-axis group here
        from kungfu_tpu.ops.fuse import defuse, fuse

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_k = jax.tree_util.tree_leaves(self.param_kinds)
        for kind in sorted(set(flat_k)):
            idxs = [i for i, k in enumerate(flat_k) if k == kind]
            buf, spec = fuse([flat_g[i] for i in idxs])
            axes, denom_axes = _KIND_AXES[kind]
            buf = all_reduce_scheduled(buf, axes, op="sum",
                                       schedule=self.schedule)
            buf = buf / _axis_prod(plan, denom_axes)
            for i, g in zip(idxs, defuse(buf, spec)):
                flat_g[i] = g
        return jax.tree_util.tree_unflatten(treedef, flat_g)

    # -- jitted step -------------------------------------------------------
    def _pure_dp(self) -> bool:
        """True when the mesh is data-parallel ONLY — the shape where
        the two-batch GNS pair is defined (each dp rank holds a full
        model replica, so "one rank's gradient" is a real small-batch
        gradient).  tp/pp/sp/expert sharding splits the model itself;
        those meshes publish per-kind norms only."""
        p = self.plan
        return (p.pp == 1 and p.sp == 1 and p.tp == 1
                and self.n_experts == 0)

    def _build_step(self, with_pulse: bool = False):
        plan = self.plan
        pspecs = self.param_specs
        batch_spec = P(AXIS_DP, AXIS_SP)
        kinds = sorted(set(jax.tree_util.tree_leaves(self.param_kinds)))
        all_axes = (AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP)
        pure_dp = self._pure_dp()

        def per_device(lparams, ids, targets):
            grad_fn = jax.value_and_grad(self._local_loss, has_aux=True)
            (own, (nll, aux)), grads = grad_fn(lparams, ids, targets)
            gl = jnp.float32(0.0)
            if with_pulse and pure_dp:
                # kf-pulse small-batch side: this rank's full-replica
                # gradient square norm, MEANed across dp peers (the
                # plane's only extra collective — one scalar)
                gl = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in jax.tree_util.tree_leaves(grads))
                gl = jax.lax.pmean(gl, AXIS_DP)
            grads = self.sync_grads(grads)
            group_sq = {}
            if with_pulse:
                # per-kind |g|^2 of the POST-sync gradients: leaves of
                # a kind are replicated over its psum axes and sharded
                # over the rest, so a psum over (all - psum_axes)
                # reassembles the exact global square norm — scalar
                # collectives only, on 1-in-`every` steps
                flat_g = jax.tree_util.tree_leaves(grads)
                flat_k = jax.tree_util.tree_leaves(self.param_kinds)
                for kind in kinds:
                    s = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                            for g, k in zip(flat_g, flat_k) if k == kind)
                    shard_axes = tuple(a for a in all_axes
                                       if a not in _KIND_AXES[kind][0])
                    if shard_axes:
                        s = jax.lax.psum(s, shard_axes)
                    group_sq[kind] = s
            # report: gather the stage-masked terms into global means
            nll = jax.lax.pmean(
                jax.lax.psum(nll, AXIS_PP), (AXIS_DP, AXIS_SP, AXIS_TP)
            )
            aux = jax.lax.pmean(
                jax.lax.psum(aux, AXIS_PP), (AXIS_DP, AXIS_SP, AXIS_TP)
            )
            if with_pulse:
                return grads, nll, aux, group_sq, gl
            return grads, nll, aux

        out_specs = ((pspecs, P(), P(), {k: P() for k in kinds}, P())
                     if with_pulse else (pspecs, P(), P()))
        sharded = shard_map(
            per_device,
            mesh=self.mesh,
            in_specs=(pspecs, batch_spec, batch_spec),
            out_specs=out_specs,
            check_vma=False,
        )

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, batch):
            ids, targets = batch
            if with_pulse:
                grads, nll, aux, group_sq, gl = sharded(
                    state["params"], ids, targets)
            else:
                grads, nll, aux = sharded(state["params"], ids, targets)
            updates, opt_state = self.tx.update(grads, state["opt_state"], state["params"])
            params = optax.apply_updates(state["params"], updates)
            out = (
                {"params": params, "opt_state": opt_state, "step": state["step"] + 1},
                nll + MOE_AUX_COEF * aux,
            )
            if with_pulse:
                return out + (group_sq, gl)
            return out

        return step

    def step(self, state, batch) -> Tuple[Dict[str, Any], jnp.ndarray]:
        """One full training step; batch = (ids, targets) global [B, S]."""
        if self._step_fn is None:
            self._step_fn = self._build_step()
        ids, targets = batch
        bspec = NamedSharding(self.mesh, P(AXIS_DP, AXIS_SP))
        ids = jax.device_put(jnp.asarray(ids), bspec)
        targets = jax.device_put(jnp.asarray(targets), bspec)
        mon = self.pulse
        if mon is not None and mon.should_sample():
            if self._pulse_fn is None:
                # compiled on the first pulse step only (runs shorter
                # than KF_PULSE_EVERY never pay this compile)
                self._pulse_fn = self._build_step(with_pulse=True)
            new_state, loss, group_sq, gl = self._pulse_fn(
                state, (ids, targets))
            self._publish_pulse(mon, group_sq, gl, int(ids.shape[0]))
            return new_state, loss
        return self._step_fn(state, (ids, targets))

    def _publish_pulse(self, mon, group_sq, gl, global_batch: int) -> None:
        norms = {k: math.sqrt(max(0.0, float(v)))
                 for k, v in group_sq.items()}
        if self._pure_dp():
            n = int(self.plan.dp)
            # sorted fold: the replayed sum must not depend on the
            # param-kind dict's insertion order (docs/determinism.md)
            gg = sum(float(group_sq[k]) for k in sorted(group_sq))
            b_small = max(1, global_batch // max(1, n))
            mon.update(float(gl), gg, b_small, n, group_norms=norms)
        else:
            # sharded meshes: the GNS pair is undefined (no rank holds
            # a full small-batch gradient) — norms are still exact
            mon.publish_norms(norms)

    # -- losses without update (for tests) ---------------------------------
    def loss(self, state, batch) -> jnp.ndarray:
        """Global loss (nll + aux) without updating — test/eval helper."""
        pspecs = self.param_specs

        def per_device(lparams, ids, targets):
            _, (nll, aux) = self._local_loss(lparams, ids, targets)
            nll = jax.lax.pmean(jax.lax.psum(nll, AXIS_PP), (AXIS_DP, AXIS_SP, AXIS_TP))
            aux = jax.lax.pmean(jax.lax.psum(aux, AXIS_PP), (AXIS_DP, AXIS_SP, AXIS_TP))
            return nll + MOE_AUX_COEF * aux

        f = shard_map(
            per_device,
            mesh=self.mesh,
            in_specs=(pspecs, P(AXIS_DP, AXIS_SP), P(AXIS_DP, AXIS_SP)),
            out_specs=P(),
            check_vma=False,
        )
        ids, targets = batch
        bspec = NamedSharding(self.mesh, P(AXIS_DP, AXIS_SP))
        ids = jax.device_put(jnp.asarray(ids), bspec)
        targets = jax.device_put(jnp.asarray(targets), bspec)
        return jax.jit(f)(state["params"], ids, targets)


def dp_train_step(
    loss_fn,
    tx,
    comm,
    replicated_params: bool = True,
    has_aux: bool = False,
    donate: bool = False,
    zero_stage: Optional[int] = None,
    plan: Optional[ParallelPlan] = None,
):
    """Pure data-parallel training step over a
    :class:`~kungfu_tpu.comm.device.Communicator` mesh.

    ``zero_stage`` (1/2/3) routes to the weight-update-sharded family
    (:func:`kungfu_tpu.parallel.zero.zero_train_step`): ``tx`` is then
    the **inner elementwise** optax transform (the ZeRO step owns the
    gradient collective itself — do not wrap in ``synchronous_sgd``) and
    the return value is a :class:`~kungfu_tpu.parallel.zero.ZeroStep`,
    which still unpacks as ``step, init_opt = ...`` for stages 1/2.

    The DP-only analog of :class:`ShardedTrainer` (and of the reference's
    whole training model — S-SGD over gradient buffers): ``loss_fn(params,
    batch) -> scalar`` runs per device on the batch shard, ``tx`` is any
    :mod:`kungfu_tpu.optimizers` transform bound to ``comm.axis`` (it does
    the gradient/weight collective).

    ``replicated_params=True`` (S-SGD/GNS/variance: psummed grads keep
    params identical) holds one replicated copy.  ``False`` (SMA/
    AdaptiveSGD: each replica owns diverging weights) expects params and
    opt_state **stacked** on a leading ``comm.size`` axis.

    ``has_aux=True`` threads non-trained model state (BatchNorm running
    stats): ``loss_fn(params, aux, batch) -> (loss, new_aux)``; the new
    aux is pmean'd over the mesh so replicas stay identical, and the step
    signature becomes ``step(params, aux, opt_state, batch) -> (params,
    aux, opt_state, loss)``.

    ``donate=True`` donates the train-state buffers to XLA (in-place
    update — halves HBM traffic/footprint for the state); the caller must
    not reuse the old params/opt_state after the call.

    Returns ``step(params[, aux], opt_state, batch) -> (params[, aux],
    opt_state, loss)`` jitted over the mesh; ``batch`` leading axis must
    be divisible by ``comm.size``.
    """
    if plan is not None:
        # the ParallelPlan route: this entrypoint is the pure-DP one —
        # other axes have their own consumers (ShardedTrainer for the
        # in-mesh 4-D step, parallel/pp.HostPipeline for cross-DCN pp)
        if plan.tp != 1 or plan.pp != 1 or plan.sp != 1:
            raise ValueError(
                f"dp_train_step is the dp-only entrypoint but the plan "
                f"carries tp={plan.tp} pp={plan.pp} sp={plan.sp} — use "
                "ShardedTrainer (one mesh) or HostPipeline (cross-DCN)")
        if zero_stage is not None and zero_stage != plan.zero_stage:
            raise ValueError(
                f"zero_stage={zero_stage} disagrees with "
                f"plan.zero_stage={plan.zero_stage}")
        if not plan.zero_stage and plan.collective_schedule != "psum":
            # the replicated dp step reduces with psum/pmean only —
            # silently ignoring the requested arm would defeat the
            # ParallelPlan contract (entrypoints CONSUME the plan)
            raise ValueError(
                f"dp_train_step's replicated step has no "
                f"{plan.collective_schedule!r} arm — use ShardedTrainer "
                "(in-mesh schedule arms) or a ZeRO stage (bucket "
                "schedules)")
        zero_stage = plan.zero_stage or None
    if zero_stage is not None:
        if has_aux or not replicated_params:
            raise ValueError(
                "zero_stage composes with the plain replicated-params, "
                "no-aux step only (the sharded update is elementwise over "
                "the fused flat buffer)")
        from kungfu_tpu.parallel.zero import zero_train_step

        # zero's bucket collectives speak FLAT_SCHEDULES ("lax" |
        # "pallas_ring"); the plan's allreduce arm maps onto them —
        # pallas_ring passes through, everything else is the lax default
        zsched = ("pallas_ring"
                  if plan is not None
                  and plan.collective_schedule == "pallas_ring" else "lax")
        return zero_train_step(loss_fn, tx, comm, stage=zero_stage,
                               donate=donate, schedule=zsched)
    mesh, axis = comm.mesh, comm.axis
    pspec = P() if replicated_params else P(axis)

    def body(params, aux, opt_state, batch):
        if has_aux:
            (loss, new_aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, aux, batch
            )
            # per-shard batch statistics diverge across replicas; average
            # them like the gradients so the replicated copy stays in sync
            new_aux = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, axis)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                else a,
                new_aux,
            )
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            new_aux = aux
        updates, new_state = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_aux, new_state, jax.lax.pmean(loss, axis)

    def body_stacked(params, aux, opt_state, batch):
        # strip/restore the per-replica leading axis around the same body
        squeeze = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
        unsqueeze = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
        p, a, s, l = body(squeeze(params), squeeze(aux), squeeze(opt_state), batch)
        return unsqueeze(p), unsqueeze(a), unsqueeze(s), l

    def batch_spec(x):
        return P(axis) if hasattr(x, "ndim") and x.ndim > 0 else P()

    inner = body if replicated_params else body_stacked

    def step4(params, aux, opt_state, batch):
        bspecs = jax.tree_util.tree_map(batch_spec, batch)
        f = shard_map(
            inner,
            mesh=mesh,
            in_specs=(pspec, pspec, pspec, bspecs),
            out_specs=(pspec, pspec, pspec, P()),
            check_vma=False,
        )
        return f(params, aux, opt_state, batch)

    if has_aux:
        donate_args = (0, 1, 2) if donate else ()
        return jax.jit(step4, donate_argnums=donate_args)

    def step3(params, opt_state, batch):
        p, _, s, l = step4(params, (), opt_state, batch)
        return p, s, l

    donate_args = (0, 1) if donate else ()
    base = jax.jit(step3, donate_argnums=donate_args)

    # -- kf-pulse: GNS/variance sampling on the replicated no-aux step --
    # replicated_params=False trains intentionally DIVERGED replicas
    # (SMA/AdaptiveSGD) — "one rank's gradient vs the mean" is not a
    # small/large-batch pair there, so only the S-SGD shape samples.
    from kungfu_tpu.monitor import pulse as pulselib

    mon = pulselib.PulseMonitor.from_env() if replicated_params else None
    if mon is None:
        return base

    from kungfu_tpu import ops
    from kungfu_tpu.ops.monitor import _sq_norm

    def body_pulse(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        # small-batch side: per-rank square norm, MEANed across peers
        # (one extra scalar collective)
        g_local_sq = jax.lax.pmean(_sq_norm(grads), axis)
        # large-batch side: the mean gradient.  `tx` performs the
        # identical mean-allreduce inside update(); when the ops match
        # XLA CSEs the two psums into one, and this program only runs
        # on 1-in-`every` steps regardless
        avg = ops.group_all_reduce(grads, axis, op="mean")
        g_global_sq = _sq_norm(avg)
        updates, new_state = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return (new_params, new_state, jax.lax.pmean(loss, axis),
                g_local_sq, g_global_sq)

    def pulse_outer(params, opt_state, batch):
        bspecs = jax.tree_util.tree_map(batch_spec, batch)
        f = shard_map(
            body_pulse,
            mesh=mesh,
            in_specs=(pspec, pspec, bspecs),
            out_specs=(pspec, pspec, P(), P(), P()),
            check_vma=False,
        )
        return f(params, opt_state, batch)

    # compiled lazily on the first pulse step (never, for runs shorter
    # than KF_PULSE_EVERY)
    pulse_jit = jax.jit(pulse_outer, donate_argnums=donate_args)
    n = int(comm.size)

    def stepped(params, opt_state, batch):
        if mon.should_sample():
            p, s, loss, gl, gg = pulse_jit(params, opt_state, batch)
            gl, gg = float(gl), float(gg)
            leaves = jax.tree_util.tree_leaves(batch)
            b_small = (max(1, int(leaves[0].shape[0]) // n)
                       if (leaves and n) else 1)
            mon.update(gl, gg, b_small, n,
                       group_norms={"flat": max(0.0, gg) ** 0.5})
            return p, s, loss
        return base(params, opt_state, batch)

    stepped.pulse = mon  # introspection hook for tests/tools
    return stepped


def stack_for_replicas(tree, n: int):
    """Tile a pytree onto a leading replica axis (for
    ``dp_train_step(replicated_params=False)``)."""
    return jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (n,) + jnp.shape(a)), tree
    )
