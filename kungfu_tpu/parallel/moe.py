"""Mixture-of-experts with expert parallelism over a mesh axis.

Switch-style top-1 routing with capacity dropping, experts sharded one
group per ``ep`` rank, tokens moved to their expert's owner and back via
``lax.all_to_all`` (the TPU-idiomatic EP data path — a single fused ICI
all-to-all each way, instead of point-to-point sends).

Gradients: ``all_to_all`` transposes to itself, so expert-weight gradients
accumulate contributions from every rank's tokens without any explicit
cross-rank sync over ``ep``; see
:meth:`kungfu_tpu.parallel.train.ShardedTrainer.sync_grads` for the axis
bookkeeping.

Shapes (per device): tokens ``[T, D]``; global expert count ``E`` must be
divisible by the axis size; each rank owns ``E_local = E / ep`` experts
stacked as ``w_in [E_local, D, F]``, ``w_out [E_local, F, D]``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from kungfu_tpu.models import nn
from kungfu_tpu.utils.jaxcompat import axis_size


def moe_init(key, n_experts_local: int, d_model: int, d_ff: int, n_experts_global: int):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "gate": nn.dense_init(k1, d_model, n_experts_global, use_bias=False),
        "w_in": nn.glorot_uniform(k2, (n_experts_local, d_model, d_ff)),
        "w_out": nn.glorot_uniform(k3, (n_experts_local, d_ff, d_model)),
    }


def moe_apply(
    params,
    x,
    axis: Optional[str],
    n_experts_global: int,
    capacity_factor: float = 1.25,
    dtype=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x: [..., D] local tokens → (y [..., D], aux_loss scalar).

    ``axis=None`` runs all experts locally (no EP) — the single-device
    reference used by tests.  ``aux_loss`` is the switch load-balancing
    term E * Σ_e f_e · p̄_e.
    """
    orig_shape = x.shape
    D = orig_shape[-1]
    xt = x.reshape(-1, D)
    T = xt.shape[0]
    E = n_experts_global
    ep = 1 if axis is None else axis_size(axis)

    logits = (xt.astype(jnp.float32) @ params["gate"]["w"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]
    gate = jnp.max(probs, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [T]

    cap = int(max(1, -(-T * capacity_factor // E)))
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # [T, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based slot per token
    keep = (pos > 0) & (pos <= cap)
    slot = jnp.where(keep, pos - 1, 0).astype(jnp.int32)
    dispatch = (
        onehot * keep
    )[:, :, None] * jax.nn.one_hot(jnp.max(slot, axis=-1), cap, dtype=jnp.float32)[:, None, :]
    combine = dispatch * gate[:, None, None]  # [T, E, C]

    # load-balance aux (computed on the full pre-drop distribution)
    frac_tokens = jnp.mean(onehot, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, xt.astype(jnp.float32))  # [E, C, D]
    if axis is not None and ep > 1:
        # [E, C, D] -> each rank keeps its E_local experts, gathering every
        # rank's C slots for them: [E_local, ep*C, D]
        expert_in = jax.lax.all_to_all(
            expert_in, axis, split_axis=0, concat_axis=1, tiled=True
        )
    cd = dtype or x.dtype
    h = jnp.einsum("egd,edf->egf", expert_in.astype(cd), params["w_in"].astype(cd))
    h = nn.gelu(h)
    expert_out = jnp.einsum("egf,efd->egd", h, params["w_out"].astype(cd)).astype(
        jnp.float32
    )
    if axis is not None and ep > 1:
        expert_out = jax.lax.all_to_all(
            expert_out, axis, split_axis=1, concat_axis=0, tiled=True
        )
    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y.reshape(orig_shape).astype(x.dtype), aux
