"""Mesh plans: named parallel axes over the TPU device grid.

This is where the framework goes past the reference's capability set
(reference is data-parallel only, SURVEY §2.4): a :class:`MeshPlan`
factorizes the device count into four named axes —

* ``dp``  — data parallelism (gradient psum; the reference's allreduce axis).
  Expert parallelism rides this axis: MoE expert shards live one-per-dp-rank
  and tokens move via ``all_to_all`` over it (``ep`` is an alias of ``dp``).
* ``pp``  — pipeline parallelism (layer stages; activations flow stage to
  stage via ``ppermute``).
* ``sp``  — sequence/context parallelism (activations sharded on the
  sequence dim; ring attention rotates K/V blocks over this axis).
* ``tp``  — tensor parallelism (Megatron-style column/row sharded matmuls
  with paired fwd/bwd psums).

On hardware, axis order maps onto the ICI torus: the innermost axes (tp,
sp) carry the most frequent/latency-sensitive collectives, so they should
map to the shortest ICI rings; dp is outermost (one psum per step, can ride
DCN across slices).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_SP = "sp"
AXIS_TP = "tp"
AXES = (AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP)


def _prime_factors(n: int):
    out, p = [], 2
    while p * p <= n:
        while n % p == 0:
            out.append(p)
            n //= p
        p += 1
    if n > 1:
        out.append(n)
    return out


@dataclass(frozen=True)
class MeshPlan:
    """Static factorization of the device count into parallel axes."""

    dp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.sp * self.tp

    @property
    def ep(self) -> int:
        """Expert parallelism degree (MoE experts are sharded over dp)."""
        return self.dp

    @classmethod
    def auto(cls, n_devices: int) -> "MeshPlan":
        """Spread prime factors round-robin over (dp, tp, sp, pp) — largest
        factors first so e.g. 8 → dp=2, tp=2, sp=2 and 16 adds pp=2."""
        sizes = {AXIS_DP: 1, AXIS_TP: 1, AXIS_SP: 1, AXIS_PP: 1}
        order = (AXIS_DP, AXIS_TP, AXIS_SP, AXIS_PP)
        for i, f in enumerate(sorted(_prime_factors(n_devices), reverse=True)):
            sizes[order[i % len(order)]] *= f
        return cls(dp=sizes[AXIS_DP], pp=sizes[AXIS_PP], sp=sizes[AXIS_SP], tp=sizes[AXIS_TP])

    def build_mesh(self, devices: Optional[Sequence] = None) -> Mesh:
        devs = list(devices) if devices is not None else self._default_devices()
        if len(devs) < self.size:
            raise ValueError(f"plan needs {self.size} devices, have {len(devs)}")
        grid = np.asarray(devs[: self.size]).reshape(self.dp, self.pp, self.sp, self.tp)
        return Mesh(grid, AXES)

    def _default_devices(self):
        """The device order the mesh is carved from.  On a multislice pod
        (``MEGASCALE_NUM_SLICES`` > 1) devices are re-ordered slice-major
        so the OUTERMOST plan axis — dp, the gradient-allreduce axis the
        two-stage schedule decomposes hierarchically — spans slices in
        contiguous blocks: within-slice neighbors stay ICI neighbors and
        only the dp reduction crosses the DCN, instead of every axis
        straddling slices in jax's arbitrary enumeration order."""
        import os as _os

        from kungfu_tpu.utils import envs as _envs

        if int(_os.environ.get(_envs.MEGASCALE_NUM_SLICES, "0") or 0) > 1:
            from kungfu_tpu.platforms.tpu_pod import slice_mesh_layout

            flat, _ = slice_mesh_layout()
            return flat
        return list(jax.devices())

    def __str__(self):
        return f"MeshPlan(dp={self.dp}, pp={self.pp}, sp={self.sp}, tp={self.tp})"
