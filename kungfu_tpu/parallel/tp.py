"""Tensor parallelism: Megatron-style column/row sharded matmuls.

Activations are replicated over the ``tp`` axis; weights are sharded on
one contraction side.  Correct gradients with replicated-activation compute
require the classic paired pseudo-collectives (Megatron's *f*/*g*):

* :func:`tp_region_enter` — identity forward, **psum backward** — placed
  where a replicated activation enters a tp-sharded block, so the partial
  cotangents each tp rank produces are summed back into the full gradient;
* :func:`tp_region_exit` — **psum forward**, identity backward — the
  row-parallel output reduction (each rank holds a partial product).

Under jit these lower to single ICI all-reduces on the tp ring (the analog
of the reference's intra-host "local" collectives, session/strategy.go).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_enter(x, axis: str):
    return x


def _enter_fwd(x, axis):
    return x, None


def _enter_bwd(axis, _, g):
    return (jax.lax.psum(g, axis),)


tp_region_enter.defvjp(_enter_fwd, _enter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_exit(x, axis: str):
    return jax.lax.psum(x, axis)


def _exit_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _exit_bwd(axis, _, g):
    return (g,)


tp_region_exit.defvjp(_exit_fwd, _exit_bwd)


def column_dense(p, x, dtype=None):
    """x @ w_shard — weight sharded on the OUTPUT dim; result is the local
    feature shard.  ``p = {"w": [in, out/tp], "b": [out/tp]?}``."""
    w = p["w"].astype(dtype) if dtype else p["w"]
    y = x @ w
    if "b" in p:
        y = y + (p["b"].astype(dtype) if dtype else p["b"])
    return y


def row_dense(p, x, axis: str, dtype=None):
    """x_shard @ w_shard with psum — weight sharded on the INPUT dim, input
    is the local feature shard, output is fully reduced & replicated.
    Bias is replicated and added once, after the reduction."""
    w = p["w"].astype(dtype) if dtype else p["w"]
    y = tp_region_exit(x @ w, axis)
    if "b" in p:
        y = y + (p["b"].astype(dtype) if dtype else p["b"])
    return y
