"""Model parallelism over the TPU mesh: dp / pp / sp / tp (+ ep over dp).

This subsystem goes beyond the reference's data-parallel-only scope
(SURVEY §2.4) — it is the TPU-first answer to "the same scale": tensor
parallelism, pipeline parallelism, sequence/context parallelism with ring
attention, and expert parallelism, all composed in a single
``shard_map``-compiled training step.
"""

from kungfu_tpu.parallel.mesh import AXES, AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP, MeshPlan
from kungfu_tpu.parallel.moe import moe_apply, moe_init
from kungfu_tpu.parallel.ring import make_ring_attn, ring_attention
from kungfu_tpu.parallel.tp import (
    column_dense,
    row_dense,
    tp_region_enter,
    tp_region_exit,
)
from kungfu_tpu.parallel.train import (ParallelPlan, ShardedTrainer,
                                       dp_train_step)
from kungfu_tpu.parallel.zero import (zero1_reshard, zero1_restore,
                                      zero1_snapshot, zero1_train_step)

__all__ = [
    "AXES",
    "AXIS_DP",
    "AXIS_PP",
    "AXIS_SP",
    "AXIS_TP",
    "MeshPlan",
    "ParallelPlan",
    "ShardedTrainer",
    "zero1_reshard",
    "zero1_restore",
    "zero1_snapshot",
    "zero1_train_step",
    "column_dense",
    "row_dense",
    "make_ring_attn",
    "moe_apply",
    "moe_init",
    "ring_attention",
    "tp_region_enter",
    "tp_region_exit",
]
