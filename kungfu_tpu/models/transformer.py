"""GPT/BERT-style transformer — the flagship model.

Fresh TPU-first design (the reference has no model code; its BERT appears
only as a gradient-size list, ``model_sizes.py``):

* pre-LN blocks, RoPE or learned positions, bf16 activations / f32 params;
* attention is pluggable: the default is plain softmax attention (XLA fuses
  it well at moderate sequence lengths); :mod:`kungfu_tpu.parallel` plugs
  in ring attention (sequence-parallel over the mesh) or the Pallas flash
  kernel for long context;
* shapes are MXU-friendly (`d_model`, `d_ff` multiples of 128) and all
  control flow is static — one trace, one compile.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from kungfu_tpu.models import nn


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32128
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 2048
    dropout: float = 0.0
    causal: bool = True
    pos: str = "rope"  # "rope" | "learned"
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def _rope(q, k, positions):
    """Rotary position embedding on the head dim."""
    d = q.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32) * (jnp.log(10000.0) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., S, half]
    cos, sin = jnp.cos(angles), jnp.sin(angles)

    def rot(x):
        # x: [B, H, S, D]; cos/sin: [B, S, half] -> broadcast over heads
        x1, x2 = x[..., :half], x[..., half:]
        c = cos[:, None, :, :].astype(x.dtype)
        s = sin[:, None, :, :].astype(x.dtype)
        return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)

    return rot(q), rot(k)


def pick_attention() -> Callable:
    """Attention impl for the current backend (``KF_TPU_ATTN`` overrides:
    ``auto`` | ``xla`` | ``flash``).  ``auto`` uses the Pallas flash
    kernel on TPU — fused online softmax, no [S, S] score matrix in HBM —
    and plain XLA attention elsewhere (the interpreter-mode kernel is for
    tests, far too slow as a CPU default)."""
    import os

    mode = os.environ.get("KF_TPU_ATTN", "auto").lower()
    if mode == "xla":
        return default_attention
    if mode == "flash" or (mode == "auto" and jax.default_backend() == "tpu"):
        from kungfu_tpu.ops.pallas import make_flash_attn

        return make_flash_attn()
    return default_attention


def default_attention(q, k, v, causal: bool, segment_positions=None):
    """Plain softmax attention.  q,k,v: [B, H, S, D] (bf16).  Logits and
    softmax in f32 for stability; output back in input dtype."""
    d = q.shape[-1]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / jnp.sqrt(d)
    if causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        q_pos = jnp.arange(s_q)[:, None]
        k_pos = jnp.arange(s_k)[None, :]
        if segment_positions is not None:
            q_pos = q_pos + segment_positions[0]
            k_pos = k_pos + segment_positions[1]
        logits = jnp.where(q_pos >= k_pos, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


class Transformer:
    def __init__(self, config: TransformerConfig):
        self.cfg = config

    # -- init ------------------------------------------------------------
    def init(self, key) -> dict:
        cfg = self.cfg
        params = {}
        key, k1, k2 = jax.random.split(key, 3)
        params["embed"] = nn.embedding_init(k1, cfg.vocab_size, cfg.d_model)
        if cfg.pos == "learned":
            params["pos_embed"] = nn.embedding_init(k2, cfg.max_seq, cfg.d_model)
        for i in range(cfg.n_layers):
            key, *ks = jax.random.split(key, 7)
            params[f"layer_{i}"] = {
                "ln1": nn.layernorm_init(cfg.d_model),
                "wq": nn.dense_init(ks[0], cfg.d_model, cfg.d_model),
                "wk": nn.dense_init(ks[1], cfg.d_model, cfg.d_model),
                "wv": nn.dense_init(ks[2], cfg.d_model, cfg.d_model),
                "wo": nn.dense_init(ks[3], cfg.d_model, cfg.d_model),
                "ln2": nn.layernorm_init(cfg.d_model),
                "ffn_in": nn.dense_init(ks[4], cfg.d_model, cfg.d_ff),
                "ffn_out": nn.dense_init(ks[5], cfg.d_ff, cfg.d_model),
            }
        params["ln_f"] = nn.layernorm_init(cfg.d_model)
        key, k = jax.random.split(key)
        params["head"] = nn.dense_init(k, cfg.d_model, cfg.vocab_size, use_bias=False)
        return params

    # -- apply -----------------------------------------------------------
    def apply(
        self,
        params,
        ids,
        train: bool = False,
        rng=None,
        attn_fn: Optional[Callable] = None,
        positions=None,
    ):
        """ids: [B, S] int32 → logits [B, S, vocab] f32.

        ``attn_fn(q, k, v, causal)`` overrides attention (ring attention /
        flash kernel); ``positions`` overrides token positions (sequence
        parallelism passes the global positions of the local shard)."""
        h = self.hidden(params, ids, train=train, rng=rng, attn_fn=attn_fn,
                        positions=positions)
        return nn.dense_apply(params["head"], h).astype(jnp.float32)

    def hidden(self, params, ids, train: bool = False, rng=None,
               attn_fn: Optional[Callable] = None, positions=None):
        """Features after the final norm, BEFORE the LM head — the input
        the fused LM-head kernel (:func:`kungfu_tpu.ops.pallas.lm_head.
        lm_head_nll`) consumes together with ``params["head"]["w"]``, so
        the [*, vocab] logits never materialize."""
        cfg = self.cfg
        dt = cfg.compute_dtype
        attn = attn_fn or pick_attention()
        B, S = ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        h = nn.embedding_apply(params["embed"], ids, dtype=dt)
        if cfg.pos == "learned":
            h = h + nn.embedding_apply(params["pos_embed"], positions, dtype=dt)
        for i in range(cfg.n_layers):
            lp = params[f"layer_{i}"]
            x = nn.layernorm_apply(lp["ln1"], h)
            q = self._heads(nn.dense_apply(lp["wq"], x, dtype=dt))
            k = self._heads(nn.dense_apply(lp["wk"], x, dtype=dt))
            v = self._heads(nn.dense_apply(lp["wv"], x, dtype=dt))
            if cfg.pos == "rope":
                q, k = _rope(q, k, positions)
            o = attn(q, k, v, cfg.causal)
            o = self._merge(o)
            h = h + nn.dense_apply(lp["wo"], o, dtype=dt)
            x = nn.layernorm_apply(lp["ln2"], h)
            y = nn.gelu(nn.dense_apply(lp["ffn_in"], x, dtype=dt))
            if train and cfg.dropout > 0 and rng is not None:
                rng, sub = jax.random.split(rng)
                y = nn.dropout(sub, y, cfg.dropout, train)
            h = h + nn.dense_apply(lp["ffn_out"], y, dtype=dt)
        return nn.layernorm_apply(params["ln_f"], h)

    def _heads(self, x):
        B, S, _ = x.shape
        return x.reshape(B, S, self.cfg.n_heads, self.cfg.head_dim).transpose(0, 2, 1, 3)

    def _merge(self, x):
        B, H, S, D = x.shape
        return x.transpose(0, 2, 1, 3).reshape(B, S, H * D)

    def loss(self, params, batch, train: bool = True, rng=None, attn_fn=None, positions=None):
        """Next-token LM loss; batch = (ids, targets) both [B, S].

        ``KF_TPU_LM_HEAD`` (``fused`` | ``plain`` | ``auto``, default
        auto) selects the head implementation: ``fused`` computes the
        NLL straight from the pre-head features with the fused LM-head
        kernel pair (:func:`kungfu_tpu.ops.pallas.lm_head.lm_head_nll`
        — neither logits nor dlogits reach HBM); ``auto`` takes it on
        TPU exactly when the plain path's O(N·V) residual set would
        blow the same HBM budget the xent router uses (the shapes where
        XLA OOMs outright).  Otherwise the logits materialize and
        :func:`token_nll`'s own router picks the xent implementation."""
        import os

        from kungfu_tpu.ops.pallas.xent import (route_fused_lm_head,
                                                token_nll)

        ids, targets = batch
        mode = os.environ.get("KF_TPU_LM_HEAD", "auto").lower()
        if mode not in ("fused", "plain", "auto"):
            raise ValueError(
                f"KF_TPU_LM_HEAD={mode!r}: one of fused | plain | auto")
        fused_head = mode == "fused"
        if mode == "auto" and train and jax.default_backend() == "tpu":
            fused_head = route_fused_lm_head(ids.size, self.cfg.vocab_size)
        if fused_head:
            from kungfu_tpu.ops.pallas.lm_head import lm_head_nll

            h = self.hidden(params, ids, train=train, rng=rng,
                            attn_fn=attn_fn, positions=positions)
            return jnp.mean(lm_head_nll(h, params["head"]["w"], targets))
        logits = self.apply(params, ids, train=train, rng=rng, attn_fn=attn_fn, positions=positions)
        # train also steers the xent router: eval-only calls take the
        # fwd-only crossover (the kernel wins much earlier without a
        # backward to fuse)
        return token_nll(logits, targets, training=train)


def bert_base() -> Transformer:
    """BERT-base sized (the reference's benchmark size list model)."""
    return Transformer(
        TransformerConfig(
            vocab_size=30528, d_model=768, n_layers=12, n_heads=12, d_ff=3072,
            causal=False, pos="learned", max_seq=512,
        )
    )


def gpt_small(vocab: int = 32128, max_seq: int = 2048) -> Transformer:
    return Transformer(
        TransformerConfig(
            vocab_size=vocab, d_model=768, n_layers=12, n_heads=12, d_ff=3072,
            causal=True, pos="rope", max_seq=max_seq,
        )
    )
