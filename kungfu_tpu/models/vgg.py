"""VGG (11/16/19) — NHWC, bf16 compute, TPU-friendly.

Completes the reference's benchmark model-family trio: its harnesses
sweep ResNet-50 / VGG16 / BERT gradient sets
(``srcs/python/kungfu/tensorflow/v1/benchmarks/model_sizes.py``,
``srcs/python/kungfu/tensorflow/v1/benchmarks/__main__.py:112-120``) and
its fake-model tables carry ``vgg16-imagenet``
(``tests/go/fakemodel/fakemodel.go:12-17``).  Fresh implementation,
batch-norm variant included (VGG trains poorly in bf16 without it): plain
3x3 conv stacks + 2x2 maxpool, classifier head sized by ``num_classes``.

VGG's uniform 3x3/channel-doubling stacks are nearly all MXU work — the
historical "heavy" ImageNet model is a natural throughput payload for
``benchmarks/system.py`` next to ResNet-50.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from kungfu_tpu.models import nn

# channels per conv layer, "M" = 2x2 maxpool (the classic configurations)
_CFGS = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


def _maxpool2x2(x):
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


class VGG:
    def __init__(self, depth: int = 16, num_classes: int = 1000,
                 batch_norm: bool = True, hidden: int = 4096):
        if depth not in _CFGS:
            raise ValueError(f"depth must be one of {sorted(_CFGS)}")
        self.cfg = _CFGS[depth]
        self.num_classes = num_classes
        self.batch_norm = batch_norm
        self.hidden = hidden

    # -- init ------------------------------------------------------------
    def init(self, key) -> Tuple[dict, dict]:
        """Returns (params, bn_state); bn_state is empty without BN."""
        params, state = {}, {}
        in_ch = 3
        li = 0
        for c in self.cfg:
            if c == "M":
                continue
            key, k = jax.random.split(key)
            name = f"conv{li}"
            params[name] = nn.conv_init(k, in_ch, c, (3, 3),
                                        use_bias=not self.batch_norm)
            if self.batch_norm:
                params[f"{name}_bn"] = nn.batchnorm_init(c)
                state[f"{name}_bn"] = nn.batchnorm_state_init(c)
            in_ch = c
            li += 1
        # global-average-pooled head (the TF-era 7x7x512 flatten would pin
        # the input size; GAP keeps the model resolution-agnostic and
        # drops the 100M-param fc6 without changing the conv benchmark
        # profile)
        key, k1, k2 = jax.random.split(key, 3)
        params["fc1"] = nn.dense_init(k1, in_ch, self.hidden)
        params["head"] = nn.dense_init(k2, self.hidden, self.num_classes)
        return params, state

    # -- apply -----------------------------------------------------------
    def apply(self, params, state, x, train: bool = False,
              dtype=jnp.bfloat16, axis_name=None):
        """x: [N, H, W, 3] float.  Returns (logits_f32, new_state)."""
        new_state = {}
        h = x.astype(dtype)
        li = 0
        for c in self.cfg:
            if c == "M":
                h = _maxpool2x2(h)
                continue
            name = f"conv{li}"
            h = nn.conv_apply(params[name], h, dtype=dtype)
            if self.batch_norm:
                h, ns = nn.batchnorm_apply(
                    params[f"{name}_bn"], state[f"{name}_bn"], h, train,
                    axis_name=axis_name,
                )
                new_state[f"{name}_bn"] = ns
            h = jax.nn.relu(h)
            li += 1
        h = jnp.mean(h.astype(jnp.float32), axis=(1, 2))  # GAP
        h = jax.nn.relu(nn.dense_apply(params["fc1"], h))
        logits = nn.dense_apply(params["head"], h)
        return logits, new_state

    def loss(self, params, state, batch, train: bool = True,
             dtype=jnp.bfloat16, axis_name=None):
        x, y = batch
        logits, new_state = self.apply(
            params, state, x, train=train, dtype=dtype, axis_name=axis_name
        )
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1).squeeze(1)
        return jnp.mean(nll), new_state


def vgg16(num_classes: int = 1000) -> VGG:
    return VGG(16, num_classes)
