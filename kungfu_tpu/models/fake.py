"""Gradient-shaped fake models for collective benchmarking.

Parity with reference ``tests/go/fakemodel/fakemodel.go:12-17`` and the
benchmark size lists (``kungfu/tensorflow/v1/benchmarks/model_sizes.py``):
parameter-count lists for resnet50-imagenet, vgg16-imagenet, bert and
slp-mnist, materialized as gradient-shaped buffers without any compute —
used to measure allreduce bus bandwidth.

Sizes are the classic per-variable parameter counts used by such harnesses
(grouped to keep the lists manageable); totals match the well-known model
sizes (~25.6M ResNet-50, ~138M VGG16, ~110M BERT-base, 7.9k SLP).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

# per-tensor float counts (grouped); totals are what matters for bandwidth
_FAKE_SIZES: Dict[str, List[int]] = {
    "slp-mnist": [784 * 10, 10],
    "resnet50-imagenet": (
        [9408, 64, 64]
        + [4096, 16384, 36864, 64, 64, 256] * 3
        + [32768, 131072, 147456, 128, 128, 512] * 4
        + [131072, 524288, 589824, 256, 256, 1024] * 6
        + [524288, 2097152, 2359296, 512, 512, 2048] * 3
        + [2048 * 1000, 1000]
    ),
    "vgg16-imagenet": [
        1728, 36864, 73728, 147456, 294912, 589824, 589824,
        1179648, 2359296, 2359296, 2359296, 2359296, 2359296,
        102760448, 16777216, 4096000,
    ],
    "bert": [30528 * 768, 512 * 768, 2 * 768]
    + [768 * 768 * 4 + 768 * 4 + 768 * 3072 * 2 + 3072 + 768 * 3] * 12
    + [768 * 768, 768],
}


def fake_model_names() -> List[str]:
    return sorted(_FAKE_SIZES)


def fake_model_sizes(name: str) -> List[int]:
    try:
        return list(_FAKE_SIZES[name])
    except KeyError:
        raise ValueError(f"unknown fake model {name!r}; one of {fake_model_names()}") from None


def fake_grads(name: str, dtype=np.float32, stacked: int = 0, seed: int = 0):
    """Materialize gradient-shaped buffers; with ``stacked=n`` adds a
    leading peer axis for the eager communicator."""
    rng = np.random.RandomState(seed)
    out = []
    for sz in fake_model_sizes(name):
        shape = (stacked, sz) if stacked else (sz,)
        out.append(rng.uniform(-1, 1, size=shape).astype(dtype))
    return out


def total_params(name: str) -> int:
    return sum(fake_model_sizes(name))
