"""Model zoo + minimal functional NN library.

The reference framework carries no model code (it moves gradient buffers;
models live in its examples/benchmarks: MNIST SLP/CNN examples, and the
ResNet-50/VGG16/BERT *size lists* used by its benchmark harnesses —
``srcs/python/kungfu/tensorflow/v1/benchmarks/model_sizes.py``,
``tests/go/fakemodel/fakemodel.go:12-17``).  The TPU build ships real
models because they are its benchmark workload:

* :mod:`kungfu_tpu.models.nn` — tiny functional layer library (explicit
  param pytrees, pure apply fns — jit/shard_map friendly, bf16-first).
* :mod:`kungfu_tpu.models.mlp` — MNIST SLP/MLP (the reference's minimum
  end-to-end example, ``examples/tf1_mnist_session.py``).
* :mod:`kungfu_tpu.models.resnet` — ResNet-50 (v1.5), NHWC, bf16 compute.
* :mod:`kungfu_tpu.models.vgg` — VGG-16 (the reference benchmark trio's
  second ImageNet family), NHWC, bf16, optional sync-BN.
* :mod:`kungfu_tpu.models.transformer` — GPT-style transformer (the
  flagship; BERT-base-sized config included), ring-attention capable.
* :mod:`kungfu_tpu.models.fake` — gradient-shaped fake models for
  collective benchmarking without real compute (parity with
  ``tests/go/fakemodel``).
"""

from kungfu_tpu.models import nn
from kungfu_tpu.models.mlp import MLP, mnist_slp
from kungfu_tpu.models.resnet import ResNet, resnet50
from kungfu_tpu.models.transformer import Transformer, TransformerConfig, bert_base, gpt_small
from kungfu_tpu.models.vgg import VGG, vgg16
from kungfu_tpu.models.fake import fake_model_sizes, fake_grads

__all__ = [
    "nn",
    "MLP",
    "mnist_slp",
    "ResNet",
    "resnet50",
    "VGG",
    "vgg16",
    "Transformer",
    "TransformerConfig",
    "bert_base",
    "gpt_small",
    "fake_model_sizes",
    "fake_grads",
]
