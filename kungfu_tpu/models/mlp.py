"""MNIST SLP / MLP — the minimum end-to-end model.

Parity with the reference's canonical example workload
(``examples/tf1_mnist_session.py`` single-layer perceptron, also used by
its convergence test ``tests/python/integration/test_mnist_slp.py`` and
the ``slp-mnist`` fake model ``tests/go/fakemodel/fakemodel.go``).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from kungfu_tpu.models import nn


class MLP:
    """Plain MLP: flatten → dense(+relu)* → dense(logits)."""

    def __init__(self, layer_dims: Sequence[int], num_classes: int = 10, input_dim: int = 784):
        self.dims = [input_dim] + list(layer_dims) + [num_classes]

    def init(self, key):
        keys = jax.random.split(key, len(self.dims) - 1)
        return {
            f"dense_{i}": nn.dense_init(keys[i], self.dims[i], self.dims[i + 1])
            for i in range(len(self.dims) - 1)
        }

    def apply(self, params, x, dtype=None):
        x = x.reshape(x.shape[0], -1)
        if dtype is not None:
            x = x.astype(dtype)
        n = len(self.dims) - 1
        for i in range(n):
            x = nn.dense_apply(params[f"dense_{i}"], x, dtype=dtype)
            if i < n - 1:
                x = jax.nn.relu(x)
        return x.astype(jnp.float32)

    def loss(self, params, batch, dtype=None):
        """Softmax cross-entropy mean loss; batch = (images, int labels)."""
        x, y = batch
        logits = self.apply(params, x, dtype=dtype)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1).squeeze(1)
        return jnp.mean(nll)

    def accuracy(self, params, batch, dtype=None):
        x, y = batch
        return jnp.mean((jnp.argmax(self.apply(params, x, dtype=dtype), -1) == y).astype(jnp.float32))


def mnist_slp() -> MLP:
    """Single-layer perceptron 784→10 (the reference example's model)."""
    return MLP(layer_dims=[], num_classes=10, input_dim=784)
