"""Minimal functional NN layer library.

Design: every layer is a pair of pure functions — ``*_init(key, ...) ->
params`` (a dict pytree) and ``*_apply(params, x, ...) -> y``.  No module
objects, no tracing magic: params are explicit pytrees that optimizers,
collectives, fusion, and checkpointing all see uniformly.  bf16-first: the
``dtype`` argument controls *compute/activation* dtype; params are kept in
float32 (the standard TPU mixed-precision recipe — MXU eats bf16, master
weights stay f32).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


# -- initializers --------------------------------------------------------
def glorot_uniform(key, shape, dtype=jnp.float32, in_axis=-2, out_axis=-1):
    fan_in = shape[in_axis] * (math.prod(shape[:-2]) if len(shape) > 2 else 1)
    fan_out = shape[out_axis] * (math.prod(shape[:-2]) if len(shape) > 2 else 1)
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -limit, limit)


def he_normal(key, shape, dtype=jnp.float32):
    fan_in = math.prod(shape[:-1])
    std = math.sqrt(2.0 / fan_in)
    return jax.random.normal(key, shape, dtype) * std


def normal(key, shape, dtype=jnp.float32, stddev=0.02):
    return jax.random.normal(key, shape, dtype) * stddev


# -- dense ---------------------------------------------------------------
def dense_init(key, in_dim: int, out_dim: int, use_bias: bool = True):
    p = {"w": glorot_uniform(key, (in_dim, out_dim))}
    if use_bias:
        p["b"] = jnp.zeros((out_dim,), jnp.float32)
    return p


def dense_apply(p, x, dtype=None):
    w = p["w"].astype(dtype) if dtype else p["w"]
    y = x @ w
    if "b" in p:
        y = y + (p["b"].astype(dtype) if dtype else p["b"])
    return y


# -- conv (NHWC) ---------------------------------------------------------
def conv_init(key, in_ch: int, out_ch: int, kernel: Tuple[int, int], use_bias: bool = False):
    p = {"w": he_normal(key, kernel + (in_ch, out_ch))}
    if use_bias:
        p["b"] = jnp.zeros((out_ch,), jnp.float32)
    return p


def conv_apply(p, x, stride: int = 1, padding="SAME", dtype=None):
    w = p["w"].astype(dtype) if dtype else p["w"]
    y = jax.lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "b" in p:
        y = y + (p["b"].astype(dtype) if dtype else p["b"])
    return y


def conv_stem_s2d_apply(p, x, dtype=None):
    """The classic TPU stem trick: a 7x7/stride-2 conv on [N,H,W,3] runs
    at 3/128 MXU lane efficiency; computing the SAME linear map as a
    4x4/stride-1 conv on 2x2 space-to-depth input ([N,H/2,W/2,12]) packs
    4x more channels per lane.  The trainable parameter stays the
    original [7,7,C,F] kernel (checkpoint-compatible; gradients flow
    through the rearrangement, which is pure indexing/zero-padding).

    Exactness: SAME padding for k=7,s=2 is (2,3), so output o reads
    input p = 2o+a-2, a in [0,7).  With p = 2i+di the taps become
    i = o+u-1, a = 2u+di for u in [0,4), di in {0,1} — a 4x4 kernel
    W'[u,v,(di,dj,c),f] = W[2u+di, 2v+dj, c, f] (the a=7 taps are
    zero-padded) over pad ((1,2),(1,2)) stride 1.  Matches the direct
    conv up to float reassociation.

    Falls back to :func:`conv_apply` when the shape doesn't fit the
    pattern (odd H/W, non-7x7 kernel).
    """
    kh, kw, c, f = p["w"].shape
    n, h, w_, xc = x.shape
    if (kh, kw) != (7, 7) or h % 2 or w_ % 2 or xc != c:
        return conv_apply(p, x, stride=2, dtype=dtype)
    wgt = p["w"].astype(dtype) if dtype else p["w"]
    x = x.reshape(n, h // 2, 2, w_ // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w_ // 2, 4 * c)
    w8 = jnp.pad(wgt, ((0, 1), (0, 1), (0, 0), (0, 0)))
    w4 = w8.reshape(4, 2, 4, 2, c, f)
    w4 = w4.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, f)
    y = jax.lax.conv_general_dilated(
        x, w4,
        window_strides=(1, 1),
        padding=((1, 2), (1, 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "b" in p:
        y = y + (p["b"].astype(dtype) if dtype else p["b"])
    return y


# -- norms ---------------------------------------------------------------
def batchnorm_init(ch: int):
    """Trainable affine params; running stats live in a separate state tree
    (see :func:`batchnorm_state_init`) so optimizers never see them."""
    return {"scale": jnp.ones((ch,), jnp.float32), "bias": jnp.zeros((ch,), jnp.float32)}


def batchnorm_state_init(ch: int):
    return {"mean": jnp.zeros((ch,), jnp.float32), "var": jnp.ones((ch,), jnp.float32)}


def batchnorm_apply(p, stats, x, train: bool, momentum=0.9, eps=1e-5,
                    axis_name=None, compute_dtype=None):
    """Returns (y, new_stats).  In train mode, batch stats; cross-replica
    mean via psum when ``axis_name`` given (sync BN over the DP axis).

    The statistics (moments, running stats) are ALWAYS f32.  The
    normalize/scale/shift elementwise chain — BN's big HBM reads and
    writes — runs in ``compute_dtype``: the activation dtype by default,
    so bf16 activations stay 2 bytes end to end (the round-4 BN-tax
    diagnosis: the f32 chain cost ~20% of the ResNet-50 step,
    ``benchmarks/bn_sweep.py`` ``bf16_norm`` variant; the per-channel
    mean/inv fold to scalars, so only bf16 rounding of the normalized
    output differs).  ``KF_TPU_BN_COMPUTE=f32`` restores the legacy
    all-f32 chain globally; an explicit ``compute_dtype`` wins."""
    xf = x.astype(jnp.float32)
    if train:
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(xf, axes)
        m2 = jnp.mean(jnp.square(xf), axes)
        if axis_name is not None:
            # sync-BN: average the raw moments, THEN form the variance —
            # pmean of per-shard variances drops the cross-shard mean
            # spread (E[var_s] != E[x^2] - E[x]^2 when shard means differ)
            mean = jax.lax.pmean(mean, axis_name)
            m2 = jax.lax.pmean(m2, axis_name)
        var = m2 - jnp.square(mean)
        new_stats = {
            "mean": momentum * stats["mean"] + (1 - momentum) * mean,
            "var": momentum * stats["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = stats["mean"], stats["var"]
        new_stats = stats
    if compute_dtype is None:
        import os

        compute_dtype = (jnp.float32
                         if os.environ.get("KF_TPU_BN_COMPUTE") == "f32"
                         else x.dtype)
    cd = jnp.dtype(compute_dtype)
    inv = (jax.lax.rsqrt(var + eps) * p["scale"]).astype(cd)
    y = (xf.astype(cd) - mean.astype(cd)) * inv + p["bias"].astype(cd)
    return y.astype(x.dtype), new_stats


def layernorm_init(dim: int):
    return {"scale": jnp.ones((dim,), jnp.float32), "bias": jnp.zeros((dim,), jnp.float32)}


def layernorm_apply(p, x, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, -1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), -1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]
    return y.astype(x.dtype)


def rmsnorm_init(dim: int):
    return {"scale": jnp.ones((dim,), jnp.float32)}


def rmsnorm_apply(p, x, eps=1e-6):
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(jnp.square(xf), -1, keepdims=True) + eps)
    return (y * p["scale"]).astype(x.dtype)


# -- embedding -----------------------------------------------------------
def embedding_init(key, vocab: int, dim: int):
    return {"table": normal(key, (vocab, dim))}


def embedding_apply(p, ids, dtype=None):
    t = p["table"].astype(dtype) if dtype else p["table"]
    return jnp.take(t, ids, axis=0)


# -- misc ----------------------------------------------------------------
def dropout(key, x, rate: float, train: bool):
    if not train or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def gelu(x):
    return jax.nn.gelu(x, approximate=True)


def num_params(params) -> int:
    return sum(int(math.prod(l.shape)) for l in jax.tree_util.tree_leaves(params))


def cast_floats(tree, dtype):
    """Cast floating leaves (for bf16 checkpoints / transfers)."""
    def f(l):
        if jnp.issubdtype(l.dtype, jnp.floating):
            return l.astype(dtype)
        return l

    return jax.tree_util.tree_map(f, tree)
