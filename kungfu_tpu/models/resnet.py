"""ResNet v1.5 (50/101/152) — NHWC, bf16 compute, TPU-friendly.

The reference benchmarks throughput on ResNet-50 via Keras applications
(``benchmarks/system/benchmark_kungfu.py``) and ships its layer-size list
as a fake model (``tests/go/fakemodel/fakemodel.go:12``).  This is a fresh
implementation: bottleneck v1.5 (stride in the 3x3), sync-BN capable,
channels-last for XLA's TPU conv layouts.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from kungfu_tpu.models import nn

_STAGES = {
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}


class ResNet:
    def __init__(self, depth: int = 50, num_classes: int = 1000, width: int = 64):
        if depth not in _STAGES:
            raise ValueError(f"depth must be one of {sorted(_STAGES)}")
        self.blocks_per_stage = _STAGES[depth]
        self.num_classes = num_classes
        self.width = width

    # -- init ------------------------------------------------------------
    def init(self, key) -> Tuple[dict, dict]:
        """Returns (params, bn_state)."""
        params, state = {}, {}
        key, k = jax.random.split(key)
        params["stem"] = nn.conv_init(k, 3, self.width, (7, 7))
        params["stem_bn"] = nn.batchnorm_init(self.width)
        state["stem_bn"] = nn.batchnorm_state_init(self.width)

        in_ch = self.width
        for s, nblocks in enumerate(self.blocks_per_stage):
            mid = self.width * (2 ** s)
            out_ch = mid * 4
            for b in range(nblocks):
                name = f"s{s}b{b}"
                key, *ks = jax.random.split(key, 5)
                blk = {
                    "conv1": nn.conv_init(ks[0], in_ch, mid, (1, 1)),
                    "bn1": nn.batchnorm_init(mid),
                    "conv2": nn.conv_init(ks[1], mid, mid, (3, 3)),
                    "bn2": nn.batchnorm_init(mid),
                    "conv3": nn.conv_init(ks[2], mid, out_ch, (1, 1)),
                    "bn3": nn.batchnorm_init(out_ch),
                }
                st = {
                    "bn1": nn.batchnorm_state_init(mid),
                    "bn2": nn.batchnorm_state_init(mid),
                    "bn3": nn.batchnorm_state_init(out_ch),
                }
                if b == 0:
                    blk["proj"] = nn.conv_init(ks[3], in_ch, out_ch, (1, 1))
                    blk["proj_bn"] = nn.batchnorm_init(out_ch)
                    st["proj_bn"] = nn.batchnorm_state_init(out_ch)
                params[name] = blk
                state[name] = st
                in_ch = out_ch
        key, k = jax.random.split(key)
        params["head"] = nn.dense_init(k, in_ch, self.num_classes)
        return params, state

    # -- apply -----------------------------------------------------------
    def apply(self, params, state, x, train: bool = False, dtype=jnp.bfloat16, axis_name=None):
        """x: [N, H, W, 3] float.  Returns (logits_f32, new_state)."""
        new_state = {}
        x = x.astype(dtype)

        # space-to-depth stem: same linear map as conv_apply(stride=2),
        # MXU-lane-efficient on TPU (see nn.conv_stem_s2d_apply)
        h = nn.conv_stem_s2d_apply(params["stem"], x, dtype=dtype)
        h, ns = nn.batchnorm_apply(params["stem_bn"], state["stem_bn"], h, train, axis_name=axis_name)
        new_state["stem_bn"] = ns
        h = jax.nn.relu(h)
        h = jax.lax.reduce_window(
            h, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
        )

        for s, nblocks in enumerate(self.blocks_per_stage):
            for b in range(nblocks):
                name = f"s{s}b{b}"
                blk, bst = params[name], state[name]
                nst = {}
                stride = 2 if (b == 0 and s > 0) else 1
                shortcut = h
                y = nn.conv_apply(blk["conv1"], h, dtype=dtype)
                y, nst["bn1"] = nn.batchnorm_apply(blk["bn1"], bst["bn1"], y, train, axis_name=axis_name)
                y = jax.nn.relu(y)
                y = nn.conv_apply(blk["conv2"], y, stride=stride, dtype=dtype)
                y, nst["bn2"] = nn.batchnorm_apply(blk["bn2"], bst["bn2"], y, train, axis_name=axis_name)
                y = jax.nn.relu(y)
                y = nn.conv_apply(blk["conv3"], y, dtype=dtype)
                y, nst["bn3"] = nn.batchnorm_apply(blk["bn3"], bst["bn3"], y, train, axis_name=axis_name)
                if "proj" in blk:
                    shortcut = nn.conv_apply(blk["proj"], h, stride=stride, dtype=dtype)
                    shortcut, nst["proj_bn"] = nn.batchnorm_apply(
                        blk["proj_bn"], bst["proj_bn"], shortcut, train, axis_name=axis_name
                    )
                h = jax.nn.relu(y + shortcut)
                new_state[name] = nst

        h = jnp.mean(h.astype(jnp.float32), axis=(1, 2))
        logits = nn.dense_apply(params["head"], h)
        return logits, new_state

    def loss(self, params, state, batch, train: bool = True, dtype=jnp.bfloat16, axis_name=None):
        x, y = batch
        logits, new_state = self.apply(params, state, x, train=train, dtype=dtype, axis_name=axis_name)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1).squeeze(1)
        return jnp.mean(nll), new_state


def resnet50(num_classes: int = 1000) -> ResNet:
    return ResNet(50, num_classes)
