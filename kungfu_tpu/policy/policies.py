"""Concrete adaptation policies.

``ScheduledSizePolicy`` is the policy form of the reference's
``StepBasedSchedule`` elastic hook; ``GNSResizePolicy`` closes the loop the
reference designed its monitoring for (SURVEY §5.5: GNS "the signal meant
to drive resize decisions") — grow the cluster when the gradient noise
scale says larger batches would still help, shrink when it says they're
wasted.
"""

from __future__ import annotations

from typing import Optional

from kungfu_tpu.elastic.schedule import step_based_schedule
from kungfu_tpu.policy.base import BasePolicy, PolicyContext


class ScheduledSizePolicy(BasePolicy):
    """Propose the size given by a ``"size:steps,..."`` schedule."""

    def __init__(self, schedule: str):
        self.schedule = schedule

    def after_step(self, ctx: PolicyContext) -> None:
        target = step_based_schedule(self.schedule, ctx.step)
        if target != ctx.cluster_size:
            ctx.request_resize(target)


class AdaptiveStrategyPolicy(BasePolicy):
    """Policy form of the closed adaptation loop: run the
    :class:`~kungfu_tpu.monitor.adaptive.AdaptiveStrategyDriver` after
    every step (it self-paces via ``check_every``).  Every rank's policy
    runner must drive it at the same step points — the swap decision is a
    collective."""

    def __init__(self, peer, **driver_kwargs):
        from kungfu_tpu.monitor.adaptive import AdaptiveStrategyDriver

        self.driver = AdaptiveStrategyDriver(peer, **driver_kwargs)

    def after_step(self, ctx: PolicyContext) -> None:
        if self.driver.step():
            ctx.metrics["strategy_swaps"] = float(self.driver.swaps)


class GNSResizePolicy(BasePolicy):
    """Resize toward ``gns / batch_size`` workers, within bounds.

    The critical-batch heuristic (OpenAI GNS estimator, reference
    ``grad_noise_scale.py``): efficiency drops once the global batch
    exceeds the noise scale, so the useful worker count is about
    ``gns / per_worker_batch``.  Hysteresis: only move when the target
    differs from the current size by ``threshold`` (fraction)."""

    def __init__(
        self,
        min_size: int = 1,
        max_size: int = 64,
        threshold: float = 0.5,
        cooldown_steps: int = 10,
    ):
        self.min_size = min_size
        self.max_size = max_size
        self.threshold = threshold
        self.cooldown_steps = cooldown_steps
        self._last_change: Optional[int] = None

    def target_size(self, ctx: PolicyContext) -> Optional[int]:
        gns, bs = ctx.gradient_noise_scale, ctx.batch_size
        if not gns or gns <= 0 or bs <= 0:
            return None
        want = max(self.min_size, min(self.max_size, round(gns / bs)))
        lo = ctx.cluster_size * (1 - self.threshold)
        hi = ctx.cluster_size * (1 + self.threshold)
        if lo <= want <= hi:
            return None  # within hysteresis band
        return want

    def after_step(self, ctx: PolicyContext) -> None:
        if (
            self._last_change is not None
            and ctx.step - self._last_change < self.cooldown_steps
        ):
            return
        want = self.target_size(ctx)
        if want is not None and want != ctx.cluster_size:
            self._last_change = ctx.step
            ctx.request_resize(want)
