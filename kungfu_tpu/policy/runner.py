"""Drive policies around a training loop and execute their intents.

The PolicyHook analog (reference ``policy/policy_hook.py:8-77``): wraps a
set of :class:`BasePolicy` objects, maintains the named training globals
(batch size, trained samples, GNS), and on ``after_step`` executes any
resize intent through the elastic protocol — propose to the config
server, run the consensus resize, re-broadcast parameters, stop if
detached (reference ``policy_hook.py:69-70``).

Single-process mode (no channel / no config server) degrades to running
the callbacks only, so policy-instrumented loops work unchanged.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

from kungfu_tpu.elastic.hooks import sync_step
from kungfu_tpu.initializer import broadcast_parameters
from kungfu_tpu.policy.base import BasePolicy, PolicyContext
from kungfu_tpu.utils.log import get_logger, log_event

_log = get_logger("policy")


class PolicyRunner:
    def __init__(
        self,
        policies: Iterable[BasePolicy],
        peer=None,
        batch_size: int = 0,
    ):
        self.policies = list(policies)
        self.peer = peer
        self.ctx = PolicyContext(
            batch_size=batch_size,
            cluster_size=peer.size() if peer is not None else 1,
        )
        #: resize intent awaiting the NEXT step's fused step-sync/unanimity
        #: collective (multi-worker mode defers execution by one step so
        #: the whole control plane costs ONE small allreduce per step)
        self._pending_target: Optional[int] = None

    # -- lifecycle callbacks (reference before/after train/epoch) --------
    def before_train(self) -> None:
        for p in self.policies:
            p.before_train(self.ctx)

    def after_train(self) -> None:
        for p in self.policies:
            p.after_train(self.ctx)

    def before_epoch(self) -> None:
        for p in self.policies:
            p.before_epoch(self.ctx)
        self.ctx.epoch += 1

    def after_epoch(self) -> None:
        for p in self.policies:
            p.after_epoch(self.ctx)

    def before_step(self) -> None:
        for p in self.policies:
            p.before_step(self.ctx)

    # -- the per-step driver ---------------------------------------------
    def after_step(
        self,
        params=None,
        gradient_noise_scale: Optional[float] = None,
        gradient_variance: Optional[float] = None,
        **metrics: float,
    ) -> Tuple[object, bool]:
        """Run after each optimizer step.  Returns ``(params, stop)``;
        ``params`` are re-broadcast from rank 0 when membership changed.

        Multi-worker resize intents execute ONE STEP after the policy
        raises them: the step-sync collective that opens each call also
        carries the previous step's intent, fencing unanimity (divergent
        per-rank monitor values must not let one rank start a resize the
        others won't join — that deadlocks their consensus) without a
        second control-plane round trip."""
        ctx = self.ctx
        agreed: Optional[int] = None
        engine = self.peer.engine() if self.peer is not None else None
        if engine is not None and self.peer.size() > 1:
            # fused control op (same ordering slot as elastic_step's
            # sync_step — each step's single engine control collective):
            # [step, enc, -enc] under MAX gives the global step plus the
            # unanimity check (max enc == -max(-enc) iff all ranks agree)
            import numpy as np

            enc = -1 if self._pending_target is None else int(self._pending_target)
            out = engine.all_reduce(
                np.array([ctx.step, enc, -enc], np.int64), op="max",
                record=False,
            )
            ctx.step = int(out[0])
            hi, lo = int(out[1]), -int(out[2])
            if hi != lo:
                _log.warning(
                    "ranks disagree on the resize target (%d..%d) — "
                    "dropping the intent", lo, hi,
                )
            elif hi != -1:
                agreed = hi
            self._pending_target = None
        elif self.peer is not None:
            ctx.step = sync_step(self.peer, ctx.step)
            agreed, self._pending_target = self._pending_target, None
        ctx.step += 1
        ctx.trained_samples += ctx.batch_size * ctx.cluster_size
        if gradient_noise_scale is not None:
            ctx.gradient_noise_scale = float(gradient_noise_scale)
        if gradient_variance is not None:
            ctx.gradient_variance = float(gradient_variance)
        ctx.metrics.update(metrics)

        for p in self.policies:
            p.after_step(ctx)

        stop = ctx.stop_requested
        intent, ctx.requested_size, ctx.stop_requested = (
            ctx.requested_size, None, False,
        )
        if self.peer is None:
            return params, stop
        # this step's intent rides the NEXT step's fused collective
        if intent is not None:
            self._pending_target = int(intent)

        peer = self.peer
        target = agreed
        if target is None:
            return params, stop
        if target == peer.size():
            return params, stop
        if not peer.config.config_server:
            _log.warning("policy requested size %d but no config server", target)
            return params, stop
        log_event(f"policy-resize-{peer.size()}->{target}-at-step-{ctx.step}")
        peer.propose_new_size(target)
        changed = peer.resize_cluster_from_url()
        if changed:
            if peer.detached:
                log_event("policy-detached-stopping")
                return params, True
            ctx.cluster_size = peer.size()
            if params is not None:
                # host-channel broadcast only — NO engine collective after a
                # resize (kungfu_tpu/elastic/hooks.py alignment invariant: the
                # new epoch's first engine op must be the next step's gradient
                # allreduce on every member; step alignment happens at the top
                # of the next after_step via sync_step)
                params = broadcast_parameters(params, peer)
        return params, stop
