"""Policy-layer reads of the kf-sentinel judging plane.

Read-only, pre-staged for the autopilot (ROADMAP item 4): a future
closed-loop policy consumes :func:`sentinel_signals` the way the
serving controllers consume :func:`~kungfu_tpu.policy.serve.
serve_signals` — one schema-checked extraction over the ``/cluster``
view (or the ``/alerts`` payload directly), no side effects.  Nothing
here mutates the cluster; acting on an alert stays a human decision
until the autopilot PR wires these signals into resize/swap intents.
"""

from __future__ import annotations

from typing import Optional

from kungfu_tpu.monitor.aggregator import field


def sentinel_signals(view: dict) -> Optional[dict]:
    """The sentinel alert state out of a ``/cluster`` view (or an
    ``/alerts`` payload — both carry the same section shape).
    ``None`` when no sentinel is attached, so a policy can distinguish
    "plane off" from "no alerts"."""
    # a /cluster view NESTS the section under "alerts"; an /alerts
    # payload IS the section — but itself carries an "alerts" key (the
    # fired-alert LIST), so the nesting test must check the shape, not
    # just the key
    nested = field(view, "alerts")
    al = nested if isinstance(nested, dict) and "active" in nested else view
    if not al or not isinstance(al, dict) or "active" not in al:
        return None
    active = list(field(al, "active") or [])
    fired = field(al, "alerts") or []
    verdicts = field(al, "verdicts") or {}
    return {
        "active": active,
        "firing": bool(active),
        # the coarse shapes a policy steers by: is the cluster
        # regressing (changepoints), burning SLO budget, or tripping a
        # watermark — without re-parsing rule evidence
        "regressing": sorted(r.split(":", 1)[1] for r in active
                             if r.startswith("regress:")),
        "burning": sorted(r.split(":", 1)[1] for r in active
                          if r.startswith("sloburn:")),
        "watermarks": sorted(r.split(":", 1)[1] for r in active
                             if r.startswith("watermark:")),
        "fired_total": len(fired),
        "verdicts": verdicts,
        # the kf-ledger rollup: how many decisions the adaptive actors
        # made and how their measured effects judged (None on builds
        # whose sentinel predates the ledger)
        "decisions": field(al, "decisions"),
    }
