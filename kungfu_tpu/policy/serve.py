"""Serving control policies: batch-width controller + autoscaler.

Both read the SAME aggregator ``/cluster`` view every other consumer
renders (``kftop``), through :func:`serve_signals` — one schema-checked
extraction of the serving rollup — and steer against the
:class:`~kungfu_tpu.serve.slo.SLOTargets`:

* :class:`BatchWidthController` moves a LOCAL knob: the engine's
  admitted decode width (:meth:`~kungfu_tpu.serve.engine.
  InferenceEngine.set_width`).  Wider = more throughput per replica but
  longer decode steps (every active slot pays every step); the
  controller widens while there is queue pressure and the e2e window
  is inside budget, narrows when the SLO is being blown.  Local
  backpressure, like the overlap-depth bandit: replicas may legally run
  different widths, so no consensus fence is needed.
* :class:`ServeAutoscalePolicy` raises GLOBAL intents on the standard
  :class:`~kungfu_tpu.policy.base.PolicyContext`: queue pressure with
  the SLO blown asks for one more worker; a drained queue with a wide
  margin releases one — the elastic resize path (or the operator)
  executes the intent exactly as it does for training policies.
  Hysteresis + cooldown keep it from flapping on one bad window.
"""

from __future__ import annotations

from typing import Callable, Optional

from kungfu_tpu.monitor.aggregator import field
from kungfu_tpu.policy.base import BasePolicy, PolicyContext
from kungfu_tpu.serve.slo import SLOTargets
from kungfu_tpu.utils.log import get_logger

_log = get_logger("serve-policy")


def serve_signals(view: dict) -> Optional[dict]:
    """The serving rollup out of a ``/cluster`` view (schema-checked
    field reads; ``None`` when the deployment serves nothing)."""
    srv = field(view, "serving")
    if not srv:
        return None
    return {
        "active": field(srv, "active") or 0,
        "queued": field(srv, "queued") or 0,
        "completed": field(srv, "completed") or 0,
        "replayed": field(srv, "replayed") or 0,
        "ttft_ms": field(srv, "ttft_ms"),
        "e2e_ms": field(srv, "e2e_ms"),
    }


class BatchWidthController:
    """Hysteresis controller over one engine's admitted decode width.

    ``apply_fn(width) -> int`` installs the width and returns the
    effective value (:meth:`InferenceEngine.set_width` has exactly this
    shape).  Driven by :meth:`observe` with the queue depth and the
    window-mean e2e latency (ms) — either from local registry numbers
    or from :func:`serve_signals` on the aggregator view."""

    def __init__(self, apply_fn: Callable[[int], int], *,
                 lo: int = 1, hi: int = 8,
                 start: Optional[int] = None,
                 targets: Optional[SLOTargets] = None,
                 widen_at_queue: int = 2,
                 cooldown_steps: int = 3):
        self._apply = apply_fn
        self.lo = int(lo)
        self.hi = int(hi)
        self.targets = targets or SLOTargets.from_env()
        self.widen_at_queue = int(widen_at_queue)
        self.cooldown_steps = int(cooldown_steps)
        self._cool = 0
        self.width = self._apply(int(start if start is not None else hi))

    def observe(self, queued: int, e2e_ms: Optional[float]) -> int:
        """One control tick; returns the (possibly new) width."""
        if self._cool > 0:
            self._cool -= 1
            return self.width
        budget_ms = self.targets.e2e_s * 1e3
        over = e2e_ms is not None and e2e_ms > budget_ms
        if over and self.width > self.lo:
            # blowing the SLO: shed decode width — fewer slots per step
            # shortens every active request's per-token latency
            prev, self.width = self.width, self._apply(self.width - 1)
            self._cool = self.cooldown_steps
            self._record(prev, queued, e2e_ms)
            _log.info("batch width -> %d (e2e %.0fms > %.0fms budget)",
                      self.width, e2e_ms, budget_ms)
        elif (not over and queued >= self.widen_at_queue
              and self.width < self.hi):
            prev, self.width = self.width, self._apply(self.width + 1)
            self._cool = self.cooldown_steps
            self._record(prev, queued, e2e_ms)
            _log.info("batch width -> %d (queue %d)", self.width, queued)
        return self.width

    def _record(self, prev: int, queued: int,
                e2e_ms: Optional[float]) -> None:
        from kungfu_tpu.monitor import ledger

        # kf-ledger: width moves answer to serving latency, not step
        # time — the effect series is the e2e window mean
        ledger.record_decision(
            "batch-width", "width", prev, self.width,
            evidence={"queued": int(queued), "e2e_ms": e2e_ms},
            effect_series="e2e_ms")

    def observe_view(self, view: dict) -> int:
        sig = serve_signals(view)
        if sig is None:
            return self.width
        return self.observe(sig["queued"], sig["e2e_ms"])


class ServeAutoscalePolicy(BasePolicy):
    """Worker-count intents from the serving rollup.

    Feed it per-step metrics (``runner.after_step(serve_queued=...,
    serve_e2e_ms=...)``) or call :meth:`observe_view` with the
    aggregator view before the runner tick.  Scale-up: queue pressure
    AND the e2e window over budget.  Scale-down: idle queue, nothing
    active, and a wide latency margin.  ``min_workers`` floors the
    release path — the router's fault ladder, not the autoscaler, is
    who removes the last capacity."""

    def __init__(self, *, targets: Optional[SLOTargets] = None,
                 scale_up_queue: int = 4,
                 min_workers: int = 1,
                 max_workers: int = 64,
                 cooldown_steps: int = 10):
        self.targets = targets or SLOTargets.from_env()
        self.scale_up_queue = int(scale_up_queue)
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.cooldown_steps = int(cooldown_steps)
        self._cool = 0
        self._view_sig: Optional[dict] = None

    def observe_view(self, view: dict) -> None:
        self._view_sig = serve_signals(view)

    def after_step(self, ctx: PolicyContext) -> None:
        sig = self._view_sig or {
            "queued": ctx.metrics.get("serve_queued", 0),
            "active": ctx.metrics.get("serve_active", 0),
            "e2e_ms": ctx.metrics.get("serve_e2e_ms"),
        }
        self._view_sig = None
        if self._cool > 0:
            self._cool -= 1
            return
        budget_ms = self.targets.e2e_s * 1e3
        e2e = sig.get("e2e_ms")
        queued = sig.get("queued") or 0
        active = sig.get("active") or 0
        over = e2e is not None and e2e > budget_ms
        if (queued >= self.scale_up_queue and over
                and ctx.cluster_size < self.max_workers):
            _log.info("autoscale: +1 worker (queue %d, e2e %.0fms)",
                      queued, e2e)
            self._record(ctx.cluster_size, ctx.cluster_size + 1,
                         queued, e2e)
            ctx.request_resize(ctx.cluster_size + 1)
            self._cool = self.cooldown_steps
        elif (queued == 0 and active == 0 and not over
              and ctx.cluster_size > self.min_workers
              and (e2e is None or e2e < 0.25 * budget_ms)):
            _log.info("autoscale: -1 worker (idle)")
            self._record(ctx.cluster_size, ctx.cluster_size - 1,
                         queued, e2e)
            ctx.request_resize(ctx.cluster_size - 1)
            self._cool = self.cooldown_steps

    @staticmethod
    def _record(prev: int, new: int, queued: int, e2e) -> None:
        from kungfu_tpu.monitor import ledger

        ledger.record_decision(
            "serve-autoscale", "workers", prev, new,
            evidence={"queued": int(queued), "e2e_ms": e2e},
            effect_series="e2e_ms")
