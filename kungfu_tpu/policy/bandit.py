"""UCB bandit over collective strategies — the measured half of kf-adapt.

The reference's signature capability is *adaptive* communication:
strategy switchover on measured throughput windows
(``adaptiveStrategies.go``), MST re-selection over measured latencies,
interference votes.  This module is the decision core of the TPU-native
version: a UCB1-style bandit whose **arms are collective strategies**
(host-plane :class:`~kungfu_tpu.plan.strategy.Strategy` graphs + the
measured-latency MST tree, or device-plane allreduce schedules
``psum``/``two_stage``/``ring``/``pallas_ring`` — the last being the
in-kernel-overlap ICI ring of :mod:`kungfu_tpu.ops.pallas.collectives`)
and whose **reward is measured window latency** (lower is better).  PAPERS.md 2011.03641 (the best collective
schedule shifts with scale and payload) and 1909.09756 (report
adaptation as measured curves, not assumptions) are why the winner is
measured per regime, online, instead of fixed at startup.

Determinism contract — the part that makes the bandit safe to run on a
cluster: every decision is a **pure function of the agreed stats
table**.  The drivers (:mod:`kungfu_tpu.monitor.adapt_device`) allreduce
each window's per-arm ``(count, sum)`` deltas, every rank folds the SAME
agreed numbers into its table, and :meth:`ArmStats.select` breaks every
tie by arm order — so N ranks fed the same collective stream make the
same swap decision at the same step without any leader.  Two tables fed
identical observation sequences produce identical selection sequences
(asserted in ``tests/test_bandit.py``).

Non-stationarity (interference comes and goes) is handled the classic
way: the active arm keeps being measured, so a degraded incumbent's mean
climbs within a window or two and UCB moves off it; the ``log N``
exploration bonus re-probes abandoned arms at a decaying rate, and
``decay`` optionally ages the table so ancient measurements cannot pin
a stale winner forever.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from kungfu_tpu.policy.base import BasePolicy, PolicyContext

#: default exploration weight: the bonus is ``c * mean_latency *
#: sqrt(2 ln N / n_arm)`` — scaled by the observed mean so it is in
#: latency units and one constant works for microsecond device windows
#: and 100 ms degraded host windows alike
DEFAULT_EXPLORE_C = 0.5


class ArmStats:
    """Per-arm ``(count, sum-of-latency)`` table with UCB selection for
    MINIMIZATION.  Pure state machine: no clocks, no randomness — the
    same observation sequence always yields the same selections."""

    def __init__(self, arms: Sequence[str], c: float = DEFAULT_EXPLORE_C,
                 min_pulls: int = 1, decay: float = 1.0):
        if not arms:
            raise ValueError("bandit needs at least one arm")
        if len(set(arms)) != len(arms):
            raise ValueError(f"duplicate arms in {arms}")
        self.arms: Tuple[str, ...] = tuple(arms)
        self.c = float(c)
        self.min_pulls = max(1, int(min_pulls))
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        self.decay = float(decay)
        self.counts: List[float] = [0.0] * len(self.arms)
        self.sums: List[float] = [0.0] * len(self.arms)

    # -- feeding ---------------------------------------------------------
    def index(self, arm: str) -> int:
        try:
            return self.arms.index(arm)
        except ValueError:
            raise KeyError(f"unknown arm {arm!r}; arms are {self.arms}")

    def observe(self, arm: str, latency_s: float, count: float = 1.0) -> None:
        """Fold ``count`` observations summing to ``latency_s * count``
        seconds into ``arm``.  Drivers pass the ALLREDUCED window deltas
        here (count = ranks, latency = mean over ranks), so the table
        stays identical on every rank.  Non-finite, negative, or
        exactly-zero samples are rejected loudly — a 0-second "winner"
        is how the old startup probe went wrong (ROADMAP #4), and an arm
        with mean 0 would also zero its UCB score floor and become
        permanently unbeatable."""
        if count <= 0:
            raise ValueError(f"count must be positive, got {count}")
        if not math.isfinite(latency_s) or latency_s <= 0:
            raise ValueError(
                f"latency must be finite and positive, got {latency_s!r}")
        if self.decay < 1.0:
            for i in range(len(self.arms)):
                self.counts[i] *= self.decay
                self.sums[i] *= self.decay
        i = self.index(arm)
        self.counts[i] += count
        self.sums[i] += latency_s * count

    def reset(self) -> None:
        """Forget everything — the re-explore after a membership change
        (a 4-rank winner says nothing about the 2-rank regime)."""
        self.counts = [0.0] * len(self.arms)
        self.sums = [0.0] * len(self.arms)

    # -- deciding --------------------------------------------------------
    def mean(self, arm: str) -> Optional[float]:
        i = self.index(arm)
        return self.sums[i] / self.counts[i] if self.counts[i] > 0 else None

    def unexplored(self) -> Optional[str]:
        """First arm (in declaration order) still under ``min_pulls`` —
        the deterministic exploration phase."""
        for i, a in enumerate(self.arms):
            if self.counts[i] < self.min_pulls:
                return a
        return None

    def select(self) -> str:
        """The UCB1 pick: unexplored arms first (declaration order), then
        the argmin of ``mean - c * overall_mean * sqrt(2 ln N / n)``.
        Ties break to the earlier arm — arrival order can never flip a
        cluster-wide decision."""
        arm = self.unexplored()
        if arm is not None:
            return arm
        total = sum(self.counts)
        overall = sum(self.sums) / total if total > 0 else 0.0
        best_i, best_score = 0, math.inf
        for i in range(len(self.arms)):
            bonus = self.c * overall * math.sqrt(
                2.0 * math.log(max(total, math.e)) / self.counts[i])
            score = self.sums[i] / self.counts[i] - bonus
            if score < best_score:  # strict: ties keep the earlier arm
                best_i, best_score = i, score
        return self.arms[best_i]

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """``{arm: {count, mean_s}}`` for observability surfaces."""
        out = {}
        for i, a in enumerate(self.arms):
            out[a] = {
                "count": round(self.counts[i], 3),
                "mean_s": (self.sums[i] / self.counts[i]
                           if self.counts[i] > 0 else None),
            }
        return out


class ScheduleTable:
    """Size-bucketed arm tables: small control tensors and large fused
    gradient buckets learn **independent** winners (the per-``nbytes``
    schedule table installed into
    :meth:`kungfu_tpu.comm.device.Communicator.set_bucket_strategy`)."""

    def __init__(self, arms: Sequence[str], n_buckets: int,
                 c: float = DEFAULT_EXPLORE_C, min_pulls: int = 1,
                 decay: float = 1.0):
        if n_buckets < 1:
            raise ValueError(f"need >= 1 bucket, got {n_buckets}")
        self.tables = [ArmStats(arms, c=c, min_pulls=min_pulls, decay=decay)
                       for _ in range(n_buckets)]
        self.active: List[str] = [self.tables[0].arms[0]] * n_buckets

    @property
    def arms(self) -> Tuple[str, ...]:
        return self.tables[0].arms

    def observe(self, bucket: int, arm: str, latency_s: float,
                count: float = 1.0) -> None:
        self.tables[bucket].observe(arm, latency_s, count)

    def select(self, bucket: int) -> str:
        return self.tables[bucket].select()

    def install(self, bucket: int, arm: str) -> None:
        self.tables[bucket].index(arm)  # unknown arm raises before install
        self.active[bucket] = arm

    def reset(self) -> None:
        for t in self.tables:
            t.reset()

    def summary(self) -> Dict[int, Dict]:
        return {b: {"active": self.active[b], "arms": t.snapshot()}
                for b, t in enumerate(self.tables)}


class OverlapDepthBandit:
    """UCB arms over the engine's async in-flight window depth
    (kf-overlap): the measured reward is the wall time of one bucketed
    pipeline run (``parallel/zero.py::host_bucket_pipeline``) at the
    active depth, fed via :meth:`observe`; every ``check_every``
    observations the table re-selects and installs the winner with
    :meth:`~kungfu_tpu.comm.engine.CollectiveEngine.set_overlap_depth`.

    Unlike the strategy arms this needs **no fence and no consensus**:
    the window is local backpressure — tags and issue order never
    change with it — so each rank may legally learn its own depth
    (a straggler host with slow NICs wants a deeper window than its
    peers; forcing agreement would deny exactly that).  The per-bucket
    latencies behind the pipeline measurement arrive through the
    engine's kf-adapt latency hook (``engine.set_latency_hook``), the
    same feed shape the device bandit drinks from."""

    def __init__(self, engine, depths: Sequence[int] = (1, 2, 4),
                 check_every: int = 3, c: float = DEFAULT_EXPLORE_C,
                 min_pulls: int = 1, decay: float = 1.0):
        if not depths or any(d < 1 for d in depths):
            raise ValueError(f"depths must be positive, got {depths}")
        self.stats = ArmStats([str(d) for d in depths], c=c,
                              min_pulls=min_pulls, decay=decay)
        self.check_every = max(1, int(check_every))
        self._engine = engine
        self.swaps = 0
        self._n = 0
        # start on the table's first arm so exploration order is the
        # declaration order (determinism contract of ArmStats)
        self.active = self.stats.arms[0]
        engine.set_overlap_depth(int(self.active))

    def observe(self, pipeline_seconds: float) -> bool:
        """Fold one pipeline run's wall time into the active depth's
        arm; True when a new depth was just installed."""
        self.stats.observe(self.active, pipeline_seconds)
        self._n += 1
        if self._n % self.check_every:
            return False
        pick = self.stats.select()
        if pick == self.active:
            return False
        from kungfu_tpu.monitor import ledger

        # kf-ledger: depth changes are local (no consensus fence — the
        # depth is not collective-shape-bearing), so consensus_seq=None
        ledger.record_decision(
            "overlap-depth", "depth", int(self.active), int(pick),
            evidence={"checks": self._n // self.check_every})
        self.active = pick
        self._engine.set_overlap_depth(int(pick))
        self.swaps += 1
        return True

    def reset(self) -> None:
        """Re-explore (post-resize: a 4-rank depth winner says nothing
        about the 2-rank wire regime) — same contract as the strategy
        tables."""
        self.stats.reset()
        self._n = 0
        self.active = self.stats.arms[0]
        self._engine.set_overlap_depth(int(self.active))


class CollectiveBanditPolicy(BasePolicy):
    """Policy-runner wiring for the bandit drivers: runs the host-plane
    (and optionally device-plane) bandit after every step, feeding it the
    measured step collective seconds the loop reports via
    ``runner.after_step(..., step_collective_s=dt)``.  Every rank's
    policy runner must drive it at the same step points — the swap fence
    is collective (:mod:`kungfu_tpu.monitor.adapt_device`)."""

    #: metric key the training loop reports measured collective seconds
    #: under (``runner.after_step(step_collective_s=dt)``)
    METRIC = "step_collective_s"

    def __init__(self, peer, device_comm=None, **driver_kwargs):
        from kungfu_tpu.monitor.adapt_device import (DeviceBanditDriver,
                                                     HostBanditDriver)

        self.host = HostBanditDriver(peer, **driver_kwargs)
        self.device = (DeviceBanditDriver(device_comm, peer=peer)
                       if device_comm is not None else None)

    def after_step(self, ctx: PolicyContext) -> None:
        dt = ctx.metrics.get(self.METRIC)
        if self.host.step(dt):
            ctx.metrics["bandit_swaps"] = float(self.host.swaps)
        if self.device is not None and self.device.step():
            ctx.metrics["bandit_device_swaps"] = float(self.device.swaps)
