"""Adaptation-policy subsystem.

Parity with reference ``kungfu/tensorflow/policy/{base_policy,policy_hook}.py``
(SURVEY §2.3): a ``BasePolicy`` interface with before/after train/epoch/step
callbacks, driven by a :class:`PolicyRunner` that maintains the named
training globals the reference keeps as TF variables
(``kungfu/tensorflow/variables.py`` — batch size, trained samples, gradient
noise scale) and executes the policies' resize/stop intents through the
elastic protocol.
"""

from kungfu_tpu.policy.base import BasePolicy, PolicyContext  # noqa: F401
from kungfu_tpu.policy.bandit import (  # noqa: F401
    ArmStats,
    CollectiveBanditPolicy,
    ScheduleTable,
)
from kungfu_tpu.policy.policies import (  # noqa: F401
    AdaptiveStrategyPolicy,
    GNSResizePolicy,
    ScheduledSizePolicy,
)
from kungfu_tpu.policy.runner import PolicyRunner  # noqa: F401


def __getattr__(name):
    # the serving policies pull in serve/slo (and its registry/env
    # stack); lazy like monitor/__init__'s bandit drivers so importing
    # the policy package never costs the serving plane
    if name in ("BatchWidthController", "ServeAutoscalePolicy",
                "serve_signals"):
        from kungfu_tpu.policy import serve as _serve

        return getattr(_serve, name)
    if name == "sentinel_signals":
        from kungfu_tpu.policy.sentinel import sentinel_signals

        return sentinel_signals
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
