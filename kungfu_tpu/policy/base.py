"""Policy interface + mutable training context.

Reference ``policy/base_policy.py`` defines before/after train/epoch/step
hooks; ``policy_hook.py:8-77`` threads TF global variables (batch size,
trained samples) through them.  Here the globals live on a plain
:class:`PolicyContext` — policies read metrics and record intents on it;
the :class:`~kungfu_tpu.policy.runner.PolicyRunner` applies the intents.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class PolicyContext:
    """Named training globals (reference ``variables.py``) + intents."""

    batch_size: int = 0
    trained_samples: int = 0
    step: int = 0
    epoch: int = 0
    cluster_size: int = 1
    gradient_noise_scale: Optional[float] = None
    gradient_variance: Optional[float] = None
    metrics: Dict[str, float] = field(default_factory=dict)

    # intents — consumed (and reset) by the runner after each callback
    requested_size: Optional[int] = None
    stop_requested: bool = False

    def request_resize(self, new_size: int) -> None:
        self.requested_size = int(new_size)

    def request_stop(self) -> None:
        self.stop_requested = True


class BasePolicy:
    """Override any subset; every hook receives the shared context
    (reference ``BasePolicy`` before/after train/epoch/step interface)."""

    def before_train(self, ctx: PolicyContext) -> None:  # noqa: B027
        pass

    def after_train(self, ctx: PolicyContext) -> None:  # noqa: B027
        pass

    def before_epoch(self, ctx: PolicyContext) -> None:  # noqa: B027
        pass

    def after_epoch(self, ctx: PolicyContext) -> None:  # noqa: B027
        pass

    def before_step(self, ctx: PolicyContext) -> None:  # noqa: B027
        pass

    def after_step(self, ctx: PolicyContext) -> None:  # noqa: B027
        pass
