"""wire-contract checker: Python↔C++ framing must agree byte-for-byte.

The host channel's wire format lives twice: :mod:`kungfu_tpu.comm.host`
packs it with :class:`HeaderCodec` (``struct`` format strings) and
``native/transport.cpp`` decodes it with ``get_u16``/``get_u32`` reads
at hand-computed offsets.  A one-byte drift between them — a widened
field, a reordered pair, a changed magic — is invisible to either
side's unit tests and surfaces as a cluster-wide decode hang.  This
checker parses BOTH sides into one schema IR and diffs them:

* **fixed-field sequence** — the ordered widths of the non-variable
  header fields (``magic u32 | token u32 | conn_type u8 | src_len u16``
  then ``name_len u16`` and ``payload_len u32``), extracted from the
  ``HeaderCodec`` format constants (Python) and from
  ``encode_head``/``decode_head`` (C++: ``put_u32``→u32, ``put_u16``→
  u16, ``push_back``→u8; ``get_u32(head+k)``/``head[k]`` reads with
  offset-contiguity checking);
* **header prefix size** — ``struct.calcsize(HEAD_FMT)`` must equal the
  C++ ``uint8_t head[N]`` stack buffer;
* **shared constants** — ``MAGIC``/``kMagic``, ``MAX_FRAME``/
  ``kMaxFrame``, ``MAX_META_LEN``/``kMaxMetaLen`` evaluated and
  compared as integers;
* **codec bypass** — a raw ``struct.pack``/``unpack`` format literal
  inside the framing functions that is not one of the ``HeaderCodec``
  constants (a second copy is exactly how drift starts).

Both files must be present for the diff to run (a partial fixture tree
lints as empty).  Endianness is pinned little ("<" / the C++
shift-composed reads).
"""

from __future__ import annotations

import ast
import os
import re
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kungfu_tpu.analysis.core import Violation, parse_module, read_lines

CHECKER = "wire-contract"

HOST_PATH = os.path.join("kungfu_tpu", "comm", "host.py")
CPP_PATH = os.path.join("kungfu_tpu", "native", "transport.cpp")

#: struct letters (little-endian) -> width; case-normalized (the wire
#: contract is width + order; all live fields are unsigned and bounded)
_WIDTHS = {"B": 1, "H": 2, "I": 4, "L": 4, "Q": 8}

#: Python framing scopes whose struct literals must come from the codec
_PY_FRAMING_FUNCS = {"_encode_head", "_encode", "_decode", "HeaderCodec"}

#: constant pairs diffed across the two languages
_CONST_PAIRS = (("MAGIC", "kMagic"), ("MAX_FRAME", "kMaxFrame"),
                ("MAX_META_LEN", "kMaxMetaLen"))


@dataclass
class Schema:
    fields: List[str] = field(default_factory=list)  # canonical letters
    head_size: Optional[int] = None  # fixed-prefix byte count
    consts: Dict[str, int] = field(default_factory=dict)
    lines: Dict[str, int] = field(default_factory=dict)  # anchor -> line
    errors: List[Tuple[int, str]] = field(default_factory=list)


def _const_fold(node: ast.AST) -> Optional[int]:
    """Evaluate the small integer expressions the contract uses
    (``3 << 30``, ``0x4B465450``, ``4096``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.BinOp):
        lhs, rhs = _const_fold(node.left), _const_fold(node.right)
        if lhs is None or rhs is None:
            return None
        if isinstance(node.op, ast.LShift):
            return lhs << rhs
        if isinstance(node.op, ast.Mult):
            return lhs * rhs
        if isinstance(node.op, ast.Add):
            return lhs + rhs
        if isinstance(node.op, ast.Sub):
            return lhs - rhs
        if isinstance(node.op, ast.BitOr):
            return lhs | rhs
    return None


#: width -> canonical letter: the contract is WIDTH + order, so "<LLBH"
#: (byte-identical to "<IIBH" under "<") must not read as drift
_CANONICAL = {1: "B", 2: "H", 4: "I", 8: "Q"}


def _fmt_letters(fmt: str) -> Optional[List[str]]:
    """``"<IIBH"`` -> ["I","I","B","H"] (width-canonicalized: ``L`` and
    ``I`` both -> "I"); None for a non-LE or unknown format (the
    contract is pinned little-endian)."""
    body = fmt
    if body[:1] in ("<", ">", "=", "!", "@"):
        if body[0] != "<":
            return None
        body = body[1:]
    out = []
    for ch in body:
        if ch.upper() not in _WIDTHS:
            return None
        out.append(_CANONICAL[_WIDTHS[ch.upper()]])
    return out


# -- Python side -------------------------------------------------------------

def python_schema(path: str) -> Schema:
    s = Schema()
    tree = parse_module(path).tree
    if tree is None:
        raise SyntaxError(f"{path}: unparseable")

    codec: Optional[ast.ClassDef] = None
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "HeaderCodec":
            codec = node
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if name in ("MAGIC", "MAX_FRAME", "MAX_META_LEN"):
                val = _const_fold(node.value)
                if val is not None:
                    s.consts[name] = val
                    s.lines[name] = node.lineno

    fmt_values: List[str] = []
    if codec is not None:
        s.lines["HeaderCodec"] = codec.lineno
        for node in codec.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id.endswith("_FMT") and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                fmt_values.append(node.value.value)
                s.lines.setdefault("fmt", node.lineno)
    if not fmt_values:
        s.errors.append((1, "no HeaderCodec *_FMT constants found — the "
                            "wire checker has lost its Python anchor"))
        return s

    for fmt in fmt_values:
        letters = _fmt_letters(fmt)
        if letters is None:
            s.errors.append((s.lines.get("fmt", 1),
                             f"unparseable/non-little-endian header format "
                             f"{fmt!r}"))
            return s
        s.fields.extend(letters)
    try:
        s.head_size = struct.calcsize(fmt_values[0])
    except struct.error as e:
        s.errors.append((s.lines.get("fmt", 1),
                         f"struct.calcsize({fmt_values[0]!r}) failed: {e}"))

    # codec-bypass scan: any struct format literal in the framing
    # functions must be one of the codec constants' values
    allowed = set(fmt_values)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.ClassDef)):
            continue
        if node.name not in _PY_FRAMING_FUNCS:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call) or not call.args:
                continue
            f = call.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if attr not in ("pack", "unpack", "pack_into", "unpack_from",
                            "calcsize"):
                continue
            arg = call.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                    and arg.value not in allowed:
                s.errors.append((call.lineno,
                                 f"raw struct format {arg.value!r} in "
                                 f"{node.name} bypasses HeaderCodec — the "
                                 f"single wire anchor"))
    return s


# -- C++ side ----------------------------------------------------------------

def _cpp_function_body(lines: List[str], name: str) -> Tuple[int, List[str]]:
    """(1-based start line, body lines) of function ``name`` by brace
    matching; ([], 0) when absent."""
    sig = re.compile(r"\b" + re.escape(name) + r"\s*\(")
    for i, line in enumerate(lines):
        if not sig.search(line) or ";" in line.split("//")[0].replace(
                ") {", ")").split("{")[0] and "{" not in line:
            continue
        if sig.search(line) and ("{" in line or "{" in "".join(
                lines[i:i + 3])):
            depth = 0
            body: List[str] = []
            started = False
            for j in range(i, len(lines)):
                code = lines[j].split("//")[0]
                depth += code.count("{") - code.count("}")
                body.append(lines[j])
                if "{" in code:
                    started = True
                if started and depth <= 0:
                    return i + 1, body
            break
    return 0, []


def cpp_schema(path: str) -> Schema:
    s = Schema()
    lines = read_lines(path)
    text = "\n".join(lines)

    for pyname, cppname in _CONST_PAIRS:
        m = re.search(re.escape(cppname) + r"\s*=\s*([^;]+);", text)
        if not m:
            continue
        expr = m.group(1).split("//")[0].strip()
        s.lines[cppname] = text[:m.start()].count("\n") + 1
        lit = re.fullmatch(r"(0[xX][0-9a-fA-F]+|\d+)[uU]?[lL]{0,2}", expr)
        shift = re.fullmatch(r"(\d+)[uU]?[lL]{0,2}\s*<<\s*(\d+)", expr)
        if lit:
            s.consts[cppname] = int(lit.group(1), 0)
        elif shift:
            s.consts[cppname] = int(shift.group(1)) << int(shift.group(2))

    # encode_head: ordered put/push tokens are the field sequence
    enc_line, enc = _cpp_function_body(lines, "encode_head")
    if not enc:
        s.errors.append((1, "encode_head not found in transport.cpp — the "
                            "wire checker has lost its C++ encode anchor"))
    else:
        s.lines["encode_head"] = enc_line
        for ln in enc:
            code = ln.split("//")[0]
            for m in re.finditer(
                    r"\b(put_u32|put_u16|push_back)\s*\(", code):
                s.fields.append({"put_u32": "I", "put_u16": "H",
                                 "push_back": "B"}[m.group(1)])

    # decode_head: head[N] buffer + offset-addressed reads, then the
    # trailing length reads
    dec_line, dec = _cpp_function_body(lines, "decode_head")
    if not dec:
        s.errors.append((1, "decode_head not found in transport.cpp — the "
                            "wire checker has lost its C++ decode anchor"))
        return s
    s.lines["decode_head"] = dec_line
    decode_fields: List[Tuple[int, int, str]] = []  # (offset, width, letter)
    tail_fields: List[str] = []
    head_size = None
    for ln in dec:
        code = ln.split("//")[0]
        m = re.search(r"uint8_t\s+head\s*\[\s*(\d+)\s*\]", code)
        if m:
            head_size = int(m.group(1))
            continue
        for m in re.finditer(r"\b(get_u32|get_u16)\s*\(\s*(\w+)"
                             r"(?:\s*\+\s*(\d+))?\s*\)", code):
            width, letter = (4, "I") if m.group(1) == "get_u32" else (2, "H")
            if m.group(2) == "head":
                decode_fields.append((int(m.group(3) or 0), width, letter))
            else:
                tail_fields.append(letter)
        if "uint8_t" not in code:
            for m in re.finditer(r"\bhead\s*\[\s*(\d+)\s*\]", code):
                decode_fields.append((int(m.group(1)), 1, "B"))
    s.head_size = head_size
    if head_size is None:
        s.errors.append((dec_line, "decode_head has no `uint8_t head[N]` "
                                   "fixed prefix"))
        return s
    decode_fields.sort()
    off = 0
    dec_letters: List[str] = []
    for field_off, width, letter in decode_fields:
        if field_off != off:
            s.errors.append((
                dec_line,
                f"decode_head field at offset {field_off} does not follow "
                f"the previous field (expected offset {off}) — gap or "
                f"overlap in the fixed header reads"))
            return s
        dec_letters.append(letter)
        off += width
    if off != head_size:
        s.errors.append((
            dec_line,
            f"decode_head reads {off} bytes of fixed fields out of a "
            f"head[{head_size}] prefix — size and reads drifted"))
    dec_letters.extend(tail_fields)
    # the decode sequence must equal the encode sequence (C++-internal)
    if s.fields and dec_letters != s.fields:
        s.errors.append((
            dec_line,
            f"transport.cpp decode_head field sequence "
            f"{''.join(dec_letters)} != encode_head sequence "
            f"{''.join(s.fields)}"))
    if not s.fields:
        s.fields = dec_letters
    return s


# -- the diff ----------------------------------------------------------------

def check(root: str) -> List[Violation]:
    host = os.path.join(root, HOST_PATH)
    cpp = os.path.join(root, CPP_PATH)
    if not (os.path.isfile(host) and os.path.isfile(cpp)):
        return []  # partial tree (fixture layouts): nothing to diff
    host_rel = HOST_PATH.replace(os.sep, "/")
    cpp_rel = CPP_PATH.replace(os.sep, "/")

    py = python_schema(host)
    cc = cpp_schema(cpp)
    out: List[Violation] = []
    for line, msg in py.errors:
        out.append(Violation(CHECKER, host_rel, line, msg))
    for line, msg in cc.errors:
        out.append(Violation(CHECKER, cpp_rel, line, msg))
    if py.errors or cc.errors:
        return out

    if py.fields != cc.fields:
        out.append(Violation(
            CHECKER, host_rel, py.lines.get("fmt", 1),
            f"Python fixed-field sequence {''.join(py.fields)} != C++ "
            f"{''.join(cc.fields)} (transport.cpp encode_head/decode_head) "
            f"— the two decoders will misparse each other's frames"))
    if py.head_size is not None and cc.head_size is not None and \
            py.head_size != cc.head_size:
        out.append(Violation(
            CHECKER, host_rel, py.lines.get("fmt", 1),
            f"HeaderCodec.HEAD_SIZE={py.head_size} but transport.cpp reads "
            f"a head[{cc.head_size}] fixed prefix — framing offset drift"))
    for pyname, cppname in _CONST_PAIRS:
        if pyname in py.consts and cppname in cc.consts and \
                py.consts[pyname] != cc.consts[cppname]:
            out.append(Violation(
                CHECKER, host_rel, py.lines.get(pyname, 1),
                f"{pyname}={py.consts[pyname]:#x} != transport.cpp "
                f"{cppname}={cc.consts[cppname]:#x} — shared wire constant "
                f"drifted"))
        elif pyname not in py.consts:
            out.append(Violation(
                CHECKER, host_rel, 1,
                f"{pyname} constant not found in comm/host.py — the wire "
                f"checker has lost an anchor"))
        elif cppname not in cc.consts:
            out.append(Violation(
                CHECKER, cpp_rel, 1,
                f"{cppname} constant not found in transport.cpp — the wire "
                f"checker has lost an anchor"))
    return sorted(out, key=lambda v: (v.path, v.line))
