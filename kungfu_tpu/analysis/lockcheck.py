"""lock-discipline checker for the native C++ transport.

Fields annotated ``// guarded_by(<mutex>)`` at their declaration may
only be *written* in a scope that holds that mutex via
``std::lock_guard`` / ``std::unique_lock``.  This is a structural
checker, not a compiler: it tracks brace depth line by line, records
lock acquisitions for the lifetime of their enclosing block, and flags
writes (assignment, compound assignment, increment/decrement,
``operator[]``, and mutating container calls) to annotated fields made
while the declared mutex is not among the held set.

Explicit ``lk.unlock()`` / ``lk.lock()`` windows on a ``unique_lock``
ARE tracked (line granularity): a write between an unlock and the
relock is flagged.

Known limits (by design — keep the checker simple and the code honest):

* writes through iterators/pointers into a container are invisible;
* an ``if { unlock(); }`` branch that falls through (rather than
  returning) is treated as re-locked after the brace;
* a scope whose safety comes from declaration *order* (RAII guard
  destructors running while another unique_lock is still alive), from
  single ownership (a buffer provably unreachable by other threads
  during an unlock window), or from being provably single-threaded
  (constructors, join points) carries an explicit
  ``// kflint: allow(lock-discipline)`` with a comment, so the
  invariant is documented exactly where it is subtle.

Mutex and field references are normalized to their terminal component:
``ch->q_mu_`` and ``q_mu_`` are the same lock, ``entry->fd_mu`` and
``e->fd_mu`` likewise — the transport never holds two instances' locks
of the same name simultaneously except PoolEntry handoffs, which take
only their own.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from kungfu_tpu.analysis.core import (
    Violation,
    iter_cpp_files,
    read_lines,
    relpath,
    suppressed,
    suppressions,
)

CHECKER = "lock-discipline"

_ANNOT_RE = re.compile(r"//\s*guarded_by\((\w+)\)")
_DECL_RE = re.compile(r"\b([A-Za-z_]\w*)\s*(?:=[^=;]*|\{[^}]*\})?\s*;")
_LOCK_RE = re.compile(
    r"\b(?:std::)?(?:lock_guard|unique_lock)\s*<[^>]*>\s*(\w+)\s*[({]\s*"
    r"([\w.>:\-]+?)\s*[)}]"
)
_UNLOCK_RE = re.compile(r"\b(\w+)\s*\.\s*unlock\s*\(\s*\)")
_RELOCK_RE = re.compile(r"\b(\w+)\s*\.\s*lock\s*\(\s*\)")
_MUTATORS = (
    "push_back|pop_front|pop_back|clear|erase|emplace|emplace_back|"
    "insert|resize|swap|assign"
)


def _strip_comment(line: str) -> str:
    # good enough for this tree: no multi-line /* */ in statement position
    i = line.find("//")
    return line if i < 0 else line[:i]


def _terminal(expr: str) -> str:
    return re.split(r"->|\.", expr)[-1].strip()


def _field_annotations(lines: List[str]) -> Dict[str, Tuple[str, int]]:
    """``{field: (mutex, decl line)}`` from guarded_by comments."""
    out: Dict[str, Tuple[str, int]] = {}
    for i, line in enumerate(lines, 1):
        m = _ANNOT_RE.search(line)
        if not m:
            continue
        decl = _strip_comment(line)
        d = _DECL_RE.search(decl)
        if d:
            out[d.group(1)] = (m.group(1), i)
    return out


def _write_patterns(field: str) -> List[re.Pattern]:
    f = re.escape(field)
    return [
        re.compile(r"\b" + f + r"\s*=(?!=)"),           # assignment
        re.compile(r"\b" + f + r"\s*(\+=|-=|\|=|&=|\^=)"),
        re.compile(r"\b" + f + r"\s*(\+\+|--)"),
        re.compile(r"(\+\+|--)\s*(\w+\s*->\s*)?" + f + r"\b"),
        re.compile(r"\b" + f + r"\s*\["),               # map operator[]
        re.compile(r"\b" + f + r"\s*\.\s*(?:" + _MUTATORS + r")\b"),
    ]


def _scan_file(root: str, path: str) -> List[Violation]:
    lines = read_lines(path)
    annots = _field_annotations(lines)
    if not annots:
        return []
    supp = suppressions(lines)
    patterns = {f: _write_patterns(f) for f in annots}
    decl_lines = {line for _, line in annots.values()}
    out: List[Violation] = []

    depth = 0
    # (decl depth, mutex, guard var, active) — `lk.unlock()` deactivates
    # an entry, `lk.lock()` reactivates it, scope exit drops it
    held: List[List] = []
    for i, raw in enumerate(lines, 1):
        code = _strip_comment(raw)
        # locks declared on this line are active from here to the end of
        # the enclosing block (RAII); the declaration depth counts any
        # `{` earlier on the same line, so `{ lock_guard lk(mu); ... }`
        # one-liners expire at their own closing brace
        for m in _LOCK_RE.finditer(code):
            decl_depth = depth + code[:m.start()].count("{") \
                - code[:m.start()].count("}")
            held.append([decl_depth, _terminal(m.group(2)), m.group(1), True])
        # explicit unlock/relock windows on a unique_lock: applied before
        # the write checks, so `lk.unlock(); x_ = 1;` on one line flags
        # (the conservative direction for a gate).  The deactivation is
        # scoped to the block it happens in: when that block exits the
        # lock is considered re-held — an `unlock(); return;` branch is
        # gone on the fall-through path (an `if { unlock } fallthrough`
        # that does NOT return is the one shape this misses; see module
        # docstring limits)
        for m in _UNLOCK_RE.finditer(code):
            unlock_depth = depth + code[:m.start()].count("{") \
                - code[:m.start()].count("}")
            for entry in held:
                if entry[2] == m.group(1):
                    entry[3] = False
                    entry.append(unlock_depth)  # -> entry[4]
        for m in _RELOCK_RE.finditer(code):
            for entry in held:
                if entry[2] == m.group(1):
                    entry[3] = True
                    del entry[4:]
        if i not in decl_lines:
            held_set = {e[1] for e in held if e[3]}
            for field, (mutex, _) in annots.items():
                if suppressed(supp, i, CHECKER):
                    continue
                for pat in patterns[field]:
                    if pat.search(code):
                        if mutex not in held_set:
                            out.append(Violation(
                                CHECKER, relpath(root, path), i,
                                f"write to `{field}` (guarded_by {mutex}) "
                                f"without {mutex} held "
                                f"(held: {sorted(held_set) or 'none'})",
                            ))
                        break
        # update depth AFTER checking the line; a lock declared at depth
        # d dies when depth drops below d (its enclosing block closed)
        depth += code.count("{") - code.count("}")
        held = [e for e in held if depth >= e[0]]
        for e in held:
            if not e[3] and len(e) > 4 and depth < e[4]:
                e[3] = True  # the unlocking block exited
                del e[4:]
    return out


def check(root: str) -> List[Violation]:
    out: List[Violation] = []
    for path in iter_cpp_files(root):
        out.extend(_scan_file(root, path))
    return out
