"""Shared plumbing for the kf-lint checkers.

A checker is a callable ``check(root) -> list[Violation]``.  Suppression
is per-line: a trailing ``# kflint: allow(<rule>)`` (Python) or
``// kflint: allow(<rule>)`` (C++) comment on the flagged line silences
that rule there — and ONLY there, so every waiver is visible in the diff
that introduces it.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: directories under the repo root that the tree-wide checkers scan
PY_SCAN_DIRS = ("kungfu_tpu", "scripts", "benchmarks", "examples")

#: single top-level files in scan scope (the driver entry point compiles
#: sharded steps like any module and must obey the same invariants)
PY_SCAN_FILES = ("__graft_entry__.py",)

_SUPPRESS_RE = re.compile(r"(?:#|//)\s*kflint:\s*allow\(([a-z0-9_,\s-]+)\)")


@dataclass(frozen=True)
class Violation:
    checker: str
    path: str  # repo-root relative
    line: int  # 1-based
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


def repo_root(start: str = None) -> str:
    """The tree to lint: the directory holding the ``kungfu_tpu``
    package (walks up from ``start`` / this file)."""
    d = os.path.abspath(start or os.path.dirname(__file__))
    while True:
        if os.path.isdir(os.path.join(d, "kungfu_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            raise RuntimeError("cannot locate repo root (no kungfu_tpu/)")
        d = parent


def iter_py_files(root: str, dirs: Iterable[str] = PY_SCAN_DIRS,
                  files: Optional[Iterable[str]] = None) -> Iterable[str]:
    if files is None:
        # top-level scan files ride the DEFAULT full-tree scan only — a
        # caller narrowing `dirs` (blocking-io scans just the package)
        # must not silently regain them
        files = PY_SCAN_FILES if dirs is PY_SCAN_DIRS else ()
    for base in dirs:
        top = os.path.join(root, base)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)
    for fn in files:
        path = os.path.join(root, fn)
        if os.path.isfile(path):
            yield path


def iter_cpp_files(root: str) -> Iterable[str]:
    native = os.path.join(root, "kungfu_tpu", "native")
    if not os.path.isdir(native):
        return
    for fn in sorted(os.listdir(native)):
        if fn.endswith((".cpp", ".cc", ".h", ".hpp")):
            yield os.path.join(native, fn)


@dataclass
class ParsedModule:
    """One source file, parsed once per run and shared by every rule.

    ``tree`` is None for non-Python sources and for files whose parse
    failed (``error`` then carries the SyntaxError).  ``supp`` is the
    per-line ``kflint: allow(...)`` map, computed once alongside.
    """

    path: str
    source: str
    lines: List[str]
    tree: Optional[ast.AST]
    error: Optional[SyntaxError]
    supp: Dict[int, Set[str]] = field(default_factory=dict)


#: abspath -> (stat key, ParsedModule).  The stat key (mtime_ns, size)
#: invalidates rewrites naturally — and ONE entry per path means a
#: rewritten file replaces its stale parse instead of accumulating
#: historical versions for the process lifetime.
_MODULE_CACHE: Dict[str, Tuple[Tuple[int, int], ParsedModule]] = {}

#: abspath -> number of real ast.parse() calls this process made for it;
#: the single-parse test asserts this stays at 1 per file per run
PARSE_COUNTS: Dict[str, int] = {}


def clear_parse_cache() -> None:
    """Tests that count parses (or rewrite files in place) call this.
    Cascades through the derived caches (call graph, axis environment)
    — they are built FROM these parses and would serve stale analysis
    otherwise."""
    _MODULE_CACHE.clear()
    PARSE_COUNTS.clear()
    from kungfu_tpu.analysis import callgraph

    callgraph.invalidate_cache()


def parse_module(path: str) -> ParsedModule:
    """The cached (source, lines, AST, suppressions) view of ``path``.

    Every checker goes through here instead of open()+ast.parse() so a
    full kflint pass parses each file exactly once (the suite re-parsed
    per checker before; at thirteen rules that was the dominant cost).
    """
    abspath = os.path.abspath(path)
    st = os.stat(abspath)
    key = (st.st_mtime_ns, st.st_size)
    hit = _MODULE_CACHE.get(abspath)
    if hit is not None and hit[0] == key:
        return hit[1]
    with open(abspath, "r", encoding="utf-8", errors="replace") as f:
        source = f.read()
    lines = source.splitlines()
    tree = error = None
    if abspath.endswith(".py"):
        PARSE_COUNTS[abspath] = PARSE_COUNTS.get(abspath, 0) + 1
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            error = e
    mod = ParsedModule(
        path=abspath, source=source, lines=lines, tree=tree, error=error,
        supp=suppressions(lines),
    )
    _MODULE_CACHE[abspath] = (key, mod)
    return mod


def read_lines(path: str) -> List[str]:
    return parse_module(path).lines


def suppressions(lines: List[str]) -> Dict[int, Set[str]]:
    """``{1-based line: {rule, ...}}`` for every kflint allow comment."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def suppressed(supp: Dict[int, Set[str]], line: int, rule: str) -> bool:
    rules = supp.get(line)
    return bool(rules) and (rule in rules or "all" in rules)


def relpath(root: str, path: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def terminal_name(node: ast.AST) -> Optional[str]:
    """The rightmost identifier of a Name/Attribute chain:
    ``jax.lax.psum`` -> "psum", ``shard_map`` -> "shard_map", else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None
