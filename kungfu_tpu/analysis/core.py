"""Shared plumbing for the kf-lint checkers.

A checker is a callable ``check(root) -> list[Violation]``.  Suppression
is per-line: a trailing ``# kflint: allow(<rule>)`` (Python) or
``// kflint: allow(<rule>)`` (C++) comment on the flagged line silences
that rule there — and ONLY there, so every waiver is visible in the diff
that introduces it.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set

#: directories under the repo root that the tree-wide checkers scan
PY_SCAN_DIRS = ("kungfu_tpu", "scripts", "benchmarks")

_SUPPRESS_RE = re.compile(r"(?:#|//)\s*kflint:\s*allow\(([a-z0-9_,\s-]+)\)")


@dataclass(frozen=True)
class Violation:
    checker: str
    path: str  # repo-root relative
    line: int  # 1-based
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


def repo_root(start: str = None) -> str:
    """The tree to lint: the directory holding the ``kungfu_tpu``
    package (walks up from ``start`` / this file)."""
    d = os.path.abspath(start or os.path.dirname(__file__))
    while True:
        if os.path.isdir(os.path.join(d, "kungfu_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            raise RuntimeError("cannot locate repo root (no kungfu_tpu/)")
        d = parent


def iter_py_files(root: str, dirs: Iterable[str] = PY_SCAN_DIRS) -> Iterable[str]:
    for base in dirs:
        top = os.path.join(root, base)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def iter_cpp_files(root: str) -> Iterable[str]:
    native = os.path.join(root, "kungfu_tpu", "native")
    if not os.path.isdir(native):
        return
    for fn in sorted(os.listdir(native)):
        if fn.endswith((".cpp", ".cc", ".h", ".hpp")):
            yield os.path.join(native, fn)


def read_lines(path: str) -> List[str]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return f.read().splitlines()


def suppressions(lines: List[str]) -> Dict[int, Set[str]]:
    """``{1-based line: {rule, ...}}`` for every kflint allow comment."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def suppressed(supp: Dict[int, Set[str]], line: int, rule: str) -> bool:
    rules = supp.get(line)
    return bool(rules) and (rule in rules or "all" in rules)


def relpath(root: str, path: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def terminal_name(node: ast.AST) -> Optional[str]:
    """The rightmost identifier of a Name/Attribute chain:
    ``jax.lax.psum`` -> "psum", ``shard_map`` -> "shard_map", else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None
