"""collective-consistency checker: SPMD protocol divergence at lint time.

Every peer must issue the same host-plane collectives under the same
rendezvous names in the same order — the adaptation paths (resize,
set_tree, shrink) are exactly where one rank's extra/missing collective
turns into a cluster-wide hang that no single-process unit test can see
(MLPerf-scale TPU work reports collective mismatch as the dominant
at-scale failure mode; arXiv:1909.09756, arXiv:2011.03641).  Built on
the shared :mod:`kungfu_tpu.analysis.callgraph`, three divergence shapes
are flagged:

* **rank-conditional collective** — a collective call lexically under an
  ``if`` whose test reads a rank (``peer.rank()``, ``me == 0``, ...),
  with no matching same-(op, name) call elsewhere in the function to
  balance the other side.  The symmetric split
  (``if rank == 0: broadcast(x) ... else: broadcast(None)``) has two
  matching sites and passes; the asymmetric one hangs every other rank.
  The same check runs **interprocedurally**: a helper that issues
  collectives and is *called* only under rank-conditional branches is
  flagged at its call sites.
* **rendezvous name reuse** — two distinct call sites issuing the same
  op under the same *constant* name.  Two concurrent paths that both hit
  ``barrier(peers, name="sync")`` alias each other's messages; names
  must be versioned or site-unique (the tree's idiom:
  ``f"...v{cluster_version}"``).
* **divergent name expression** — a rendezvous name built from
  local-only state (``time.time()``, ``random``, ``uuid``, ``getpid``,
  ``rank()``): peers compute different names and the collective never
  rendezvouses.  Names must derive from cluster-agreed state (version
  counters, consensus payload digests).

``kungfu_tpu/comm/`` is out of scope — it *implements* the collectives,
so its internal rank branching is the protocol, not a violation.
Suppress a deliberate exception with
``# kflint: allow(collective-consistency)`` on the call line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from kungfu_tpu.analysis.callgraph import (
    CallSite,
    FuncInfo,
    project_graph,
)
from kungfu_tpu.analysis.core import (
    Violation,
    parse_module,
    suppressed,
)

CHECKER = "collective-consistency"

#: host-plane collective primitives (every peer must call in lockstep)
COLLECTIVE_OPS = {
    "barrier", "world_barrier", "consensus_bytes",
    "gather_bytes", "broadcast_bytes", "allgather_bytes",
}

#: positional index of the rendezvous-name argument per op (call-site
#: args, receiver excluded); kwarg ``name=`` always wins
_NAME_POS = {
    "gather_bytes": 2, "broadcast_bytes": 2, "allgather_bytes": 2,
    "consensus_bytes": 2, "barrier": 1, "world_barrier": 0,
}

#: modules whose paths start with these prefixes implement the ops
_IMPL_PREFIXES = ("kungfu_tpu/comm/", "kungfu_tpu/analysis/")

#: call terminals inside a name expression that diverge across peers
_DIVERGENT_CALLS = {
    "time", "monotonic", "perf_counter", "time_ns", "random", "randint",
    "randrange", "uniform", "urandom", "uuid1", "uuid4", "getpid",
    "gethostname", "id", "rank", "local_rank",
}

#: identifiers in an ``if`` test that read a rank
_RANK_CALLS = {"rank", "local_rank", "chaos_rank"}
_RANK_NAMES = {"me", "my_rank", "self_rank"}


def _is_rank_test(test: ast.AST) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            f = n.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if name in _RANK_CALLS or (name or "").startswith("_rank"):
                return True
        elif isinstance(n, ast.Name):
            if n.id in _RANK_NAMES or "rank" in n.id.lower():
                return True
        elif isinstance(n, ast.Attribute):
            if "rank" in n.attr.lower():
                return True
    return False


def _name_expr(site: CallSite) -> Optional[ast.AST]:
    for kw in site.node.keywords:
        if kw.arg == "name":
            return kw.value
    pos = _NAME_POS.get(site.callee)
    if pos is not None and len(site.node.args) > pos:
        return site.node.args[pos]
    # peer-level consensus_bytes(data, name) has the name one slot early
    if site.callee == "consensus_bytes" and len(site.node.args) == 2:
        return site.node.args[1]
    return None


def _name_key(expr: Optional[ast.AST]) -> str:
    return ast.dump(expr) if expr is not None else ""


def _const_name(expr: Optional[ast.AST]) -> Optional[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    return None


def _collective_sites(func: FuncInfo) -> List[CallSite]:
    return [s for s in func.calls if s.callee in COLLECTIVE_OPS]


def _rank_conditional(site: CallSite) -> Optional[int]:
    """Line of the innermost rank-dependent enclosing branch, else None."""
    for b in reversed(site.branches):
        if _is_rank_test(b.test):
            return b.line
    return None


def _in_scope(func: FuncInfo) -> bool:
    return not any(func.path.startswith(p) for p in _IMPL_PREFIXES)


def check(root: str) -> List[Violation]:
    graph = project_graph(root)
    out: List[Violation] = []
    supp_cache: Dict[str, Dict[int, set]] = {}

    def supp_for(path: str) -> Dict[int, set]:
        if path not in supp_cache:
            import os

            supp_cache[path] = parse_module(os.path.join(root, path)).supp
        return supp_cache[path]

    def flag(path: str, line: int, msg: str) -> None:
        if not suppressed(supp_for(path), line, CHECKER):
            out.append(Violation(CHECKER, path, line, msg))

    # -- rank-conditional collectives (intra-function) --------------------
    for func in graph.functions:
        if not _in_scope(func):
            continue
        sites = _collective_sites(func)
        if not sites:
            continue
        # multiset of (op, name) occurrences in this function: a pair of
        # matching sites across the two sides of a rank split is the
        # symmetric root/leaf idiom and passes
        counts: Dict[Tuple[str, str], int] = {}
        for s in sites:
            key = (s.callee, _name_key(_name_expr(s)))
            counts[key] = counts.get(key, 0) + 1
        for s in sites:
            cond_line = _rank_conditional(s)
            if cond_line is None:
                continue
            if counts[(s.callee, _name_key(_name_expr(s)))] >= 2:
                continue
            flag(func.path, s.line,
                 f"collective `{s.callee}` issued only under the "
                 f"rank-conditional branch at line {cond_line} — peers on "
                 f"the other side never rendezvous (SPMD divergence hang)")

    # -- rank-conditional collectives (interprocedural) -------------------
    # a helper that issues collectives, reached ONLY through
    # rank-conditional call sites, diverges exactly like the inline form
    for func in graph.functions:
        if not _in_scope(func) or not _collective_sites(func):
            continue
        callers = graph.callers_of(func)
        if not callers:
            continue
        cond = [(f, s, _rank_conditional(s)) for f, s in callers]
        if any(line is None for _, _, line in cond):
            continue  # at least one unconditional path balances it
        # a caller with >= 2 call sites to this helper is the symmetric
        # root/leaf split (every branch of the rank test calls it) —
        # same balancing logic as the intra-function rule
        per_caller: Dict[str, int] = {}
        for caller, _, _ in cond:
            per_caller[caller.qualname] = per_caller.get(
                caller.qualname, 0) + 1
        for caller, site, line in cond:
            if not _in_scope(caller):
                continue
            if per_caller[caller.qualname] >= 2:
                continue
            flag(caller.path, site.line,
                 f"`{func.name}` issues collectives but is called only "
                 f"under rank-conditional branches (this one at line "
                 f"{line}) — non-matching ranks never issue them")

    # -- constant-name reuse across sites ---------------------------------
    # same-FUNCTION repeats are the symmetric root/leaf split (the
    # rank-conditional rule's balanced pair) and are exempt; reuse is
    # flagged across functions, where the paths really are concurrent
    seen: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for func in graph.functions:
        if not _in_scope(func):
            continue
        for s in _collective_sites(func):
            cname = _const_name(_name_expr(s))
            if cname is None:
                continue
            key = (s.callee, cname)
            prev = seen.get(key)
            if prev is None:
                seen[key] = (func.path, s.line, func.qualname)
            elif prev[2] != func.qualname:
                flag(func.path, s.line,
                     f"rendezvous name {cname!r} for `{s.callee}` is "
                     f"reused from {prev[0]}:{prev[1]} — concurrent paths "
                     f"would alias each other's messages; version the "
                     f"name or make it site-unique")

    # -- divergent name expressions ---------------------------------------
    for func in graph.functions:
        if not _in_scope(func):
            continue
        for s in _collective_sites(func):
            expr = _name_expr(s)
            if expr is None or isinstance(expr, ast.Constant):
                continue
            for n in ast.walk(expr):
                if not isinstance(n, ast.Call):
                    continue
                f = n.func
                t = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None
                )
                if t in _DIVERGENT_CALLS:
                    flag(func.path, s.line,
                         f"rendezvous name for `{s.callee}` is built from "
                         f"`{t}()` — a local-only value that diverges "
                         f"across peers, so the collective never "
                         f"rendezvouses; derive names from cluster-agreed "
                         f"state (version counters, payload digests)")
                    break

    return sorted(out, key=lambda v: (v.path, v.line))
