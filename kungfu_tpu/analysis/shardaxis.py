"""shard-axis checker: every collective's axis name must be bound.

A ``psum``/``ppermute``/``axis_index`` over a mesh-axis name that is
not bound where the code runs does not fail in review, in unit tests on
one device, or even on a small mesh that happens to bind the name — it
fails at trace time on the pod, or worse, silently reduces over the
wrong axis group (the dominant sharding-bug class of the multislice /
ZeRO arc; cf. arXiv:2004.13336, arXiv:1909.09756).  Built on the
:mod:`~kungfu_tpu.analysis.axisenv` abstract interpretation, two layers:

* **vocabulary** — a literal axis name (string constant, or a constant
  resolving through the project constant table: ``AXIS_TP``,
  ``GLOBAL_AXES``, ...) passed to any collective — the ``jax.lax``
  primitives AND the project wrappers in :mod:`kungfu_tpu.ops` /
  :mod:`kungfu_tpu.comm.device` / :mod:`kungfu_tpu.utils.jaxcompat` —
  must be an axis some ``Mesh``/``pmap`` in the tree declares.  A
  one-token typo (``"tq"`` for ``"tp"``) is caught anywhere, even in a
  helper whose calling context is unknown.
* **environment** — where the function's axis environment is statically
  known (it is a ``shard_map``/``pmap`` body with a resolved mesh, or
  reached only from such bodies through the call graph), the axis must
  be bound in EVERY context the function can run under.  Contexts are
  per-path, so a helper called from two meshes with different axis sets
  is checked against each — an axis valid under mesh A is still flagged
  for the mesh-B path.  Unresolved meshes yield *open* contexts, which
  never prove absence: indirection loses recall, not precision.

String arguments that are reduce-op names (``"sum"``, ``"mean"``, ...)
are never axis names and are skipped — ``Communicator.all_reduce(x,
"max")`` shares a terminal name with the axis-taking ops wrapper.
Axis-parameter *defaults* (``def ring_attention(..., axis="sp")``) are
checked against the vocabulary only (each caller supplies the context).
Suppress a deliberate exception with ``# kflint: allow(shard-axis)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from kungfu_tpu.analysis.axisenv import axis_environment
from kungfu_tpu.analysis.core import (
    Violation,
    parse_module,
    suppressed,
)

CHECKER = "shard-axis"

#: collective terminal name -> (positional axis-arg index, kwarg names).
#: Covers the jax.lax primitives and the project wrappers (ops/,
#: comm/device.py, utils/jaxcompat.py).  Values that evaluate to ints
#: (lax.all_gather's ``axis=0`` DIMENSION kwarg, Communicator.broadcast's
#: ``root``) are ignored — only string-valued arguments are axis names.
AXIS_ARGS: Dict[str, Tuple[int, Tuple[str, ...]]] = {
    # jax.lax primitives
    "psum": (1, ("axis_name",)),
    "pmean": (1, ("axis_name",)),
    "pmax": (1, ("axis_name",)),
    "pmin": (1, ("axis_name",)),
    "psum_scatter": (1, ("axis_name",)),
    "ppermute": (1, ("axis_name",)),
    "pshuffle": (1, ("axis_name",)),
    "pbroadcast": (1, ("axis_name",)),
    "all_to_all": (1, ("axis_name",)),
    "axis_index": (0, ("axis_name",)),
    "axis_size": (0, ("axis_name",)),
    "all_gather": (1, ("axis_name", "axis")),
    # project wrappers (kungfu_tpu.ops.collective / .schedules,
    # utils/jaxcompat)
    "all_reduce": (1, ("axis",)),
    "group_all_reduce": (1, ("axis",)),
    "all_reduce_scheduled": (1, ("axis",)),
    "broadcast": (1, ("axis",)),
    "barrier_value": (0, ("axis",)),
    "peer_rank": (0, ("axis",)),
    "peer_size": (0, ("axis",)),
    "pcast_varying": (1, ("axes",)),
    # the Pallas ICI ring collectives (ops/pallas/collectives.py): the
    # axis name threads through pallas_call kernels under shard_map —
    # a typo'd literal here fails at trace time on the pod exactly like
    # a lax primitive's would
    "ring_reduce_scatter": (1, ("axis",)),
    "ring_all_gather": (1, ("axis",)),
    "ring_all_reduce": (1, ("axis",)),
}

#: strings that are reduce-op selectors sharing call slots with axis
#: names — never axis names
_OP_NAMES = {"sum", "mean", "min", "max", "prod"}

#: the analysis suite itself names axes in its tables
_SKIP_PREFIXES = ("kungfu_tpu/analysis/",)

#: parameter names whose string default is an axis name
_AXIS_PARAMS = {"axis", "axis_name", "axes"}


def _axis_exprs(site) -> List[ast.AST]:
    """Every argument that may carry the axis name.  all_gather takes
    BOTH an `axis_name` and an int `axis` DIMENSION kwarg — first-match
    would let `axis=0` shadow a typo'd positional name, so all
    candidates are checked (non-string values skip themselves)."""
    pos, kwargs = AXIS_ARGS[site.callee]
    out = [kw.value for kw in site.node.keywords if kw.arg in kwargs]
    if len(site.node.args) > pos:
        out.append(site.node.args[pos])
    return out


def check(root: str) -> List[Violation]:
    import os

    env = axis_environment(root)
    out: List[Violation] = []
    supp_cache: Dict[str, Dict[int, set]] = {}

    def flag(path: str, line: int, msg: str) -> None:
        if path not in supp_cache:
            supp_cache[path] = parse_module(os.path.join(root, path)).supp
        if not suppressed(supp_cache[path], line, CHECKER):
            out.append(Violation(CHECKER, path, line, msg))

    vocab = env.vocabulary

    def check_axes(func, line: int, callee: str,
                   axes: Tuple[str, ...]) -> None:
        for a in axes:
            if a in _OP_NAMES:
                continue
            if a not in vocab:
                flag(func.path, line,
                     f"collective `{callee}` names axis {a!r}, which no "
                     f"Mesh/pmap in the tree declares (known axes: "
                     f"{sorted(vocab)}) — this fails at trace time on "
                     f"the pod")
                continue
            for ctx, prov in env.contexts_of(func).items():
                if not ctx.open and a not in ctx.axes:
                    flag(func.path, line,
                         f"collective `{callee}` uses axis {a!r}, not "
                         f"bound in the axis environment "
                         f"{{{', '.join(sorted(ctx.axes)) or ''}}} this "
                         f"code runs under (entered via {prov})")
                    break

    for func in env.graph.functions:
        if any(func.path.startswith(p) for p in _SKIP_PREFIXES):
            continue
        # collective call sites
        for site in func.calls:
            if site.callee not in AXIS_ARGS:
                continue
            for expr in _axis_exprs(site):
                axes = env.axis_strings(func, expr)
                if not axes:
                    continue  # dynamic / non-string: callers carry it
                check_axes(func, site.line, site.callee, axes)
        # axis-parameter string defaults (vocabulary layer only)
        a = func.node.args
        params = list(a.posonlyargs) + list(a.args)
        defaults = list(a.defaults)
        for p, d in zip(params[len(params) - len(defaults):], defaults):
            if p.arg not in _AXIS_PARAMS:
                continue
            axes = env.axis_strings(func, d)
            if not axes:
                continue
            for ax in axes:
                if ax not in vocab and ax not in _OP_NAMES:
                    flag(func.path, d.lineno,
                         f"default axis {ax!r} of `{func.name}({p.arg}=...)`"
                         f" is not declared by any Mesh/pmap in the tree "
                         f"(known axes: {sorted(vocab)})")

    return sorted(out, key=lambda v: (v.path, v.line))
