"""blocking-io checker: no unbounded blocking calls in threaded modules.

Scope: any module under ``kungfu_tpu`` that starts (or subclasses)
``threading.Thread``, plus modules whose code runs on channel/runner
background threads without spawning them locally
(``comm/engine.py``, ``runner/watch.py``).  In those modules a blocking
call with no timeout can wedge a daemon thread forever — the recent
transport racing-send hang and teardown use-after-free were exactly
this shape, caught after the fact.

Flagged when no ``timeout=`` is passed:

* ``urllib.request.urlopen`` / ``socket.create_connection``
  (positional timeout counts for the latter)
* ``.get()`` / ``.put()`` on objects constructed from ``queue.Queue``
  in the module (``block=False`` also satisfies the rule)
* ``.accept()`` / ``.recv()`` / ``.recvfrom()`` on anything — socket
  reads and channel receives both hang without a deadline
* ``subprocess.run`` / ``check_output`` / ``check_call`` /
  ``.communicate()`` / ``.wait()`` on process objects

A deliberate forever-block (e.g. a sentinel-terminated worker loop)
carries ``# kflint: allow(blocking-io)`` with a comment saying why.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from kungfu_tpu.analysis.core import (
    Violation,
    iter_py_files,
    parse_module,
    relpath,
    suppressed,
    terminal_name as _terminal,
)

CHECKER = "blocking-io"

#: modules whose handlers run on background threads owned elsewhere.
#: The serve modules spawn threads today (auto-detected), but their
#: channel handlers ALSO run on the host channel's receive threads —
#: pinned here so a refactor that moves the spawns out cannot silently
#: drop the rule from the serving plane
EXTRA_THREAD_MODULES = {
    "kungfu_tpu/comm/engine.py",
    "kungfu_tpu/runner/watch.py",
    "kungfu_tpu/serve/engine.py",
    "kungfu_tpu/serve/router.py",
}

_SUBPROCESS_FNS = {"run", "check_output", "check_call"}
_SOCKETish_METHODS = {"accept", "recv", "recvfrom"}


def _has_kw(node: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in node.keywords)


def _queue_call_bounded(node: ast.Call, method: str) -> bool:
    """True when a Queue.get/put call cannot block forever.  Signature
    ``get(block=True, timeout=None)`` / ``put(item, block=True,
    timeout=None)``: keyword block/timeout counts, and so do the legal
    POSITIONAL forms — ``get(False)``, ``get(True, 5.0)``,
    ``put(x, False)``, ``put(x, True, 2.0)``."""
    if _has_kw(node, "timeout") or _has_kw(node, "block"):
        return True
    pos = node.args if method == "get" else node.args[1:]
    if len(pos) >= 2:
        return True  # explicit positional timeout
    if len(pos) == 1:
        # block given positionally: only a literal False is provably
        # non-blocking; `get(True)` still blocks forever
        a = pos[0]
        return isinstance(a, ast.Constant) and a.value is False
    return False


def _spawns_threads(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _terminal(node.func) == "Thread":
            return True
        if isinstance(node, ast.ClassDef):
            for base in node.bases:
                if _terminal(base) == "Thread":
                    return True
    return False


def _queue_names(tree: ast.AST) -> Dict[str, bool]:
    """``{terminal name: bounded}`` for variables/attributes bound from
    queue.Queue().  ``put()`` can only block on a BOUNDED queue
    (``maxsize > 0``); ``get()`` blocks on any queue."""
    names: Dict[str, bool] = {}
    for node in ast.walk(tree):
        value = getattr(node, "value", None)
        if not (isinstance(value, ast.Call)
                and _terminal(value.func) in ("Queue", "SimpleQueue", "LifoQueue")):
            continue
        bounded = bool(value.args) or any(
            kw.arg == "maxsize" for kw in value.keywords)
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.target is not None:
            targets = [node.target]
        for t in targets:
            name = _terminal(t)
            if name:
                names[name] = bounded
    return names


def _scan_module(root: str, path: str) -> List[Violation]:
    mod = parse_module(path)
    tree = mod.tree
    if tree is None:
        return []
    rel = relpath(root, path)
    if not _spawns_threads(tree) and rel not in EXTRA_THREAD_MODULES:
        return []
    supp = mod.supp
    queues = _queue_names(tree)
    out: List[Violation] = []

    def flag(node: ast.Call, what: str) -> None:
        if not suppressed(supp, node.lineno, CHECKER):
            out.append(Violation(CHECKER, rel, node.lineno, what))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = _terminal(fn)
        if name == "urlopen" and not _has_kw(node, "timeout"):
            flag(node, "urlopen() without timeout= can hang the thread")
        elif name == "create_connection":
            if not _has_kw(node, "timeout") and len(node.args) < 2:
                flag(node, "create_connection() without a timeout")
        elif name in _SUBPROCESS_FNS and isinstance(fn, ast.Attribute) \
                and _terminal(fn.value) == "subprocess" \
                and not _has_kw(node, "timeout"):
            flag(node, f"subprocess.{name}() without timeout=")
        elif isinstance(fn, ast.Attribute):
            recv_name = _terminal(fn.value)
            if fn.attr in ("get", "put") and recv_name in queues:
                if fn.attr == "put" and not queues[recv_name]:
                    pass  # unbounded queue: put() never blocks
                elif not _queue_call_bounded(node, fn.attr):
                    flag(node, f"queue .{fn.attr}() without timeout= "
                               "blocks its thread forever")
            elif fn.attr in _SOCKETish_METHODS and not _has_kw(node, "timeout"):
                # sockets have no timeout kwarg at all — a read deadline
                # comes from settimeout(); restrict to receivers that are
                # recognizably sockets so channel recv() (whose *default*
                # timeout is bounded) stays out of scope
                if fn.attr == "accept" or (
                    recv_name and ("sock" in recv_name.lower()
                                   or recv_name.lower() in ("conn", "client"))
                ):
                    flag(node, f".{fn.attr}() on a socket without a "
                               "deadline (set settimeout, or suppress for "
                               "a connection-lifetime reader)")
            elif fn.attr in ("communicate", "wait") \
                    and recv_name not in (None, "self") \
                    and "popen" in (recv_name or "").lower() \
                    and not _has_kw(node, "timeout"):
                flag(node, f"process .{fn.attr}() without timeout=")
    return out


def check(root: str) -> List[Violation]:
    out: List[Violation] = []
    for path in iter_py_files(root, dirs=("kungfu_tpu",)):
        out.extend(_scan_module(root, path))
    return out
