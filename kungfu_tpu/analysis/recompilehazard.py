"""recompile-hazard checker: no membership constants baked into jit.

Elastic resize is this framework's core maneuver — and its quietest
failure mode is compiled code that froze the OLD world into itself.
Anything cluster-size-shaped that reaches a traced body as a Python
value becomes a compile-time constant: at best every resize triggers a
full recompile of every step function (a recompile *storm* across the
pod — cf. the per-step recompilation tax in arXiv:1909.09756), at worst
the stale constant silently mis-shapes a collective after a shrink.
Three shapes, on the :mod:`~kungfu_tpu.analysis.axisenv` jit-scope map:

* **membership read in traced code** — inside any function whose body
  is traced (jit/pmap/shard_map root, or reachable from one through
  calls/callbacks): ``jax.device_count()`` / ``jax.devices()`` /
  ``jax.process_count()`` / ``jax.process_index()``, ``len(peers)``-
  style peer-list lengths, ``os.environ`` reads, and per-process
  ``.rank()`` calls.  Sizes belong to the mesh: use
  ``lax.axis_index``/``axis_size`` (resize builds a new mesh, so those
  are correct by construction), or rebuild the step per mesh epoch the
  way :mod:`kungfu_tpu.parallel.zero` does (``comm``-scoped values are
  epoch-scoped by design and are NOT flagged).
* **hazardous static args** — ``jit(..., static_argnums=...)`` indices
  out of range of the target's signature, static parameters whose names
  say they vary per step (``batch``, ``step``, ``grads``, ...; every
  distinct value compiles a new executable), and static parameters with
  non-hashable (list/dict/set) defaults — a ``TypeError`` the first
  time the default is actually used.
* **closure leak** — a nested function that enters jit scope and closes
  over a variable its enclosing function assigned from a
  process-global membership source (``jax.device_count()``,
  ``jax.devices()``, ``jax.process_count()``, ``os.environ``): the
  world size at *build* time is frozen into the step and survives every
  resize.

Suppress a deliberate trace-time constant (with a comment saying why it
cannot go stale) via ``# kflint: allow(recompile-hazard)``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from kungfu_tpu.analysis.axisenv import axis_environment, fkey
from kungfu_tpu.analysis.core import (
    Violation,
    parse_module,
    suppressed,
    terminal_name,
)

CHECKER = "recompile-hazard"

_SKIP_PREFIXES = ("kungfu_tpu/analysis/",)

#: process-global world facts; calling these in traced code bakes the
#: launch-time world in
_PROCESS_GLOBAL = {
    "device_count", "local_device_count", "process_count",
    "process_index", "host_count",
}
_DEVICE_LISTS = {"devices", "local_devices"}

#: receiver/attr names that read as peer-list membership
_MEMBERSHIP_NAMES = {
    "peers", "workers", "hosts", "members", "survivors", "replicas",
    "peer_list", "host_list",
}

#: static params with these names vary per step — each new value is a
#: fresh compile
_VARYING_PARAMS = {
    "step", "batch", "x", "grads", "grad", "params", "state",
    "opt_state", "inputs", "targets", "ids", "data", "batch_idx", "t",
    "iteration",
}


def _jaxish(receiver: Tuple[str, ...]) -> bool:
    return bool(receiver) and receiver[0] == "jax"


def _environ_read(site) -> bool:
    if site.callee == "getenv" and (not site.receiver
                                    or site.receiver[-1] == "os"):
        return True
    return site.callee == "get" and bool(site.receiver) \
        and site.receiver[-1] == "environ"


def _len_membership(site) -> Optional[str]:
    if site.callee != "len" or not site.node.args:
        return None
    arg = site.node.args[0]
    name = None
    if isinstance(arg, ast.Name):
        name = arg.id
    elif isinstance(arg, ast.Attribute):
        name = arg.attr
    elif isinstance(arg, ast.Call):
        t = terminal_name(arg.func)
        if t in _DEVICE_LISTS:
            return f"{t}()"
        return None
    if name and name.lower() in _MEMBERSHIP_NAMES:
        return name
    return None


def _params_of(node: ast.AST) -> Tuple[List[str], bool,
                                       Dict[str, ast.AST], List[str]]:
    """(positional param names, has *args, {param: default expr},
    keyword-only param names — legal static_argnames targets too)."""
    a = node.args
    params = [p.arg for p in (list(a.posonlyargs) + list(a.args))]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    defaults: Dict[str, ast.AST] = {}
    named = [p.arg for p in (list(a.posonlyargs) + list(a.args))]
    for p, d in zip(named[len(named) - len(a.defaults):], a.defaults):
        defaults[p] = d
    kwonly = [k.arg for k in a.kwonlyargs]
    for p, d in zip(kwonly, a.kw_defaults):
        if d is not None:
            defaults[p] = d
    return params, a.vararg is not None, defaults, kwonly


def _nonhashable_default(expr: Optional[ast.AST]) -> bool:
    return isinstance(expr, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp))


def check(root: str) -> List[Violation]:
    env = axis_environment(root)
    graph = env.graph
    out: List[Violation] = []
    supp_cache: Dict[str, Dict[int, set]] = {}

    def flag(path: str, line: int, msg: str) -> None:
        if path not in supp_cache:
            supp_cache[path] = parse_module(os.path.join(root, path)).supp
        if not suppressed(supp_cache[path], line, CHECKER):
            out.append(Violation(CHECKER, path, line, msg))

    def in_scope(func) -> bool:
        return not any(func.path.startswith(p) for p in _SKIP_PREFIXES)

    # -- membership reads inside traced code ------------------------------
    for func in graph.functions:
        if not in_scope(func) or fkey(func) not in env.jit_roots:
            continue
        roots = sorted(env.jit_roots[fkey(func)])
        via = (f" (traced via jitted `{roots[0]}`)"
               if roots and roots[0] != func.name else "")
        for site in func.calls:
            if site.callee in _PROCESS_GLOBAL and (
                    _jaxish(site.receiver) or not site.receiver):
                flag(func.path, site.line,
                     f"`{site.callee}()` inside traced code{via} bakes the "
                     f"launch-time world in as a Python constant — stale "
                     f"after an elastic resize, and every size change "
                     f"recompiles; use lax.axis_index/axis_size over the "
                     f"mesh, or rebuild per mesh epoch")
            elif site.callee in _DEVICE_LISTS and _jaxish(site.receiver):
                flag(func.path, site.line,
                     f"`jax.{site.callee}()` inside traced code{via} is a "
                     f"trace-time constant of the launch-time device set — "
                     f"derive shapes from the mesh instead")
            elif _environ_read(site):
                flag(func.path, site.line,
                     f"environment read inside traced code{via} traces to "
                     f"a constant — resize/config changes never reach the "
                     f"compiled step")
            elif site.callee in ("rank", "local_rank") and site.receiver \
                    and site.receiver[0] not in ("jax", "lax"):
                flag(func.path, site.line,
                     f"`.{site.callee}()` inside traced code{via} freezes "
                     f"a per-process rank into the compiled step — after "
                     f"a shrink the surviving ranks renumber; use "
                     f"lax.axis_index over the mesh axis")
            else:
                m = _len_membership(site)
                if m is not None:
                    flag(func.path, site.line,
                         f"len({m}) inside traced code{via} bakes the "
                         f"peer-list length in as a shape/constant — a "
                         f"resize silently recompiles (or keeps the stale "
                         f"size); take sizes from the mesh axis instead")

    # -- hazardous static args --------------------------------------------
    for site in env.jit_sites:
        func = site.func
        if not in_scope(func) or not site.targets:
            continue
        sigs = [_params_of(t.node) for t in site.targets]
        if site.static_argnums is not None:
            v = env.eval_in(func, site.static_argnums)
            idxs = []
            if isinstance(v, int):
                idxs = [v]
            elif isinstance(v, tuple) and all(
                    isinstance(i, int) for i in v):
                idxs = list(v)
            for i in idxs:
                oob = [s for s in sigs if not s[1] and i >= len(s[0])]
                if len(oob) == len(sigs):
                    params = sigs[0][0]
                    flag(func.path, site.node.lineno,
                         f"static_argnums={i} is out of range for "
                         f"`{site.targets[0].name}` "
                         f"({len(params)} positional parameter(s))")
                    continue
                names = {s[0][i] for s in sigs if i < len(s[0])}
                varying = names & _VARYING_PARAMS
                if varying and len(varying) == len(names):
                    flag(func.path, site.node.lineno,
                         f"static_argnums marks `{sorted(varying)[0]}` "
                         f"static — a per-step-varying argument compiles a "
                         f"NEW executable every call (recompile storm)")
                for s in sigs:
                    if i < len(s[0]) and _nonhashable_default(
                            s[2].get(s[0][i])):
                        flag(func.path, site.node.lineno,
                             f"static argument `{s[0][i]}` has a "
                             f"non-hashable default — jit static args must "
                             f"hash; this raises the first time the "
                             f"default is used")
                        break
        if site.static_argnames is not None:
            v = env.eval_in(func, site.static_argnames)
            names = []
            if isinstance(v, str):
                names = [v]
            elif isinstance(v, tuple) and all(
                    isinstance(s, str) for s in v):
                names = list(v)
            for name in names:
                known = [s for s in sigs if name in s[0]
                         or name in s[3]]
                if not known:
                    flag(func.path, site.node.lineno,
                         f"static_argnames={name!r} does not name a "
                         f"parameter of `{site.targets[0].name}`")
                elif name in _VARYING_PARAMS:
                    flag(func.path, site.node.lineno,
                         f"static_argnames marks `{name}` static — a "
                         f"per-step-varying argument compiles a NEW "
                         f"executable every call (recompile storm)")

    # -- closure leaks ----------------------------------------------------
    # nested jitted functions closing over process-global membership
    for func in graph.functions:
        if not in_scope(func):
            continue
        # hazard assigns in func's own scope
        hazards: Dict[str, Tuple[str, int]] = {}
        stack: List[ast.AST] = list(func.node.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                from kungfu_tpu.analysis.axisenv import MESH_CTORS

                # a Mesh IS the sanctioned carrier of the device set:
                # resize builds a new mesh and re-traces, so closing
                # over one is the pattern, not the hazard
                if any(isinstance(sub, ast.Call)
                       and terminal_name(sub.func) in MESH_CTORS
                       for sub in ast.walk(n.value)):
                    continue
                for sub in ast.walk(n.value):
                    if isinstance(sub, ast.Call):
                        t = terminal_name(sub.func)
                        if t in (_PROCESS_GLOBAL | _DEVICE_LISTS):
                            hazards[n.targets[0].id] = (
                                f"{t}()", n.lineno)
                    elif isinstance(sub, ast.Attribute) \
                            and sub.attr == "environ":
                        hazards[n.targets[0].id] = (
                            "os.environ", n.lineno)
            stack.extend(ast.iter_child_nodes(n))
        if not hazards:
            continue
        # nested defs of func that enter jit scope
        nested_nodes = {id(n): n for n in ast.walk(func.node)
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                        and n is not func.node}
        for g in graph.functions:
            if g.module != func.module or id(g.node) not in nested_nodes:
                continue
            if fkey(g) not in env.jit_roots:
                continue
            bound: Set[str] = {p.arg for p in (
                list(g.node.args.posonlyargs) + list(g.node.args.args)
                + list(g.node.args.kwonlyargs))}
            for n in ast.walk(g.node):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    bound.add(n.id)
            for n in ast.walk(g.node):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                        and n.id in hazards and n.id not in bound:
                    src, aline = hazards[n.id]
                    flag(func.path, n.lineno,
                         f"jitted `{g.name}` closes over `{n.id}` "
                         f"(assigned from {src} at line {aline}) — the "
                         f"launch-time world size is frozen into the "
                         f"compiled step and survives every elastic "
                         f"resize; derive it from the mesh or rebuild the "
                         f"step per mesh epoch")
                    break

    return sorted(out, key=lambda v: (v.path, v.line, v.message))
