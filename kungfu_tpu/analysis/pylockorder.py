"""lock-order checker: cross-module deadlock cycles in Python threading.

The PR-1 ``lock-discipline`` checker guards the native transport's
mutexes; the Python side (the detector's signal intake, the host
channel's queue/pool locks, the chaos controller, the config server)
grew its own lock web across PR 2 — and an AB/BA inversion between two
modules is exactly the bug no single-module review sees.  This rule
builds a project-wide lock-acquisition graph and reports cycles.

**Lock identity.**  ``self.ATTR = threading.Lock()/RLock()`` registers
``(module, Class, ATTR)``; ``NAME = threading.Lock()`` at module level
registers ``(module, NAME)``.  A ``with self.ATTR:`` (or the
``srv = self`` closure idiom: ``with srv.ATTR:`` where exactly one class
in the module owns ``ATTR``) is an acquisition; ``lk.acquire()`` holds
until the matching ``.release()`` or function end.

**Edges.**  While lock A is held, acquiring B adds A→B — directly, or
**interprocedurally**: a call made under A adds A→X for every lock X
the (conservatively resolved, see :mod:`~kungfu_tpu.analysis.callgraph`)
callee may transitively acquire.  A cycle in the resulting graph is a
potential deadlock; an A→A edge on a non-reentrant ``Lock`` is a
guaranteed self-deadlock and is reported separately.

Known limits (precision over recall — this gates tier-1):

* locks reached through containers (``entry[1]``) or handed across
  objects are invisible;
* unresolvable calls contribute no edges, so a cycle through a callback
  indirection is missed;
* ordering enforced by *runtime* discipline (e.g. always-sorted
  acquisition over a lock list) must carry
  ``# kflint: allow(lock-order)`` where it closes a textual cycle.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from kungfu_tpu.analysis.callgraph import (
    CallGraph,
    FuncInfo,
    project_graph,
)
from kungfu_tpu.analysis.core import (
    Violation,
    parse_module,
    suppressed,
)

CHECKER = "lock-order"

_LOCK_CTORS = {"Lock", "RLock"}

#: lock id: (module, owner-class or None, attr/name)
LockId = Tuple[str, Optional[str], str]


def _fmt_lock(lk: LockId) -> str:
    mod, cls, name = lk
    return f"{mod}::{cls}.{name}" if cls else f"{mod}::{name}"


def _lock_ctor_kind(node: ast.AST) -> Optional[str]:
    """"Lock"/"RLock" when ``node`` is a ``threading.Lock()`` style call."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    return name if name in _LOCK_CTORS else None


class _LockIndex:
    """All declared locks, by (module, class, attr) and per-module attr."""

    def __init__(self) -> None:
        self.kinds: Dict[LockId, str] = {}
        #: (module, attr) -> owning classes (for the srv/chan closure idiom)
        self.attr_owners: Dict[Tuple[str, str], List[Optional[str]]] = {}

    def declare(self, lk: LockId, kind: str) -> None:
        if lk in self.kinds:
            return
        self.kinds[lk] = kind
        self.attr_owners.setdefault((lk[0], lk[2]), []).append(lk[1])

    def resolve_attr(self, module: str, cls: Optional[str],
                     attr: str) -> Optional[LockId]:
        """``self.attr`` / ``srv.attr`` -> lock id, preferring the
        enclosing class, else the unique owner in the module."""
        if cls is not None and (module, cls, attr) in self.kinds:
            return (module, cls, attr)
        owners = self.attr_owners.get((module, attr), [])
        if len(owners) == 1:
            return (module, owners[0], attr)
        return None

    def resolve_name(self, module: str, name: str) -> Optional[LockId]:
        lk = (module, None, name)
        return lk if lk in self.kinds else None


def _build_lock_index(graph: CallGraph, root: str) -> _LockIndex:
    idx = _LockIndex()
    seen_modules: Set[str] = set()
    for f in graph.functions:
        # self.X = threading.Lock() inside any method of the class
        for node in ast.walk(f.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            kind = _lock_ctor_kind(node.value)
            if kind is None:
                continue
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self" \
                    and f.cls is not None:
                idx.declare((f.module, f.cls, t.attr), kind)
        seen_modules.add((f.module, f.path))
    # module-level locks: re-parse top-level assigns of each module
    for module, rel in sorted(seen_modules):
        try:
            tree = parse_module(os.path.join(root, rel)).tree
        except OSError:
            continue
        if tree is None:
            continue
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                kind = _lock_ctor_kind(node.value)
                if kind is not None:
                    idx.declare((module, None, node.targets[0].id), kind)
    return idx


def _lock_of_expr(expr: ast.AST, func: FuncInfo,
                  idx: _LockIndex) -> Optional[LockId]:
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return idx.resolve_attr(func.module, func.cls, expr.attr)
    if isinstance(expr, ast.Name):
        return idx.resolve_name(func.module, expr.id)
    return None


class _FuncLocks(ast.NodeVisitor):
    """Per-function pass: direct acquisitions, nested-order edges, and
    call sites made while holding locks."""

    def __init__(self, func: FuncInfo, idx: _LockIndex):
        self.func = func
        self.idx = idx
        self.acquires: Set[LockId] = set()
        #: (held, acquired, line) direct nesting edges
        self.edges: List[Tuple[LockId, LockId, int]] = []
        #: (held-set frozen, callee terminal, receiver, line)
        self.held_calls: List[Tuple[Tuple[LockId, ...], ast.Call, int]] = []
        self._held: List[LockId] = []

    def run(self) -> "_FuncLocks":
        self._stmts(self.func.node.body)
        return self

    def _stmts(self, body) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes analyzed as their own functions
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.With):
            acquired: List[LockId] = []
            for item in stmt.items:
                self._expr(item.context_expr)
                lk = _lock_of_expr(item.context_expr, self.func, self.idx)
                if lk is not None:
                    self._acquire(lk, stmt.lineno)
                    acquired.append(lk)
            self._stmts(stmt.body)
            for lk in reversed(acquired):
                # an explicit release() inside the body (the lock-handoff
                # pattern) may have dropped it already
                if lk in self._held:
                    self._held.remove(lk)
            return
        if isinstance(stmt, ast.If):
            self._expr(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self._expr(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self._stmts(stmt.body)
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
            return
        # explicit acquire()/release() pairs at statement level
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr in ("acquire",
                                                           "release"):
                lk = _lock_of_expr(f.value, self.func, self.idx)
                if lk is not None:
                    if f.attr == "acquire":
                        self._acquire(lk, stmt.lineno)
                    elif lk in self._held:
                        self._held.remove(lk)
                    return
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._call(node)

    def _expr(self, expr: Optional[ast.AST]) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._call(node)

    def _acquire(self, lk: LockId, line: int) -> None:
        self.acquires.add(lk)
        for held in self._held:
            self.edges.append((held, lk, line))
        self._held.append(lk)

    def _call(self, call: ast.Call) -> None:
        if self._held:
            self.held_calls.append((tuple(self._held), call, call.lineno))


def check(root: str) -> List[Violation]:
    graph = project_graph(root)
    idx = _build_lock_index(graph, root)
    if not idx.kinds:
        return []

    passes = {f.qualname: _FuncLocks(f, idx).run() for f in graph.functions}

    # transitive may-acquire fixpoint over resolved call edges
    call_edges: Dict[str, Set[str]] = {}
    for f in graph.functions:
        targets: Set[str] = set()
        for site in f.calls:
            for callee in graph.resolve(f, site):
                targets.add(callee.qualname)
        call_edges[f.qualname] = targets
    may: Dict[str, Set[LockId]] = {
        q: set(p.acquires) for q, p in passes.items()
    }
    changed = True
    while changed:
        changed = False
        for q, targets in call_edges.items():
            for t in targets:
                extra = may.get(t, set()) - may[q]
                if extra:
                    may[q] |= extra
                    changed = True

    # assemble the lock graph: direct nesting edges + call-under-lock
    # edges; remember one witness (path, line, note) per edge
    edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]] = {}
    supp_cache: Dict[str, Dict[int, set]] = {}

    def supp_for(path: str) -> Dict[int, set]:
        if path not in supp_cache:
            supp_cache[path] = parse_module(
                os.path.join(root, path)).supp
        return supp_cache[path]

    def add_edge(a: LockId, b: LockId, path: str, line: int,
                 note: str) -> None:
        if suppressed(supp_for(path), line, CHECKER):
            return
        edges.setdefault((a, b), (path, line, note))

    out: List[Violation] = []
    for f in graph.functions:
        p = passes[f.qualname]
        for a, b, line in p.edges:
            add_edge(a, b, f.path, line, "nested `with`")
        for held, call, line in p.held_calls:
            # re-resolve this call through the graph
            for site in f.calls:
                if site.node is call:
                    for callee in graph.resolve(f, site):
                        for lk in may.get(callee.qualname, ()):
                            for h in held:
                                add_edge(h, lk, f.path, line,
                                         f"call into {callee.name}()")
                    break

    # self-deadlock: A -> A on a non-reentrant Lock
    for (a, b), (path, line, note) in sorted(
            edges.items(), key=lambda kv: (_fmt_lock(kv[0][0]),
                                           _fmt_lock(kv[0][1]))):
        if a == b and idx.kinds.get(a) == "Lock":
            out.append(Violation(
                CHECKER, path, line,
                f"non-reentrant lock {_fmt_lock(a)} may be re-acquired "
                f"while already held ({note}) — guaranteed self-deadlock"))

    # cycles: DFS over the lock digraph (self-edges reported above)
    adj: Dict[LockId, List[LockId]] = {}
    for (a, b) in edges:
        if a != b:
            adj.setdefault(a, []).append(b)
    reported: Set[frozenset] = set()

    def dfs(start: LockId, node: LockId, path: List[LockId],
            visiting: Set[LockId]) -> None:
        for nxt in sorted(adj.get(node, []), key=_fmt_lock):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key in reported:
                    continue
                reported.add(key)
                steps = []
                for i, lk in enumerate(path):
                    nlk = path[(i + 1) % len(path)]
                    wpath, wline, note = edges[(lk, nlk)]
                    steps.append(
                        f"{_fmt_lock(lk)} -> {_fmt_lock(nlk)} "
                        f"({wpath}:{wline}, {note})")
                wpath, wline, _ = edges[(path[0], path[1 % len(path)])]
                out.append(Violation(
                    CHECKER, wpath, wline,
                    "lock-order cycle (potential deadlock): "
                    + "; ".join(steps)))
            elif nxt not in visiting:
                visiting.add(nxt)
                dfs(start, nxt, path + [nxt], visiting)
                visiting.discard(nxt)

    for start in sorted(adj, key=_fmt_lock):
        dfs(start, start, [start], {start})

    return sorted(out, key=lambda v: (v.path, v.line, v.message))
