"""Axis-environment abstract interpretation — the kf-shard substrate.

The sharding bugs that only surface at trace time on a pod are all
*environment* bugs: a collective names a mesh axis that is not bound
where it runs, a ``PartitionSpec`` names an axis its mesh never
declared, a jitted closure bakes the world size in as a Python
constant.  This module computes, once per tree, everything the three
kf-shard rules (``shard-axis``, ``shard-spec``, ``recompile-hazard``)
need to see those statically:

* a project-wide **constant table** (module-level ``AXIS_DP = "dp"`` /
  ``AXES = (AXIS_DP, ...)`` bindings, resolved through imports);
* every **mesh**: ``Mesh(...)`` constructors, functions that return
  one (``MeshPlan.build_mesh``), and ``self.mesh = ...`` class
  attributes — each reduced to its frozenset of axis names where the
  names are static, plus the **global axis vocabulary** (every axis
  any mesh/pmap in the tree declares);
* the **axis environment of every function**, as a set of *contexts*:
  a function directly passed to ``shard_map``/``pmap`` (call form,
  decorator form, ``functools.partial(shard_map, mesh=...)`` aliases,
  and mesh-entry *parameters* like ``Communicator._shard_jit(body)``)
  gets the mapped mesh's axes as a context; functions it calls — or
  references as callbacks (``value_and_grad(self._local_loss)``,
  ``lax.scan(step, ...)``) — inherit each caller context through a
  fixpoint over the shared :mod:`~kungfu_tpu.analysis.callgraph`.
  Contexts are kept SEPARATE, not unioned: a helper reached from two
  meshes with different axis sets must be valid in each (an axis from
  mesh A is a bug when the helper runs under mesh B — union-merging
  would hide exactly that).  A context whose mesh could not be
  resolved is *open* (more axes may be live), and open contexts never
  prove an axis absent, so unresolved indirection loses recall, never
  precision;
* **jit-scope membership** with root attribution — which functions'
  bodies end up traced into compiled code (``jax.jit``/``pmap``/
  ``shard_map`` roots plus everything reachable through calls and
  callback references), shared with the migrated ``jit-sync`` rule.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from kungfu_tpu.analysis.callgraph import (
    CallGraph,
    FuncInfo,
    project_graph,
)
from kungfu_tpu.analysis.core import terminal_name

#: constructors that declare mesh axis names (arg 1 / ``axis_names=``)
MESH_CTORS = {"Mesh", "AbstractMesh", "make_mesh"}

#: wrappers that bind mesh axes over a mapped function
MAP_WRAPPERS = {"shard_map", "pmap"}

#: wrappers that enter jit scope (compiled-code membership)
JIT_WRAPPERS = {"jit"} | MAP_WRAPPERS

#: transparent wrappers a jitted function threads through —
#: ``jit(value_and_grad(f))`` traces ``f``
TRANSPARENT_WRAPPERS = {
    "grad", "value_and_grad", "vmap", "checkpoint", "remat", "partial",
}

_EVAL_FAIL = object()  #: sentinel: expression is not statically constant

#: unique per-function key: qualnames COLLIDE for same-named nested
#: defs (every builder has a ``body``), and collisions would merge —
#: i.e. cross-contaminate — their axis environments
FKey = Tuple[str, int]


def fkey(func: FuncInfo) -> FKey:
    return (func.qualname, func.lineno)


# ---------------------------------------------------------------------------
# contexts

@dataclass(frozen=True)
class Ctx:
    """One axis environment a function may execute under.

    ``axes`` are the names proven bound; ``open`` means the mesh (or an
    enclosing one) could not be resolved, so MORE axes may be live and
    absence cannot be proven."""

    axes: FrozenSet[str]
    open: bool

    def merged(self, axes: Optional[FrozenSet[str]]) -> "Ctx":
        if axes is None:
            return Ctx(self.axes, True)
        return Ctx(self.axes | axes, self.open)


@dataclass
class ShardMapSite:
    """One ``shard_map(...)`` call site, for the shard-spec rule."""

    func: FuncInfo                      #: the function containing the call
    node: ast.Call
    axes: Optional[FrozenSet[str]]      #: mesh axes (None = unresolved)
    targets: List[FuncInfo]             #: resolved mapped functions
    in_specs: Optional[ast.AST]
    out_specs: Optional[ast.AST]


@dataclass
class JitSite:
    """One ``jit(...)`` call/decorator, for the recompile-hazard rule."""

    func: FuncInfo                      #: containing function
    node: ast.Call
    targets: List[FuncInfo]             #: resolved jitted functions
    static_argnums: Optional[ast.AST]
    static_argnames: Optional[ast.AST]


class AxisEnv:
    def __init__(self, root: str, graph: CallGraph) -> None:
        self.root = root
        self.graph = graph
        #: module -> {name: value expr AST} (module-level constants)
        self.consts: Dict[str, Dict[str, ast.AST]] = {}
        #: every axis name any mesh/pmap in the tree declares
        self.vocabulary: Set[str] = set()
        #: fkey -> {Ctx: provenance string}
        self.contexts: Dict[FKey, Dict[Ctx, str]] = {}
        #: fkey -> root names whose trace this function joins
        self.jit_roots: Dict[FKey, Set[str]] = {}
        self.shard_sites: List[ShardMapSite] = []
        self.jit_sites: List[JitSite] = []
        #: (module, cls, attr) -> axes for ``self.attr = <mesh>``
        self.class_mesh: Dict[Tuple[str, Optional[str], str],
                              Optional[FrozenSet[str]]] = {}
        #: fkey -> axes for functions returning a mesh
        self.mesh_returns: Dict[FKey, Optional[FrozenSet[str]]] = {}
        #: fkey -> {local name: value expr} (function-local constants)
        self._local_consts: Dict[FKey, Dict[str, ast.AST]] = {}
        #: fkey -> {local name: axes} (function-local mesh variables)
        self._mesh_vars: Dict[FKey, Dict[str, Optional[FrozenSet[str]]]] = {}

    # -- constant evaluation ------------------------------------------------
    def _const_lookup(self, module: str, name: str,
                      seen: Set[Tuple[str, str]]):
        if (module, name) in seen:
            return _EVAL_FAIL
        # `seen` is the recursion STACK (cycle guard), not a visited
        # set: pop on the way out, or `AXES = (A, B)` with A and B both
        # aliasing AXIS_DP would fail its second lookup and silently
        # unresolve the whole tuple
        seen.add((module, name))
        try:
            expr = self.consts.get(module, {}).get(name)
            if expr is not None:
                return self._eval(expr, module, seen)
            src = self.graph.module_imports.get(module, {}).get(name)
            if src:
                for mod in self.consts:
                    if mod == src or mod.endswith("." + src):
                        return self._const_lookup(mod, name, seen)
            return _EVAL_FAIL
        finally:
            seen.discard((module, name))

    def _eval(self, expr: ast.AST, module: str,
              seen: Optional[Set[Tuple[str, str]]] = None,
              local: Optional[Dict[str, ast.AST]] = None):
        """Evaluate an expression to a static value (str/int/None/tuple)
        or ``_EVAL_FAIL``.  ``local`` layers a function's own constant
        assignments over the module table."""
        seen = seen if seen is not None else set()
        if isinstance(expr, ast.Constant):
            return expr.value
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = []
            for e in expr.elts:
                v = self._eval(e, module, seen, local)
                if v is _EVAL_FAIL:
                    return _EVAL_FAIL
                out.append(v)
            return tuple(out)
        if isinstance(expr, ast.Name):
            if local and expr.id in local:
                return self._eval(local[expr.id], module, seen)
            return self._const_lookup(module, expr.id, seen)
        return _EVAL_FAIL

    def eval_in(self, func: FuncInfo, expr: ast.AST):
        """Static value of ``expr`` inside ``func`` (or ``None`` when
        dynamic — callers must treat that as unknowable, not falsy:
        use :data:`EVAL_FAIL` sentinel via :meth:`eval_raw`)."""
        return self._eval(expr, func.module,
                          local=self._local_consts.get(fkey(func)))

    def axis_strings(self, func: FuncInfo,
                     expr: ast.AST) -> Optional[Tuple[str, ...]]:
        """The literal axis names ``expr`` denotes, flattened — or None
        when the expression is dynamic or not axis-shaped (ints, etc.)."""
        v = self.eval_in(func, expr)
        if v is _EVAL_FAIL:
            return None
        flat: List[str] = []

        def flatten(x) -> bool:
            if isinstance(x, str):
                flat.append(x)
                return True
            if isinstance(x, tuple):
                return all(flatten(e) for e in x)
            return False

        if not flatten(v):
            return None
        return tuple(flat)

    # -- context queries ----------------------------------------------------
    def contexts_of(self, func: FuncInfo) -> Dict[Ctx, str]:
        return self.contexts.get(fkey(func), {})

    def jit_scope(self, func: FuncInfo) -> bool:
        return fkey(func) in self.jit_roots

    # -- mesh resolution ----------------------------------------------------
    def site_for(self, func: FuncInfo, call: ast.Call):
        """A transient CallSite for :meth:`CallGraph.resolve`."""
        from kungfu_tpu.analysis.callgraph import CallSite

        callee = terminal_name(call.func)
        chain: List[str] = []
        n: ast.AST = call.func
        while isinstance(n, ast.Attribute):
            chain.append(n.attr)
            n = n.value
        if isinstance(n, ast.Name):
            chain.append(n.id)
        chain.reverse()
        return CallSite(callee=callee or "", node=call,
                        line=call.lineno, receiver=tuple(chain[:-1]),
                        branches=())

    def mesh_axes(self, func: FuncInfo,
                  expr: Optional[ast.AST]) -> Optional[FrozenSet[str]]:
        """Axis names of a mesh-typed expression: a ``Mesh(...)`` ctor,
        a local variable bound from one, a ``self.mesh`` class attribute,
        or a call to a mesh-returning function.  None = unresolvable."""
        if expr is None:
            return None
        if isinstance(expr, ast.Call):
            name = terminal_name(expr.func)
            if name in MESH_CTORS:
                return _mesh_ctor_axes(self, func, expr)
            for g in self.graph.resolve(func, self.site_for(func, expr)):
                if fkey(g) in self.mesh_returns:
                    return self.mesh_returns[fkey(g)]
            return None
        if isinstance(expr, ast.Name):
            # own scope first, then enclosing functions (a nested body
            # may close over a mesh its builder constructed), then
            # module-level constants (MESH = Mesh(...))
            scope: Optional[FuncInfo] = func
            while scope is not None:
                hit = self._mesh_vars.get(fkey(scope), {}).get(expr.id)
                if hit is not None:
                    return hit
                scope = scope.parent
            cexpr = self.consts.get(func.module, {}).get(expr.id)
            if isinstance(cexpr, ast.Call) \
                    and terminal_name(cexpr.func) in MESH_CTORS:
                return _mesh_ctor_axes(self, func, cexpr)
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id in ("self", "cls") and func.cls is not None:
            return self.class_mesh.get((func.module, func.cls, expr.attr))
        return None


# ---------------------------------------------------------------------------
# build

#: cap on distinct contexts tracked per function; beyond it the set
#: collapses to one open union (degrades to the vocabulary check)
_CTX_CAP = 8


def _positional_params(node: ast.AST) -> List[str]:
    a = node.args
    return [p.arg for p in (list(a.posonlyargs) + list(a.args))]


def _mesh_kwarg(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "mesh":
            return kw.value
    # positional: shard_map(f, mesh, in_specs, out_specs)
    if len(call.args) >= 2:
        return call.args[1]
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _mesh_ctor_axes(env: AxisEnv, func: FuncInfo,
                    call: ast.Call) -> Optional[FrozenSet[str]]:
    """Axis names a Mesh/make_mesh constructor declares (None=dynamic)."""
    expr = _kwarg(call, "axis_names")
    if expr is None and len(call.args) >= 2:
        expr = call.args[1]
    if expr is None:
        return None
    axes = env.axis_strings(func, expr)
    if axes is None:
        return None
    return frozenset(axes)


class _ModuleConstVisitor(ast.NodeVisitor):
    """Top-level ``NAME = <const expr>`` bindings of one module."""

    def __init__(self) -> None:
        self.consts: Dict[str, ast.AST] = {}

    def visit_Module(self, node: ast.Module) -> None:
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                self.consts[stmt.targets[0].id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name) \
                    and stmt.value is not None:
                self.consts[stmt.target.id] = stmt.value


def _name_targets(graph: CallGraph, func: FuncInfo,
                  name: str) -> List[FuncInfo]:
    """Scope-aware bare-name resolution: defs nested in ``func`` or an
    enclosing function (innermost first), then module-level functions,
    then explicit imports.  Never every same-named def in the module —
    that would hand one builder's mesh context to another's body."""
    cands = graph.by_name.get(name, [])
    scope: Optional[FuncInfo] = func
    while scope is not None:
        nested = [g for g in cands if g.parent is scope]
        if nested:
            return nested
        scope = scope.parent
    top = [g for g in cands if g.module == func.module
           and g.parent is None and g.cls is None]
    if top:
        return top
    src = graph.module_imports.get(func.module, {}).get(name)
    if src:
        # dotted-boundary match: `from core import f` must not suffix-
        # match an unrelated in-tree module like kungfu_tpu.score
        hit = [g for g in cands if g.cls is None
               and (g.module == src or g.module.endswith("." + src))]
        if hit:
            return hit
    return []


def _jit_ref_targets(graph: CallGraph, func: FuncInfo,
                     expr: ast.AST) -> List[FuncInfo]:
    """Targets of a jit-wrapper argument.  Wider than _fn_targets for
    bound references: `jax.jit(t.step)` marks every same-module `step`
    as traced (the pre-callgraph checker's over-report stance — for jit
    SCOPE an over-approximation flags more, never less; axis contexts
    keep the strict resolver)."""
    res = _fn_targets(graph, func, expr)
    if res or not isinstance(expr, ast.Attribute):
        return res
    name = terminal_name(expr)
    return [g for g in graph.by_name.get(name or "", [])
            if g.module == func.module]


def _fn_targets(graph: CallGraph, func: FuncInfo,
                expr: ast.AST) -> List[FuncInfo]:
    """Functions a Name/Attribute reference may denote (conservative:
    scope-aware for bare names, same class for ``self.x``; [] when
    ambiguous across objects)."""
    if isinstance(expr, ast.Name):
        return _name_targets(graph, func, expr.id)
    if isinstance(expr, ast.Attribute):
        chain: List[str] = []
        n: ast.AST = expr
        while isinstance(n, ast.Attribute):
            chain.append(n.attr)
            n = n.value
        if isinstance(n, ast.Name) and n.id in ("self", "cls") \
                and len(chain) == 1 and func.cls is not None:
            return [g for g in graph.by_name.get(chain[0], [])
                    if g.cls == func.cls and g.module == func.module]
    return []


class _FuncScan(ast.NodeVisitor):
    """One function's own-scope facts: local consts, mesh vars,
    partial-shard_map aliases, return shapes."""

    def __init__(self) -> None:
        self.consts: Dict[str, ast.AST] = {}
        self.assigns: Dict[str, ast.AST] = {}   # every single-Name assign
        self.self_assigns: Dict[str, ast.AST] = {}  # self.X = expr
        self.returns: List[ast.AST] = []

    def _visit_func(self, node) -> None:
        pass  # nested defs own their scope — do not descend

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    def visit_Assign(self, node: ast.Assign) -> None:
        if len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                self.assigns[t.id] = node.value
                if isinstance(node.value, (ast.Constant, ast.Tuple,
                                           ast.List, ast.Name)):
                    self.consts[t.id] = node.value
            elif isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                self.self_assigns[t.attr] = node.value
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            self.returns.append(node.value)
        self.generic_visit(node)


def _unwrap_mapped(expr: ast.AST) -> Tuple[ast.AST, List[ast.Call]]:
    """Peel transparent/jit wrappers off a mapped-function expression:
    ``jit(value_and_grad(f))`` -> (f ref, [wrapper calls]).  Returns the
    innermost non-wrapper expression."""
    wrappers: List[ast.Call] = []
    n = expr
    while isinstance(n, ast.Call):
        name = terminal_name(n.func)
        if name in TRANSPARENT_WRAPPERS | JIT_WRAPPERS and n.args:
            wrappers.append(n)
            n = n.args[0]
            continue
        break
    return n, wrappers


def build(root: str) -> AxisEnv:
    graph = project_graph(root)
    env = AxisEnv(root, graph)

    # pass 0: module constants (from the cached ASTs the graph indexed)
    from kungfu_tpu.analysis.core import iter_py_files, parse_module

    modpaths: Dict[str, str] = {}
    for f in graph.functions:
        modpaths.setdefault(f.module, f.path)
    for path in iter_py_files(root):
        tree = parse_module(path).tree
        if tree is None:
            continue
        from kungfu_tpu.analysis.callgraph import _module_of

        module = _module_of(root, path)
        v = _ModuleConstVisitor()
        v.visit(tree)
        env.consts[module] = v.consts
        # modules with no functions still carry imports for const lookup
        graph.module_imports.setdefault(module, {})
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    graph.module_imports[module].setdefault(
                        alias.asname or alias.name, node.module or "")

    # pass 1: function facts + vocabulary from mesh ctors / pmap kinds
    facts: Dict[FKey, _FuncScan] = {}
    for f in graph.functions:
        scan = _FuncScan()
        # visit children of the def (visiting the def itself would stop)
        for stmt in f.node.body:
            scan.visit(stmt)
        facts[fkey(f)] = scan
        env._local_consts[fkey(f)] = scan.consts

    mesh_axes_of = env.mesh_axes

    # fixpoint over mesh-returning functions / mesh vars / class attrs
    for _ in range(4):  # nesting depth of mesh plumbing is shallow
        changed = False
        for f in graph.functions:
            scan = facts[fkey(f)]
            mvars = env._mesh_vars.setdefault(fkey(f), {})
            for name, expr in scan.assigns.items():
                axes = mesh_axes_of(f, expr) if isinstance(
                    expr, (ast.Call,)) else None
                if axes is not None and mvars.get(name) != axes:
                    mvars[name] = axes
                    changed = True
            for attr, expr in scan.self_assigns.items():
                if not isinstance(expr, ast.Call):
                    continue
                axes = mesh_axes_of(f, expr)
                key = (f.module, f.cls, attr)
                if axes is not None and env.class_mesh.get(key) != axes:
                    env.class_mesh[key] = axes
                    changed = True
            for rexpr in scan.returns:
                axes = None
                if isinstance(rexpr, ast.Call):
                    axes = mesh_axes_of(f, rexpr)
                elif isinstance(rexpr, ast.Name):
                    axes = mvars.get(rexpr.id)
                if axes is not None \
                        and env.mesh_returns.get(fkey(f)) != axes:
                    env.mesh_returns[fkey(f)] = axes
                    changed = True
        if not changed:
            break

    # vocabulary: every mesh ctor + pmap axis_name anywhere in the tree
    for f in graph.functions:
        for site in f.calls:
            if site.callee in MESH_CTORS:
                axes = _mesh_ctor_axes(env, f, site.node)
                if axes:
                    env.vocabulary |= axes
            elif site.callee == "pmap":
                expr = _kwarg(site.node, "axis_name")
                if expr is not None:
                    ax = env.axis_strings(f, expr)
                    if ax:
                        env.vocabulary |= set(ax)
    # module-level Mesh(...) constructors (outside any function)
    for module, consts in env.consts.items():
        for expr in consts.values():
            for node in ast.walk(expr):
                if isinstance(node, ast.Call) \
                        and terminal_name(node.func) in MESH_CTORS:
                    dummy = FuncInfo(module=module, cls=None, name="<mod>",
                                     path=modpaths.get(module, module),
                                     node=expr, lineno=1)
                    axes = _mesh_ctor_axes(env, dummy, node)
                    if axes:
                        env.vocabulary |= axes

    # pass 2: binding sites — shard_map/pmap/jit wrappings, partial
    # aliases, mesh-entry params
    bindings: List[Tuple[FKey, FKey, Optional[FrozenSet[str]], str]] = []
    jit_root_names: Dict[FKey, Set[str]] = {}
    mesh_entry: Dict[FKey, Tuple[int, Optional[FrozenSet[str]], str]] = {}
    #: mapped-function argument nodes of binding wrappers: the binding
    #: models their context flow, a plain callback edge would leak the
    #: binder's context WITHOUT the mapped mesh's axes
    mapped_args: Set[int] = set()

    def bind(binder: FuncInfo, target: FuncInfo,
             axes: Optional[FrozenSet[str]], prov: str) -> None:
        bindings.append((fkey(binder), fkey(target), axes, prov))

    def note_jit_root(target: FuncInfo, why: str) -> None:
        jit_root_names.setdefault(fkey(target), set()).add(why)

    def record_shard_map(f: FuncInfo, call: ast.Call,
                         axes: Optional[FrozenSet[str]]) -> None:
        mapped_expr = call.args[0] if call.args else None
        targets: List[FuncInfo] = []
        if mapped_expr is not None:
            mapped_args.add(id(mapped_expr))
            inner, _ = _unwrap_mapped(mapped_expr)
            targets = _fn_targets(graph, f, inner)
            for g in targets:
                prov = (f"shard_map at {f.path}:{call.lineno} over mesh "
                        f"{{{', '.join(sorted(axes))}}}" if axes is not None
                        else f"shard_map at {f.path}:{call.lineno} "
                             f"(unresolved mesh)")
                bind(f, g, axes, prov)
                note_jit_root(g, g.name)
            # the mapped expr may be one of f's own parameters: f is a
            # mesh-entry helper (Communicator._shard_jit(body) idiom)
            if isinstance(mapped_expr, ast.Name) \
                    and hasattr(f.node, "args"):
                params = _positional_params(f.node)
                if mapped_expr.id in params and not targets:
                    mesh_entry[fkey(f)] = (
                        params.index(mapped_expr.id), axes,
                        f"{f.qualname} (shard_map at {f.path}:{call.lineno})",
                    )
        env.shard_sites.append(ShardMapSite(
            func=f, node=call, axes=axes, targets=targets,
            in_specs=_kwarg(call, "in_specs") or (
                call.args[2] if len(call.args) > 2 else None),
            out_specs=_kwarg(call, "out_specs") or (
                call.args[3] if len(call.args) > 3 else None),
        ))

    for f in graph.functions:
        scan = facts[fkey(f)]
        # partial(shard_map, mesh=...) local aliases
        partial_alias: Dict[str, Optional[FrozenSet[str]]] = {}
        for name, expr in scan.assigns.items():
            if isinstance(expr, ast.Call) \
                    and terminal_name(expr.func) == "partial" and expr.args:
                inner_name = terminal_name(expr.args[0])
                if inner_name == "shard_map":
                    partial_alias[name] = mesh_axes_of(
                        f, _kwarg(expr, "mesh"))

        # decorators on f itself
        def _deco_pmap_bind(deco_call, form: str) -> None:
            ax_expr = _kwarg(deco_call, "axis_name")
            ax = (env.axis_strings(f, ax_expr)
                  if ax_expr is not None else ())
            if ax:
                env.vocabulary |= set(ax)
            bindings.append((
                fkey(f), fkey(f),
                frozenset(ax) if ax is not None else None,
                f"{form} at {f.path}:{f.lineno}"))

        for deco in f.node.decorator_list if hasattr(
                f.node, "decorator_list") else []:
            name = terminal_name(deco if not isinstance(deco, ast.Call)
                                 else deco.func)
            if isinstance(deco, ast.Call) and name == "partial" and deco.args:
                inner = terminal_name(deco.args[0])
                if inner in JIT_WRAPPERS:
                    note_jit_root(f, f.name)
                if inner == "shard_map":
                    axes = mesh_axes_of(f, _kwarg(deco, "mesh"))
                    bindings.append((
                        fkey(f), fkey(f), axes,
                        f"@partial(shard_map) at {f.path}:{f.lineno}"))
                if inner == "pmap":
                    _deco_pmap_bind(deco, "@partial(pmap)")
                if inner == "jit":
                    env.jit_sites.append(JitSite(
                        func=f, node=deco, targets=[f],
                        static_argnums=_kwarg(deco, "static_argnums"),
                        static_argnames=_kwarg(deco, "static_argnames")))
            elif name in JIT_WRAPPERS:
                note_jit_root(f, f.name)
                if isinstance(deco, ast.Call) and name == "pmap":
                    _deco_pmap_bind(deco, "@pmap")
                if isinstance(deco, ast.Call) and name == "jit":
                    env.jit_sites.append(JitSite(
                        func=f, node=deco, targets=[f],
                        static_argnums=_kwarg(deco, "static_argnums"),
                        static_argnames=_kwarg(deco, "static_argnames")))

        # call sites inside f
        for site in f.calls:
            call = site.node
            if site.callee == "shard_map":
                record_shard_map(f, call, mesh_axes_of(f, _mesh_kwarg(call)))
                continue
            if site.callee in partial_alias and not site.receiver:
                if call.args:
                    mapped_args.add(id(call.args[0]))
                    inner, _ = _unwrap_mapped(call.args[0])
                    for g in _fn_targets(graph, f, inner):
                        axes = partial_alias[site.callee]
                        prov = (f"partial(shard_map) at {f.path}:"
                                f"{call.lineno}" + (
                                    f" over mesh {{{', '.join(sorted(axes))}}}"
                                    if axes is not None else
                                    " (unresolved mesh)"))
                        bind(f, g, axes, prov)
                        note_jit_root(g, g.name)
                continue
            if site.callee == "pmap":
                if call.args:
                    mapped_args.add(id(call.args[0]))
                    inner, _ = _unwrap_mapped(call.args[0])
                    axis_expr = _kwarg(call, "axis_name")
                    ax = env.axis_strings(f, axis_expr) \
                        if axis_expr is not None else ()
                    axes = frozenset(ax) if ax is not None else None
                    for g in _fn_targets(graph, f, inner):
                        bind(f, g, axes,
                             f"pmap at {f.path}:{call.lineno}")
                        note_jit_root(g, g.name)
                continue
            if site.callee == "jit":
                if call.args:
                    inner, wrappers = _unwrap_mapped(call.args[0])
                    targets = _jit_ref_targets(graph, f, inner)
                    for g in targets:
                        note_jit_root(g, g.name)
                    env.jit_sites.append(JitSite(
                        func=f, node=call, targets=targets,
                        static_argnums=_kwarg(call, "static_argnums"),
                        static_argnames=_kwarg(call, "static_argnames")))
                continue

    # module-level wrappings: `train_step = jax.jit(step)` at import
    # time enters jit scope too (the pre-callgraph jit-sync saw these;
    # losing them would be a silent coverage regression)
    from kungfu_tpu.analysis.callgraph import _module_of
    from kungfu_tpu.analysis.core import relpath as _relpath

    for path in iter_py_files(root):
        mod = parse_module(path)
        if mod.tree is None:
            continue
        dummy = FuncInfo(module=_module_of(root, path), cls=None,
                         name="<module>", path=_relpath(root, path),
                         node=mod.tree, lineno=0)
        stack: List[ast.AST] = list(mod.tree.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue  # function-level sites: pass 2 covered them
            if isinstance(n, ast.Call):
                name = terminal_name(n.func)
                if name == "jit" and n.args:
                    inner, _ = _unwrap_mapped(n.args[0])
                    targets = _jit_ref_targets(graph, dummy, inner)
                    for g in targets:
                        note_jit_root(g, g.name)
                    if targets:
                        env.jit_sites.append(JitSite(
                            func=dummy, node=n, targets=targets,
                            static_argnums=_kwarg(n, "static_argnums"),
                            static_argnames=_kwarg(n, "static_argnames")))
                elif name == "shard_map":
                    record_shard_map(dummy, n,
                                     env.mesh_axes(dummy, _mesh_kwarg(n)))
                elif name == "pmap" and n.args:
                    inner, _ = _unwrap_mapped(n.args[0])
                    ax_expr = _kwarg(n, "axis_name")
                    ax = (env.axis_strings(dummy, ax_expr)
                          if ax_expr is not None else ())
                    for g in _fn_targets(graph, dummy, inner):
                        bind(dummy, g,
                             frozenset(ax) if ax is not None else None,
                             f"pmap at {dummy.path}:{n.lineno}")
                        note_jit_root(g, g.name)
            stack.extend(ast.iter_child_nodes(n))

    # mesh-entry params: callers passing a function into a helper that
    # shard_maps its argument (one indirection level)
    for f in graph.functions:
        for site in f.calls:
            for g in graph.resolve(f, site):
                entry = mesh_entry.get(fkey(g))
                if entry is None:
                    continue
                idx, axes, prov = entry
                # account for the bound receiver: self.helper(body) calls
                # helper(self, body)
                pos = idx - (1 if (g.cls is not None and site.receiver)
                             else 0)
                if 0 <= pos < len(site.node.args):
                    mapped_args.add(id(site.node.args[pos]))
                    inner, _ = _unwrap_mapped(site.node.args[pos])
                    for h in _fn_targets(graph, f, inner):
                        bind(f, h, axes, f"via {prov}")
                        note_jit_root(h, h.name)

    # pass 3: propagation fixpoint — contexts flow binder->target and
    # caller->callee (calls and callback references)
    edges: Dict[FKey, Set[FKey]] = {}
    for f in graph.functions:
        out = edges.setdefault(fkey(f), set())
        for site in f.calls:
            resolved = graph.resolve(f, site)
            for g in resolved:
                out.add(fkey(g))
            if not resolved and not site.receiver:
                # bare call to a nested def inside a method: the shared
                # resolver skips these (they carry a cls) — scope-aware
                # resolution finds the one the name actually binds
                for g in _name_targets(graph, f, site.callee):
                    if fkey(g) != fkey(f):
                        out.add(fkey(g))
            args: List[ast.AST] = [
                a for a in site.node.args if id(a) not in mapped_args
            ] + [kw.value for kw in site.node.keywords]
            # one level into list/tuple args: lax.switch branch lists,
            # defvjp pairs
            for arg in list(args):
                if isinstance(arg, (ast.List, ast.Tuple)):
                    args.extend(arg.elts)
            for arg in args:
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    for g in _fn_targets(graph, f, arg):
                        if fkey(g) != fkey(f):
                            out.add(fkey(g))

    def add_ctx(qual: FKey, ctx: Ctx, prov: str) -> bool:
        cur = env.contexts.setdefault(qual, {})
        if ctx in cur:
            return False
        if len(cur) >= _CTX_CAP:
            # collapse: one open union context keeps soundness
            union = frozenset().union(*(c.axes for c in cur)) | ctx.axes
            collapsed = Ctx(frozenset(union), True)
            if collapsed in cur:
                return False
            cur.clear()
            cur[collapsed] = "(merged contexts)"
            return True
        cur[ctx] = prov
        return True

    # a binder that is itself a binding target — or reachable from one
    # — may GAIN contexts during the fixpoint; processing its bindings'
    # base case early would freeze a stale closed context (a
    # definition-order-dependent false positive).  Such binders wait for
    # their contexts; only binders that provably never gain any use the
    # base case.
    holders: Set[FKey] = {t for _, t, _, _ in bindings}
    hchanged = True
    while hchanged:
        hchanged = False
        for src, dsts in edges.items():
            if src in holders:
                new = dsts - holders
                if new:
                    holders |= new
                    hchanged = True

    changed = True
    iters = 0
    while changed and iters < 64:
        changed = False
        iters += 1
        for binder, target, axes, prov in bindings:
            base = env.contexts.get(binder)
            if base:
                for ctx in list(base):
                    if add_ctx(target, ctx.merged(axes), prov):
                        changed = True
            elif binder not in holders:
                ctx = Ctx(axes or frozenset(), axes is None)
                if add_ctx(target, ctx, prov):
                    changed = True
        for src, dsts in edges.items():
            ctxs = env.contexts.get(src)
            if not ctxs:
                continue
            for dst in dsts:
                for ctx, prov in list(ctxs.items()):
                    if add_ctx(dst, ctx, prov):
                        changed = True

    # pass 4: jit-scope reachability with root attribution
    roots = dict(jit_root_names)
    changed = True
    iters = 0
    while changed and iters < 64:
        changed = False
        iters += 1
        for src, dsts in edges.items():
            names = roots.get(src)
            if not names:
                continue
            for dst in dsts:
                cur = roots.setdefault(dst, set())
                before = len(cur)
                cur |= names
                if len(cur) != before:
                    changed = True
    env.jit_roots = roots
    # direct roots keep their own name as attribution
    for qual in jit_root_names:
        env.jit_roots.setdefault(qual, set()).update(jit_root_names[qual])

    return env


_ENV_CACHE: Dict[str, AxisEnv] = {}


def axis_environment(root: str) -> AxisEnv:
    """Build (or reuse) the axis environment for ``root`` — all three
    kf-shard rules run over one tree in one CLI pass."""
    key = os.path.abspath(root)
    envp = _ENV_CACHE.get(key)
    if envp is None:
        envp = _ENV_CACHE[key] = build(key)
    return envp


def invalidate_cache() -> None:
    _ENV_CACHE.clear()
