"""shard-spec checker: PartitionSpecs must match the mesh that runs them.

A ``PartitionSpec`` axis the mesh never declared, a duplicated axis
(one mesh axis cannot shard two dims), or an ``in_specs``/``out_specs``
tuple whose arity disagrees with the mapped function's signature all
raise at trace time — on the pod, hours into a queue slot, never in a
single-device unit test.  Three checks, on the shared
:mod:`~kungfu_tpu.analysis.axisenv` substrate:

* **axis validity** — every *literal* axis entry of every
  ``PartitionSpec(...)`` (aliased ``P`` included, resolved through the
  module's real imports) must be an axis some mesh in the tree declares;
  where the spec is lexically an ``in_specs``/``out_specs`` of a
  ``shard_map`` whose mesh resolved (or the spec half of a
  ``NamedSharding(mesh, ...)``), it must name an axis of THAT mesh.
  ``None`` entries (unconstrained dims) and dynamic expressions are
  fine; nested tuples (multi-axis dims) are flattened.
* **duplicate axis** — the same axis twice in one spec.
* **arity** — ``in_specs`` given as a literal tuple is diffed against
  the mapped function's positional signature (defaults give a range;
  ``*args`` drops the upper bound), and a literal ``out_specs`` tuple
  against the function's return statements when every return is an
  explicit tuple literal.  Either mismatch is today's
  ``TypeError``/``ValueError`` at trace time; pre-submit here.

Suppress with ``# kflint: allow(shard-spec)`` on the flagged line.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from kungfu_tpu.analysis.axisenv import AxisEnv, axis_environment
from kungfu_tpu.analysis.core import (
    Violation,
    iter_py_files,
    parse_module,
    relpath,
    suppressed,
    terminal_name,
)

CHECKER = "shard-spec"

_SKIP_PREFIXES = ("kungfu_tpu/analysis/",)


def _pspec_aliases(tree: ast.AST) -> Set[str]:
    """Names this module binds to jax.sharding.PartitionSpec."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "PartitionSpec":
                    out.add(a.asname or a.name)
    out.add("PartitionSpec")  # attribute form jax.sharding.PartitionSpec
    return out


def _flatten_axes(value) -> Optional[List[Optional[str]]]:
    """Spec entries -> flat axis names (None entries kept as None);
    None result = some entry is not statically a str/None/tuple."""
    flat: List[Optional[str]] = []

    def rec(v) -> bool:
        if v is None or isinstance(v, str):
            flat.append(v)
            return True
        if isinstance(v, tuple):
            return all(rec(e) for e in v)
        return False

    return flat if rec(value) else None


def _spec_entries(env: AxisEnv, func, call: ast.Call
                  ) -> List[Tuple[ast.AST, Optional[List[Optional[str]]]]]:
    """Each P(...) argument with its statically-evaluated axis names."""
    out = []
    for arg in call.args:
        v = env.eval_in(func, arg)
        from kungfu_tpu.analysis.axisenv import _EVAL_FAIL

        out.append((arg, None if v is _EVAL_FAIL else _flatten_axes(v)))
    return out


def _positional_params(node: ast.AST,
                       drop_self: bool) -> Tuple[int, Optional[int]]:
    """(required, max|None-for-varargs) positional arity."""
    a = node.args
    params = list(a.posonlyargs) + list(a.args)
    if drop_self and params and params[0].arg in ("self", "cls"):
        params = params[1:]
    required = len(params) - len(a.defaults)
    return required, (None if a.vararg is not None else len(params))


def _return_arity(node: ast.AST) -> Optional[int]:
    """Length of the function's returned tuple, when EVERY return is an
    explicit tuple literal of one consistent length; else None (a
    single-expression return may still be a tuple-valued variable)."""
    lens: Set[int] = set()
    stack: List[ast.AST] = list(node.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Return) and n.value is not None:
            if isinstance(n.value, ast.Tuple):
                lens.add(len(n.value.elts))
            else:
                return None
        stack.extend(ast.iter_child_nodes(n))
    return lens.pop() if len(lens) == 1 else None


def check(root: str) -> List[Violation]:
    env = axis_environment(root)
    out: List[Violation] = []
    supp_cache: Dict[str, Dict[int, set]] = {}

    def flag(path: str, line: int, msg: str) -> None:
        if path not in supp_cache:
            supp_cache[path] = parse_module(os.path.join(root, path)).supp
        if not suppressed(supp_cache[path], line, CHECKER):
            out.append(Violation(CHECKER, path, line, msg))

    vocab = env.vocabulary
    #: P-call nodes already checked precisely against a specific mesh
    precise: Set[int] = set()

    def check_spec(func, call: ast.Call,
                   mesh_axes: Optional[frozenset],
                   where: str) -> None:
        seen: Set[str] = set()
        for arg, axes in _spec_entries(env, func, call):
            if axes is None:
                continue
            for a in axes:
                if a is None:
                    continue  # unconstrained dim
                if a in seen:
                    flag(func.path, call.lineno,
                         f"PartitionSpec{where} names axis {a!r} twice — "
                         f"one mesh axis cannot shard two dimensions")
                seen.add(a)
                if mesh_axes is not None and a not in mesh_axes:
                    flag(func.path, call.lineno,
                         f"PartitionSpec{where} names axis {a!r}, but the "
                         f"mesh that reaches it declares only "
                         f"{{{', '.join(sorted(mesh_axes))}}}")
                elif mesh_axes is None and a not in vocab:
                    flag(func.path, call.lineno,
                         f"PartitionSpec{where} names axis {a!r}, which no "
                         f"Mesh/pmap in the tree declares (known axes: "
                         f"{sorted(vocab)})")

    # -- pass 1: shard_map sites — precise mesh + arity -------------------
    alias_cache: Dict[str, Set[str]] = {}

    def aliases_for(rel: str) -> Set[str]:
        if rel not in alias_cache:
            tree = parse_module(os.path.join(root, rel)).tree
            alias_cache[rel] = (_pspec_aliases(tree) if tree is not None
                                else {"PartitionSpec"})
        return alias_cache[rel]

    for site in env.shard_sites:
        func = site.func
        if any(func.path.startswith(p) for p in _SKIP_PREFIXES):
            continue
        aliases = aliases_for(func.path)
        for spec_expr, which in ((site.in_specs, "in_specs"),
                                 (site.out_specs, "out_specs")):
            if spec_expr is None:
                continue
            for node in ast.walk(spec_expr):
                if isinstance(node, ast.Call) \
                        and terminal_name(node.func) in aliases:
                    precise.add(id(node))
                    check_spec(func, node, site.axes, f" in {which}")
        # arity: in_specs literal tuple vs mapped signature
        if isinstance(site.in_specs, ast.Tuple) and site.targets:
            n = len(site.in_specs.elts)
            bad = []
            for t in site.targets:
                # drop_self only fires when the first param is literally
                # named self/cls — a bound `shard_map(self._body, ...)`
                # must diff against the CALLED arity, not the def's
                req, mx = _positional_params(t.node, drop_self=True)
                if n < req or (mx is not None and n > mx):
                    bad.append((t, req, mx))
            if bad and len(bad) == len(site.targets):
                t, req, mx = bad[0]
                want = (f"{req}" if mx == req
                        else f"{req}..{mx if mx is not None else '*'}")
                flag(func.path, site.node.lineno,
                     f"shard_map in_specs has {n} entr"
                     f"{'y' if n == 1 else 'ies'} but mapped function "
                     f"`{t.name}` takes {want} positional parameter(s) — "
                     f"this raises at trace time")
        # arity: out_specs literal tuple vs explicit tuple returns
        if isinstance(site.out_specs, ast.Tuple) and site.targets:
            n = len(site.out_specs.elts)
            arities = {_return_arity(t.node) for t in site.targets}
            arities.discard(None)
            if arities and all(a != n for a in arities):
                flag(func.path, site.node.lineno,
                     f"shard_map out_specs has {n} entr"
                     f"{'y' if n == 1 else 'ies'} but the mapped function "
                     f"returns a {sorted(arities)[0]}-tuple — this raises "
                     f"at trace time")

    # -- pass 2: every other PartitionSpec in the tree --------------------
    funcs_by_path: Dict[str, list] = {}
    for f in env.graph.functions:
        funcs_by_path.setdefault(f.path, []).append(f)
    for path in iter_py_files(root):
        rel = relpath(root, path)
        if any(rel.startswith(p) for p in _SKIP_PREFIXES):
            continue
        mod = parse_module(path)
        if mod.tree is None:
            continue
        aliases = _pspec_aliases(mod.tree)
        # map each P call to its enclosing function (for local consts
        # and NamedSharding mesh resolution)
        funcs = funcs_by_path.get(rel, [])

        def enclosing(node: ast.AST):
            best = None
            for f in funcs:
                fn = f.node
                if fn.lineno <= node.lineno <= max(
                        getattr(fn, "end_lineno", fn.lineno), fn.lineno):
                    if best is None or fn.lineno > best.node.lineno:
                        best = f
            return best

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) == "NamedSharding" and node.args:
                func = enclosing(node)
                if func is None:
                    continue
                mesh_axes = env.mesh_axes(func, node.args[0])
                if mesh_axes is None or len(node.args) < 2:
                    continue
                for sub in ast.walk(node.args[1]):
                    if isinstance(sub, ast.Call) \
                            and terminal_name(sub.func) in aliases \
                            and id(sub) not in precise:
                        precise.add(id(sub))
                        check_spec(func, sub, mesh_axes,
                                   " in NamedSharding")
            elif terminal_name(node.func) in aliases \
                    and id(node) not in precise:
                func = enclosing(node)
                if func is None:
                    # module-level spec: module consts still resolve
                    from kungfu_tpu.analysis.callgraph import (FuncInfo,
                                                               _module_of)

                    func = FuncInfo(module=_module_of(root, path), cls=None,
                                    name="<module>", path=rel, node=node,
                                    lineno=node.lineno)
                precise.add(id(node))
                check_spec(func, node, None, "")

    return sorted(out, key=lambda v: (v.path, v.line, v.message))
