"""ledger-schema checker: decision-ledger fields come from the schema.

The decision ledger (:mod:`kungfu_tpu.monitor.ledger`) is the durable
record joining every adaptive actor's knob move to its measured effect;
``kfhist --decisions`` replays those records byte-identically offline.
A typo'd field name would not error — the decision would simply replay
without its evidence (or the offline join would silently miss), the
exact failure mode ``agg-schema`` kills for the live snapshot plane.
So: every producer goes through ``ledger.ledger_record(<name>=...)`` /
``ledger.record_decision(actor, knob, old, new, <name>=...)`` and every
reader through ``ledger.lfield(obj, "<name>")``, and this rule requires
the names at those call sites to be **string literals / literal
keywords** that appear in the ``LEDGER_FIELDS`` declaration (parsed
straight from ledger.py, so the schema cannot drift from the
enforcement).

Recognized call shapes (per-file import tracking, same conservatism as
``agg-schema``/``trace-vocab``):

* ``from kungfu_tpu.monitor import ledger [as L]`` →
  ``L.ledger_record(...)`` / ``L.lfield(...)`` /
  ``L.record_decision(...)``
* ``from kungfu_tpu.monitor.ledger import ledger_record [as r],
  lfield [as f], record_decision [as d]`` → direct calls
* ``import kungfu_tpu.monitor.ledger`` → full-path attribute calls

Unrelated methods of the same names on other objects are not flagged
(their receiver does not resolve to the ledger module).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from kungfu_tpu.analysis.core import (
    Violation,
    iter_py_files,
    parse_module,
    relpath,
    suppressed,
)

CHECKER = "ledger-schema"

LEDGER_PATH = os.path.join("kungfu_tpu", "monitor", "ledger.py")
LEDGER_MODULE = "kungfu_tpu.monitor.ledger"
_FUNCS = ("ledger_record", "lfield", "record_decision")
_SCHEMA_NAME = "LEDGER_FIELDS"
#: record_decision's positional/named parameters — keywords that bind
#: them are checked as fields too (they ARE fields), but a caller may
#: also pass them positionally
_DECISION_PARAMS = ("actor", "knob", "old", "new")


def _schema(root: str) -> Set[str]:
    """``LEDGER_FIELDS`` parsed from ledger.py (string constants inside
    the declaration — the same structural read agg-schema does)."""
    path = os.path.join(root, LEDGER_PATH)
    if not os.path.isfile(path):
        return set()
    tree = parse_module(path).tree
    if tree is None:
        return set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == _SCHEMA_NAME
        ):
            return {
                sub.value
                for sub in ast.walk(node.value)
                if isinstance(sub, ast.Constant)
                and isinstance(sub.value, str)
            }
    return set()


def _ledger_aliases(tree: ast.Module) -> tuple:
    """``(module_aliases, func_aliases)``: names bound to the ledger
    module, and names bound directly to the checked functions."""
    mod_aliases: Set[str] = set()
    func_aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "kungfu_tpu.monitor":
                for a in node.names:
                    if a.name == "ledger":
                        mod_aliases.add(a.asname or a.name)
            elif node.module == LEDGER_MODULE:
                for a in node.names:
                    if a.name in _FUNCS:
                        func_aliases[a.asname or a.name] = a.name
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == LEDGER_MODULE and a.asname:
                    mod_aliases.add(a.asname)
    return mod_aliases, func_aliases


def _full_path(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _ledger_call(node: ast.Call, mod_aliases: Set[str],
                 func_aliases: Dict[str, str]) -> Optional[str]:
    """The checked function's name when the call resolves to the
    ledger module, else None."""
    f = node.func
    if isinstance(f, ast.Name) and f.id in func_aliases:
        return func_aliases[f.id]
    if isinstance(f, ast.Attribute) and f.attr in _FUNCS:
        if isinstance(f.value, ast.Name) and f.value.id in mod_aliases:
            return f.attr
        if _full_path(f.value) == LEDGER_MODULE:
            return f.attr
    return None


def _check_lfield(node: ast.Call, schema: Set[str], rel: str,
                  out: List[Violation]) -> None:
    name_arg = None
    if len(node.args) >= 2:
        name_arg = node.args[1]
    else:
        for kw in node.keywords:
            if kw.arg == "name":
                name_arg = kw.value
    if name_arg is None:
        out.append(Violation(
            CHECKER, rel, node.lineno,
            "ledger.lfield() called without a field name",
        ))
        return
    if not (isinstance(name_arg, ast.Constant)
            and isinstance(name_arg.value, str)):
        out.append(Violation(
            CHECKER, rel, node.lineno,
            "ledger.lfield() name must be a string literal from "
            "LEDGER_FIELDS (a dynamic field cannot be checked and a "
            "typo would silently drop the decision's evidence)",
        ))
    elif name_arg.value not in schema:
        out.append(Violation(
            CHECKER, rel, node.lineno,
            f"ledger.lfield() name {name_arg.value!r} is not in "
            f"LEDGER_FIELDS (kungfu_tpu/monitor/ledger.py) — add it "
            f"there first or fix the typo",
        ))


def _check_record(node: ast.Call, fn: str, schema: Set[str], rel: str,
                  out: List[Violation]) -> None:
    for kw in node.keywords:
        if kw.arg is None:
            out.append(Violation(
                CHECKER, rel, node.lineno,
                f"{fn}(**dynamic) cannot be schema-checked — pass "
                f"literal keyword fields",
            ))
        elif kw.arg not in schema:
            out.append(Violation(
                CHECKER, rel, node.lineno,
                f"{fn}() field {kw.arg!r} is not in LEDGER_FIELDS "
                f"(kungfu_tpu/monitor/ledger.py) — add it there first "
                f"or fix the typo",
            ))


def check(root: str) -> List[Violation]:
    schema = _schema(root)
    if not schema:
        return []  # no ledger module in this tree — nothing to enforce
    out: List[Violation] = []
    for path in iter_py_files(root):
        # the schema owner builds/validates records structurally
        if os.path.abspath(path) == os.path.abspath(
                os.path.join(root, LEDGER_PATH)):
            continue
        mod = parse_module(path)
        if mod.tree is None or "ledger" not in mod.source:
            continue
        tree = mod.tree
        mod_aliases, func_aliases = _ledger_aliases(tree)
        if not mod_aliases and not func_aliases:
            continue
        supp = mod.supp
        rel = relpath(root, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _ledger_call(node, mod_aliases, func_aliases)
            if fn is None or suppressed(supp, node.lineno, CHECKER):
                continue
            if fn == "lfield":
                _check_lfield(node, schema, rel, out)
            else:
                _check_record(node, fn, schema, rel, out)
    return sorted(out, key=lambda v: (v.path, v.line))
