"""jit-purity checker: no host syncs or side effects inside jitted code.

Inside a function that is jit-compiled — decorated with (or passed to)
``jax.jit`` / ``pmap`` / ``shard_map``, including the
``functools.partial(jax.jit, ...)`` form — and inside module-local
functions it calls (one level deep), flag the classic host-round-trip
and side-effect calls:

* ``.item()`` / ``.tolist()`` / ``.block_until_ready()``
* ``float(x)`` / ``int(x)`` on non-static values (shape/len/ndim/size
  arithmetic is static under trace and stays legal)
* ``np.asarray`` / ``np.array`` (device→host copy mid-trace)
* ``print`` (tracer leak; use ``jax.debug.print``)
* ``time.time()`` / ``time.monotonic()`` / ``time.perf_counter()``
  (traces to a constant — a silent correctness bug)

Host round-trips in jitted code are exactly the cost the cross-replica
weight-update sharding work (arXiv:2004.13336) shows dominating update
time at pod scale; a checker keeps them out structurally.  Suppress a
deliberate sync with ``# kflint: allow(jit-sync)`` on the line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from kungfu_tpu.analysis.core import (
    Violation,
    iter_py_files,
    read_lines,
    relpath,
    suppressed,
    suppressions,
    terminal_name as _terminal_name,
)

CHECKER = "jit-sync"

_JIT_NAMES = {"jit", "pmap", "shard_map"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_STATIC_MARKERS = {"shape", "ndim", "size", "len", "dtype", "itemsize", "nbytes"}


def _jit_wrapper_name(call_or_deco: ast.AST) -> Optional[str]:
    """The jit-family name if this decorator/callee is one, unwrapping
    ``functools.partial(jax.jit, ...)``."""
    node = call_or_deco
    if isinstance(node, ast.Call):
        fname = _terminal_name(node.func)
        if fname == "partial" and node.args:
            inner = _terminal_name(node.args[0])
            if inner in _JIT_NAMES:
                return inner
        if fname in _JIT_NAMES:
            return fname
        return None
    name = _terminal_name(node)
    return name if name in _JIT_NAMES else None


class _ModuleIndex(ast.NodeVisitor):
    """All function defs in a module + which ones enter jit scope."""

    def __init__(self) -> None:
        # name -> ALL defs carrying it: names repeat across scopes in
        # this tree (every trainer has a `body`/`step`), and scanning
        # only the first def would silently pass a sync in the others
        self.funcs: Dict[str, List[ast.AST]] = {}
        self.jitted: Set[str] = set()
        self.np_aliases: Set[str] = set()
        self.time_aliases: Set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "numpy":
                self.np_aliases.add(a.asname or "numpy")
            if a.name == "time":
                self.time_aliases.add(a.asname or "time")
        self.generic_visit(node)

    def _visit_func(self, node) -> None:
        self.funcs.setdefault(node.name, []).append(node)
        for deco in node.decorator_list:
            if _jit_wrapper_name(deco):
                self.jitted.add(node.name)
        self.generic_visit(node)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node: ast.Call) -> None:
        # call form: jax.jit(fn) / shard_map(body, mesh=...) — possibly
        # nested, jit(shard_map(fn, ...)); mark every local function
        # threaded through a jit-family wrapper
        if _jit_wrapper_name(node):
            queue = list(node.args[:1])
            while queue:
                arg = queue.pop()
                if isinstance(arg, ast.Call) and _jit_wrapper_name(arg):
                    queue.extend(arg.args[:1])
                else:
                    name = _terminal_name(arg)
                    if name:
                        self.jitted.add(name)
        self.generic_visit(node)


def _is_static_expr(node: ast.AST) -> bool:
    """Shape arithmetic and other trace-time constants: legal under jit."""
    if isinstance(node, ast.Constant):
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_MARKERS:
            return True
        if isinstance(sub, ast.Call) and _terminal_name(sub.func) == "len":
            return True
    return False


class _BodyScan(ast.NodeVisitor):
    def __init__(self, index: _ModuleIndex, depth: int) -> None:
        self.index = index
        self.depth = depth  # 0 = the jitted function, 1 = direct callee
        self.hits: List[tuple] = []  # (line, message)
        self.callees: Set[str] = set()

    def _flag(self, node: ast.AST, what: str) -> None:
        self.hits.append((node.lineno, what))

    def visit_FunctionDef(self, node) -> None:
        # nested defs share the trace; keep scanning
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = _terminal_name(fn)
        if isinstance(fn, ast.Attribute):
            if fn.attr in _SYNC_METHODS:
                self._flag(node, f".{fn.attr}() forces a host sync")
            base = _terminal_name(fn.value)
            if base in self.index.np_aliases and fn.attr in ("asarray", "array"):
                self._flag(node, f"{base}.{fn.attr}() copies device→host mid-trace")
            if base in self.index.time_aliases and fn.attr in (
                "time", "monotonic", "perf_counter",
            ):
                self._flag(
                    node,
                    f"{base}.{fn.attr}() traces to a constant (stale clock)",
                )
        elif isinstance(fn, ast.Name):
            if name == "print":
                self._flag(node, "print() in jitted code (use jax.debug.print)")
            elif name in ("float", "int") and node.args:
                if not _is_static_expr(node.args[0]):
                    self._flag(
                        node,
                        f"{name}() on a traced value forces a host sync",
                    )
            elif (
                self.depth == 0
                and name in self.index.funcs
                and name not in self.index.jitted
            ):
                self.callees.add(name)
        self.generic_visit(node)


def _scan_file(root: str, path: str) -> List[Violation]:
    src = open(path, encoding="utf-8", errors="replace").read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Violation(CHECKER, relpath(root, path), e.lineno or 1,
                          f"syntax error prevents analysis: {e.msg}")]
    index = _ModuleIndex()
    index.visit(tree)
    if not index.jitted:
        return []
    lines = read_lines(path)
    supp = suppressions(lines)
    out: List[Violation] = []
    seen: Set[tuple] = set()

    def run(fn_name: str, depth: int, via: Optional[str]) -> None:
        # scan EVERY def of the name: which one the jit wrapper binds is
        # scope-dependent, and a gate must over- rather than under-report
        for node in index.funcs.get(fn_name, ()):
            scan = _BodyScan(index, depth)
            for stmt in node.body:
                scan.visit(stmt)
            for line, what in scan.hits:
                key = (fn_name, line, what)
                if key in seen or suppressed(supp, line, CHECKER):
                    continue
                seen.add(key)
                ctx = f" (called from jitted {via})" if via else ""
                out.append(Violation(
                    CHECKER, relpath(root, path), line,
                    f"in jit scope `{fn_name}`{ctx}: {what}",
                ))
            if depth == 0:
                for callee in sorted(scan.callees):
                    run(callee, 1, fn_name)

    for fn_name in sorted(index.jitted):
        run(fn_name, 0, None)
    return out


def check(root: str) -> List[Violation]:
    out: List[Violation] = []
    for path in iter_py_files(root):
        out.extend(_scan_file(root, path))
    return out
