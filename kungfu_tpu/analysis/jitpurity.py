"""jit-purity checker: no host syncs or side effects inside jitted code.

Inside any function whose body is traced into compiled code — decorated
with (or passed to) ``jax.jit`` / ``pmap`` / ``shard_map``, including
``functools.partial(jax.jit, ...)``, nested call forms like
``jit(shard_map(f, ...))`` and ``jit(value_and_grad(f))`` — and inside
every function reachable from one through the project call graph
(resolved calls AND callback references like ``lax.scan(step, ...)``,
to any depth, across modules), flag the classic host-round-trip and
side-effect calls:

* ``.item()`` / ``.tolist()`` / ``.block_until_ready()``
* ``float(x)`` / ``int(x)`` on non-static values (shape/len/ndim/size
  arithmetic and env-string parsing are trace-static and stay legal)
* ``np.asarray`` / ``np.array`` (device→host copy mid-trace)
* ``print`` (tracer leak; use ``jax.debug.print``)
* ``time.time()`` / ``time.monotonic()`` / ``time.perf_counter()``
  (traces to a constant — a silent correctness bug)

Host round-trips in jitted code are exactly the cost the cross-replica
weight-update sharding work (arXiv:2004.13336) shows dominating update
time at pod scale.  Reach comes from the shared
:mod:`~kungfu_tpu.analysis.axisenv` jit-scope map (the same fixpoint
the kf-shard rules use), so a sync two helpers deep — the shape the old
one-level walk missed — is attributed back to its jitted root.
Suppress a deliberate sync with ``# kflint: allow(jit-sync)``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from kungfu_tpu.analysis.axisenv import axis_environment, fkey
from kungfu_tpu.analysis.core import (
    Violation,
    iter_py_files,
    parse_module,
    relpath,
    suppressed,
    terminal_name as _terminal_name,
)

CHECKER = "jit-sync"

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_STATIC_MARKERS = {"shape", "ndim", "size", "dtype", "itemsize", "nbytes"}
#: bare-call terminals whose result is a host value, not a tracer
_HOST_VALUE_BARE = {"len", "getenv", "axis_size"}
#: (method, receiver terminal) pairs that are host values — receiver-
#: qualified so `x.prod()`/`state.get()` on a TRACED x stay syncs
_HOST_VALUE_QUALIFIED = {
    ("get", "environ"), ("getenv", "os"),
    ("prod", "math"), ("prod", "np"), ("prod", "numpy"),
    ("ceil", "math"), ("floor", "math"),
    # lax.axis_size is a static mesh-axis extent — the exact remedy the
    # recompile-hazard messages prescribe (axis_index stays OUT: it
    # returns a tracer)
    ("axis_size", "lax"),
}


def _host_value_call(call: ast.Call,
                     static_names: Optional[Set[str]] = None) -> bool:
    fn = call.func
    name = _terminal_name(fn)
    if isinstance(fn, ast.Name):
        return name in _HOST_VALUE_BARE
    if isinstance(fn, ast.Attribute):
        if (name, _terminal_name(fn.value)) not in _HOST_VALUE_QUALIFIED:
            return False
        if name in ("prod", "ceil", "floor"):
            # np.prod(x.shape) is static; np.prod(x) on a TRACED x is a
            # host concretization — the math family qualifies only when
            # its own arguments are static
            return all(_is_static_expr(a, static_names)
                       for a in call.args)
        return True
    return False


def _module_aliases(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(numpy aliases, time aliases) bound by this module's imports."""
    np_aliases: Set[str] = set()
    time_aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    np_aliases.add(a.asname or "numpy")
                if a.name == "time":
                    time_aliases.add(a.asname or "time")
    return np_aliases, time_aliases


def _is_static_expr(node: ast.AST,
                    static_names: Optional[Set[str]] = None) -> bool:
    """Shape arithmetic, env parsing, and other trace-time constants:
    legal under jit.  ``static_names`` are locals the enclosing body
    assigned from static expressions (``T = x.shape[0]``)."""
    if isinstance(node, ast.Constant):
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_MARKERS:
            return True
        if isinstance(sub, ast.Call) \
                and _host_value_call(sub, static_names):
            return True
        if static_names and isinstance(sub, ast.Name) \
                and sub.id in static_names:
            return True
    return False


def _static_locals(stmts) -> Set[str]:
    """Names assigned from static expressions anywhere in the body —
    one flow-insensitive pass, transitive (``T = x.shape[0]; C = T * 2``)."""
    assigns: List[Tuple[str, ast.AST]] = []
    for stmt in stmts:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                assigns.append((n.targets[0].id, n.value))
    static: Set[str] = set()
    # to convergence, not a fixed pass count: textual order need not be
    # topological (chains assigned inside loops arrive reversed)
    for _ in range(len(assigns) + 1):
        grew = False
        for name, value in assigns:
            if name not in static and _is_static_expr(value, static):
                static.add(name)
                grew = True
        if not grew:
            break
    return static


class _BodyScan(ast.NodeVisitor):
    """Sync/side-effect call sites in one function body (nested defs
    included — they share the trace)."""

    def __init__(self, np_aliases: Set[str], time_aliases: Set[str],
                 static_names: Optional[Set[str]] = None) -> None:
        self.np_aliases = np_aliases
        self.time_aliases = time_aliases
        self.static_names = static_names or set()
        self.hits: List[Tuple[int, str]] = []

    def _flag(self, node: ast.AST, what: str) -> None:
        self.hits.append((node.lineno, what))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = _terminal_name(fn)
        if isinstance(fn, ast.Attribute):
            if fn.attr in _SYNC_METHODS:
                self._flag(node, f".{fn.attr}() forces a host sync")
            base = _terminal_name(fn.value)
            if base in self.np_aliases and fn.attr in ("asarray", "array"):
                self._flag(node,
                           f"{base}.{fn.attr}() copies device→host mid-trace")
            if base in self.time_aliases and fn.attr in (
                "time", "monotonic", "perf_counter",
            ):
                self._flag(
                    node,
                    f"{base}.{fn.attr}() traces to a constant (stale clock)",
                )
        elif isinstance(fn, ast.Name):
            if name == "print":
                self._flag(node, "print() in jitted code (use jax.debug.print)")
            elif name in ("float", "int") and node.args:
                if not _is_static_expr(node.args[0], self.static_names):
                    self._flag(
                        node,
                        f"{name}() on a traced value forces a host sync",
                    )
        self.generic_visit(node)


def check(root: str) -> List[Violation]:
    env = axis_environment(root)
    alias_cache: Dict[str, Tuple[Set[str], Set[str]]] = {}

    def aliases_for(path: str) -> Tuple[Set[str], Set[str]]:
        if path not in alias_cache:
            tree = parse_module(os.path.join(root, path)).tree
            alias_cache[path] = (_module_aliases(tree) if tree is not None
                                 else (set(), set()))
        return alias_cache[path]

    out: List[Violation] = []
    seen: Set[Tuple[str, int, str]] = set()

    # an unparseable file is invisible to EVERY rule — this checker owns
    # surfacing it (as it did pre-callgraph), so the suite cannot go
    # green on a tree it could not actually analyze
    for path in iter_py_files(root):
        err = parse_module(path).error
        if err is not None:
            out.append(Violation(
                CHECKER, relpath(root, path), err.lineno or 1,
                f"syntax error prevents analysis: {err.msg}"))

    for func in env.graph.functions:
        roots = env.jit_roots.get(fkey(func))
        if not roots:
            continue
        np_aliases, time_aliases = aliases_for(func.path)
        scan = _BodyScan(np_aliases, time_aliases,
                         _static_locals(func.node.body))
        for stmt in func.node.body:
            scan.visit(stmt)
        if not scan.hits:
            continue
        supp = parse_module(os.path.join(root, func.path)).supp
        is_root = func.name in roots
        via = "" if is_root else (
            f" (called from jitted {sorted(roots)[0]})")
        for line, what in scan.hits:
            key = (func.path, line, what)
            if key in seen or suppressed(supp, line, CHECKER):
                continue
            seen.add(key)
            out.append(Violation(
                CHECKER, func.path, line,
                f"in jit scope `{func.name}`{via}: {what}",
            ))

    return sorted(out, key=lambda v: (v.path, v.line, v.message))
