"""handle-discipline checker: async collective handles must settle.

kf-overlap made collectives issueable (``all_reduce_async`` /
``reduce_scatter_async`` / ``all_gather_async`` return a
:class:`~kungfu_tpu.comm.engine.CollectiveHandle`), which creates three
brand-new ways to write a latent hang or a silent data loss:

* **dropped** — the call's result is discarded.  The collective still
  runs and still consumes the in-flight window, but its typed failure
  (``PeerFailureError`` with the suspect rank) can never surface: the
  first symptom is the NEXT collective wedging on a stranded recv.
* **never waited / not waited on every path** — an early ``return`` (or
  an ``if`` with a wait on only one side) leaks the handle past its
  issuing scope; same failure mode, harder to find.
* **held across a membership change** — ``elastic_step`` / the shrink
  ladder rebuild the engine for the new epoch; a handle issued before
  the change references the OLD epoch's tags and peer set.  The engine
  fences this at runtime (``drain_async`` in ``Peer._propose`` and
  ``shrink_to_survivors``), but code that *waits on the stale handle
  after the change* is wrong even when the drain saves the wire — the
  lint catches it statically.

Scope and mechanics (per function, conservative): a handle is a name
assigned directly from a ``*_async(...)`` call.  A handle **settles**
when ``<name>.wait(...)`` is called; it **escapes** (ownership
transferred — fine) when returned/yielded, passed as a call argument
(e.g. ``handles.append(h)``), stored into an attribute/subscript, or
placed in a container literal.  ``*_async`` calls nested inside larger
expressions already flow somewhere and are not tracked.  Path checking
is block-structured (if/else both sides, try body+handlers or finally),
not a full CFG — suppress deliberate exceptions with
``# kflint: allow(handle-discipline)`` and a comment saying why.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from kungfu_tpu.analysis.core import (
    Violation,
    iter_py_files,
    parse_module,
    relpath,
    suppressed,
    terminal_name as _terminal,
)

CHECKER = "handle-discipline"

#: call sites that apply a membership change — a live handle must not
#: straddle one (the engine's runtime drain is the belt; this is the
#: suspenders)
_FENCE_CALLS = {
    "elastic_step", "shrink_to_survivors", "recover_from_peer_failure",
    "recover_from_failure", "propose_new_size", "resize_cluster",
    "resize_cluster_from_url", "_propose",
    # the serving plane's membership boundary (kf-serve): excluding a
    # worker/slice re-dispatches its in-flight requests — a live async
    # handle must not straddle that either
    "mark_worker_dead",
    # kf-pipeline stage re-carve (parallel/pp.py): the boundary's
    # segment exchange reuses the host channel and the post-carve world
    # has a different stage map — a handle issued under the old stage
    # geometry (its tags name the old epoch's virtual stages) must
    # settle before the carve, exactly like a resize
    "recarve", "recarve_stages_after_shrink", "recarve_after_shrink",
    # kf-persist (elastic/persist.py): a live async handle must not
    # straddle the durable plane's boundaries either.  restore_from_
    # manifest rebuilds state from disk — a handle issued against the
    # pre-restore state would settle into a world that no longer exists;
    # persist_fence drains the plane's own internally-tracked writes, so
    # an explicitly-held handle crossing it is at best a double-wait
    # and usually a straddle bug
    "persist_fence", "restore_from_manifest",
}

_WAIT_ATTRS = {"wait"}

#: ``*_async``-named calls that do NOT return a handle (the drain is
#: the fence itself — its return value is a drained count)
_NON_ISSUE = {"drain_async"}


def _is_async_issue(call: ast.Call) -> bool:
    name = _terminal(call.func)
    return bool(name) and name.endswith("_async") and name not in _NON_ISSUE


def _stmt_settles(stmt: ast.stmt, name: str) -> bool:
    """Does executing this single statement wait or escape ``name``?
    (Looks only at the statement's own expressions — compound bodies are
    the path walker's job.)  Nested function definitions are opaque."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return False
    nodes = (
        list(ast.walk(stmt)) if not isinstance(
            stmt, (ast.If, ast.For, ast.While, ast.Try, ast.With))
        else [n for expr in _stmt_exprs(stmt) for n in ast.walk(expr)]
    )
    for n in nodes:
        if isinstance(n, ast.Call):
            f = n.func
            if (isinstance(f, ast.Attribute) and f.attr in _WAIT_ATTRS
                    and isinstance(f.value, ast.Name)
                    and f.value.id == name):
                return True
            for a in list(n.args) + [k.value for k in n.keywords]:
                if _expr_mentions(a, name):
                    return True  # passed on: ownership transferred
        elif isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
            v = getattr(n, "value", None)
            if v is not None and _expr_mentions(v, name):
                return True
        elif isinstance(n, (ast.List, ast.Tuple, ast.Set, ast.Dict)):
            if _expr_mentions(n, name):
                return True
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)) \
                    and _expr_mentions(stmt.value, name):
                return True
    return False


def _stmt_exprs(stmt: ast.stmt) -> List[ast.expr]:
    """The header expressions of a compound statement (test/iter/items)
    — the parts that execute before its body."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, ast.For):
        return [stmt.iter]
    if isinstance(stmt, ast.With):
        return [i.context_expr for i in stmt.items]
    return []


def _expr_mentions(node: ast.expr, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


#: tri-state path verdicts for one block: every path settled the handle
#: / some path left the function with it live / fell through unsettled
_SETTLED, _LEAKED, _FLOWS = "settled", "leaked", "flows"


def _walk_paths(stmts: List[ast.stmt], name: str) -> str:
    for st in stmts:
        if _stmt_settles(st, name):
            return _SETTLED
        if isinstance(st, (ast.Return, ast.Raise)):
            return _LEAKED  # leaves the function with the handle live
        if isinstance(st, ast.If):
            a = _walk_paths(st.body, name)
            b = _walk_paths(st.orelse, name) if st.orelse else _FLOWS
            if _LEAKED in (a, b):
                return _LEAKED
            if a == _SETTLED and b == _SETTLED:
                return _SETTLED
            # one side settled, the other falls through: keep scanning —
            # the fall-through path still needs a settle below
        elif isinstance(st, ast.Try):
            if _walk_paths(st.finalbody, name) == _SETTLED:
                return _SETTLED  # finally runs on every exit, even return
            b = _walk_paths(st.body, name)
            if b == _LEAKED:
                return _LEAKED
            hs = [_walk_paths(h.body, name) for h in st.handlers]
            # a handler that re-raises abandons the handle deliberately
            # (the failure is the collective's own); one that swallows
            # and falls through keeps the obligation alive
            if b == _SETTLED and all(
                    h == _SETTLED or (hh.body
                                      and isinstance(hh.body[-1], ast.Raise))
                    for h, hh in zip(hs, st.handlers)):
                return _SETTLED
            if any(h == _LEAKED for h in hs):
                return _LEAKED
        elif isinstance(st, ast.With):
            t = _walk_paths(st.body, name)
            if t != _FLOWS:
                return t
        # loops: a settle inside may run zero times — no guarantee
    return _FLOWS


def _block_settles(stmts: List[ast.stmt], name: str) -> bool:
    """Block-structured guarantee: executing ``stmts`` settles ``name``
    on EVERY path (an early return/raise without a settle is a leak)."""
    return _walk_paths(stmts, name) == _SETTLED


def _settled_anywhere(stmts: List[ast.stmt], name: str) -> bool:
    for st in stmts:
        for n in ast.walk(st):
            if isinstance(n, ast.stmt) and _stmt_settles(n, name):
                return True
    return False


def _fence_before_settle(stmts: List[ast.stmt], name: str
                         ) -> Optional[ast.Call]:
    """First membership-change call executed while ``name`` is still
    live (scanning stops at the first statement guaranteeing a
    settle)."""
    for st in stmts:
        if _stmt_settles(st, name):
            return None
        for n in ast.walk(st):
            if isinstance(n, ast.Call) and _terminal(n.func) in _FENCE_CALLS:
                return n
        if isinstance(st, ast.If) and st.orelse \
                and _block_settles(st.body, name) \
                and _block_settles(st.orelse, name):
            return None
        if isinstance(st, ast.Try) and (
                _block_settles(st.finalbody, name)
                or _block_settles(st.body, name)):
            return None
        if isinstance(st, ast.With) and _block_settles(st.body, name):
            # a wait inside a with-block settles before the block exits
            # — a fence AFTER the with is fine (the fence scan above
            # already covered a fence inside it, conservatively)
            return None
    return None


def _continuation(body: List[ast.stmt], target: ast.stmt
                  ) -> Optional[List[ast.stmt]]:
    """The statements that execute after ``target`` within ``body``'s
    block structure: the suffix of the innermost block holding it,
    then the suffixes of each enclosing block, flattened in execution
    order.  None when ``target`` is not under ``body``."""
    for i, st in enumerate(body):
        if st is target:
            return list(body[i + 1:])
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue  # a nested scope owns its own discipline
        for sub in (getattr(st, "body", None), getattr(st, "orelse", None),
                    getattr(st, "finalbody", None)):
            if sub:
                got = _continuation(sub, target)
                if got is not None:
                    return got + list(body[i + 1:])
        for h in getattr(st, "handlers", []) or []:
            got = _continuation(h.body, target)
            if got is not None:
                return got + list(body[i + 1:])
    return None


def _scan_function(fn, rel: str, supp, out: List[Violation]) -> None:
    def flag(line: int, msg: str) -> None:
        if not suppressed(supp, line, CHECKER):
            out.append(Violation(CHECKER, rel, line, msg))

    # statements of THIS function only — nested defs are scanned as
    # their own functions by _scan_module's walk
    own_stmts: List[ast.stmt] = []
    stack: List[ast.stmt] = list(fn.body)
    while stack:
        n = stack.pop()
        own_stmts.append(n)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        stack.extend(s for s in ast.iter_child_nodes(n)
                     if isinstance(s, ast.stmt))
        stack.extend(s for h in getattr(n, "handlers", []) or []
                     for s in h.body)
    for st in own_stmts:
        # dropped: the call IS the statement — result discarded
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call) \
                and _is_async_issue(st.value):
            flag(st.lineno,
                 f"async handle from {_terminal(st.value.func)}() is "
                 "dropped — its typed failure (PeerFailureError with the "
                 "suspect rank) can never surface; wait() it or hand it "
                 "to an owner")
            continue
        if not (isinstance(st, ast.Assign) and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
                and isinstance(st.value, ast.Call)
                and _is_async_issue(st.value)):
            continue
        name = st.targets[0].id
        cont = _continuation(fn.body, st)
        if cont is None:
            continue
        verb = _terminal(st.value.func)
        if not _settled_anywhere(cont, name):
            flag(st.lineno,
                 f"async handle {name!r} from {verb}() is never waited "
                 "in this function and never escapes it — a leaked "
                 "in-flight collective")
            continue
        if not _block_settles(cont, name):
            flag(st.lineno,
                 f"async handle {name!r} from {verb}() is not waited on "
                 "every control-flow path (an early return or one-sided "
                 "branch leaks the in-flight collective)")
        fence = _fence_before_settle(cont, name)
        if fence is not None:
            flag(fence.lineno,
                 f"membership-change call {_terminal(fence.func)}() runs "
                 f"while async handle {name!r} is still in flight — a "
                 "handle may never cross a resize/shrink boundary; "
                 "wait() it first (the engine drain is the runtime "
                 "backstop, not a license)")


def _scan_module(root: str, path: str) -> List[Violation]:
    mod = parse_module(path)
    if mod.tree is None:
        return []
    rel = relpath(root, path)
    out: List[Violation] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_function(node, rel, mod.supp, out)
    return out


def check(root: str) -> List[Violation]:
    out: List[Violation] = []
    for path in iter_py_files(root):
        out.extend(_scan_module(root, path))
    return out
