"""kf-verify: static SPMD protocol verifier (the ``proto-verify`` rule).

Proves three properties of the comm plane over *every* valid
``ParallelPlan`` geometry up to ``KF_VERIFY_MAX_RANKS`` ranks, without
importing or running any of it:

1. **ordering consistency** — all members of a group issue the same
   collective sequence; an ``if`` guard reading rank-like state that
   feeds a collective on one side only is flagged, as is a bucket loop
   whose tag index runs against canonical order (``reversed(...)`` /
   ``b{N - 1 - i}`` — a *uniform* swap is invisible to cross-rank
   comparison, so this is a static rule, not a simulation rule);
2. **tag pairing** — within a self-contained entrypoint every p2p send
   skeleton is matched by a recv skeleton and vice versa (no orphans),
   no duplicate in-flight tags in any simulated geometry, and the
   prefetch window stays below the engine async pool;
3. **deadlock freedom** — symmetric blocking-recv-before-send is
   flagged statically; rank-guarded mirror arms (two sides of an ``if``
   that exchange with each other) are 2-rank simulated including
   ``drain_async``-style fences; and every enumerated geometry of the
   pipeline step, the ZeRO bucket loops, both recarve protocols, the
   ring mirrors and the serve replay path is run through an
   event-driven multi-rank simulator that must terminate with an empty
   wire.

The front half (site extraction, tag templates, branch/loop context)
lives in :mod:`kungfu_tpu.analysis.commgraph`.  The geometry layer does
not re-model the schedule math: ``build_schedule``, ``stage_partition``,
``_chunk_splits``, ``reshard_plan`` etc. are *executed from the parsed
source* of ``parallel/pp.py`` / ``parallel/zero.py`` (they are pure,
jax-free functions by construction), so the verifier cannot drift from
the shipped schedules.  ``EXPECTED_BINDINGS`` pins the simulator's tag
model to extracted sites the same way — if a protocol's tags change
shape, the verifier fails loudly instead of proving the wrong thing.

Knobs (read directly from the environment — this module must not
import ``utils/envs.py``, which pulls the jax-backed plan layer; the
registry entries live there, see ``verify_knobs()``):

* ``KF_VERIFY_MAX_RANKS`` (default 16) — geometry world-size ceiling;
* ``KF_VERIFY_GEOMETRY_CAP`` (default 0 = uncapped) — max geometries;
* ``KF_VERIFY_TIMEOUT_S`` (default 60) — wall-clock budget for the
  simulation sweep; on expiry remaining geometries are skipped
  (coverage shrinks, the build does not flake red).
"""

from __future__ import annotations

import ast
import math
import os
import sys
import time
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from kungfu_tpu.analysis import commgraph
from kungfu_tpu.analysis.callgraph import project_graph
from kungfu_tpu.analysis.commgraph import (
    CommSite,
    EntryProtocol,
    FALLBACK_SPECS,
    TagTemplate,
    Hole,
    engine_specs,
    entry_protocols,
)
from kungfu_tpu.analysis.core import Violation, parse_module

CHECKER = "proto-verify"

DEFAULT_MAX_RANKS = 16
DEFAULT_GEOMETRY_CAP = 0
DEFAULT_TIMEOUT_S = 60.0

PP_RELPATH = "kungfu_tpu/parallel/pp.py"
ZERO_RELPATH = "kungfu_tpu/parallel/zero.py"

#: the simulator's tag model, pinned to extraction: each (entry suffix,
#: op, skeleton) must match at least one extracted site of the shipped
#: tree, else the protocol drifted out from under the model
EXPECTED_BINDINGS: Tuple[Tuple[str, str, str], ...] = (
    ("zero::host_bucket_pipeline", "reduce_scatter", "{}.b{}"),
    ("zero::host_bucket_pipeline", "reduce_scatter_async", "{}.b{}"),
    ("zero::host_bucket_all_gather", "all_gather", "{}.b{}"),
    ("zero::host_bucket_all_gather", "all_gather_async", "{}.b{}"),
    ("HostPipeline.train_step", "send_async", "{}.t{}.rs.c{}.b{}.o{}"),
    ("HostPipeline.train_step", "recv_async", "{}.b{}.o{}"),
    ("HostPipeline.train_step", "send_async", "{}.t{}.{}.c{}.o{}"),
    ("HostPipeline.train_step", "recv_async", "{}.t{}.{}.c{}.o{}"),
    ("StageBoundary.replicate_ring", "channel.send", "kf.ppbuddy.{}"),
    ("StageBoundary.replicate_ring", "_recv_or_fail", "kf.ppbuddy.{}"),
    ("StageBoundary.recarve", "channel.send", "kf.pprc.{}.{}{}"),
    ("StageBoundary.recarve", "_recv_or_fail", "kf.pprc.{}.{}{}"),
    ("StageBoundary.recarve", "channel.send", "kf.pprc.{}.{}{}.{}"),
    ("StageBoundary.recarve", "_recv_or_fail", "kf.pprc.{}.{}{}.{}"),
    ("ZeroBoundary.replicate_ring", "channel.send", "kf.zbuddy.{}"),
    ("ZeroBoundary.replicate_ring", "_recv_or_fail", "kf.zbuddy.{}"),
    ("ZeroBoundary._recarve_channel", "channel.send",
     "kf.zrc.{}.l{}.o{}"),
    ("ZeroBoundary._recarve_channel", "_recv_or_fail",
     "kf.zrc.{}.l{}.o{}"),
    ("ZeroBoundary._recarve_channel", "channel.send",
     "kf.zrc.{}.scalars"),
    ("ZeroBoundary._recarve_channel", "_recv_or_fail",
     "kf.zrc.{}.scalars"),
    ("PersistPlane.agree_manifest", "channel.send",
     "kf.persist.agree.v{}"),
    ("PersistPlane.agree_manifest", "_recv_or_fail",
     "kf.persist.agree.v{}"),
)


def _knobs() -> Tuple[int, int, float]:
    def _int(name: str, dflt: int) -> int:
        try:
            return int(os.environ.get(name, "") or dflt)
        except ValueError:
            return dflt

    try:
        timeout = float(os.environ.get("KF_VERIFY_TIMEOUT_S", "")
                        or DEFAULT_TIMEOUT_S)
    except ValueError:
        timeout = DEFAULT_TIMEOUT_S
    return (_int("KF_VERIFY_MAX_RANKS", DEFAULT_MAX_RANKS),
            _int("KF_VERIFY_GEOMETRY_CAP", DEFAULT_GEOMETRY_CAP),
            timeout)


# -- entry point -------------------------------------------------------------
_CACHE: Dict[str, Tuple[object, List[Violation]]] = {}


def check(root: str) -> List[Violation]:
    """All proto-verify findings for ``root`` (cached per call graph —
    the CLI and the tests drive this repeatedly over one tree)."""
    key = os.path.abspath(root)
    graph = project_graph(key)
    hit = _CACHE.get(key)
    if hit is not None and hit[0] is graph:
        return list(hit[1])
    specs, entries, out = entry_protocols(key)
    out = list(out)
    for entry in entries:
        out.extend(_check_order_divergence(entry))
        out.extend(_check_canonical_order(entry))
        out.extend(_check_tag_pairing(entry))
        out.extend(_check_recv_before_send(entry))
        out.extend(_check_mirror_arms(entry))
    out.extend(_check_window_bound(key))
    out.extend(_check_bindings(entries))
    out.extend(_geometry_checks(key, entries))
    _CACHE[key] = (graph, list(out))
    return out


# -- rule A: collective ordering consistency ---------------------------------
def _skel(site: CommSite) -> Optional[str]:
    return site.tag.skeleton() if site.tag is not None else None


def _check_order_divergence(entry: EntryProtocol) -> List[Violation]:
    """A collective issued under a rank-dependent guard with no
    balancing issue of the same (op, tag skeleton) outside that guard
    side: group members diverge on the collective sequence."""
    out: List[Violation] = []
    colls = entry.collective_sites()
    for site in colls:
        guard = site.rank_guard()
        if guard is None:
            continue
        balanced = False
        for other in colls:
            if other is site:
                continue
            if other.op != site.op or _skel(other) != _skel(site):
                continue
            # balancing = same collective reachable when this guard
            # resolves the other way (other side, or not under it)
            sides = {b.side for b in other.branches
                     if b.key[0] == guard.key[0]}
            if guard.side not in sides:
                balanced = True
                break
        if not balanced:
            out.append(Violation(
                CHECKER, site.path, site.line,
                f"collective `{site.op}` issued under rank-dependent "
                f"guard (line {guard.line}, {guard.side}) with no "
                "matching issue on the other side — group members "
                "diverge on the collective sequence"))
    return out


# -- rule B: canonical bucket order ------------------------------------------
def _hole_names(hole: Hole) -> Set[str]:
    if hole.node is None:
        return set()
    return {n.id for n in ast.walk(hole.node) if isinstance(n, ast.Name)}


def _sub_right_names(expr: ast.AST) -> Set[str]:
    """Names appearing in the right operand of any ``-`` inside
    ``expr`` (the ``b{N - 1 - i}`` shape)."""
    out: Set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
            out |= {m.id for m in ast.walk(n.right)
                    if isinstance(m, ast.Name)}
    return out


def _check_canonical_order(entry: EntryProtocol) -> List[Violation]:
    """Bucket/segment tags must be issued in canonical (ascending)
    order on every rank: a *uniform* reversal passes the cross-rank
    rendezvous (all ranks swap identically) yet breaks the documented
    dp member-order reduction contract and every mixed-version
    rollout, so iteration direction is checked statically."""
    out: List[Violation] = []
    for site in entry.sites:
        if site.tag is None:
            continue
        loop_vars: Set[str] = set()
        rev_vars: Set[str] = set()
        for lp in site.loops:
            loop_vars |= set(lp.targets)
            if lp.reversed_iter:
                rev_vars |= set(lp.targets)
        for hole in site.tag.holes():
            names = _hole_names(hole)
            if not (names & loop_vars):
                continue
            if names & rev_vars:
                out.append(Violation(
                    CHECKER, site.path, site.line,
                    f"`{site.op}` tag index `{hole.src}` is driven by a "
                    "reversed loop — bucket tags must be issued in "
                    "canonical ascending order on every rank"))
                break
            if hole.node is not None \
                    and (_sub_right_names(hole.node) & loop_vars):
                out.append(Violation(
                    CHECKER, site.path, site.line,
                    f"`{site.op}` tag index `{hole.src}` subtracts the "
                    "loop variable — bucket tags must be issued in "
                    "canonical ascending order on every rank"))
                break
    return out


# -- rule C: tag pairing -----------------------------------------------------
def _check_tag_pairing(entry: EntryProtocol) -> List[Violation]:
    """Within a self-contained entrypoint, every p2p send skeleton must
    appear as a recv skeleton and vice versa.  Skipped when any tag is
    dynamic (the geometry simulation covers those) or when the entry's
    recvs live in another process (``pair_scope is None``)."""
    if entry.pair_scope != "local" or not entry.resolvable:
        return []
    sends = [s for s in entry.p2p_sites() if s.kind == "p2p-send"]
    recvs = [s for s in entry.p2p_sites() if s.kind == "p2p-recv"]
    if not sends and not recvs:
        return []
    send_sk = {_skel(s) for s in sends}
    recv_sk = {_skel(s) for s in recvs}
    out: List[Violation] = []
    for s in sends:
        if _skel(s) not in recv_sk:
            out.append(Violation(
                CHECKER, s.path, s.line,
                f"p2p send tag `{s.tag.skeleton()}` has no matching "
                "recv anywhere in this protocol — orphan send (the "
                "peer's recv window will starve or overflow)"))
    for s in recvs:
        if _skel(s) not in send_sk:
            out.append(Violation(
                CHECKER, s.path, s.line,
                f"p2p recv tag `{s.tag.skeleton()}` has no matching "
                "send anywhere in this protocol — orphan recv (every "
                "rank reaching it blocks until the peer deadline)"))
    return out


# -- rule D: deadlock freedom (static part) ----------------------------------
def _same_context(a: CommSite, b: CommSite) -> bool:
    return [x.key for x in a.branches] == [x.key for x in b.branches]


def _check_recv_before_send(entry: EntryProtocol) -> List[Violation]:
    """In a symmetric protocol (same guards on both sites), a BLOCKING
    recv of tag T ordered before every send of T deadlocks all ranks:
    each blocks receiving what its peer only sends later.  The shipped
    mirrors all send-before-recv; serve/client splits (different guard
    arms) are exempt — the 2-arm simulation covers those."""
    if entry.pair_scope != "local" or not entry.resolvable:
        return []
    out: List[Violation] = []
    sends = [s for s in entry.p2p_sites() if s.kind == "p2p-send"]
    for r in entry.p2p_sites():
        if r.kind != "p2p-recv" or not r.blocking:
            continue
        peers = [s for s in sends if _skel(s) == _skel(r)
                 and _same_context(r, s)]
        if peers and all(r.order < s.order for s in peers):
            out.append(Violation(
                CHECKER, r.path, r.line,
                f"blocking recv of `{r.tag.skeleton()}` precedes every "
                "send of the same tag in this symmetric protocol — all "
                "ranks block on a frame no rank has sent yet "
                "(serve-all-then-assemble: sends must go first)"))
    return out


def _check_mirror_arms(entry: EntryProtocol) -> List[Violation]:
    """2-rank simulation of rank-guarded mirror arms: when both sides
    of a rank-dependent ``if`` hold p2p traffic and each side's sends
    are exactly the other side's recvs (a self-contained exchange), run
    one rank down each arm — posted recvs, fences (``drain_async``)
    and blocking recvs must settle.  Catches the
    handle-across-fence cycle: post recv, fence on it, and only then
    send what the peer's fence is waiting for."""
    out: List[Violation] = []
    guards: Dict[int, Dict[str, List[CommSite]]] = {}
    for site in entry.sites:
        g = site.rank_guard()
        if g is None or site.kind == "collective":
            continue
        guards.setdefault(g.line, {}).setdefault(g.side, []).append(site)
    for line, arms in guards.items():
        body, orelse = arms.get("body", []), arms.get("orelse", [])
        if not body or not orelse:
            continue
        if any(s.tag is None for s in body + orelse):
            continue

        def skels(sites: List[CommSite], kind: str) -> Set[str]:
            return {_skel(s) for s in sites if s.kind == kind}

        if skels(body, "p2p-send") != skels(orelse, "p2p-recv") \
                or skels(orelse, "p2p-send") != skels(body, "p2p-recv"):
            continue  # not a self-contained mirror — sim layer's job
        fences = [(f.order, f.line) for f in entry.fences]

        def arm_events(sites: List[CommSite]):
            """(order-merged) sim events for one arm, one peer."""
            evs = []
            for s in sorted(sites, key=lambda s: s.order):
                for fo, _fl in fences:
                    if evs and evs[-1][0] < fo < s.order:
                        evs.append((fo, ("fence",)))
                tag = _skel(s)
                if s.kind == "p2p-send":
                    evs.append((s.order, ("send", "peer", tag)))
                elif s.blocking:
                    evs.append((s.order, ("recv", "peer", tag)))
                else:
                    evs.append((s.order,
                                ("arecv", "peer", tag, f"k{s.order}",
                                 None)))
            # a fence after the last site still gates nothing — but a
            # fence between arecv and send is the cycle, keep interior
            return [e for _, e in evs]

        def prog(events):
            for ev in events:
                if ev[0] == "fence":
                    yield ("fence",)
                else:
                    yield ev

        findings, _ = _simulate(
            {"r0": prog(arm_events(body)),
             "r1": prog(arm_events(orelse))},
            peers={"r0": "r1", "r1": "r0"})
        if findings:
            detail = findings[0]
            if detail.startswith("deadlock: "):
                detail = detail[len("deadlock: "):]
            out.append(Violation(
                CHECKER, entry.func.path, line,
                f"rank-guarded mirror arms deadlock: {detail} — a "
                "fence between posting a recv and sending the peer's "
                "frame cycles the wait-for graph"))
    return out


# -- rule F: static window bound ---------------------------------------------
def _module_int(root: str, rel: str, name: str) -> Tuple[Optional[int],
                                                         int]:
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        return None, 1
    mod = parse_module(path)
    if mod.tree is None:
        return None, 1
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            return node.value.value, node.lineno
    return None, 1


def _check_window_bound(root: str) -> List[Violation]:
    """The pipeline's static handle window (prefetch + bounded sends +
    warmup slack) must fit the engine async pool, the invariant
    ``HostPipeline.__init__`` now asserts at plan-validation time."""
    pool, _ = _module_int(root, commgraph.ENGINE_RELPATH,
                          "ASYNC_POOL_WORKERS")
    pf, pf_line = _module_int(root, PP_RELPATH, "_PREFETCH")
    mi, _ = _module_int(root, PP_RELPATH, "_MAX_INFLIGHT_SENDS")
    if pool is None or pf is None or mi is None:
        return []
    if pf + mi + 2 > pool:
        return [Violation(
            CHECKER, PP_RELPATH, pf_line,
            f"pipeline handle window _PREFETCH({pf}) + "
            f"_MAX_INFLIGHT_SENDS({mi}) + 2 = {pf + mi + 2} exceeds the "
            f"engine async pool ({pool}) — queued recv tasks never "
            "post and the per-peer deadline reads peers as dead")]
    return []


# -- model drift pin ---------------------------------------------------------
def _check_bindings(entries: List[EntryProtocol]) -> List[Violation]:
    out: List[Violation] = []
    for suffix, op, skeleton in EXPECTED_BINDINGS:
        entry = next((e for e in entries if e.name.endswith(suffix)),
                     None)
        if entry is None:
            continue  # subset tree (fixtures): nothing to pin
        if not any(s.op == op and _skel(s) == skeleton
                   for s in entry.sites):
            out.append(Violation(
                CHECKER, entry.func.path, entry.func.lineno,
                f"protocol model drift: expected a `{op}` site with tag "
                f"skeleton `{skeleton}` in {suffix} — the simulator's "
                "tag model no longer matches the shipped protocol; "
                "update EXPECTED_BINDINGS and the geometry models "
                "together"))
    return out


# -- the event-driven multi-rank simulator -----------------------------------
def _simulate(programs: Dict[object, Iterable],
              peers: Optional[Dict[object, object]] = None,
              deadline: Optional[float] = None
              ) -> Tuple[List[str], Dict[str, int]]:
    """Run rank programs (generators of comm events) to completion.

    Events::

        ("send",  dst, tag)              buffered, never blocks
        ("recv",  src, tag)              blocks on matching send
        ("arecv", src, tag, key, win)    posts; ("wait", key) blocks
        ("wait",  key)                   recv handle or acoll handle
        ("fence",)                       blocks until all posted recvs
                                         of this rank are matchable
        ("coll",  group, op, tag)        blocking rendezvous on tag
        ("acoll", group, op, tag, key)   arrival now, block at wait
        ("die",)                         rank stops; its wire clears

    Returns (findings, max window occupancy per label).  Findings cover
    deadlock (with per-rank blocked-state dump), duplicate in-flight
    tags, orphan sends/posted recvs at exit, and collective stragglers.
    ``peers`` maps the literal dst/src token "peer" per rank (the 2-arm
    mirror sim).
    """
    findings: List[str] = []
    gens = {r: iter(p) for r, p in programs.items()}
    wire: Dict[Tuple[object, object, str], int] = {}
    posted: Dict[object, Dict[str, Tuple[object, str]]] = \
        {r: {} for r in gens}
    acoll_keys: Dict[object, Dict[str, Tuple[tuple, str]]] = \
        {r: {} for r in gens}
    arrivals: Dict[Tuple[tuple, str], Dict[object, str]] = {}
    released: Dict[Tuple[tuple, str], int] = {}
    windows: Dict[Tuple[object, object], int] = {}
    maxwin: Dict[str, int] = {}
    pending: Dict[object, tuple] = {}
    dead: Set[object] = set()
    done: Set[object] = set()

    def _peer(rank: object, token: object) -> object:
        if token == "peer" and peers is not None:
            return peers[rank]
        return token

    def _try(rank: object, ev: tuple) -> bool:
        """True when ``ev`` completed (non-blocking or satisfied)."""
        op = ev[0]
        if op == "send":
            dst, tag = _peer(rank, ev[1]), ev[2]
            if dst in dead:
                return True
            k = (rank, dst, tag)
            wire[k] = wire.get(k, 0) + 1
            if wire[k] > 1:
                findings.append(
                    f"duplicate in-flight tag `{tag}` {rank}->{dst} — "
                    "a recv can match either frame (double-match)")
            return True
        if op == "recv":
            src, tag = _peer(rank, ev[1]), ev[2]
            k = (src, rank, tag)
            if wire.get(k, 0) > 0:
                wire[k] -= 1
                if not wire[k]:
                    del wire[k]
                return True
            return False
        if op == "arecv":
            src, tag, key, win = \
                _peer(rank, ev[1]), ev[2], ev[3], ev[4]
            posted[rank][key] = (src, tag)
            if win is not None:
                wk = (rank, win)
                windows[wk] = windows.get(wk, 0) + 1
                maxwin[win] = max(maxwin.get(win, 0), windows[wk])
            return True
        if op == "wait":
            key = ev[1]
            if key in posted[rank]:
                src, tag = posted[rank][key]
                if _try(rank, ("recv", src, tag)):
                    del posted[rank][key]
                    for (wr, wl), _n in list(windows.items()):
                        pass
                    # window release: key prefixes map 1:1 to labels
                    for wl in list(maxwin):
                        wk = (rank, wl)
                        if key.startswith(wl) and windows.get(wk, 0) > 0:
                            windows[wk] -= 1
                            break
                    return True
                return False
            if key in acoll_keys[rank]:
                ck = acoll_keys[rank][key]
                group = ck[0]
                if len(arrivals.get(ck, {})) == len(group) \
                        or released.get(ck, 0) > 0:
                    if ck not in released:
                        _validate_coll(ck)
                        released[ck] = len(group)
                    released[ck] -= 1
                    if not released[ck]:
                        released.pop(ck)
                        arrivals.pop(ck, None)
                    del acoll_keys[rank][key]
                    return True
                return False
            return True  # unknown handle: treat settled
        if op == "fence":
            for key, (src, tag) in list(posted[rank].items()):
                if _try(rank, ("recv", src, tag)):
                    del posted[rank][key]
            return not posted[rank]
        if op == "coll":
            group, cop, tag = tuple(ev[1]), ev[2], ev[3]
            ck = (group, tag)
            arrivals.setdefault(ck, {})[rank] = cop
            if len(arrivals[ck]) == len(group) \
                    or released.get(ck, 0) > 0:
                if ck not in released:
                    _validate_coll(ck)
                    released[ck] = len(group)
                released[ck] -= 1
                if not released[ck]:
                    released.pop(ck)
                    arrivals.pop(ck, None)
                return True
            return False
        if op == "acoll":
            group, cop, tag, key = tuple(ev[1]), ev[2], ev[3], ev[4]
            ck = (group, tag)
            arrivals.setdefault(ck, {})[rank] = cop
            acoll_keys[rank][key] = ck
            return True
        if op == "die":
            dead.add(rank)
            # frames already handed to the channel still deliver
            # (buffered); only undelivered frames TO the dead rank void
            for k in [k for k in wire if k[1] == rank]:
                del wire[k]
            posted[rank].clear()
            return True
        raise AssertionError(f"unknown sim event {ev!r}")

    def _validate_coll(ck: Tuple[tuple, str]) -> None:
        ops = set(arrivals[ck].values())
        if len(ops) > 1:
            findings.append(
                f"collective divergence on tag `{ck[1]}`: members "
                f"issued {sorted(ops)}")

    def _advance(rank: object) -> bool:
        progressed = False
        if rank in pending:
            if not _try(rank, pending[rank]):
                return False
            del pending[rank]
            progressed = True
        gen = gens.get(rank)
        while gen is not None:
            try:
                ev = next(gen)
            except StopIteration:
                done.add(rank)
                del gens[rank]
                return True
            if ev[0] == "die":
                _try(rank, ev)
                done.add(rank)
                del gens[rank]
                return True
            if _try(rank, ev):
                progressed = True
                continue
            pending[rank] = ev
            return progressed
        return progressed

    while gens:
        if deadline is not None and time.monotonic() > deadline:
            return findings, maxwin  # budget hit: partial, not red
        progress = False
        for rank in list(gens):
            if _advance(rank):
                progress = True
        if not progress:
            def _dump(r: object) -> str:
                ev = pending.get(r, ("?",))
                if len(ev) > 1:
                    return f"{r} blocked on {ev[0]} `{ev[-1]}`"
                return f"{r} blocked on {ev[0]}"
            findings.append("deadlock: " + "; ".join(
                _dump(r) for r in sorted(gens, key=str)))
            return findings, maxwin

    leftover = sorted({k[2] for k, n in wire.items()
                       if n > 0 and k[1] not in dead})
    if leftover:
        findings.append(
            "orphan sends never received: "
            + ", ".join(f"`{t}`" for t in leftover[:5]))
    for rank, ps in posted.items():
        if ps and rank not in dead:
            tags = sorted({t for _, t in ps.values()})
            findings.append(
                f"rank {rank} exited with posted recvs never matched: "
                + ", ".join(f"`{t}`" for t in tags[:5]))
            break
    if arrivals:
        ck = next(iter(arrivals))
        findings.append(
            f"collective straggler: tag `{ck[1]}` reached only "
            f"{len(arrivals[ck])}/{len(ck[0])} members")
    return findings, maxwin


# -- pure schedule math, executed from source --------------------------------
_PP_PURE = ("SCHEDULES", "_MAX_INFLIGHT_SENDS", "_PREFETCH",
            "_UNIT_EMBED", "_UNIT_FINAL", "stage_partition",
            "interleaved_partition", "schedule_1f1b",
            "schedule_sequential", "schedule_interleaved",
            "build_schedule", "stage_recarve_plan", "_chunk_splits")
_ZERO_PURE = ("reshard_plan", "host_bucket_spans")


def _pure_namespace(root: str, rel: str,
                    names: Sequence[str]) -> Optional[dict]:
    """Exec the named top-level defs/constants of ``rel`` (pure,
    jax-free schedule math by construction) into a fresh namespace —
    the simulator runs the SHIPPED schedules, not a re-model."""
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        return None
    mod = parse_module(path)
    if mod.tree is None:
        return None
    import typing
    ns: dict = {"math": math, "typing": typing}
    for t in ("List", "Tuple", "Optional", "Sequence", "Dict", "Set",
              "Iterable"):
        ns[t] = getattr(typing, t)
    wanted = set(names)
    body = []
    for node in mod.tree.body:
        if isinstance(node, ast.FunctionDef) and node.name in wanted:
            body.append(node)
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
              and isinstance(node.targets[0], ast.Name)
              and node.targets[0].id in wanted):
            body.append(node)
    try:
        code = compile(ast.Module(body=body, type_ignores=[]),
                       path, "exec")
        exec(code, ns)  # noqa: S102 - parsed project source, not input
    except Exception:  # noqa: BLE001 - reported as a finding upstream
        return None
    if not wanted.issubset(ns):
        return None
    return ns


# -- geometry models ---------------------------------------------------------
def _pipeline_program(stage: int, d: int, S: int, dp: int, v: int,
                      schedule: str, m: int, zero: int, prefetch: int,
                      ops: list, nb: int = 2):
    """One rank of ``HostPipeline.train_step``: the extracted schedule's
    op list driven through the prefetch window, bounded act/grad sends,
    per-chunk dp reduce-scatter begin/finish, and the zero-dependent
    exchange — tags shaped exactly like the extracted sites (see
    EXPECTED_BINDINGS)."""
    V = S * v
    pf_on = schedule != "sequential"
    me = stage * dp + d

    def peer(stg: int) -> int:
        return stg * dp + d

    def op_dep(op):
        kind, mb, c = op
        vs = c * S + stage
        if kind == "F":
            if vs == 0:
                return None
            return (f"f{mb}.v{vs}", peer((vs - 1) % S))
        if vs == V - 1:
            return None
        return (f"b{mb}.v{vs}", peer((vs + 1) % S))

    recvs: Dict[str, str] = {}
    nkey = [0]

    def ensure(idx: int):
        for op in ops[idx: idx + 1 + prefetch]:
            dep = op_dep(op)
            if dep is None or dep[0] in recvs:
                continue
            tag, src = dep
            key = f"prefetch{nkey[0]}"
            nkey[0] += 1
            recvs[tag] = key
            yield ("arecv", src, tag, key, "prefetch")

    b_done = [0] * v
    pend: List[int] = []
    if pf_on:
        yield from ensure(0)
    for idx, op in enumerate(ops):
        if pf_on:
            yield from ensure(idx + 1)
        kind, mb, c = op
        vs = c * S + stage
        dep = op_dep(op)
        if dep is not None:
            tag, src = dep
            if tag in recvs:
                yield ("wait", recvs.pop(tag))
            else:
                yield ("recv", src, tag)
        if kind == "F":
            if vs < V - 1:
                yield ("send", peer((vs + 1) % S), f"f{mb}.v{vs + 1}")
            continue
        if vs > 0:
            yield ("send", peer((vs - 1) % S), f"b{mb}.v{vs - 1}")
        b_done[c] += 1
        if b_done[c] == m and dp > 1:
            for b in range(nb):
                for j in range(dp):
                    if j != d:
                        yield ("send", stage * dp + j,
                               f"rs.c{c}.b{b}.o{d}")
            pend.append(c)
    if dp > 1:
        for c in pend:
            hb: Dict[Tuple[int, int], str] = {}

            def post(b: int):
                if b >= nb:
                    return
                for j in range(dp):
                    if j != d:
                        key = f"dpc{c}b{b}j{j}"
                        hb[(b, j)] = key
                        yield ("arecv", stage * dp + j,
                               f"rs.c{c}.b{b}.o{j}", key, "dpbucket")

            yield from post(0)
            for b in range(nb):
                yield from post(b + 1)
                for j in range(dp):
                    if j != d:
                        yield ("wait", hb.pop((b, j)))
            what = "ag" if zero == 2 else "gg"
            hs: List[str] = []
            for j in range(dp):
                if j == d:
                    continue
                yield ("send", stage * dp + j, f"{what}.c{c}.o{d}")
                key = f"exc{c}j{j}"
                hs.append(key)
                yield ("arecv", stage * dp + j, f"{what}.c{c}.o{j}",
                       key, None)
            for key in hs:
                yield ("wait", key)
    assert me == stage * dp + d  # addressing invariant


def _bucket_program(r: int, world: int, nb: int, depth: int, op: str):
    """zero.host_bucket_pipeline / _all_gather: serial rendezvous per
    bucket, or the depth-k async pipeline (issue-ahead then wait)."""
    group = tuple(range(world))
    if depth <= 0:
        for i in range(nb):
            yield ("coll", group, op, f"z.b{i}")
        return
    q: List[Tuple[int, str]] = []
    for i in range(min(depth, nb)):
        yield ("acoll", group, op, f"z.b{i}", f"h{i}")
        q.append((i, f"h{i}"))
    while q:
        i, h = q.pop(0)
        nxt = i + depth
        if nxt < nb:
            yield ("acoll", group, op, f"z.b{nxt}", f"h{nxt}")
            q.append((nxt, f"h{nxt}"))
        yield ("wait", h)


def _ring_program(r: int, n: int, prefix: str):
    """StageBoundary/ZeroBoundary.replicate_ring: send the mirror to
    the predecessor BEFORE receiving from the successor."""
    yield ("send", (r - 1) % n, f"{prefix}.state")
    yield ("recv", (r + 1) % n, f"{prefix}.state")


def _zero_recarve_programs(old_n: int, new_n: int, stride: int,
                           dead: Set[int], plan: list):
    """ZeroBoundary._recarve_channel over a membership change: serve
    phase (buddy-predecessor serves dead ranks, lowest survivor serves
    scalars to pure joiners), then assemble phase."""
    alive = [o for o in range(old_n) if o not in dead]
    stayers = alive[:new_n]
    joiners = [f"j{k}" for k in range(max(0, new_n - len(stayers)))]
    old_addr = {o: f"w{o}" for o in range(old_n)}
    new_workers = [old_addr[o] for o in stayers] + joiners
    old_of_addr = {old_addr[o]: o for o in alive}
    new_of_addr = {a: r for r, a in enumerate(new_workers)}
    serving_scal = min(alive)

    def server_of(o: int) -> Optional[int]:
        if o in dead:
            p = (o - stride) % old_n
            return None if p in dead else p
        return o

    def prog(me: str):
        my_old = old_of_addr.get(me)
        my_new = new_of_addr.get(me)
        if my_old is not None:
            for (o, r, s, ln) in plan:
                if server_of(o) != my_old:
                    continue
                dst = new_workers[r]
                if dst == me:
                    continue
                for i in (0, 1):
                    yield ("send", dst, f"zrc.l{i}.o{s}")
            if my_old == serving_scal:
                for w in joiners:
                    yield ("send", w, "zrc.scalars")
        if my_new is None:
            return  # leaver: served, detaches
        if my_old is None:
            yield ("recv", old_addr[serving_scal], "zrc.scalars")
        for (o, r, s, ln) in plan:
            if r != my_new:
                continue
            serv = server_of(o)
            if my_old is not None and serv == my_old:
                continue  # local copy
            for i in (0, 1):
                yield ("recv", old_addr[serv], f"zrc.l{i}.o{s}")

    participants = [old_addr[o] for o in alive] + joiners
    return {a: prog(a) for a in participants}


def _pp_recarve_programs(ns_pure: dict, old_n: int, staying: List[int],
                         dead: Set[int], dp: int, zero: int,
                         n_layers: int = 8):
    """StageBoundary.recarve at layer-unit granularity: synthesize the
    flat segment list from the SHIPPED stage_partition (embed on stage
    0, final on the last), then run the exact two-phase serve/assemble
    pairing with the shipped _chunk_splits for ZeRO-2 opt chunks."""
    stage_partition = ns_pure["stage_partition"]
    _chunk_splits = ns_pure["_chunk_splits"]
    new_n = len(staying)
    lw, ew, fw = 5, 3, 2  # synthetic per-unit flat widths

    def totals(parts, n):
        t = []
        for s, (lo, hi) in enumerate(parts):
            w = (hi - lo) * lw
            if s == 0:
                w += ew
            if s == n - 1:
                w += fw
            t.append(max(1, w))
        return t

    old_parts = stage_partition(n_layers, old_n)
    new_parts = stage_partition(n_layers, new_n)
    old_totals = totals(old_parts, old_n)
    new_totals = totals(new_parts, new_n)

    def starts(tot):
        out, off = [], 0
        for w in tot:
            out.append(off)
            off += w
        return out, off

    old_start, g1 = starts(old_totals)
    new_start, g2 = starts(new_totals)
    assert g1 == g2, "stage flat layouts must cover the same vector"
    segs = []
    for os_ in range(old_n):
        for ns in range(new_n):
            lo = max(old_start[os_], new_start[ns])
            hi = min(old_start[os_] + old_totals[os_],
                     new_start[ns] + new_totals[ns])
            if lo < hi:
                segs.append((os_, lo - old_start[os_], ns,
                             lo - new_start[ns], hi - lo))
    new_of_old = {os_: ns for ns, os_ in enumerate(staying)}
    oc = {s: max(1, math.ceil(old_totals[s] / dp))
          for s in range(old_n)}
    nc = {s: max(1, math.ceil(new_totals[s] / dp))
          for s in range(new_n)}

    def server_stage(os_: int) -> int:
        return (os_ - 1) % old_n if os_ in dead else os_

    def addr(stage: int, lane: int) -> str:
        return f"s{stage}d{lane}"

    def prog(my_stage: int, my_dp: int):
        me = addr(my_stage, my_dp)
        my_new_stage = new_of_old.get(my_stage)
        # PHASE 1 — serve every span this rank hosts
        for i, (os_, ooff, ns, noff, ln) in enumerate(segs):
            serv = server_stage(os_)
            if serv == my_stage:
                if not (my_new_stage is not None
                        and ns == my_new_stage):
                    dst = addr(staying[ns], my_dp)
                    if dst != me:
                        yield ("send", dst, f"pprc.p{i}")
            if zero == 2:
                for (jo, jn, oo, no, l) in _chunk_splits(
                        ooff, noff, ln, oc[os_], nc[ns]):
                    if not (serv == my_stage and jo == my_dp):
                        continue
                    dst_is_me = (my_new_stage is not None
                                 and ns == my_new_stage
                                 and jn == my_dp)
                    if not dst_is_me:
                        dst = addr(staying[ns], jn)
                        for k in (0, 1):
                            yield ("send", dst, f"pprc.z{k}.{i}.{oo}")
        # PHASE 2 — assemble my new stage
        for i, (os_, ooff, ns, noff, ln) in enumerate(segs):
            serv = server_stage(os_)
            if my_new_stage is not None and ns == my_new_stage \
                    and serv != my_stage:
                yield ("recv", addr(serv, my_dp), f"pprc.p{i}")
            if zero == 2:
                for (jo, jn, oo, no, l) in _chunk_splits(
                        ooff, noff, ln, oc[os_], nc[ns]):
                    dst_is_me = (my_new_stage is not None
                                 and ns == my_new_stage
                                 and jn == my_dp)
                    if not dst_is_me \
                            or (serv == my_stage and jo == my_dp):
                        continue
                    for k in (0, 1):
                        yield ("recv", addr(serv, jo),
                               f"pprc.z{k}.{i}.{oo}")

    return {addr(s, j): prog(s, j)
            for s in range(old_n) if s not in dead
            for j in range(dp)}


def _serve_replay_programs():
    """The serve dispatch/replay protocol: a worker death mid-request
    clears its wire; the router replays the committed request to a
    live worker exactly once — no double-delivery to live ranks."""
    def router():
        yield ("send", "w0", "req.srv.r1")
        # w0 dies before serving; the undelivered frame voids with it
        # and the deadline path replays to w1 (a recv-from-dead is the
        # deadline recovery branch — deadline expiry is not a wire
        # event, so the model takes the replay leg directly)
        yield ("send", "w1", "req.srv.r1")
        yield ("recv", "w1", "req.srvc.r1")

    def w0():
        yield ("die",)

    def w1():
        yield ("recv", "rt", "req.srv.r1")
        yield ("send", "rt", "req.srvc.r1")

    return {"rt": router(), "w0": w0(), "w1": w1()}


def _persist_agree_programs(n: int):
    """The kf-persist restore-time agreement (elastic/persist.py
    ``agree_manifest``): rank 0 fans its manifest choice to every other
    rank in ascending order; each non-zero rank blocks on exactly that
    one frame.  n=1 degenerates to no wire traffic at all."""
    def prog(r: int):
        if r == 0:
            for k in range(1, n):
                yield ("send", k, "persist.agree")
        else:
            yield ("recv", 0, "persist.agree")

    return {r: prog(r) for r in range(n)}


# -- geometry enumeration ----------------------------------------------------
def _geometry_checks(root: str,
                     entries: List[EntryProtocol]) -> List[Violation]:
    """Enumerate every valid geometry ≤ max_ranks and simulate each
    protocol; any finding names its geometry.  Runs only on trees that
    ship the real pipeline (fixture trees carry proto_entry_* functions
    and are covered purely statically)."""
    train = next((e for e in entries
                  if e.name.endswith("HostPipeline.train_step")), None)
    if train is None:
        return []
    max_ranks, cap, timeout = _knobs()
    deadline = time.monotonic() + timeout
    pp_ns = _pure_namespace(root, PP_RELPATH, _PP_PURE)
    zero_ns = _pure_namespace(root, ZERO_RELPATH, _ZERO_PURE)
    if pp_ns is None or zero_ns is None:
        which = PP_RELPATH if pp_ns is None else ZERO_RELPATH
        return [Violation(
            CHECKER, which, 1,
            "could not extract the pure schedule math for geometry "
            "simulation — keep build_schedule/stage_partition/"
            "reshard_plan free of jax/numpy (the verifier executes "
            "them from source)")]
    pool, _ = _module_int(root, commgraph.ENGINE_RELPATH,
                          "ASYNC_POOL_WORKERS")
    pool = pool or 8
    out: List[Violation] = []
    count = [0]

    def budget() -> bool:
        count[0] += 1
        if cap and count[0] > cap:
            return _trunc("KF_VERIFY_GEOMETRY_CAP")
        if time.monotonic() >= deadline:
            return _trunc("KF_VERIFY_TIMEOUT_S")
        return True

    def _trunc(knob: str) -> bool:
        # never truncate silently: shrunk coverage must be visible in
        # the gate log even though it does not fail the build
        print(f"kflint: proto-verify geometry sweep truncated by {knob} "
              f"after {count[0] - 1} geometries — raise the knob for "
              f"full coverage", file=sys.stderr)
        return False

    def report(label: str, findings: List[str], path: str,
               line: int) -> None:
        for f in findings[:2]:
            out.append(Violation(
                CHECKER, path, line, f"[{label}] {f}"))

    # 1) pipeline train_step over every (pp, dp, schedule, zero, m)
    build_schedule = pp_ns["build_schedule"]
    prefetch = pp_ns["_PREFETCH"]
    tpath, tline = train.func.path, train.func.lineno
    for S in range(2, max_ranks + 1):
        for dp in range(1, max_ranks // S + 1):
            for schedule in pp_ns["SCHEDULES"]:
                v = 2 if schedule == "interleaved" else 1
                for m in (S, 2 * S):
                    try:
                        ops = {s: build_schedule(schedule, m, S, s, v)
                               for s in range(S)}
                    except (ValueError, AssertionError):
                        continue  # invalid geometry, not a finding
                    for zero in (0, 2):
                        if not budget():
                            return out
                        label = (f"pp={S} dp={dp} sched={schedule} "
                                 f"m={m} zero={zero}")
                        programs = {
                            s * dp + d: _pipeline_program(
                                s, d, S, dp, v, schedule, m, zero,
                                prefetch, ops[s])
                            for s in range(S) for d in range(dp)}
                        findings, maxwin = _simulate(
                            programs, deadline=deadline)
                        if maxwin.get("prefetch", 0) >= pool:
                            findings.append(
                                f"prefetch window reaches "
                                f"{maxwin['prefetch']} outstanding "
                                f"recvs — must stay below the async "
                                f"pool ({pool})")
                        report(label, findings, tpath, tline)
                        if out:
                            return out  # fail fast: first geometry

    # 2) zero host bucket loops
    for world in (2, 3, 4, min(8, max_ranks)):
        for nb in (1, 2, 3):
            for depth in (0, 1, 2):
                for op in ("rs", "ag"):
                    if not budget():
                        return out
                    findings, _ = _simulate(
                        {r: _bucket_program(r, world, nb, depth, op)
                         for r in range(world)}, deadline=deadline)
                    report(f"bucket world={world} nb={nb} "
                           f"depth={depth} op={op}",
                           findings, ZERO_RELPATH, 1)

    # 3) ring mirrors
    for n in (2, 3, 4, 6):
        if not budget():
            return out
        findings, _ = _simulate(
            {r: _ring_program(r, n, "ring") for r in range(n)},
            deadline=deadline)
        report(f"ring n={n}", findings, PP_RELPATH, 1)

    # 4) zero recarve over membership changes
    reshard_plan = zero_ns["reshard_plan"]
    zr_geoms = [
        (4, 4, 1, set()), (4, 3, 1, set()), (4, 3, 1, {1}),
        (4, 5, 1, set()), (4, 5, 1, {2}), (3, 4, 1, {0}),
        (5, 3, 2, {1}), (4, 2, 1, {1, 3}), (2, 4, 1, set()),
        (6, 4, 1, {5}), (4, 4, 1, {2}), (4, 4, 2, {1}),
        (3, 6, 1, set()), (8, 4, 1, {6}), (4, 8, 1, set()),
        (5, 5, 1, {0}), (2, 2, 1, {1}), (6, 6, 1, {3}),
        (4, 6, 2, {0}),
    ]
    for (old_n, new_n, stride, dead) in zr_geoms:
        if old_n > max_ranks or new_n > max_ranks:
            continue
        if not budget():
            return out
        alive = [o for o in range(old_n) if o not in dead]
        if any((o - stride) % old_n in dead for o in dead):
            continue  # double failure domain: protocol refuses upfront
        total = 48
        plan = reshard_plan(total, old_n, new_n)
        findings, _ = _simulate(
            _zero_recarve_programs(old_n, new_n, stride, dead, plan),
            deadline=deadline)
        report(f"zero-recarve {old_n}->{new_n} stride={stride} "
               f"dead={sorted(dead)}",
               findings, "kungfu_tpu/elastic/reshard.py", 1)

    # 5) pp stage recarve
    pr_geoms = [
        (2, [0, 1], set(), 1), (3, [0, 1, 2], set(), 1),
        (3, [0, 2], {1}, 1), (4, [0, 1, 2], {3}, 1),
        (4, [0, 1, 2, 3], set(), 2), (4, [1, 2, 3], {0}, 2),
        (3, [0, 1], set(), 2), (4, [0, 1], {2, 3}, 1),
    ]
    for (old_n, staying, dead, dp) in pr_geoms:
        if old_n * dp > max_ranks:
            continue
        if any((s - 1) % old_n in dead for s in dead):
            continue
        for zero in (0, 2):
            if not budget():
                return out
            findings, _ = _simulate(
                _pp_recarve_programs(pp_ns, old_n, staying, dead, dp,
                                     zero),
                deadline=deadline)
            report(f"pp-recarve {old_n}->{len(staying)} dp={dp} "
                   f"dead={sorted(dead)} zero={zero}",
                   findings, PP_RELPATH, 1)

    # 6) serve dispatch/replay
    if budget():
        findings, _ = _simulate(_serve_replay_programs(),
                                deadline=deadline)
        report("serve-replay", findings,
               "kungfu_tpu/serve/router.py", 1)

    # 7) persist restore-time manifest agreement (kf-persist): rank 0
    # fans the chosen manifest out, everyone else blocks on rank 0 —
    # including the 1-rank degenerate world (no frames at all)
    for n in sorted({1, 2, 3, 4, min(8, max_ranks), max_ranks}):
        if n < 1 or n > max_ranks:
            continue
        if not budget():
            return out
        findings, _ = _simulate(
            _persist_agree_programs(n), deadline=deadline)
        report(f"persist-agree n={n}", findings,
               "kungfu_tpu/elastic/persist.py", 1)
    return out
