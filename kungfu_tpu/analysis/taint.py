"""Interprocedural entropy-taint engine — the kf-det substrate.

Every recovery rung in this repo (shrink replay from a
``StepSnapshot``, ``ZeroBoundary`` recarve, kf-persist cold restart,
serve replay-from-committed, bandit lockstep installs) rests on one
invariant: re-executing from an agreed boundary is *bitwise
deterministic* and *cross-rank consistent*.  The ways that invariant
breaks are all *data-flow* facts — a wall-clock read, an unseeded RNG
draw, or an unordered-iteration artifact flows, possibly through
several calls and an f-string, into a consensus digest, a rendezvous
tag, or a persisted manifest record.  The existing
``collective-consistency`` heuristic only sees a divergent call
*syntactically inside* a name expression; ``x = time.time()`` two
functions upstream escapes it.  This module closes that gap with a
forward taint analysis over the shared project call graph
(:mod:`kungfu_tpu.analysis.callgraph`) and parse cache
(:mod:`kungfu_tpu.analysis.core`):

* **Sources** introduce taint: wall-clock reads (``time.time`` /
  ``monotonic`` / ``perf_counter`` and their ``_ns`` variants,
  ``datetime.now``), unseeded RNG (module-level ``random.*`` /
  ``np.random.*`` draws, ``default_rng()`` / ``Random()`` /
  ``RandomState()`` with no seed), ``uuid1``/``uuid4``,
  ``os.urandom`` / ``secrets`` tokens, process identity
  (``getpid``/``gethostname``/``getnode``), CPython object identity
  (``id()``), and — as a separate *order* kind — ``set`` /
  ``frozenset`` iteration order.  A rank read is deliberately NOT a
  source: rank is replay-stable, and rank-*divergent* collectives are
  ``collective-consistency``'s existing domain.
* **Propagation** is a flow-sensitive walk per function: assignments
  (incl. tuple unpack, ``self.attr``, augmented and walrus forms),
  f-strings, containers and comprehensions, BinOp/BoolOp arithmetic,
  subscripts, and calls — an unknown call propagates the union of its
  receiver-object and argument taints (``hashlib.blake2b(t).hexdigest()``
  stays tainted); ``if``/``else`` branches analyze on forked
  environments and merge by union, so a sanitizer on ONE branch never
  launders the other.
* **Interprocedural** flow uses per-function summaries (return taint +
  which params flow to the return) computed to fixpoint over the call
  graph, so a source two calls deep and a helper that formats its
  argument into a tag both carry taint to the caller — with the hop
  chain preserved for source→sink path reporting.
* **Sanitizers** terminate taint: a value returned by an agreement op
  (``consensus_bytes`` / ``broadcast_bytes`` / ``allgather_bytes`` /
  ``agree_manifest``) is the *agreed* value on every rank; ``sorted()``
  cancels order taint (not value taint); order-insensitive reductions
  (``len``/``min``/``max``) cancel order taint; ``chaos_rank()`` and
  declared launch knobs (``utils.envs`` reads) are replay-stable and
  never tainted to begin with.

What the engine deliberately does NOT do (precision over recall — the
kf-det rules gate tier-1 with an empty baseline, so a false finding is
a red build): ``self`` attribute taint stays within the method that
wrote it (``self._last_done_t = time.monotonic()`` in a checkpoint
writer must not condemn every other method of the class — local gauges
are sanctioned); dict iteration order is left to the
``reduction-order`` rule's pinned-path scopes (insertion order is
deterministic per run; only geometry-varying insertion is a hazard);
unresolved calls propagate their *arguments'* taint but never invent
new taint.

The rules themselves live in :mod:`kungfu_tpu.analysis.detrules`; this
module knows nothing about sinks.  Like the call graph and the axis
environment, the engine is built once per root per process and
invalidated through the same cascade
(``core.clear_parse_cache`` → ``callgraph.invalidate_cache`` → here).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple

from kungfu_tpu.analysis.callgraph import (
    CallGraph,
    CallSite,
    FuncInfo,
    _terminal_and_receiver,
    project_graph,
)

# ---------------------------------------------------------------------------
# taint values

#: taint kinds whose hazard is *iteration order*, not the value itself —
#: ``sorted()`` and order-insensitive reductions cancel exactly these
ORDER_KINDS = frozenset({"set-order"})


@dataclass(frozen=True)
class Taint:
    """One entropy source, with the interprocedural path it traveled."""

    kind: str  #: "time" | "rng" | "uuid" | "os-entropy" | "object-id" | "set-order"
    desc: str  #: source expression, e.g. "time.time()"
    path: str  #: repo-relative path of the source
    line: int
    #: interprocedural hops, source-first: "returned by _token() (x.py:8)"
    chain: Tuple[str, ...] = ()

    def via(self, hop: str) -> "Taint":
        if len(self.chain) >= 8:  # recursion guard; depth 8 is plenty
            return self
        return Taint(self.kind, self.desc, self.path, self.line,
                     self.chain + (hop,))

    def render(self) -> str:
        trail = "".join(f", {h}" for h in self.chain)
        return f"{self.desc} [{self.kind}] at {self.path}:{self.line}{trail}"


@dataclass(frozen=True)
class TV:
    """Abstract value: the taints it may carry + the formal params of the
    enclosing function it may alias (for summary building)."""

    taints: FrozenSet[Taint] = frozenset()
    params: FrozenSet[int] = frozenset()

    def __or__(self, other: "TV") -> "TV":
        if not other.taints and not other.params:
            return self
        if not self.taints and not self.params:
            return other
        return TV(self.taints | other.taints, self.params | other.params)

    def drop_order(self) -> "TV":
        if not any(t.kind in ORDER_KINDS for t in self.taints):
            return self
        return TV(frozenset(t for t in self.taints
                            if t.kind not in ORDER_KINDS), self.params)

    @property
    def tainted(self) -> bool:
        return bool(self.taints)


EMPTY = TV()


@dataclass(frozen=True)
class Summary:
    """What a call to this function contributes to the caller."""

    ret: FrozenSet[Taint] = frozenset()
    #: formal param indices whose taint flows into the return value
    param_flows: FrozenSet[int] = frozenset()


@dataclass
class CallRecord:
    """One call site with the abstract value of every argument at the
    point of the call — the raw material the sink rules consume."""

    node: ast.Call
    terminal: str
    receiver: Tuple[str, ...]
    line: int
    arg_tv: List[TV]
    kw_tv: Dict[str, TV]
    #: taint of the receiver *expression* (``obj`` in ``obj.m(...)``) —
    #: distinguishes a tainted payload calling .encode() from a tainted
    #: argument
    obj_tv: TV


@dataclass
class FuncResult:
    env: Dict[str, TV]
    calls: List[CallRecord] = field(default_factory=list)


# ---------------------------------------------------------------------------
# source / sanitizer tables (docs/determinism.md mirrors these)

_TIME_FNS = {"time", "time_ns", "monotonic", "monotonic_ns",
             "perf_counter", "perf_counter_ns", "process_time"}
_DATETIME_FNS = {"now", "utcnow", "today"}
#: module-level draws on the process-global (OS-seeded) RNG state
_RNG_DRAWS = {"random", "randint", "randrange", "uniform", "normal",
              "choice", "choices", "shuffle", "sample", "getrandbits",
              "rand", "randn", "standard_normal", "permutation",
              "integers", "bytes"}
#: RNG constructors: entropy when called with NO seed argument
_RNG_CTORS = {"default_rng", "Random", "RandomState", "SystemRandom"}
_UUID_FNS = {"uuid1", "uuid4"}
_OS_ENTROPY_FNS = {"urandom", "getpid", "getppid", "gethostname",
                   "getnode", "token_hex", "token_bytes"}

#: receiver chains that denote the stdlib/numpy RNG module (``random.``,
#: ``np.random.``) — NOT jax.random, whose draws are keyed and pure
_RNG_MODULES = {("random",), ("np", "random"), ("numpy", "random")}

#: ops whose *result* is the agreed value on every rank — taint dies here
AGREEMENT_OPS = frozenset({"consensus_bytes", "broadcast_bytes",
                           "allgather_bytes", "agree_manifest"})

#: calls whose result is insensitive to input *order* (value taint of the
#: inputs still flows; ``sum`` is deliberately absent — float accumulation
#: order is exactly the reduction-order hazard)
_ORDER_INSENSITIVE = frozenset({"sorted", "len", "min", "max"})

#: replay-stable identity reads — sanctioned, never sources
_STABLE_CALLS = frozenset({"chaos_rank"})

#: in-place container mutators: ``parts.append(tainted)`` taints the
#: container binding itself (weak update)
_MUTATORS = frozenset({"append", "add", "extend", "insert", "update",
                       "setdefault", "appendleft", "push"})


def _source_taint(terminal: str, receiver: Tuple[str, ...],
                  node: ast.Call, path: str) -> Optional[Taint]:
    """The taint a call introduces by itself, if any."""
    def t(kind: str, desc: str) -> Taint:
        return Taint(kind, desc, path, node.lineno)

    recv_mod = receiver[-1] if receiver else ""
    if terminal in _TIME_FNS and (not receiver or recv_mod == "time"):
        return t("time", f"time.{terminal}()")
    if terminal in _DATETIME_FNS and recv_mod in ("datetime", "date"):
        return t("time", f"datetime.{terminal}()")
    if terminal in _RNG_DRAWS and tuple(receiver[-2:]) in _RNG_MODULES:
        return t("rng", f"{'.'.join(receiver)}.{terminal}() "
                        f"(process-global RNG)")
    if terminal in _RNG_CTORS and not node.args and not node.keywords:
        return t("rng", f"{terminal}() with no seed (OS entropy)")
    if terminal in _UUID_FNS:
        return t("uuid", f"{terminal}()")
    if terminal in _OS_ENTROPY_FNS:
        return t("os-entropy", f"{terminal}()")
    if terminal == "id" and not receiver:
        return t("object-id", "id() (CPython address)")
    return None


# ---------------------------------------------------------------------------
# per-function abstract interpretation

class _FuncWalk:
    """One flow-sensitive walk of a function body.

    ``record=True`` (the final pass) additionally captures a
    :class:`CallRecord` per call site for the sink rules.
    """

    def __init__(self, engine: "TaintEngine", func: FuncInfo,
                 record: bool = False):
        self.eng = engine
        self.func = func
        self.record = record
        self.calls: List[CallRecord] = []
        self.ret = EMPTY

    # -- statements ------------------------------------------------------

    def run(self) -> Dict[str, TV]:
        env: Dict[str, TV] = {}
        node = self.func.node
        args = getattr(node, "args", None)
        if args is not None:
            formals = [a.arg for a in
                       args.posonlyargs + args.args + args.kwonlyargs]
            for i, name in enumerate(formals):
                env[name] = TV(params=frozenset({i}))
        self._stmts(node.body, env)
        return env

    def _stmts(self, body: List[ast.stmt], env: Dict[str, TV]) -> None:
        for stmt in body:
            self._stmt(stmt, env)

    def _stmt(self, stmt: ast.stmt, env: Dict[str, TV]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes have their own FuncInfo / walk
        if isinstance(stmt, ast.Assign):
            tv = self._eval(stmt.value, env)
            for tgt in stmt.targets:
                self._bind(tgt, tv, env)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._bind(stmt.target, self._eval(stmt.value, env), env)
        elif isinstance(stmt, ast.AugAssign):
            tv = self._eval(stmt.value, env)
            key = self._target_key(stmt.target)
            if key is not None:
                env[key] = env.get(key, EMPTY) | tv
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.ret = self.ret | self._eval(stmt.value, env)
        elif isinstance(stmt, ast.If):
            self._eval(stmt.test, env)
            env_b = dict(env)
            self._stmts(stmt.body, env_b)
            env_o = dict(env)
            self._stmts(stmt.orelse, env_o)
            # union merge: a sanitizer on one branch must not launder
            # the taint the other branch keeps
            for k in set(env_b) | set(env_o):
                env[k] = env_b.get(k, EMPTY) | env_o.get(k, EMPTY)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            iter_tv = self._eval(stmt.iter, env)
            self._bind(stmt.target, iter_tv, env)
            # two passes for loop-carried bindings; record only once
            rec, self.record = self.record, False
            self._stmts(stmt.body, dict(env))
            self.record = rec
            self._bind(stmt.target, self._eval_quiet(stmt.iter, env), env)
            self._stmts(stmt.body, env)
            self._stmts(stmt.orelse, env)
        elif isinstance(stmt, ast.While):
            self._eval(stmt.test, env)
            rec, self.record = self.record, False
            self._stmts(stmt.body, dict(env))
            self.record = rec
            self._stmts(stmt.body, env)
            self._stmts(stmt.orelse, env)
        elif isinstance(stmt, ast.With) or isinstance(stmt, ast.AsyncWith):
            for item in stmt.items:
                tv = self._eval(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, tv, env)
            self._stmts(stmt.body, env)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body, env)
            for h in stmt.handlers:
                self._stmts(h.body, env)
            self._stmts(stmt.orelse, env)
            self._stmts(stmt.finalbody, env)
        elif isinstance(stmt, ast.Expr):
            self._eval(stmt.value, env)
        elif isinstance(stmt, (ast.Assert, ast.Raise)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._eval(child, env)
        # Pass/Import/Global/Nonlocal/Delete/Break/Continue: no flow

    def _bind(self, target: ast.expr, tv: TV, env: Dict[str, TV]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tv, env)
            return
        if isinstance(target, ast.Starred):
            self._bind(target.value, tv, env)
            return
        if isinstance(target, ast.Subscript):
            # d["k"] = tainted taints the container (weak update — a
            # later clean store must not launder the tainted element)
            key = self._target_key(target.value)
            if key is not None:
                env[key] = env.get(key, EMPTY) | tv
            return
        key = self._target_key(target)
        if key is not None:
            env[key] = tv

    @staticmethod
    def _target_key(target: ast.expr) -> Optional[str]:
        """Name -> "x"; dotted Name/Attribute chain -> "self.x"; else None
        (subscript stores keep the container's existing binding)."""
        if isinstance(target, ast.Name):
            return target.id
        if isinstance(target, ast.Attribute):
            chain: List[str] = []
            n: ast.expr = target
            while isinstance(n, ast.Attribute):
                chain.append(n.attr)
                n = n.value
            if isinstance(n, ast.Name):
                chain.append(n.id)
                return ".".join(reversed(chain))
        return None

    # -- expressions -----------------------------------------------------

    def _eval_quiet(self, node: ast.expr, env: Dict[str, TV]) -> TV:
        rec, self.record = self.record, False
        try:
            return self._eval(node, env)
        finally:
            self.record = rec

    def _eval(self, node: ast.expr, env: Dict[str, TV]) -> TV:  # noqa: C901
        if isinstance(node, ast.Name):
            return env.get(node.id, EMPTY)
        if isinstance(node, ast.Constant):
            return EMPTY
        if isinstance(node, ast.Attribute):
            key = self._target_key(node)
            if key is not None and key in env:
                return env[key]
            return self._eval(node.value, env)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.JoinedStr):
            tv = EMPTY
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    tv = tv | self._eval(v.value, env)
            return tv
        if isinstance(node, ast.FormattedValue):
            return self._eval(node.value, env)
        if isinstance(node, (ast.Set,)):
            tv = EMPTY
            for elt in node.elts:
                tv = tv | self._eval(elt, env)
            return tv | TV(taints=frozenset({Taint(
                "set-order", "set literal iteration order",
                self.func.path, node.lineno)}))
        if isinstance(node, (ast.List, ast.Tuple)):
            tv = EMPTY
            for elt in node.elts:
                tv = tv | self._eval(elt, env)
            return tv
        if isinstance(node, ast.Dict):
            tv = EMPTY
            for k in node.keys:
                if k is not None:
                    tv = tv | self._eval(k, env)
            for v in node.values:
                tv = tv | self._eval(v, env)
            return tv
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            tv = self._comp(node.generators, [node.elt], env)
            if isinstance(node, ast.SetComp):
                tv = tv | TV(taints=frozenset({Taint(
                    "set-order", "set comprehension iteration order",
                    self.func.path, node.lineno)}))
            return tv
        if isinstance(node, ast.DictComp):
            return self._comp(node.generators, [node.key, node.value], env)
        if isinstance(node, ast.BinOp):
            return self._eval(node.left, env) | self._eval(node.right, env)
        if isinstance(node, ast.BoolOp):
            tv = EMPTY
            for v in node.values:
                tv = tv | self._eval(v, env)
            return tv
        if isinstance(node, ast.UnaryOp):
            return self._eval(node.operand, env)
        if isinstance(node, ast.Compare):
            tv = self._eval(node.left, env)
            for c in node.comparators:
                tv = tv | self._eval(c, env)
            # a membership/equality result is order-insensitive
            return tv.drop_order()
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env)
            return self._eval(node.body, env) | self._eval(node.orelse, env)
        if isinstance(node, ast.Subscript):
            return self._eval(node.value, env) | self._eval(node.slice, env)
        if isinstance(node, ast.Slice):
            tv = EMPTY
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    tv = tv | self._eval(part, env)
            return tv
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env)
        if isinstance(node, ast.Await):
            return self._eval(node.value, env)
        if isinstance(node, ast.NamedExpr):
            tv = self._eval(node.value, env)
            self._bind(node.target, tv, env)
            return tv
        if isinstance(node, ast.Lambda):
            return EMPTY
        return EMPTY

    def _comp(self, generators, elts, env: Dict[str, TV]) -> TV:
        scope = dict(env)
        tv = EMPTY
        for gen in generators:
            iter_tv = self._eval(gen.iter, scope)
            tv = tv | iter_tv
            self._bind(gen.target, iter_tv, scope)
            for cond in gen.ifs:
                self._eval(cond, scope)
        for elt in elts:
            tv = tv | self._eval(elt, scope)
        return tv

    # -- calls -----------------------------------------------------------

    def _eval_call(self, node: ast.Call, env: Dict[str, TV]) -> TV:
        terminal, receiver = _terminal_and_receiver(node.func)
        obj_tv = EMPTY
        if isinstance(node.func, ast.Attribute):
            obj_tv = self._eval(node.func.value, env)
        arg_tv = [self._eval(a.value if isinstance(a, ast.Starred) else a,
                             env) for a in node.args]
        kw_tv = {kw.arg: self._eval(kw.value, env)
                 for kw in node.keywords if kw.arg is not None}
        for kw in node.keywords:  # **kwargs splat
            if kw.arg is None:
                obj_tv = obj_tv | self._eval(kw.value, env)

        if self.record and terminal is not None:
            self.calls.append(CallRecord(
                node=node, terminal=terminal, receiver=receiver,
                line=node.lineno, arg_tv=arg_tv, kw_tv=kw_tv,
                obj_tv=obj_tv))

        if terminal in _MUTATORS and receiver:
            arg_union = EMPTY
            for t in arg_tv:
                arg_union = arg_union | t
            for t in kw_tv.values():
                arg_union = arg_union | t
            if arg_union.tainted or arg_union.params:
                key = ".".join(receiver)
                env[key] = env.get(key, EMPTY) | arg_union

        if terminal is None:
            tv = obj_tv
            for t in arg_tv:
                tv = tv | t
            for t in kw_tv.values():
                tv = tv | t
            return tv

        # sources / sanitizers first — they beat generic propagation
        src = _source_taint(terminal, receiver, node, self.func.path)
        if src is not None:
            return TV(taints=frozenset({src}))
        if terminal in AGREEMENT_OPS:
            return EMPTY  # the result IS the agreed value
        if terminal in _STABLE_CALLS:
            return EMPTY
        combined = obj_tv
        for t in arg_tv:
            combined = combined | t
        for t in kw_tv.values():
            combined = combined | t
        if terminal in _ORDER_INSENSITIVE:
            return combined.drop_order()
        if terminal in ("set", "frozenset") and not receiver:
            return combined | TV(taints=frozenset({Taint(
                "set-order", f"{terminal}() iteration order",
                self.func.path, node.lineno)}))

        # project-resolved call: use the callee summary (precise) instead
        # of blanket arg propagation
        site = CallSite(callee=terminal, node=node, line=node.lineno,
                        receiver=receiver, branches=())
        cands = self.eng.graph.resolve(self.func, site)
        if cands:
            tv = EMPTY
            for cand in cands:
                summ = self.eng.summary(cand)
                hop = (f"returned through {cand.name}() "
                       f"({cand.path}:{cand.lineno})")
                tv = tv | TV(taints=frozenset(
                    t.via(hop) for t in summ.ret))
                for i in summ.param_flows:
                    atv = self._arg_for_param(cand, i, node, arg_tv, kw_tv)
                    if atv is not None:
                        tv = tv | atv
            return tv

        # unknown call: taint in, taint out
        return combined

    @staticmethod
    def _arg_for_param(cand: FuncInfo, index: int, node: ast.Call,
                       arg_tv: List[TV],
                       kw_tv: Dict[str, TV]) -> Optional[TV]:
        """Map a callee formal index back to this call's argument TV."""
        args = getattr(cand.node, "args", None)
        if args is None:
            return None
        formals = [a.arg for a in
                   args.posonlyargs + args.args + args.kwonlyargs]
        if index >= len(formals):
            return None
        name = formals[index]
        if name in kw_tv:
            return kw_tv[name]
        pos = index
        if cand.cls is not None and formals and formals[0] in ("self", "cls"):
            pos = index - 1  # bound-method call: args exclude self
        if 0 <= pos < len(arg_tv):
            return arg_tv[pos]
        return None


# ---------------------------------------------------------------------------
# the engine

class TaintEngine:
    """Demand-driven summaries: each function is walked exactly once.

    ``summary(f)`` memoizes; a walk that needs a callee's summary
    recurses depth-first, so a source K calls deep resolves in the one
    pass (the transitive chain is computed bottom-up).  Mutual
    recursion is the only approximation: the back edge of a cycle reads
    an empty summary (taint through recursive self-calls is not
    tracked — none of the tree's protocol helpers recurse)."""

    def __init__(self, root: str):
        self.root = root
        self.graph: CallGraph = project_graph(root)
        self._summaries: Dict[int, Summary] = {}
        self._results: Dict[int, FuncResult] = {}
        self._in_flight: set = set()

    def summary(self, func: FuncInfo) -> Summary:
        fid = id(func)
        summ = self._summaries.get(fid)
        if summ is None:
            if fid in self._in_flight:
                return Summary()  # recursion back edge
            self._analyze(func)
            summ = self._summaries[fid]
        return summ

    def result_of(self, func: FuncInfo) -> FuncResult:
        fid = id(func)
        res = self._results.get(fid)
        if res is None:
            self._analyze(func)
            res = self._results[fid]
        return res

    def _analyze(self, func: FuncInfo) -> None:
        fid = id(func)
        self._in_flight.add(fid)
        try:
            walk = _FuncWalk(self, func, record=True)
            env = walk.run()
        finally:
            self._in_flight.discard(fid)
        self._summaries[fid] = Summary(ret=walk.ret.taints,
                                       param_flows=walk.ret.params)
        self._results[fid] = FuncResult(env=env, calls=walk.calls)


_ENGINE_CACHE: Dict[str, TaintEngine] = {}


def taint_engine(root: str) -> TaintEngine:
    """Build (or reuse) the engine for ``root`` — all three kf-det rules
    run over one tree in one CLI pass, so one build serves all."""
    key = os.path.abspath(root)
    eng = _ENGINE_CACHE.get(key)
    if eng is None:
        eng = _ENGINE_CACHE[key] = TaintEngine(key)
    return eng


def invalidate_cache() -> None:
    """Cascaded from ``callgraph.invalidate_cache`` — the engine is
    derived from the call graph and goes stale with it."""
    _ENGINE_CACHE.clear()
