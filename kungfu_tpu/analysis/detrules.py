"""kf-det: the three replay-determinism rules over the taint engine.

``replay-taint``
    An entropy-tainted value (see :mod:`kungfu_tpu.analysis.taint` for
    the source table) reaches a **replay-critical sink**: consensus
    proposal/digest construction, a rendezvous/tag name headed for the
    engine collectives or a ``req.srv*`` frame, a
    ``StepSnapshot``/``ZeroBoundary`` commit payload, a
    ``PersistPlane`` manifest record, or a chaos-deterministic matcher.
    Findings carry the full source→sink hop chain, so a ``time.time()``
    two helpers upstream reads as a path, not a mystery.

``rng-discipline``
    JAX PRNG keys are values, not state — the four ways this tree can
    get that wrong: (a) a key is *used again* after ``jax.random.split``
    consumed it (duplicate streams across ranks/replays), (b)
    ``fold_in`` mixes rank-local entropy into a key (streams diverge on
    replay), (c) a process-global ``np.random``/``random`` draw runs
    inside traced/jitted code (bakes one draw into the compiled
    artifact), (d) seed material for ``PRNGKey``/``default_rng`` is
    derived from entropy instead of agreed values like
    ``(cluster_version, step)``.

``reduction-order``
    Float accumulation is not associative; bitwise-pinned paths
    (``parallel/``, ``ops/``, ``elastic/``, ``models/``,
    ``optimizers/``) must not fold values in an order the runtime does
    not pin.  Flagged: accumulation (``+=`` / ``.append`` into an
    ordered container / ``sum()``) over ``set``/``frozenset`` iteration
    anywhere, and over dict ``.keys()/.values()/.items()`` iteration in
    the pinned dirs (insertion order is deterministic per run but
    *geometry-varying* across restart shapes).  The ``sorted(...)``
    canonical-order escape hatch is recognized — checked, not assumed.

Sink groups are **named** so future protocol surfaces inherit coverage
the day they land: the ROADMAP item 1–3 groups (``kv-migration``,
``moe-dispatch``, ``reshard-record``) are pre-registered below with the
terminal names those PRs will introduce.

All three gate with an EMPTY baseline (scripts/check.sh): a
determinism finding can never land as legacy debt — the replay
contract (docs/determinism.md) is all-or-nothing.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from kungfu_tpu.analysis.callgraph import FuncInfo, project_graph
from kungfu_tpu.analysis.collectives import _NAME_POS
from kungfu_tpu.analysis.core import (
    Violation,
    parse_module,
    suppressed,
)
from kungfu_tpu.analysis.taint import (
    _RNG_CTORS,
    _source_taint,
    CallRecord,
    ORDER_KINDS,
    TV,
    taint_engine,
)

CHECKER_TAINT = "replay-taint"
CHECKER_RNG = "rng-discipline"
CHECKER_RED = "reduction-order"

#: the linter's own modules name every source/sink as string tables and
#: fixtures; they are not protocol code
_EXEMPT_PREFIXES = ("kungfu_tpu/analysis/",)

ANY = "any"
NAME = "name"

#: terminal -> (group, selector).  Selector ANY = every argument is
#: replay-critical; NAME = only the rendezvous-name argument (payloads
#: of gather/broadcast legitimately carry rank-local data — the *name*
#: must rendezvous).
SINKS: Dict[str, Tuple[str, object]] = {}

for _t in ("consensus_bytes", "_propose", "_slice_consensus",
           "agree_manifest"):
    SINKS[_t] = ("consensus", ANY)
for _t in ("barrier", "world_barrier", "gather_bytes", "broadcast_bytes",
           "allgather_bytes"):
    SINKS[_t] = ("rendezvous", NAME)
#: host-channel frame tag: chan.send(dst, name, payload)
SINKS["send"] = ("rendezvous", 1)
for _t in ("commit", "commit_local"):
    SINKS[_t] = ("commit", ANY)
for _t in ("persist_async", "_atomic_write", "manifest_name"):
    SINKS[_t] = ("manifest", ANY)
SINKS["parse_spec"] = ("chaos", ANY)
# -- pre-registered sink groups for the ROADMAP item 1-3 surfaces -------
# (KV-block migration frames, MoE all-to-all dispatch tags, restore-time
# resharding records).  The terminals match nothing today; the PRs that
# introduce them inherit kf-det coverage on day one.
for _t in ("migrate_kv_blocks", "kv_block_frame", "send_kv_block",
           "kv_migration_tag"):
    SINKS[_t] = ("kv-migration", ANY)
for _t in ("dispatch_all_to_all", "moe_dispatch_tag", "all_to_all_tag"):
    SINKS[_t] = ("moe-dispatch", ANY)
for _t in ("reshard_record", "stage_restore_plan", "restore_plan_record"):
    SINKS[_t] = ("reshard-record", ANY)


def _exempt(path: str) -> bool:
    return path.startswith(_EXEMPT_PREFIXES)


class _Flagger:
    """Dedup + suppression-aware violation collector."""

    def __init__(self, root: str, checker: str):
        self.root = root
        self.checker = checker
        self.out: List[Violation] = []
        self._seen: Set[Tuple[str, int, str]] = set()

    def flag(self, path: str, line: int, message: str) -> None:
        key = (path, line, message)
        if key in self._seen:
            return
        self._seen.add(key)
        mod = parse_module(os.path.join(self.root, path))
        if suppressed(mod.supp, line, self.checker):
            return
        self.out.append(Violation(self.checker, path, line, message))

    def done(self) -> List[Violation]:
        return sorted(self.out, key=lambda v: (v.path, v.line, v.message))


# ---------------------------------------------------------------------------
# replay-taint

def _sink_args(rec: CallRecord, selector) -> List[Tuple[str, TV]]:
    """(description, value) pairs of the replay-critical arguments."""
    if selector == ANY:
        pairs = [(f"arg {i}", tv) for i, tv in enumerate(rec.arg_tv)]
        pairs += [(f"{k}=", tv) for k, tv in sorted(rec.kw_tv.items())]
        return pairs
    if selector == NAME:
        if "name" in rec.kw_tv:
            return [("name=", rec.kw_tv["name"])]
        pos = _NAME_POS.get(rec.terminal)
        if pos is not None and pos < len(rec.arg_tv):
            return [(f"name (arg {pos})", rec.arg_tv[pos])]
        # peer-level consensus_bytes(data, name): name one slot early
        if rec.terminal == "consensus_bytes" and len(rec.arg_tv) == 2:
            return [("name (arg 1)", rec.arg_tv[1])]
        return []
    if isinstance(selector, int):
        if "name" in rec.kw_tv:
            return [("name=", rec.kw_tv["name"])]
        if selector < len(rec.arg_tv):
            return [(f"arg {selector}", rec.arg_tv[selector])]
    return []


def check_replay_taint(root: str) -> List[Violation]:
    eng = taint_engine(root)
    fl = _Flagger(root, CHECKER_TAINT)
    for func in eng.graph.functions:
        if _exempt(func.path):
            continue
        for rec in eng.result_of(func).calls:
            spec = SINKS.get(rec.terminal)
            if spec is None:
                continue
            group, selector = spec
            for desc, tv in _sink_args(rec, selector):
                for t in sorted(tv.taints,
                                key=lambda t: (t.path, t.line, t.desc)):
                    fl.flag(func.path, rec.line,
                            f"{group} sink `{rec.terminal}(...)` {desc} "
                            f"carries entropy: {t.render()} — derive it "
                            f"from agreed state or run it through an "
                            f"agreement op (docs/determinism.md)")
    return fl.done()


# ---------------------------------------------------------------------------
# rng-discipline

#: jax.random functions that consume a key as their first argument
_KEY_CONSUMERS = {
    "split", "fold_in", "normal", "uniform", "bernoulli", "permutation",
    "categorical", "gumbel", "truncated_normal", "randint", "bits",
    "choice", "dirichlet", "exponential", "gamma", "laplace", "poisson",
    "shuffle", "dropout",
}

_KEY_CTORS = {"PRNGKey", "key"}


def _is_jax_random(receiver: Tuple[str, ...]) -> bool:
    """``jax.random.*`` under any alias (``jax.random``, ``jrandom``,
    ``jr``); the stdlib ``random`` module is excluded by its lack of
    ``split``/``fold_in``/``PRNGKey`` at the call sites we match."""
    return bool(receiver) and "random" in receiver[-1].lower() \
        or receiver[-2:] == ("jax", "random")


def _scope_stmts(func_node: ast.AST):
    """Every node of this function body, nested defs excluded, in
    source order."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(ast.iter_child_nodes(func_node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return sorted((n for n in out if hasattr(n, "lineno")),
                  key=lambda n: (n.lineno, n.col_offset))


def _target_names(target: ast.expr) -> Set[str]:
    names: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            names.add(n.id)
    return names


def _split_reuse(func: FuncInfo, fl: _Flagger) -> None:
    """A key passed to ``jax.random.split`` and not rebound by the same
    assignment is dead; any later keyed use duplicates a stream."""
    consumed: Dict[str, int] = {}
    handled_calls: Set[int] = set()
    for n in _scope_stmts(func.node):
        if isinstance(n, (ast.Assign, ast.AnnAssign)):
            value = n.value
            call = value
            if isinstance(call, ast.Subscript):
                call = call.value
            targets = set()
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    targets |= _target_names(t)
            elif n.target is not None:
                targets |= _target_names(n.target)
            if isinstance(call, ast.Call):
                f = call.func
                term = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                recv_ok = (isinstance(f, ast.Attribute)
                           and _is_jax_random(
                               tuple(_recv_chain(f))) or
                           isinstance(f, ast.Name))
                if term == "split" and recv_ok and call.args \
                        and isinstance(call.args[0], ast.Name):
                    handled_calls.add(id(call))
                    key_name = call.args[0].id
                    if key_name in consumed:
                        fl.flag(func.path, call.lineno,
                                f"PRNG key `{key_name}` split again after "
                                f"jax.random.split consumed it at line "
                                f"{consumed[key_name]} — duplicate "
                                f"streams; thread the returned keys "
                                f"(docs/determinism.md)")
                    if key_name not in targets:
                        consumed[key_name] = call.lineno
            # any rebinding discharges the consumed mark
            for name in targets:
                consumed.pop(name, None)
        elif isinstance(n, ast.Call):
            if id(n) in handled_calls:
                continue
            f = n.func
            if not isinstance(f, ast.Attribute):
                continue
            term = f.attr
            if term not in _KEY_CONSUMERS:
                continue
            if not _is_jax_random(tuple(_recv_chain(f))):
                continue
            if n.args and isinstance(n.args[0], ast.Name):
                key_name = n.args[0].id
                if key_name in consumed:
                    fl.flag(func.path, n.lineno,
                            f"PRNG key `{key_name}` reused after "
                            f"jax.random.split consumed it at line "
                            f"{consumed[key_name]} — the stream "
                            f"duplicates; use a key returned by the "
                            f"split (docs/determinism.md)")
        elif isinstance(n, ast.For):
            for name in _target_names(n.target):
                consumed.pop(name, None)


def _recv_chain(attr: ast.Attribute) -> List[str]:
    chain: List[str] = []
    n: ast.expr = attr.value
    while isinstance(n, ast.Attribute):
        chain.append(n.attr)
        n = n.value
    if isinstance(n, ast.Name):
        chain.append(n.id)
    chain.reverse()
    return chain


def check_rng_discipline(root: str) -> List[Violation]:
    from kungfu_tpu.analysis.axisenv import axis_environment, fkey

    eng = taint_engine(root)
    env = axis_environment(root)
    fl = _Flagger(root, CHECKER_RNG)
    for func in eng.graph.functions:
        if _exempt(func.path):
            continue
        _split_reuse(func, fl)
        # (c) process-global RNG draw inside traced code — call-site
        # syntactic, so it needs no taint records
        jit_roots = env.jit_roots.get(fkey(func))
        if jit_roots:
            for site in func.calls:
                src = _source_taint(site.callee, site.receiver,
                                    site.node, func.path)
                if src is not None and src.kind == "rng":
                    roots = ", ".join(sorted(jit_roots))
                    fl.flag(func.path, site.line,
                            f"{src.desc} inside traced code (jit roots: "
                            f"{roots}) — the draw is baked into the "
                            f"compiled artifact; thread a jax.random "
                            f"key instead (docs/determinism.md)")
        for rec in eng.result_of(func).calls:
            # (b) fold_in with entropy-derived data
            if rec.terminal == "fold_in" and _is_jax_random(rec.receiver):
                data_tv = rec.kw_tv.get("data")
                if data_tv is None and len(rec.arg_tv) >= 2:
                    data_tv = rec.arg_tv[1]
                for t in _value_taints(data_tv):
                    fl.flag(func.path, rec.line,
                            f"jax.random.fold_in mixes entropy into the "
                            f"key: {t.render()} — fold in agreed values "
                            f"(step, cluster_version, layer index) "
                            f"instead (docs/determinism.md)")
            # (d) seed material derived from entropy
            seed_tv: Optional[TV] = None
            if rec.terminal in _KEY_CTORS and _is_jax_random(rec.receiver):
                seed_tv = rec.kw_tv.get("seed") or (
                    rec.arg_tv[0] if rec.arg_tv else None)
            elif rec.terminal in _RNG_CTORS:
                seed_tv = rec.kw_tv.get("seed") or (
                    rec.arg_tv[0] if rec.arg_tv else None)
            if seed_tv is not None:
                for t in _value_taints(seed_tv):
                    fl.flag(func.path, rec.line,
                            f"`{rec.terminal}` seed material derives "
                            f"from entropy: {t.render()} — seed from "
                            f"agreed values like (cluster_version, "
                            f"step) (docs/determinism.md)")
    return fl.done()


def _value_taints(tv: Optional[TV]):
    if tv is None:
        return []
    return sorted((t for t in tv.taints if t.kind not in ORDER_KINDS),
                  key=lambda t: (t.path, t.line, t.desc))


# ---------------------------------------------------------------------------
# reduction-order

#: dirs whose numerics are bitwise-pinned by the replay contract —
#: dict-iteration order (geometry-varying insertion) is a hazard HERE;
#: set iteration is a hazard everywhere
PINNED_PREFIXES = (
    "kungfu_tpu/parallel/", "kungfu_tpu/ops/", "kungfu_tpu/elastic/",
    "kungfu_tpu/models/", "kungfu_tpu/optimizers/",
)

#: ordered-container mutators: appending under an unordered iteration
#: builds an ordered artifact from an unordered order
_ORDERED_APPENDS = {"append", "extend", "insert", "appendleft"}

_DICT_ITERS = {"keys", "values", "items"}


def _call_terminal(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
    return None


def _unordered_iter(node: ast.expr, order_tainted_names: Set[str],
                    pinned: bool) -> Optional[str]:
    """Why iterating ``node`` has no pinned order, or None if it does."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set literal"
    term = _call_terminal(node)
    if term == "sorted":
        return None  # the canonical-order escape hatch
    if term in ("set", "frozenset"):
        return f"{term}(...)"
    if term in ("list", "tuple", "reversed"):
        # ordered wrapper: order comes from the inner iterable
        inner = node.args[0] if isinstance(node, ast.Call) and node.args \
            else None
        return _unordered_iter(inner, order_tainted_names, pinned) \
            if inner is not None else None
    if pinned and term in _DICT_ITERS and isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute):
        return f".{term}() of a dict (insertion order is geometry-shaped)"
    if isinstance(node, ast.Name) and node.id in order_tainted_names:
        return f"`{node.id}` (carries set iteration order)"
    return None


def _accumulations(body: List[ast.stmt]) -> List[Tuple[int, str]]:
    """(line, description) of order-sensitive accumulations in a loop
    body (nested loops included — they run under the outer order)."""
    out: List[Tuple[int, str]] = []
    stack: List[ast.AST] = list(body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(n, ast.AugAssign) and isinstance(
                n.op, (ast.Add, ast.Mult, ast.Sub)):
            tgt = n.target
            name = tgt.id if isinstance(tgt, ast.Name) else (
                tgt.attr if isinstance(tgt, ast.Attribute) else "?")
            out.append((n.lineno, f"`{name} {_op_sym(n.op)}= ...`"))
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _ORDERED_APPENDS:
            recv = _recv_chain(n.func)
            out.append((n.lineno,
                        f"`{'.'.join(recv) or '?'}.{n.func.attr}(...)`"))
        stack.extend(ast.iter_child_nodes(n))
    return sorted(out)


def _op_sym(op: ast.operator) -> str:
    return {"Add": "+", "Mult": "*", "Sub": "-"}.get(
        type(op).__name__, "?")


def check_reduction_order(root: str) -> List[Violation]:
    eng = taint_engine(root)
    fl = _Flagger(root, CHECKER_RED)
    for func in eng.graph.functions:
        if _exempt(func.path):
            continue
        pinned = func.path.startswith(PINNED_PREFIXES)
        res = eng.result_of(func)
        order_names = {
            name for name, tv in res.env.items()
            if any(t.kind in ORDER_KINDS for t in tv.taints)
        }
        for n in _scope_stmts(func.node):
            if isinstance(n, (ast.For, ast.AsyncFor)):
                why = _unordered_iter(n.iter, order_names, pinned)
                if why is None:
                    continue
                accs = _accumulations(n.body)
                for line, desc in accs:
                    fl.flag(func.path, line,
                            f"order-sensitive accumulation {desc} under "
                            f"iteration over {why} — the fold order is "
                            f"not pinned, so bitwise replay diverges; "
                            f"iterate sorted(...) "
                            f"(docs/determinism.md)")
            elif isinstance(n, ast.Call):
                # bare sum()/prod() and math.fsum fold in Python
                # iteration order; jnp.sum/np.sum reduce arrays and are
                # pinned by the runtime, not by iteration
                if isinstance(n.func, ast.Name):
                    term = n.func.id
                    if term not in ("sum", "prod"):
                        continue
                elif isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "fsum":
                    term = "fsum"
                else:
                    continue
                if not n.args:
                    continue
                arg = n.args[0]
                why = None
                if isinstance(arg, (ast.ListComp, ast.GeneratorExp,
                                    ast.SetComp)):
                    for gen in arg.generators:
                        why = _unordered_iter(gen.iter, order_names,
                                              pinned)
                        if why:
                            break
                else:
                    why = _unordered_iter(arg, order_names, pinned)
                if why:
                    fl.flag(func.path, n.lineno,
                            f"`{term}(...)` folds floats over {why} — "
                            f"unordered reduction in a bitwise-pinned "
                            f"path; sort the operands first "
                            f"(docs/determinism.md)")
    return fl.done()
