"""Comm-plane extraction for the kf-verify protocol checker.

This is the front half of ``proto-verify`` (``analysis/protoverify.py``):
an abstract interpreter over the communication plane that lifts, per
registered **entrypoint** (the dp/zero host bucket loops, the pipeline
``train_step``, both re-carve protocols, the ring mirrors, the serve
dispatch/replay path), the symbolic sequence of collective / p2p
operations the function issues — *without importing any of it* (the
analysis layer is stdlib-only; kflint runs in bare CI images).

What gets extracted per entrypoint:

* every **issue site** of a :class:`~kungfu_tpu.comm.engine.CollectiveEngine`
  wire op (matched against the declarative ``COMM_OP_SPECS`` table the
  engine module carries — op kind, group axis, tag template, arg
  positions), plus the host-channel p2p layer (``chan.send`` /
  ``channel.send`` / ``_recv_or_fail``);
* the **tag template** of each site — f-strings become constant parts
  with ``{}`` holes, local straight-line assigns (``name = f"kf.zbuddy.
  {tag}"``) and single-return tag helpers (``self._act_tag(mb, vs)``,
  ``seg_name("p", i)``) are inlined;
* the **branch context** (which enclosing ``if`` guards feed the site,
  and whether their tests read rank-like state) and the **loop
  context** (which loop variables feed the tag holes, and whether the
  iteration order is reversed) — the raw material of the
  ordering-consistency pass;
* **fence sites** (``drain_async`` and the membership fences of
  handle-discipline) and statically-trackable **handle waits**, for the
  wait-for-graph pass.

Resolution is conservative, like :mod:`kungfu_tpu.analysis.callgraph`
(precision over recall — a false protocol finding is a red build): a
site whose tag cannot be resolved to a template with at least one
constant part marks the entrypoint *unresolved* rather than guessing,
and the downstream pairing rules skip what they cannot see (the
concrete geometry simulation in ``protoverify.py`` covers those).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kungfu_tpu.analysis.callgraph import (
    CallGraph,
    FuncInfo,
    _terminal_and_receiver,
    project_graph,
)
from kungfu_tpu.analysis.core import Violation, relpath
from kungfu_tpu.analysis.handlecheck import _FENCE_CALLS

CHECKER = "proto-verify"

ENGINE_RELPATH = "kungfu_tpu/comm/engine.py"

#: mirror of ``kungfu_tpu/comm/engine.py``'s ``COMM_OP_SPECS`` — used
#: when the scanned tree carries no engine module (lint fixtures).  A
#: tier-1 test pins this against the parsed table so they cannot drift.
FALLBACK_SPECS = {
    "all_reduce":          {"kind": "collective", "group": "world",
                            "tag": "{name}", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "broadcast":           {"kind": "collective", "group": "world",
                            "tag": "{name}", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "reduce":              {"kind": "collective", "group": "world",
                            "tag": "{name}.r", "blocking": True,
                            "name_pos": 3, "peer_pos": None},
    "gather":              {"kind": "collective", "group": "world",
                            "tag": "{name}.g", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "all_gather":          {"kind": "collective", "group": "world",
                            "tag": "{name}.ag", "blocking": True,
                            "name_pos": 1, "peer_pos": None},
    "reduce_scatter":      {"kind": "collective", "group": "world",
                            "tag": "{name}.rs", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "local_reduce":        {"kind": "collective", "group": "slice",
                            "tag": "{name}.lr", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "local_broadcast":     {"kind": "collective", "group": "slice",
                            "tag": "{name}.lb", "blocking": True,
                            "name_pos": 1, "peer_pos": None},
    "cross_all_reduce":    {"kind": "collective", "group": "cross",
                            "tag": "{name}.x", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "send_to":             {"kind": "p2p-send", "group": "pair",
                            "tag": "{name}", "blocking": True,
                            "name_pos": 2, "peer_pos": 0},
    "recv_from":           {"kind": "p2p-recv", "group": "pair",
                            "tag": "{name}", "blocking": True,
                            "name_pos": 1, "peer_pos": 0},
    "send_async":          {"kind": "p2p-send", "group": "pair",
                            "tag": "{name}", "blocking": False,
                            "name_pos": 2, "peer_pos": 0},
    "recv_async":          {"kind": "p2p-recv", "group": "pair",
                            "tag": "{name}", "blocking": False,
                            "name_pos": 1, "peer_pos": 0},
    "all_reduce_async":    {"kind": "collective", "group": "world",
                            "tag": "{name}", "blocking": False,
                            "name_pos": 2, "peer_pos": None},
    "reduce_scatter_async": {"kind": "collective", "group": "world",
                             "tag": "{name}.rs", "blocking": False,
                             "name_pos": 2, "peer_pos": None},
    "all_gather_async":    {"kind": "collective", "group": "world",
                            "tag": "{name}.ag", "blocking": False,
                            "name_pos": 1, "peer_pos": None},
}

#: engine methods whose bare terminal name is too generic to claim from
#: an arbitrary receiver — these additionally need an engine-shaped
#: receiver chain (``engine.`` / ``eng.`` / ``self.engine.``)
_GENERIC_OPS = {"broadcast", "reduce", "gather"}

#: primitives the engine ops bottom out in — a *public* engine method
#: directly touching one of these is a wire op and must carry metadata
_WIRE_PRIMITIVES = {
    "_begin_collective", "_issue_async", "_send", "_recv", "_recv_into",
    "_subset_reduce", "_subset_bcast",
}

#: the registered protocol entrypoints of the shipped tree:
#: (module, class or None, function, pair_scope).  ``pair_scope`` None
#: exempts the entry from the static tag-pairing rule — its recvs live
#: on another process's entrypoint (the serve plane's push handlers) or
#: behind dynamic tag plumbing; the geometry simulation covers those.
ENTRYPOINTS: Tuple[Tuple[str, Optional[str], str, Optional[str]], ...] = (
    ("kungfu_tpu.parallel.zero", None, "host_bucket_pipeline", "local"),
    ("kungfu_tpu.parallel.zero", None, "host_bucket_all_gather", "local"),
    ("kungfu_tpu.parallel.pp", "HostPipeline", "train_step", None),
    ("kungfu_tpu.parallel.pp", "StageBoundary", "replicate_ring", "local"),
    ("kungfu_tpu.parallel.pp", "StageBoundary", "recarve", "local"),
    ("kungfu_tpu.elastic.reshard", "ZeroBoundary", "replicate_ring",
     "local"),
    ("kungfu_tpu.elastic.reshard", "ZeroBoundary", "_recarve_channel",
     "local"),
    ("kungfu_tpu.serve.router", "ServeRouter", "_dispatch", None),
    ("kungfu_tpu.serve.router", "ServeRouter", "_replay", None),
    ("kungfu_tpu.elastic.persist", "PersistPlane", "agree_manifest",
     "local"),
)

#: functions named like this anywhere in scan scope are entrypoints too
#: (the lint-fixture hook; scope "local" = full static checking)
ENTRY_NAME_PREFIX = "proto_entry"

_RANK_NAMES = {
    "me", "my_rank", "self_rank", "my_old", "my_new", "my_dp", "my_stage",
    "my_new_stage", "dp_index", "serv", "succ", "pred",
}
_RANK_CALLS = {"rank", "local_rank", "chaos_rank"}


def _is_rank_test(test: ast.AST) -> bool:
    """Does an ``if`` test read rank-like state (so its two sides run on
    different group members)?  Mirrors collective-consistency's
    heuristic, widened with the elastic re-carve vocabulary."""
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            name = None
            if isinstance(n.func, ast.Attribute):
                name = n.func.attr
            elif isinstance(n.func, ast.Name):
                name = n.func.id
            if name in _RANK_CALLS or (name or "").startswith("_rank"):
                return True
        elif isinstance(n, ast.Name):
            if n.id in _RANK_NAMES or "rank" in n.id.lower():
                return True
        elif isinstance(n, ast.Attribute):
            if "rank" in n.attr.lower() or n.attr in _RANK_NAMES:
                return True
    return False


class Hole:
    """One ``{...}`` hole of a tag template (the f-string expression)."""

    __slots__ = ("src", "node")

    def __init__(self, src: str, node: Optional[ast.AST] = None):
        self.src = src
        self.node = node

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"{{{self.src}}}"


class TagTemplate:
    """A wire tag as constant parts + holes; ``skeleton()`` is the
    canonical ``{}``-holed string two sites are matched by."""

    __slots__ = ("parts",)

    def __init__(self, parts: Sequence[object]):
        # merge adjacent constants so equal skeletons compare equal
        merged: List[object] = []
        for p in parts:
            if isinstance(p, str) and merged and isinstance(merged[-1], str):
                merged[-1] += p
            else:
                merged.append(p)
        self.parts = tuple(merged)

    def skeleton(self) -> str:
        return "".join(p if isinstance(p, str) else "{}"
                       for p in self.parts)

    def holes(self) -> List[Hole]:
        return [p for p in self.parts if isinstance(p, Hole)]

    def constant(self) -> bool:
        return all(isinstance(p, str) for p in self.parts)

    def has_constant_part(self) -> bool:
        return any(isinstance(p, str) and p for p in self.parts)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TagTemplate({self.skeleton()!r})"


@dataclass
class BranchCtx:
    test: ast.AST
    side: str  #: "body" / "orelse"
    line: int
    rank_dep: bool

    @property
    def key(self) -> Tuple[int, str]:
        return (self.line, self.side)


@dataclass
class LoopCtx:
    targets: frozenset  #: names bound by the loop target
    reversed_iter: bool
    line: int


@dataclass
class CommSite:
    """One statically-extracted comm issue site inside an entrypoint."""

    op: str
    kind: str  #: "collective" | "p2p-send" | "p2p-recv"
    blocking: bool
    tag: Optional[TagTemplate]
    peer: Optional[str]  #: source text of the peer/rank argument
    line: int
    path: str  #: repo-root relative
    func: str  #: qualname of the containing function
    branches: Tuple[BranchCtx, ...]
    loops: Tuple[LoopCtx, ...]
    order: int

    def rank_guard(self) -> Optional[BranchCtx]:
        """Innermost rank-dependent enclosing branch, if any."""
        for b in reversed(self.branches):
            if b.rank_dep:
                return b
        return None


@dataclass
class FenceSite:
    name: str
    line: int
    path: str
    func: str
    order: int


@dataclass
class WaitSite:
    site: CommSite  #: the async issue site this wait settles
    line: int
    order: int


@dataclass
class EntryProtocol:
    """Everything extracted from one protocol entrypoint."""

    name: str  #: display name ("kungfu_tpu.parallel.pp::HostPipeline.train_step")
    func: FuncInfo
    pair_scope: Optional[str]
    sites: List[CommSite] = field(default_factory=list)
    fences: List[FenceSite] = field(default_factory=list)
    waits: List[WaitSite] = field(default_factory=list)
    #: (line, reason) for sites whose tag/shape could not be resolved —
    #: non-empty disables the static pairing/deadlock rules (soundness)
    unresolved: List[Tuple[int, str]] = field(default_factory=list)

    @property
    def resolvable(self) -> bool:
        return not self.unresolved

    def p2p_sites(self) -> List[CommSite]:
        return [s for s in self.sites if s.kind != "collective"]

    def collective_sites(self) -> List[CommSite]:
        return [s for s in self.sites if s.kind == "collective"]


# -- engine metadata ---------------------------------------------------------
def engine_specs(root: str) -> Tuple[Dict[str, dict], List[Violation]]:
    """The ``COMM_OP_SPECS`` table of ``root``'s engine module (parsed,
    never imported), cross-checked both ways against the actual
    ``CollectiveEngine`` method defs.  Falls back to
    :data:`FALLBACK_SPECS` for trees without an engine (fixtures)."""
    from kungfu_tpu.analysis.core import parse_module

    path = os.path.join(root, ENGINE_RELPATH)
    if not os.path.isfile(path):
        return dict(FALLBACK_SPECS), []
    mod = parse_module(path)
    if mod.tree is None:
        return dict(FALLBACK_SPECS), []
    rel = relpath(root, path)
    out: List[Violation] = []
    specs: Optional[Dict[str, dict]] = None
    spec_line = 1
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "COMM_OP_SPECS"):
            spec_line = node.lineno
            try:
                specs = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                out.append(Violation(
                    CHECKER, rel, node.lineno,
                    "COMM_OP_SPECS must be a pure literal dict — the "
                    "analysis layer reads it without importing the "
                    "engine"))
                return dict(FALLBACK_SPECS), out
    if specs is None:
        out.append(Violation(
            CHECKER, rel, 1,
            "comm/engine.py carries no COMM_OP_SPECS table — every "
            "public wire op needs static protocol metadata"))
        return dict(FALLBACK_SPECS), out

    # both-ways drift check against the CollectiveEngine method defs
    methods: Dict[str, ast.FunctionDef] = {}
    wire_ops: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.name == "CollectiveEngine":
            for m in node.body:
                if isinstance(m, ast.FunctionDef):
                    methods[m.name] = m
                    if not m.name.startswith("_"):
                        called = set()
                        for n in ast.walk(m):
                            if isinstance(n, ast.Call):
                                t, _ = _terminal_and_receiver(n.func)
                                if t:
                                    called.add(t)
                        if called & _WIRE_PRIMITIVES:
                            wire_ops.add(m.name)
    for op in sorted(specs):
        if op not in methods:
            out.append(Violation(
                CHECKER, rel, spec_line,
                f"COMM_OP_SPECS lists `{op}` but CollectiveEngine "
                "defines no such method — stale protocol metadata"))
    for op in sorted(wire_ops - set(specs)):
        out.append(Violation(
            CHECKER, rel, methods[op].lineno,
            f"CollectiveEngine.{op} touches the wire primitives but "
            "has no COMM_OP_SPECS entry — wire ops need static "
            "protocol metadata (op kind, group axis, tag template)"))
    return specs, out


# -- template resolution -----------------------------------------------------
def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 - best-effort source text
        return "<expr>"


def _single_return_template(func_node: ast.AST) -> Optional[ast.AST]:
    """The returned expression of a ``def f(...): return <tag expr>``
    helper (docstring allowed), else None."""
    body = [s for s in func_node.body
            if not (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant)
                    and isinstance(s.value.value, str))]
    if len(body) == 1 and isinstance(body[0], ast.Return) \
            and body[0].value is not None:
        return body[0].value
    return None


class _Resolver:
    """Scope-aware lookup of bare/self call targets for one entrypoint
    walk: nested defs (by parent chain), same-class methods, same-module
    functions — the conservative subset the extractor descends into."""

    def __init__(self, graph: CallGraph, entry: FuncInfo):
        self.graph = graph
        self.entry = entry
        #: nested defs by enclosing function — bare names resolve up
        #: the CALLER's lexical scope chain, so helpers nested inside a
        #: descended-into method are visible too
        self._children: Dict[int, Dict[str, FuncInfo]] = {}
        for f in graph.functions:
            if f.parent is not None:
                self._children.setdefault(
                    id(f.parent), {}).setdefault(f.name, f)

    def target_of(self, call: ast.Call,
                  caller: FuncInfo) -> Optional[FuncInfo]:
        terminal, receiver = _terminal_and_receiver(call.func)
        if terminal is None:
            return None
        if not receiver:
            scope: Optional[FuncInfo] = caller
            while scope is not None:
                hit = self._children.get(id(scope), {}).get(terminal)
                if hit is not None:
                    return hit
                scope = scope.parent
            return self.graph.by_qualname.get(
                f"{caller.module}::{terminal}")
        if receiver == ("self",) and caller.cls:
            return self.graph.by_qualname.get(
                f"{caller.module}::{caller.cls}.{terminal}")
        return None


class _Walker:
    """One entrypoint's comm-site walk: program order, branch + loop
    context, straight-line tag environments, bounded descent into
    resolved local helpers."""

    MAX_DEPTH = 3

    def __init__(self, graph: CallGraph, specs: Dict[str, dict],
                 entry: FuncInfo, proto: EntryProtocol):
        self.graph = graph
        self.specs = specs
        self.entry = entry
        self.proto = proto
        self.resolver = _Resolver(graph, entry)
        self._order = 0
        self._visiting: Set[int] = set()
        self._handles: Dict[str, CommSite] = {}

    def run(self) -> None:
        self._walk_func(self.entry, (), (), 0)

    # -- function / statement walk ---------------------------------------
    def _walk_func(self, func: FuncInfo, branches: Tuple[BranchCtx, ...],
                   loops: Tuple[LoopCtx, ...], depth: int) -> None:
        key = id(func.node)
        if key in self._visiting:
            return
        self._visiting.add(key)
        env: Dict[str, TagTemplate] = {}
        try:
            self._walk_stmts(func.node.body, func, env, branches, loops,
                             depth)
        finally:
            self._visiting.discard(key)

    def _walk_stmts(self, stmts: Sequence[ast.stmt], func: FuncInfo,
                    env: Dict[str, TagTemplate],
                    branches: Tuple[BranchCtx, ...],
                    loops: Tuple[LoopCtx, ...], depth: int) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes walked on call
            if isinstance(stmt, ast.If):
                self._visit_expr(stmt.test, func, env, branches, loops,
                                 depth, stmt)
                b = BranchCtx(stmt.test, "body", stmt.lineno,
                              _is_rank_test(stmt.test))
                self._walk_stmts(stmt.body, func, env, branches + (b,),
                                 loops, depth)
                o = BranchCtx(stmt.test, "orelse", stmt.lineno,
                              _is_rank_test(stmt.test))
                self._walk_stmts(stmt.orelse, func, env, branches + (o,),
                                 loops, depth)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._visit_expr(stmt.iter, func, env, branches, loops,
                                 depth, stmt)
                lc = LoopCtx(frozenset(_target_names(stmt.target)),
                             _is_reversed_iter(stmt.iter), stmt.lineno)
                self._walk_stmts(stmt.body, func, env, branches,
                                 loops + (lc,), depth)
                self._walk_stmts(stmt.orelse, func, env, branches, loops,
                                 depth)
                continue
            if isinstance(stmt, ast.While):
                self._visit_expr(stmt.test, func, env, branches, loops,
                                 depth, stmt)
                lc = LoopCtx(frozenset(), False, stmt.lineno)
                self._walk_stmts(stmt.body, func, env, branches,
                                 loops + (lc,), depth)
                self._walk_stmts(stmt.orelse, func, env, branches, loops,
                                 depth)
                continue
            if isinstance(stmt, ast.Try):
                self._walk_stmts(stmt.body, func, env, branches, loops,
                                 depth)
                for h in stmt.handlers:
                    self._walk_stmts(h.body, func, env, branches, loops,
                                     depth)
                self._walk_stmts(stmt.orelse, func, env, branches, loops,
                                 depth)
                self._walk_stmts(stmt.finalbody, func, env, branches,
                                 loops, depth)
                continue
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    self._visit_expr(item.context_expr, func, env,
                                     branches, loops, depth, stmt)
                self._walk_stmts(stmt.body, func, env, branches, loops,
                                 depth)
                continue
            if isinstance(stmt, ast.Assign):
                site = self._visit_expr(stmt.value, func, env, branches,
                                        loops, depth, stmt)
                if len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    name = stmt.targets[0].id
                    if site is not None and not site.blocking:
                        self._handles[name] = site
                    else:
                        tmpl = self._template_of(stmt.value, func, env)
                        if tmpl is not None:
                            env[name] = tmpl
                        else:
                            env.pop(name, None)
                continue
            # everything else: visit contained expressions in order
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._visit_expr(child, func, env, branches, loops,
                                     depth, stmt)

    def _visit_expr(self, expr: Optional[ast.AST], func: FuncInfo,
                    env: Dict[str, TagTemplate],
                    branches: Tuple[BranchCtx, ...],
                    loops: Tuple[LoopCtx, ...], depth: int,
                    stmt: ast.stmt) -> Optional[CommSite]:
        """Process every call in ``expr``; returns the comm site when the
        expression IS directly a comm call (assignment tracking)."""
        if expr is None:
            return None
        direct: Optional[CommSite] = None
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            comp = _comp_loops(expr, node)
            site = self._handle_call(node, func, env, branches,
                                     loops + comp, depth)
            if node is expr and site is not None:
                direct = site
        return direct

    # -- call classification ----------------------------------------------
    def _handle_call(self, call: ast.Call, func: FuncInfo,
                     env: Dict[str, TagTemplate],
                     branches: Tuple[BranchCtx, ...],
                     loops: Tuple[LoopCtx, ...],
                     depth: int) -> Optional[CommSite]:
        terminal, receiver = _terminal_and_receiver(call.func)
        if terminal is None:
            return None
        spec = self.specs.get(terminal)
        if spec is not None and receiver \
                and (terminal not in _GENERIC_OPS
                     or _engineish(receiver)):
            return self._record_site(call, terminal, spec, func, env,
                                     branches, loops)
        if terminal in ("send", "recv") and receiver \
                and receiver[-1] in ("chan", "channel"):
            spec = {"kind": "p2p-send" if terminal == "send"
                    else "p2p-recv", "group": "pair", "blocking": True,
                    "name_pos": 1, "peer_pos": 0}
            return self._record_site(call, f"channel.{terminal}", spec,
                                     func, env, branches, loops)
        if terminal == "_recv_or_fail":
            spec = {"kind": "p2p-recv", "group": "pair", "blocking": True,
                    "name_pos": 4, "peer_pos": 1}
            return self._record_site(call, "_recv_or_fail", spec, func,
                                     env, branches, loops)
        if terminal == "drain_async" or terminal in _FENCE_CALLS:
            self.proto.fences.append(FenceSite(
                terminal, call.lineno, func.path, func.qualname,
                self._next_order()))
            return None
        if terminal == "wait" and len(receiver) == 1 \
                and receiver[0] in self._handles:
            self.proto.waits.append(WaitSite(
                self._handles[receiver[0]], call.lineno,
                self._next_order()))
            return None
        if depth < self.MAX_DEPTH:
            target = self.resolver.target_of(call, func)
            if target is not None and target is not self.entry:
                self._walk_func(target, branches, loops, depth + 1)
        return None

    def _record_site(self, call: ast.Call, op: str, spec: dict,
                     func: FuncInfo, env: Dict[str, TagTemplate],
                     branches: Tuple[BranchCtx, ...],
                     loops: Tuple[LoopCtx, ...]) -> CommSite:
        tag_expr = _arg(call, spec.get("name_pos"), "name")
        tmpl = (self._template_of(tag_expr, func, env)
                if tag_expr is not None else None)
        if tmpl is not None and not tmpl.has_constant_part():
            tmpl = None
        peer_expr = (_arg(call, spec["peer_pos"], None)
                     if spec.get("peer_pos") is not None else None)
        site = CommSite(
            op=op, kind=spec["kind"], blocking=spec.get("blocking", True),
            tag=tmpl,
            peer=_src(peer_expr) if peer_expr is not None else None,
            line=call.lineno, path=func.path, func=func.qualname,
            branches=branches, loops=loops, order=self._next_order())
        if tmpl is None:
            self.proto.unresolved.append(
                (call.lineno, f"dynamic tag for {op}"))
        self.proto.sites.append(site)
        return site

    def _next_order(self) -> int:
        self._order += 1
        return self._order

    # -- tag templates -----------------------------------------------------
    def _template_of(self, expr: Optional[ast.AST], func: FuncInfo,
                     env: Dict[str, TagTemplate],
                     _depth: int = 0) -> Optional[TagTemplate]:
        if expr is None or _depth > 4:
            return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return TagTemplate([expr.value])
        if isinstance(expr, ast.JoinedStr):
            parts: List[object] = []
            for v in expr.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                elif isinstance(v, ast.FormattedValue):
                    inner = self._template_of(v.value, func, env,
                                              _depth + 1)
                    if inner is not None and v.format_spec is None \
                            and v.conversion == -1:
                        parts.extend(inner.parts)
                    else:
                        parts.append(Hole(_src(v.value), v.value))
                else:
                    return None
            return TagTemplate(parts)
        if isinstance(expr, ast.Name):
            return env.get(expr.id)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            left = self._template_of(expr.left, func, env, _depth + 1)
            right = self._template_of(expr.right, func, env, _depth + 1)
            if left is not None and right is not None:
                return TagTemplate(list(left.parts) + list(right.parts))
            return None
        if isinstance(expr, ast.Call):
            target = self.resolver.target_of(expr, func)
            if target is None:
                return None
            ret = _single_return_template(target.node)
            if ret is None:
                return None
            return self._template_of(ret, target, {}, _depth + 1)
        return None


def _engineish(receiver: Tuple[str, ...]) -> bool:
    last = receiver[-1]
    return "engine" in last or last in ("eng", "self")


def _arg(call: ast.Call, pos: Optional[int],
         kw: Optional[str]) -> Optional[ast.AST]:
    if kw is not None:
        for k in call.keywords:
            if k.arg == kw:
                return k.value
    if pos is not None and pos < len(call.args):
        a = call.args[pos]
        if not isinstance(a, ast.Starred):
            return a
    return None


def _target_names(target: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}


def _is_reversed_iter(it: ast.AST) -> bool:
    """Is the loop iterable order-reversed (``reversed(...)`` anywhere
    in the iterable chain, or a negative-step ``range``)?"""
    for n in ast.walk(it):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
            if n.func.id == "reversed":
                return True
            if n.func.id == "range" and len(n.args) == 3:
                step = n.args[2]
                if isinstance(step, ast.UnaryOp) \
                        and isinstance(step.op, ast.USub):
                    return True
                if isinstance(step, ast.Constant) \
                        and isinstance(step.value, (int, float)) \
                        and step.value < 0:
                    return True
    return False


def _comp_loops(expr: ast.AST, call: ast.Call) -> Tuple[LoopCtx, ...]:
    """Loop contexts contributed by comprehensions in ``expr`` that
    enclose ``call`` (the serial bucket loops are comprehensions)."""
    out: List[LoopCtx] = []
    for node in ast.walk(expr):
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            if any(n is call for n in ast.walk(node)):
                for gen in node.generators:
                    out.append(LoopCtx(
                        frozenset(_target_names(gen.target)),
                        _is_reversed_iter(gen.iter), node.lineno))
    return tuple(out)


# -- entry discovery ---------------------------------------------------------
def entry_protocols(
        root: str) -> Tuple[Dict[str, dict], List[EntryProtocol],
                            List[Violation]]:
    """(engine op specs, extracted entry protocols, metadata findings)
    for ``root`` — the input of every proto-verify pass."""
    graph = project_graph(root)
    specs, violations = engine_specs(root)
    entries: List[EntryProtocol] = []
    seen: Set[int] = set()
    for module, cls, name, scope in ENTRYPOINTS:
        qual = f"{module}::{cls + '.' if cls else ''}{name}"
        func = graph.by_qualname.get(qual)
        if func is None:
            continue  # subset trees (fixtures) simply lack the module
        entries.append(_extract(graph, specs, func, scope))
        seen.add(id(func))
    for func in graph.functions:
        if func.name.startswith(ENTRY_NAME_PREFIX) \
                and id(func) not in seen and func.parent is None:
            entries.append(_extract(graph, specs, func, "local"))
    return specs, entries, violations


def _extract(graph: CallGraph, specs: Dict[str, dict], func: FuncInfo,
             scope: Optional[str]) -> EntryProtocol:
    proto = EntryProtocol(name=func.qualname, func=func, pair_scope=scope)
    _Walker(graph, specs, func, proto).run()
    proto.sites.sort(key=lambda s: s.order)
    return proto
