"""Shared project call graph for the interprocedural (kf-verify) rules.

Single-function lints (the PR-1 checkers) see one AST at a time; the
protocol invariants this project actually breaks on — a collective
issued on one rank only, a lock taken under another module's lock — are
properties of *paths through the tree*.  This module builds the one
index those rules share:

* every function/method in the scan dirs, keyed by
  ``module::Class.method`` / ``module::func``;
* every call site inside each function, with its terminal callee name
  and the stack of enclosing ``if`` branches (so a rule can ask "is this
  call rank-conditional?");
* best-effort static resolution of a call site to project functions.

Resolution is deliberately conservative — precision over recall, because
these rules gate tier-1 and a false cycle/false divergence is a red
build:

* ``self.foo()`` resolves only within the enclosing class;
* a bare ``foo()`` resolves to the same module's ``foo`` or a
  ``from mod import foo`` binding;
* ``obj.foo()`` (non-self) resolves only when exactly one project
  function is named ``foo`` tree-wide (unique ⇒ unambiguous).

Anything unresolved is simply not an edge.  The graph is built once per
``check()`` pass and cached per root by :func:`project_graph`.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from kungfu_tpu.analysis.core import (PY_SCAN_DIRS, iter_py_files,
                                      parse_module, relpath)

#: method names answered by the builtin containers / sync primitives —
#: a cross-object call through one of these says nothing about WHICH
#: object, so it never resolves (``self.foo()`` / bare-name calls are
#: unaffected: those paths carry their own evidence)
_UBIQUITOUS_METHODS = (
    set(dir(dict)) | set(dir(list)) | set(dir(set)) | set(dir(str))
    | set(dir(bytes)) | {
        "put", "put_nowait", "get_nowait", "acquire", "release", "start",
        "close", "send", "recv", "sendall", "connect", "read", "write",
        "wait", "set", "is_set", "submit", "result", "cancel", "shutdown",
    }
)


@dataclass(frozen=True)
class Branch:
    """One enclosing conditional of a call site."""

    test: ast.AST  #: the ``if``/``while`` test expression
    side: str  #: "body" or "orelse"
    line: int


@dataclass
class CallSite:
    callee: str  #: terminal identifier (``self.peer.barrier`` -> "barrier")
    node: ast.Call
    line: int
    #: attribute receiver chain, e.g. ["self", "channel"] for
    #: ``self.channel.send(...)``; [] for a bare-name call
    receiver: Tuple[str, ...]
    branches: Tuple[Branch, ...]  #: innermost last


@dataclass
class FuncInfo:
    module: str  #: dotted path under the repo root ("kungfu_tpu.comm.host")
    cls: Optional[str]
    name: str
    path: str  #: repo-root relative
    node: ast.AST
    lineno: int
    calls: List[CallSite] = field(default_factory=list)
    #: enclosing function for nested defs (None at module/class level) —
    #: lets scope-aware consumers resolve a bare name to the RIGHT
    #: same-named nested def instead of every one in the module
    parent: Optional["FuncInfo"] = field(default=None, compare=False,
                                         repr=False)

    @property
    def qualname(self) -> str:
        prefix = f"{self.cls}." if self.cls else ""
        return f"{self.module}::{prefix}{self.name}"


def _terminal_and_receiver(func: ast.AST) -> Tuple[Optional[str], Tuple[str, ...]]:
    """``a.b.c(...)`` -> ("c", ("a", "b")); ``f(...)`` -> ("f", ())."""
    chain: List[str] = []
    n = func
    while isinstance(n, ast.Attribute):
        chain.append(n.attr)
        n = n.value
    if isinstance(n, ast.Name):
        chain.append(n.id)
    elif not chain:
        return None, ()
    chain.reverse()
    return chain[-1], tuple(chain[:-1])


def _module_of(root: str, path: str) -> str:
    rel = relpath(root, path)
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    return rel.replace("/", ".")


class _FuncVisitor(ast.NodeVisitor):
    """Collect FuncInfos + their call sites with branch context."""

    def __init__(self, module: str, path: str):
        self.module = module
        self.path = path
        self.funcs: List[FuncInfo] = []
        self.imports: Dict[str, str] = {}  # local name -> source module
        self._cls: List[str] = []
        self._func_stack: List[FuncInfo] = []

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for alias in node.names:
            self.imports[alias.asname or alias.name] = node.module or ""

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()

    def _visit_func(self, node) -> None:
        info = FuncInfo(
            module=self.module,
            cls=self._cls[-1] if self._cls else None,
            name=node.name,
            path=self.path,
            node=node,
            lineno=node.lineno,
            parent=self._func_stack[-1] if self._func_stack else None,
        )
        self._collect_calls(node.body, info, ())
        self.funcs.append(info)
        # nested defs get their own FuncInfo (class context preserved)
        self._func_stack.append(info)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _collect_calls(self, stmts: Sequence[ast.stmt], info: FuncInfo,
                       branches: Tuple[Branch, ...]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes own their calls
            if isinstance(stmt, ast.If):
                for call in self._calls_in(stmt.test):
                    self._record(call, info, branches)
                b = Branch(stmt.test, "body", stmt.lineno)
                self._collect_calls(stmt.body, info, branches + (b,))
                o = Branch(stmt.test, "orelse", stmt.lineno)
                self._collect_calls(stmt.orelse, info, branches + (o,))
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                header = stmt.test if isinstance(stmt, ast.While) else stmt.iter
                for call in self._calls_in(header):
                    self._record(call, info, branches)
                self._collect_calls(stmt.body, info, branches)
                self._collect_calls(stmt.orelse, info, branches)
                continue
            if isinstance(stmt, ast.Try):
                self._collect_calls(stmt.body, info, branches)
                for h in stmt.handlers:
                    self._collect_calls(h.body, info, branches)
                self._collect_calls(stmt.orelse, info, branches)
                self._collect_calls(stmt.finalbody, info, branches)
                continue
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    for call in self._calls_in(item.context_expr):
                        self._record(call, info, branches)
                self._collect_calls(stmt.body, info, branches)
                continue
            for call in self._calls_in(stmt):
                self._record(call, info, branches)

    @staticmethod
    def _calls_in(node: Optional[ast.AST]) -> Iterable[ast.Call]:
        if node is None:
            return []
        return [n for n in ast.walk(node) if isinstance(n, ast.Call)]

    def _record(self, call: ast.Call, info: FuncInfo,
                branches: Tuple[Branch, ...]) -> None:
        callee, receiver = _terminal_and_receiver(call.func)
        if callee is None:
            return
        info.calls.append(CallSite(
            callee=callee, node=call, line=call.lineno,
            receiver=receiver, branches=branches,
        ))


class CallGraph:
    """The project-wide function index + conservative call resolution."""

    def __init__(self) -> None:
        self.functions: List[FuncInfo] = []
        self.by_qualname: Dict[str, FuncInfo] = {}
        self.by_name: Dict[str, List[FuncInfo]] = {}
        #: per-module ``from X import name`` bindings
        self.module_imports: Dict[str, Dict[str, str]] = {}

    @classmethod
    def build(cls, root: str,
              dirs: Iterable[str] = PY_SCAN_DIRS) -> "CallGraph":
        g = cls()
        for path in iter_py_files(root, dirs):
            try:
                tree = parse_module(path).tree
            except OSError:
                continue
            if tree is None:
                continue
            module = _module_of(root, path)
            v = _FuncVisitor(module, relpath(root, path))
            v.visit(tree)
            g.module_imports[module] = v.imports
            for f in v.funcs:
                g.functions.append(f)
                g.by_qualname[f.qualname] = f
                g.by_name.setdefault(f.name, []).append(f)
        return g

    # -- resolution ------------------------------------------------------
    def resolve(self, caller: FuncInfo, site: CallSite) -> List[FuncInfo]:
        """Project functions ``site`` may invoke (possibly empty)."""
        cands = self.by_name.get(site.callee, [])
        if not cands:
            return []
        if site.receiver and site.receiver[0] in ("self", "cls", "srv", "chan"):
            # method on the current object (incl. the `srv = self` /
            # `chan = self` closure idiom of the handler classes): same
            # class only — and only a direct attribute (`self.foo()`, not
            # `self.x.foo()`, which targets another object)
            if len(site.receiver) > 1 or caller.cls is None:
                return self._unique(cands)
            return [f for f in cands
                    if f.cls == caller.cls and f.module == caller.module]
        if not site.receiver:
            # bare name: same module, or an explicit from-import of it
            same = [f for f in cands
                    if f.module == caller.module and f.cls is None]
            if same:
                return same
            imported_from = self.module_imports.get(caller.module, {}).get(
                site.callee
            )
            if imported_from:
                hit = [f for f in cands
                       if f.cls is None and f.module.endswith(imported_from)]
                if hit:
                    return hit
            return []
        return self._unique(cands)

    @staticmethod
    def _unique(cands: List[FuncInfo]) -> List[FuncInfo]:
        """A cross-object call resolves only when unambiguous tree-wide —
        and never through a name every builtin container also answers
        (``d.clear()`` must not resolve to the one project ``clear``)."""
        if len(cands) != 1 or cands[0].name in _UBIQUITOUS_METHODS:
            return []
        return cands

    def callers_of(self, target: FuncInfo) -> List[Tuple[FuncInfo, CallSite]]:
        out: List[Tuple[FuncInfo, CallSite]] = []
        for f in self.functions:
            for site in f.calls:
                if site.callee != target.name:
                    continue
                if target in self.resolve(f, site):
                    out.append((f, site))
        return out


_GRAPH_CACHE: Dict[str, CallGraph] = {}


def project_graph(root: str) -> CallGraph:
    """Build (or reuse) the call graph for ``root`` — the kf-verify rules
    all run over one tree in one CLI pass, so one build serves all."""
    key = os.path.abspath(root)
    g = _GRAPH_CACHE.get(key)
    if g is None:
        g = _GRAPH_CACHE[key] = CallGraph.build(key)
    return g


def invalidate_cache() -> None:
    """Tests that rewrite a tree between checks call this.  The axis
    environment and the taint engine are derived from this graph and
    cascade with it."""
    _GRAPH_CACHE.clear()
    from kungfu_tpu.analysis import axisenv, taint

    axisenv.invalidate_cache()
    taint.invalidate_cache()
