"""trace-vocab checker: timeline event kinds come from the vocabulary.

The flight recorder (:mod:`kungfu_tpu.monitor.timeline`) filters, counts,
and renders events by their ``kind`` string; ``kftrace`` groups its
straggler analysis by the same strings.  A typo'd kind at one call site
would not error — the event would simply vanish from every filter and
counter, which is precisely the failure mode an observability layer must
not have.  So: every ``span()``/``event()`` call whose callee resolves to
the timeline module must pass a **string literal** kind that appears in
the ``EVENT_KINDS`` declaration (parsed straight from timeline.py, so
the vocabulary cannot drift from the enforcement).

Recognized call shapes (per-file import tracking, same conservatism as
the rest of the suite):

* ``from kungfu_tpu.monitor import timeline [as T]`` → ``T.span(...)``
* ``from kungfu_tpu.monitor.timeline import span [as s], event`` → ``s(...)``
* ``import kungfu_tpu.monitor.timeline`` → full-path attribute calls

Unrelated ``.span()``/``.event()`` methods on other objects are not
flagged (their receiver does not resolve to the timeline module).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from kungfu_tpu.analysis.core import (
    Violation,
    iter_py_files,
    parse_module,
    relpath,
    suppressed,
)

CHECKER = "trace-vocab"

TIMELINE_PATH = os.path.join("kungfu_tpu", "monitor", "timeline.py")
TIMELINE_MODULE = "kungfu_tpu.monitor.timeline"
_FUNCS = ("span", "event")


def _vocabulary(root: str) -> Set[str]:
    """The EVENT_KINDS declaration parsed from timeline.py."""
    path = os.path.join(root, TIMELINE_PATH)
    if not os.path.isfile(path):
        return set()
    tree = parse_module(path).tree
    if tree is None:
        return set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "EVENT_KINDS"
        ):
            out: Set[str] = set()
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    out.add(sub.value)
            return out
    return set()


def _timeline_aliases(tree: ast.Module) -> tuple:
    """``(module_aliases, func_aliases)`` for this file: names bound to
    the timeline module, and names bound directly to span/event."""
    mod_aliases: Set[str] = set()
    func_aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "kungfu_tpu.monitor":
                for a in node.names:
                    if a.name == "timeline":
                        mod_aliases.add(a.asname or a.name)
            elif node.module == TIMELINE_MODULE:
                for a in node.names:
                    if a.name in _FUNCS:
                        func_aliases[a.asname or a.name] = a.name
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == TIMELINE_MODULE and a.asname:
                    mod_aliases.add(a.asname)
    return mod_aliases, func_aliases


def _full_path(node: ast.AST) -> Optional[str]:
    """Dotted path of a Name/Attribute chain, or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _timeline_call(node: ast.Call, mod_aliases: Set[str],
                   func_aliases: Dict[str, str]) -> Optional[str]:
    """"span"/"event" when this call resolves to the timeline API."""
    f = node.func
    if isinstance(f, ast.Name) and f.id in func_aliases:
        return func_aliases[f.id]
    if isinstance(f, ast.Attribute) and f.attr in _FUNCS:
        if isinstance(f.value, ast.Name) and f.value.id in mod_aliases:
            return f.attr
        if _full_path(f.value) == TIMELINE_MODULE:
            return f.attr
    return None


def check(root: str) -> List[Violation]:
    vocab = _vocabulary(root)
    if not vocab:
        return []  # no timeline module in this tree — nothing to enforce
    out: List[Violation] = []
    for path in iter_py_files(root):
        # the recorder's own internals reference kinds structurally
        if os.path.abspath(path) == os.path.abspath(
                os.path.join(root, TIMELINE_PATH)):
            continue
        mod = parse_module(path)
        if mod.tree is None or "timeline" not in mod.source:
            continue
        tree = mod.tree
        mod_aliases, func_aliases = _timeline_aliases(tree)
        if not mod_aliases and not func_aliases:
            continue
        supp = mod.supp
        rel = relpath(root, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _timeline_call(node, mod_aliases, func_aliases)
            if fn is None:
                continue
            if suppressed(supp, node.lineno, CHECKER):
                continue
            if not node.args:
                out.append(Violation(
                    CHECKER, rel, node.lineno,
                    f"timeline.{fn}() called without a kind argument",
                ))
                continue
            kind = node.args[0]
            if not (isinstance(kind, ast.Constant)
                    and isinstance(kind.value, str)):
                out.append(Violation(
                    CHECKER, rel, node.lineno,
                    f"timeline.{fn}() kind must be a string literal from "
                    f"the EVENT_KINDS vocabulary (a dynamic kind cannot be "
                    f"checked and a typo would silently vanish from every "
                    f"kftrace filter)",
                ))
            elif kind.value not in vocab:
                out.append(Violation(
                    CHECKER, rel, node.lineno,
                    f"timeline.{fn}() kind {kind.value!r} is not in the "
                    f"EVENT_KINDS vocabulary "
                    f"(kungfu_tpu/monitor/timeline.py) — add it there "
                    f"first or fix the typo",
                ))
    return sorted(out, key=lambda v: (v.path, v.line))
