"""retry-discipline checker: network retry loops must bound attempts
and back off.

Motivating bugs (both shipped): the elastic resize fetch loop hammered
the config server from every worker at a constant 0.2 s — a synchronized
thundering herd the moment the server blipped — and the detector's
fan-out serialized ~10 s retry ladders per unreachable host.  Both
passed review because "it retries" *looks* robust; the discipline is
mechanical, so a checker enforces it.

A **retry loop** is a ``while``/``for`` whose body has a ``try`` that
(a) performs a network call (``urlopen``, ``connect``/
``create_connection``, channel ``send``/``recv``/``ping``,
``post_signal``, ``fetch_cluster``, ``request``, ...) and (b) has a
handler catching a network exception (``OSError`` family,
``TimeoutError``, ``URLError``, ``HTTPException``, ...) that loops again
(an explicit ``continue``, or falling off the handler's end).

Two rules over every retry loop:

* **bounded** — a ``for`` over a finite iterable, a non-trivial
  ``while`` condition, or a ``while True`` containing a deadline /
  attempt-count comparison (``time.time()``/``time.monotonic()`` or a
  name mentioning deadline/attempt/retries).  An unbounded retry turns a
  permanent failure into a silent hang.
* **backs off** — the retry path sleeps a *computed* delay:
  :func:`kungfu_tpu.utils.retry.sleep_backoff` (or a ``time.sleep``
  whose argument is an expression — ``jittered(p)``, ``0.5 * (i + 1)``);
  a bare-constant ``time.sleep(0.2)`` re-synchronizes every retrier, and
  no sleep at all is a hot hammer.

Suppress a deliberate exception (with a comment saying why) via
``# kflint: allow(retry-discipline)`` on the loop or sleep line.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from kungfu_tpu.analysis.core import (
    Violation,
    iter_py_files,
    parse_module,
    relpath,
    suppressed,
    terminal_name as _terminal,
)

CHECKER = "retry-discipline"

#: terminal names whose call marks a try body as "doing network IO"
_NET_CALLS = {
    "urlopen", "create_connection", "connect", "connect_ex", "sendall",
    "send", "recv", "recv_into", "ping", "post_signal", "fetch_cluster",
    "request", "getresponse", "wait", "query_detector",
}

#: exception terminal names that read as network failures
_NET_EXCS = {
    "OSError", "IOError", "EnvironmentError", "ConnectionError",
    "ConnectionResetError", "ConnectionRefusedError",
    "ConnectionAbortedError", "BrokenPipeError", "TimeoutError",
    "URLError", "HTTPError", "HTTPException", "SSLError",
    "error", "timeout", "gaierror", "herror",
}

_TIME_FNS = {"time", "monotonic", "perf_counter"}
_BOUND_NAME_HINTS = ("deadline", "attempt", "retr", "tries", "remaining",
                     "budget", "left")

#: sleeps that are compliant by construction (utils/retry.py vocabulary)
_BLESSED_SLEEPS = {"sleep_backoff"}


def _scoped(nodes: Iterable[ast.AST]) -> Iterable[ast.AST]:
    """Walk statements without descending into nested loops, functions,
    or classes — those own their retry discipline separately."""
    stack = list(nodes)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.While, ast.For, ast.AsyncFor)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _exc_names(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    if t is None:
        return ["<bare>"]
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return [n for n in (_terminal(e) for e in elts) if n]


def _handler_retries(handler: ast.ExceptHandler) -> bool:
    """True when the handler can lead to another iteration."""
    for n in ast.walk(handler):
        if isinstance(n, ast.Continue):
            return True
    last = handler.body[-1]
    return not isinstance(last, (ast.Raise, ast.Return, ast.Break))


def _is_net_retry_try(t: ast.Try) -> List[ast.ExceptHandler]:
    """The retrying network handlers of ``t`` ([] = not a retry try)."""
    has_net_call = any(
        isinstance(n, ast.Call) and _terminal(n.func) in _NET_CALLS
        for b in t.body for n in ast.walk(b)
    )
    if not has_net_call:
        return []
    return [
        h for h in t.handlers
        if (set(_exc_names(h)) & _NET_EXCS or "<bare>" in _exc_names(h))
        and _handler_retries(h)
    ]


def _loop_is_bounded(loop) -> bool:
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        return True
    test = loop.test
    if not (isinstance(test, ast.Constant) and test.value is True):
        return True  # a real while-condition is the bound
    for n in _scoped(loop.body):
        if not isinstance(n, ast.Compare):
            continue
        for sub in ast.walk(n):
            if isinstance(sub, ast.Call) and _terminal(sub.func) in _TIME_FNS:
                return True
            if isinstance(sub, ast.Name) and any(
                h in sub.id.lower() for h in _BOUND_NAME_HINTS
            ):
                return True
    return False


def _sleeps(nodes: Iterable[ast.AST]) -> List[ast.Call]:
    return [
        n for n in _scoped(nodes)
        if isinstance(n, ast.Call)
        and _terminal(n.func) in ({"sleep"} | _BLESSED_SLEEPS)
    ]


def _sleep_is_constant(call: ast.Call) -> bool:
    if _terminal(call.func) in _BLESSED_SLEEPS:
        return False
    if not call.args:
        return True
    # a Constant, bare Name, or module Attribute is the same value every
    # iteration; any computed expression (BinOp, Call, ...) counts as
    # backoff/jitter
    return isinstance(call.args[0], (ast.Constant, ast.Name, ast.Attribute))


def _scan_module(root: str, path: str) -> List[Violation]:
    mod = parse_module(path)
    tree = mod.tree
    if tree is None:
        return []
    rel = relpath(root, path)
    supp = mod.supp
    out: List[Violation] = []

    def flag(line: int, msg: str) -> None:
        if not suppressed(supp, line, CHECKER):
            out.append(Violation(CHECKER, rel, line, msg))

    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
            continue
        if isinstance(loop, (ast.For, ast.AsyncFor)) and not (
            isinstance(loop.iter, ast.Call)
            and _terminal(loop.iter.func) == "range"
        ):
            # `for target in collection` with a per-item try/except is
            # iteration over DIFFERENT endpoints, not a retry of one —
            # only counted `for _ in range(attempts)` ladders are retries
            continue
        retry_handlers = []
        tries = [n for n in _scoped(loop.body) if isinstance(n, ast.Try)]
        for t in tries:
            retry_handlers.extend(_is_net_retry_try(t))
        if not retry_handlers:
            continue
        if not _loop_is_bounded(loop):
            flag(loop.lineno,
                 "unbounded network retry loop — bound it with a deadline "
                 "or attempt count (a permanent failure must fail, not hang)")
        # backoff: prefer sleeps on the handler path; a handler with none
        # falls back to the loop's iteration-level sleeps (the
        # `except: pass` + sleep-at-bottom shape)
        handler_sleeps = _sleeps([n for h in retry_handlers for n in h.body])
        sleeps = handler_sleeps or _sleeps(loop.body)
        if not sleeps:
            flag(loop.lineno,
                 "network retry loop with no backoff between attempts "
                 "(hot-hammers the failing endpoint)")
            continue
        for s in sleeps:
            if _sleep_is_constant(s):
                flag(s.lineno,
                     "network retry sleeps a constant period — every "
                     "retrier re-synchronizes; back off with jitter "
                     "(kungfu_tpu.utils.retry)")
    # one loop can be visited via multiple ancestors during ast.walk? no —
    # walk yields each node once; but an inner loop's violations must not
    # also be attributed to the outer loop: _scoped() stops at nested
    # loops, so each Try belongs to exactly one loop
    return out


def check(root: str) -> List[Violation]:
    out: List[Violation] = []
    for path in iter_py_files(root):
        out.extend(_scan_module(root, path))
    return out
