"""agg-schema checker: aggregator snapshot/view fields come from the schema.

The live cluster plane (:mod:`kungfu_tpu.monitor.aggregator`) moves
plain JSON dicts between ranks, the aggregator, and ``kftop``.  A typo'd
field name at any hop would not error — the value would simply vanish
from every ``kftop`` column and ``/cluster`` consumer, the same silent
failure mode the ``trace-vocab`` rule exists to kill for event kinds.
So: every read goes through ``aggregator.field(obj, "<name>")`` and
every producer through ``aggregator.make_snapshot(<name>=...)``, and
this rule requires the names at those call sites to be **string literals
/ literal keywords** that appear in the ``SNAPSHOT_FIELDS`` /
``VIEW_FIELDS`` declarations (parsed straight from aggregator.py, so
the schema cannot drift from the enforcement).

Recognized call shapes (per-file import tracking, same conservatism as
``trace-vocab``):

* ``from kungfu_tpu.monitor import aggregator [as A]`` →
  ``A.field(...)`` / ``A.make_snapshot(...)``
* ``from kungfu_tpu.monitor.aggregator import field [as f],
  make_snapshot [as ms]`` → ``f(...)`` / ``ms(...)``
* ``import kungfu_tpu.monitor.aggregator`` → full-path attribute calls

Unrelated ``.field()``/``.make_snapshot()`` methods on other objects are
not flagged (their receiver does not resolve to the aggregator module).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from kungfu_tpu.analysis.core import (
    Violation,
    iter_py_files,
    parse_module,
    relpath,
    suppressed,
)

CHECKER = "agg-schema"

AGG_PATH = os.path.join("kungfu_tpu", "monitor", "aggregator.py")
AGG_MODULE = "kungfu_tpu.monitor.aggregator"
_FUNCS = ("field", "make_snapshot")
_SCHEMA_NAMES = ("SNAPSHOT_FIELDS", "VIEW_FIELDS")


def _schemas(root: str) -> Dict[str, Set[str]]:
    """``{declaration name: fields}`` parsed from aggregator.py.
    Kept separate: ``field()`` reads snapshots AND views (union), but
    ``make_snapshot()`` accepts SNAPSHOT_FIELDS only at runtime — a
    union check there would lint-pass a call that raises."""
    path = os.path.join(root, AGG_PATH)
    if not os.path.isfile(path):
        return {}
    tree = parse_module(path).tree
    out: Dict[str, Set[str]] = {}
    if tree is None:
        return out
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id in _SCHEMA_NAMES
        ):
            fields: Set[str] = set()
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    fields.add(sub.value)
            out[node.targets[0].id] = fields
    return out


def _agg_aliases(tree: ast.Module) -> tuple:
    """``(module_aliases, func_aliases)``: names bound to the aggregator
    module, and names bound directly to field/make_snapshot."""
    mod_aliases: Set[str] = set()
    func_aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "kungfu_tpu.monitor":
                for a in node.names:
                    if a.name == "aggregator":
                        mod_aliases.add(a.asname or a.name)
            elif node.module == AGG_MODULE:
                for a in node.names:
                    if a.name in _FUNCS:
                        func_aliases[a.asname or a.name] = a.name
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == AGG_MODULE and a.asname:
                    mod_aliases.add(a.asname)
    return mod_aliases, func_aliases


def _full_path(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _agg_call(node: ast.Call, mod_aliases: Set[str],
              func_aliases: Dict[str, str]) -> Optional[str]:
    """"field"/"make_snapshot" when the call resolves to the module."""
    f = node.func
    if isinstance(f, ast.Name) and f.id in func_aliases:
        return func_aliases[f.id]
    if isinstance(f, ast.Attribute) and f.attr in _FUNCS:
        if isinstance(f.value, ast.Name) and f.value.id in mod_aliases:
            return f.attr
        if _full_path(f.value) == AGG_MODULE:
            return f.attr
    return None


def _check_field(node: ast.Call, schema: Set[str], rel: str,
                 out: List[Violation]) -> None:
    name_arg = None
    if len(node.args) >= 2:
        name_arg = node.args[1]
    else:
        for kw in node.keywords:
            if kw.arg == "name":
                name_arg = kw.value
    if name_arg is None:
        out.append(Violation(
            CHECKER, rel, node.lineno,
            "aggregator.field() called without a field name",
        ))
        return
    if not (isinstance(name_arg, ast.Constant)
            and isinstance(name_arg.value, str)):
        out.append(Violation(
            CHECKER, rel, node.lineno,
            "aggregator.field() name must be a string literal from the "
            "declared schema (a dynamic field cannot be checked and a "
            "typo would silently empty a kftop column)",
        ))
    elif name_arg.value not in schema:
        out.append(Violation(
            CHECKER, rel, node.lineno,
            f"aggregator.field() name {name_arg.value!r} is not in "
            f"SNAPSHOT_FIELDS/VIEW_FIELDS "
            f"(kungfu_tpu/monitor/aggregator.py) — add it there first "
            f"or fix the typo",
        ))


def _check_make_snapshot(node: ast.Call, schema: Set[str], rel: str,
                         out: List[Violation]) -> None:
    for kw in node.keywords:
        if kw.arg is None:
            out.append(Violation(
                CHECKER, rel, node.lineno,
                "make_snapshot(**dynamic) cannot be schema-checked — "
                "pass literal keyword fields",
            ))
        elif kw.arg not in schema:
            out.append(Violation(
                CHECKER, rel, node.lineno,
                f"make_snapshot() field {kw.arg!r} is not in "
                f"SNAPSHOT_FIELDS (kungfu_tpu/monitor/aggregator.py) — "
                f"add it there first or fix the typo",
            ))


def check(root: str) -> List[Violation]:
    schemas = _schemas(root)
    schema = set().union(*schemas.values()) if schemas else set()
    snap_schema = schemas.get("SNAPSHOT_FIELDS", schema)
    if not schema:
        return []  # no aggregator module in this tree — nothing to enforce
    out: List[Violation] = []
    for path in iter_py_files(root):
        # the schema owner builds/reads snapshots structurally
        if os.path.abspath(path) == os.path.abspath(
                os.path.join(root, AGG_PATH)):
            continue
        mod = parse_module(path)
        if mod.tree is None or "aggregator" not in mod.source:
            continue
        tree = mod.tree
        mod_aliases, func_aliases = _agg_aliases(tree)
        if not mod_aliases and not func_aliases:
            continue
        supp = mod.supp
        rel = relpath(root, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _agg_call(node, mod_aliases, func_aliases)
            if fn is None or suppressed(supp, node.lineno, CHECKER):
                continue
            if fn == "field":
                _check_field(node, schema, rel, out)
            else:
                _check_make_snapshot(node, snap_schema, rel, out)
    return sorted(out, key=lambda v: (v.path, v.line))
