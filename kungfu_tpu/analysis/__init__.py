"""kf-lint: project-invariant static analysis for the kungfu-tpu tree.

Eighteen AST/structural checkers enforce invariants that code review
kept missing (see docs/lint.md for the catalog and suppression
syntax).

The single-function rules:

* ``env-contract``  — every ``KF_*`` env read (Python and C++) appears in
  the :mod:`kungfu_tpu.utils.envs` registry, and every registry entry has
  a reader (:mod:`kungfu_tpu.analysis.envcheck`).
* ``jit-sync``      — no host-sync / side-effect calls inside
  ``@jax.jit``/``pmap``/``shard_map`` bodies or their direct callees
  (:mod:`kungfu_tpu.analysis.jitpurity`).
* ``blocking-io``   — no timeout-less blocking calls in modules that run
  background threads (:mod:`kungfu_tpu.analysis.blockingio`).
* ``lock-discipline`` — every write to a ``// guarded_by(<mutex>)``
  C++ field happens in a scope holding that mutex
  (:mod:`kungfu_tpu.analysis.lockcheck`).
* ``retry-discipline`` — network retry loops bound their attempts and
  back off with jitter (:mod:`kungfu_tpu.analysis.retrydiscipline`).

The interprocedural (kf-verify) rules, built on the shared project call
graph (:mod:`kungfu_tpu.analysis.callgraph`):

* ``collective-consistency`` — every peer issues the same collectives
  under the same rendezvous names; rank-conditional collectives,
  constant-name reuse, and peer-divergent name expressions are flagged
  (:mod:`kungfu_tpu.analysis.collectives`).
* ``wire-contract`` — the Python framing (:class:`HeaderCodec` in
  ``comm/host.py``) and the C++ decoder (``native/transport.cpp``) parse
  into one schema IR and must diff clean
  (:mod:`kungfu_tpu.analysis.wirecontract`).
* ``lock-order`` — the cross-module Python lock-acquisition graph must
  be acyclic (:mod:`kungfu_tpu.analysis.pylockorder`).
* ``proto-verify`` — the SPMD protocol verifier: per-entrypoint
  symbolic collective/p2p protocols (extraction in
  :mod:`kungfu_tpu.analysis.commgraph`) proven ordering-consistent,
  tag-paired, and deadlock-free over every ``ParallelPlan`` geometry
  up to 16 ranks (:mod:`kungfu_tpu.analysis.protoverify`).

The replay-determinism (kf-det) rules, built on the interprocedural
taint engine (:mod:`kungfu_tpu.analysis.taint`, rules in
:mod:`kungfu_tpu.analysis.detrules`, contract in docs/determinism.md):

* ``replay-taint`` — entropy sources (wall clock, unseeded RNG draws,
  uuid, os entropy, set iteration order) must not reach replay-critical
  sinks (consensus payloads, rendezvous tag names, checkpoint commits,
  manifest records, chaos matchers); agreement-op results sanitize.
* ``rng-discipline`` — PRNG keys are consumed by ``jax.random.split``
  (no reuse, no double split), ``fold_in``/seed material derives from
  agreed values, and no process-global ``np.random`` draw happens
  inside traced code.
* ``reduction-order`` — no order-sensitive accumulation over unordered
  iteration (sets everywhere; dict views in the bitwise-pinned dirs);
  ``sorted()`` is the canonical-order escape hatch.

This package is intentionally stdlib-only (no jax/numpy import) so
``scripts/kflint`` runs in any environment, including bare CI images.
"""

from kungfu_tpu.analysis.core import Violation, repo_root
from kungfu_tpu.analysis.cli import (
    CHECKERS,
    DET_CHECKERS,
    PROTO_CHECKERS,
    VERIFY_CHECKERS,
    run_checkers,
)

__all__ = ["Violation", "repo_root", "CHECKERS", "DET_CHECKERS",
           "PROTO_CHECKERS", "VERIFY_CHECKERS", "run_checkers"]
