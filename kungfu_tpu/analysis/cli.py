"""kflint entry point: run the project checkers, print, exit nonzero.

Usage (via ``scripts/kflint``)::

    kflint                  # all checkers over the repo
    kflint --checker jit-sync --checker env-contract
    kflint --proto          # just the kf-verify protocol verifier
    kflint --changed        # only report findings in files changed vs git
    kflint --root /path/to/tree
    kflint --list
    kflint --json                          # machine-readable findings
    kflint --baseline tests/lint_baseline.json
    kflint --write-baseline tests/lint_baseline.json

``--changed`` keeps the *analysis* whole-tree (the interprocedural
rules — proto-verify, collective-consistency, lock-order — are
properties of paths through the tree, and the shared stat-keyed parse
cache in ``core.parse_module`` means every pass reuses one AST per
file) but *reports* only findings whose path changed relative to git
(worktree vs HEAD, plus untracked files).  With no relevant changes it
exits 0 without building the call graph at all.

A **baseline** is a JSON list of ``{"checker", "path", "message"}``
fingerprints (line numbers deliberately excluded — they drift with every
edit above a finding).  Findings matching a baseline entry are reported
as suppressed instead of failing the run, so a new rule can land
tree-wide on day one and ratchet the legacy findings down over time
instead of blocking on them.  ``--write-baseline`` snapshots the current
findings into that format.

Exit code 0 = clean (or fully baselined), 1 = violations, 2 =
usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from kungfu_tpu.analysis import (
    aggschema,
    blockingio,
    collectives,
    detrules,
    envcheck,
    handlecheck,
    jitpurity,
    ledgerschema,
    lockcheck,
    protoverify,
    pylockorder,
    recompilehazard,
    retrydiscipline,
    shardaxis,
    shardspec,
    tracevocab,
    wirecontract,
)
from kungfu_tpu.analysis.core import Violation, repo_root

CHECKERS: Dict[str, object] = {
    envcheck.CHECKER: envcheck.check,
    jitpurity.CHECKER: jitpurity.check,
    blockingio.CHECKER: blockingio.check,
    lockcheck.CHECKER: lockcheck.check,
    retrydiscipline.CHECKER: retrydiscipline.check,
    handlecheck.CHECKER: handlecheck.check,
    collectives.CHECKER: collectives.check,
    wirecontract.CHECKER: wirecontract.check,
    pylockorder.CHECKER: pylockorder.check,
    tracevocab.CHECKER: tracevocab.check,
    aggschema.CHECKER: aggschema.check,
    ledgerschema.CHECKER: ledgerschema.check,
    shardaxis.CHECKER: shardaxis.check,
    shardspec.CHECKER: shardspec.check,
    recompilehazard.CHECKER: recompilehazard.check,
    protoverify.CHECKER: protoverify.check,
    detrules.CHECKER_TAINT: detrules.check_replay_taint,
    detrules.CHECKER_RNG: detrules.check_rng_discipline,
    detrules.CHECKER_RED: detrules.check_reduction_order,
}

#: the kf-verify subset: the interprocedural rules built on the shared
#: call graph (scripts/check.sh names them; the set also documents which
#: rules a baseline most plausibly covers while a tree is brought clean)
VERIFY_CHECKERS = (collectives.CHECKER, wirecontract.CHECKER,
                   pylockorder.CHECKER)

#: the kf-shard subset: the axis-environment rules (make shardcheck /
#: the check.sh empty-baseline gate run exactly these)
SHARD_CHECKERS = (shardaxis.CHECKER, shardspec.CHECKER,
                  recompilehazard.CHECKER)

#: the protocol verifier (``kflint --proto``): gates with an EMPTY
#: baseline in check.sh — a collective-ordering divergence, an orphan
#: p2p tag, or a wait-for cycle can never land as "legacy debt"
PROTO_CHECKERS = (protoverify.CHECKER,)

#: the kf-det subset: the replay-determinism rules over the taint
#: engine (make detcheck / the check.sh empty-baseline gate run exactly
#: these — a determinism finding never ratchets)
DET_CHECKERS = (detrules.CHECKER_TAINT, detrules.CHECKER_RNG,
                detrules.CHECKER_RED)

#: cross-language rule contracts: a change to EITHER side must surface
#: the findings the rule reports on the other side — ``--changed``
#: expands the filter set through these couples (a transport.cpp-only
#: diff still shows the wire-contract finding anchored on host.py)
COUPLED_PATHS: Tuple[Tuple[str, ...], ...] = (
    (wirecontract.HOST_PATH.replace("\\", "/"),
     wirecontract.CPP_PATH.replace("\\", "/")),
)


def expand_coupled(changed: Sequence[str]) -> set:
    """The changed-path filter set, closed over the cross-language
    couples."""
    out = set(changed)
    for couple in COUPLED_PATHS:
        if out & set(couple):
            out.update(couple)
    return out


def _git_changed_files(root: str) -> Optional[List[str]]:
    """Repo-relative paths changed vs HEAD (staged + worktree) plus
    untracked files; None when git is unavailable (fall back to a full
    report rather than silently reporting nothing)."""
    import subprocess

    try:
        diff = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, timeout=30, check=True)
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=30, check=True)
    except Exception:  # noqa: BLE001 - any git failure: no filter
        return None
    return sorted({p for p in (diff.stdout + untracked.stdout).split("\n")
                   if p.strip()})


def run_checkers(root: Optional[str] = None,
                 names: Optional[Sequence[str]] = None) -> List[Violation]:
    """All violations from the selected checkers (default: all)."""
    root = root or repo_root()
    out: List[Violation] = []
    for name in names or CHECKERS:
        out.extend(CHECKERS[name](root))
    return sorted(out, key=lambda v: (v.path, v.line, v.checker))


def _fingerprint(v: Violation) -> Tuple[str, str, str]:
    return (v.checker, v.path, v.message)


def load_baseline(path: str) -> List[dict]:
    with open(path, encoding="utf-8") as f:
        entries = json.load(f)
    if not isinstance(entries, list) or not all(
            isinstance(e, dict) and {"checker", "path", "message"} <= set(e)
            for e in entries):
        raise ValueError(
            f"{path}: baseline must be a JSON list of "
            f'{{"checker", "path", "message"}} entries')
    return entries


def apply_baseline(violations: List[Violation],
                   entries: List[dict]) -> Tuple[List[Violation], int]:
    """(unbaselined violations, suppressed count)."""
    allowed = {(e["checker"], e["path"], e["message"]) for e in entries}
    fresh = [v for v in violations if _fingerprint(v) not in allowed]
    return fresh, len(violations) - len(fresh)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kflint", description="kungfu-tpu project-invariant linter")
    p.add_argument("--root", default=None,
                   help="tree to lint (default: auto-detected repo root)")
    p.add_argument("--checker", action="append", choices=sorted(CHECKERS),
                   help="run only this checker (repeatable)")
    p.add_argument("--proto", action="store_true",
                   help="run only the kf-verify protocol verifier "
                        "(proto-verify)")
    p.add_argument("--changed", action="store_true",
                   help="report only findings in files changed vs git "
                        "(analysis stays whole-tree; exits 0 fast when "
                        "nothing changed)")
    p.add_argument("--list", action="store_true",
                   help="list available checkers and exit")
    p.add_argument("--json", action="store_true",
                   help="emit findings as a JSON list on stdout")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="suppress findings whose (checker, path, message) "
                        "fingerprint appears in this JSON baseline")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write the current findings as a baseline and exit 0")
    args = p.parse_args(argv)
    if args.list:
        for name in sorted(CHECKERS):
            print(name)
        return 0
    names = args.checker
    if args.proto:
        names = list(names or []) + [c for c in PROTO_CHECKERS
                                     if c not in (names or [])]
    try:
        root = args.root or repo_root()
        changed: Optional[List[str]] = None
        if args.changed:
            changed = _git_changed_files(root)
            if changed is not None and not any(
                    p.endswith((".py", ".cc", ".cpp", ".h"))
                    for p in changed):
                print("kflint: 0 violation(s) (no relevant changes)",
                      file=sys.stderr)
                return 0
        violations = run_checkers(root, names)
        if changed is not None:
            changed_set = expand_coupled(changed)
            violations = [v for v in violations if v.path in changed_set]
        suppressed = 0
        if args.baseline:
            violations, suppressed = apply_baseline(
                violations, load_baseline(args.baseline))
    except Exception as e:  # noqa: BLE001 - CLI surface
        print(f"kflint: internal error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        entries = [
            {"checker": v.checker, "path": v.path, "message": v.message}
            for v in violations
        ]
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(entries, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"kflint: wrote {len(entries)} baseline entr"
              f"{'y' if len(entries) == 1 else 'ies'} to "
              f"{args.write_baseline}", file=sys.stderr)
        return 0

    if args.json:
        print(json.dumps([
            {"checker": v.checker, "path": v.path, "line": v.line,
             "message": v.message}
            for v in violations
        ], indent=2))
    else:
        for v in violations:
            print(v.render())
    n = len(violations)
    checkers = names or sorted(CHECKERS)
    note = f" ({suppressed} baselined)" if suppressed else ""
    print(f"kflint: {n} violation(s){note} [{', '.join(checkers)}]",
          file=sys.stderr)
    return 1 if n else 0


if __name__ == "__main__":
    sys.exit(main())
