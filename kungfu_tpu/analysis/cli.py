"""kflint entry point: run the project checkers, print, exit nonzero.

Usage (via ``scripts/kflint``)::

    kflint                  # all checkers over the repo
    kflint --checker jit-sync --checker env-contract
    kflint --root /path/to/tree
    kflint --list

Exit code 0 = clean, 1 = violations, 2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, List, Optional, Sequence

from kungfu_tpu.analysis import (
    blockingio,
    envcheck,
    jitpurity,
    lockcheck,
    retrydiscipline,
)
from kungfu_tpu.analysis.core import Violation, repo_root

CHECKERS: Dict[str, object] = {
    envcheck.CHECKER: envcheck.check,
    jitpurity.CHECKER: jitpurity.check,
    blockingio.CHECKER: blockingio.check,
    lockcheck.CHECKER: lockcheck.check,
    retrydiscipline.CHECKER: retrydiscipline.check,
}


def run_checkers(root: Optional[str] = None,
                 names: Optional[Sequence[str]] = None) -> List[Violation]:
    """All violations from the selected checkers (default: all five)."""
    root = root or repo_root()
    out: List[Violation] = []
    for name in names or CHECKERS:
        out.extend(CHECKERS[name](root))
    return sorted(out, key=lambda v: (v.path, v.line, v.checker))


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="kflint", description="kungfu-tpu project-invariant linter")
    p.add_argument("--root", default=None,
                   help="tree to lint (default: auto-detected repo root)")
    p.add_argument("--checker", action="append", choices=sorted(CHECKERS),
                   help="run only this checker (repeatable)")
    p.add_argument("--list", action="store_true",
                   help="list available checkers and exit")
    args = p.parse_args(argv)
    if args.list:
        for name in sorted(CHECKERS):
            print(name)
        return 0
    try:
        violations = run_checkers(args.root, args.checker)
    except Exception as e:  # noqa: BLE001 - CLI surface
        print(f"kflint: internal error: {e}", file=sys.stderr)
        return 2
    for v in violations:
        print(v.render())
    n = len(violations)
    checkers = args.checker or sorted(CHECKERS)
    print(f"kflint: {n} violation(s) [{', '.join(checkers)}]",
          file=sys.stderr)
    return 1 if n else 0


if __name__ == "__main__":
    sys.exit(main())
