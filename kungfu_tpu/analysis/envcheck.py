"""env-contract checker: the ``KF_*`` env-var registry cannot drift.

Direction 1 (unregistered read): every ``KF_[A-Z0-9_]+`` token (and
every ``MEGASCALE_[A-Z0-9_]+`` token — the TPU multislice contract the
platform adapter and slice topology read) that appears in Python under
``kungfu_tpu``/``scripts``/``benchmarks`` or in ``native/*.cpp`` must
appear in :mod:`kungfu_tpu.utils.envs` (docstring table or constant).  Direction 2 (dead registry entry): every ``KF_*``
token in the registry must have at least one reader — either the literal
elsewhere in the tree, or a reference to the envs.py constant bound to
it (``envs.SELF_SPEC`` style), including inside envs.py's own parsing
code.  Compile-time-only tokens (C macros such as ``KF_SIMD_CLONES``)
are registered in the docstring like everything else, with a note.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set, Tuple

from kungfu_tpu.analysis.core import (
    Violation,
    iter_cpp_files,
    iter_py_files,
    parse_module,
    read_lines,
    relpath,
    suppressed,
    suppressions,
)

CHECKER = "env-contract"
_TOKEN_RE = re.compile(r"\b(?:KF|MEGASCALE)_[A-Z0-9_]+\b")

REGISTRY_PATH = os.path.join("kungfu_tpu", "utils", "envs.py")


def _registry_tokens(root: str) -> Dict[str, int]:
    """``{token: first line}`` for every token in envs.py."""
    out: Dict[str, int] = {}
    for i, line in enumerate(read_lines(os.path.join(root, REGISTRY_PATH)), 1):
        for tok in _TOKEN_RE.findall(line):
            out.setdefault(tok, i)
    return out


def _registry_constants(root: str) -> Dict[str, str]:
    """``{constant_name: token}`` for ``NAME = "KF_..."`` bindings."""
    tree = parse_module(os.path.join(root, REGISTRY_PATH)).tree
    out: Dict[str, str] = {}
    if tree is None:
        return out
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
            and node.value.value.startswith(("KF_", "MEGASCALE_"))
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _tree_reads(root: str) -> Dict[str, List[Tuple[str, int]]]:
    """``{token: [(relpath, line), ...]}`` outside the registry,
    honoring per-line ``allow(env-contract)`` suppressions."""
    reads: Dict[str, List[Tuple[str, int]]] = {}
    files = list(iter_py_files(root)) + list(iter_cpp_files(root))
    reg_abs = os.path.join(root, REGISTRY_PATH)
    for path in files:
        if os.path.abspath(path) == os.path.abspath(reg_abs):
            continue
        # the linter's own sources *discuss* tokens, they don't read them
        if f"kungfu_tpu{os.sep}analysis{os.sep}" in os.path.abspath(path):
            continue
        lines = read_lines(path)
        supp = suppressions(lines)
        for i, line in enumerate(lines, 1):
            for tok in _TOKEN_RE.findall(line):
                if suppressed(supp, i, CHECKER):
                    continue
                reads.setdefault(tok, []).append((relpath(root, path), i))
    return reads


def _constant_readers(root: str, constants: Dict[str, str]) -> Set[str]:
    """KF tokens whose envs.py constant is referenced as a *load* —
    in envs.py's own code or in any module importing the registry."""
    used: Set[str] = set()
    # loads inside envs.py itself (parse_config_from_env etc.)
    reg_tree = parse_module(os.path.join(root, REGISTRY_PATH)).tree
    for node in ast.walk(reg_tree) if reg_tree is not None else ():
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in constants
        ):
            used.add(constants[node.id])
    # references from modules that import the registry
    name_re = re.compile(
        r"\b(" + "|".join(re.escape(n) for n in constants) + r")\b"
    ) if constants else None
    for path in iter_py_files(root):
        if os.path.abspath(path) == os.path.abspath(
            os.path.join(root, REGISTRY_PATH)
        ):
            continue
        src = parse_module(path).source
        if "utils.envs" not in src and "utils import envs" not in src:
            continue
        if name_re is not None:
            for m in name_re.finditer(src):
                used.add(constants[m.group(1)])
    return used


def check(root: str) -> List[Violation]:
    registry = _registry_tokens(root)
    reads = _tree_reads(root)
    constants = _registry_constants(root)
    out: List[Violation] = []

    for tok in sorted(reads):
        if tok not in registry:
            path, line = reads[tok][0]
            out.append(Violation(
                CHECKER, path, line,
                f"{tok} is read here but not registered in "
                f"kungfu_tpu/utils/envs.py ({len(reads[tok])} read site(s))",
            ))

    reg_lines = read_lines(os.path.join(root, REGISTRY_PATH))
    reg_supp = suppressions(reg_lines)
    const_readers = _constant_readers(root, constants)
    for tok, line in sorted(registry.items()):
        if tok in reads or tok in const_readers:
            continue
        if suppressed(reg_supp, line, CHECKER):
            continue
        out.append(Violation(
            CHECKER, relpath(root, os.path.join(root, REGISTRY_PATH)), line,
            f"{tok} is registered but nothing in the tree reads it",
        ))
    return out
