"""Named blob stores + p2p model exchange.

Parity with reference ``srcs/go/store/{store,versionedstore}.go`` and the
PeerToPeerEndpoint (``rchannel/handler/p2p.go``): a process-local KV store
of named byte blobs, a versioned store keeping a sliding window of model
versions (default 3, like ``handler/p2p.go:11``), and the request/response
protocol async gossip peers use to pull each other's models.

A future C++ backend (kungfu_tpu/native) can hold the blobs outside the
GIL; the Python API stays identical.
"""

from kungfu_tpu.store.store import Store, VersionedStore, get_local_store, reset_local_store
from kungfu_tpu.store.p2p import (install_p2p_handler, remote_request,
                                  remote_request_into)

__all__ = [
    "Store",
    "VersionedStore",
    "get_local_store",
    "reset_local_store",
    "install_p2p_handler",
    "remote_request",
    "remote_request_into",
]
