"""Process-local blob stores."""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional

DEFAULT_VERSION_COUNT = 3  # reference handler/p2p.go:11


def _nbytes(blob) -> int:
    """Byte length of any buffer-protocol value (len() of a numpy array
    counts elements, not bytes)."""
    return memoryview(blob).nbytes


class Store:
    """Named blob KV store with size-checked get-or-create
    (reference ``store.go:14-59``)."""

    def __init__(self):
        # values are bytes unless saved with copy=False, in which case
        # any buffer-protocol object the caller handed over
        self._blobs: Dict[str, object] = {}
        self._lock = threading.RLock()

    def save(self, name: str, blob, copy: bool = True) -> None:
        """``copy=False`` stores the caller's buffer object as-is (any
        buffer-protocol value) — the gossip hot path hands over ~100 MiB
        fused-model views it promises never to mutate; the default
        snapshots, so a caller reusing its buffer can't corrupt the
        store."""
        with self._lock:
            existing = self._blobs.get(name)
            if existing is not None and _nbytes(existing) != _nbytes(blob):
                raise ValueError(
                    f"blob {name!r} size changed: "
                    f"{_nbytes(existing)} -> {_nbytes(blob)}"
                )
            self._blobs[name] = blob if not copy else bytes(blob)

    def get(self, name: str):
        """The stored value: bytes, or the caller's buffer object for
        copy=False saves."""
        with self._lock:
            return self._blobs.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._blobs)


class VersionedStore:
    """Sliding window of named blob sets keyed by version string
    (reference ``versionedstore.go`` — keeps the last ``window`` versions)."""

    def __init__(self, window: int = DEFAULT_VERSION_COUNT):
        self._window = window
        self._versions: "OrderedDict[str, Store]" = OrderedDict()
        self._lock = threading.RLock()

    def save(self, name: str, blob, version: Optional[str] = None,
             copy: bool = True) -> None:
        version = version or ""
        with self._lock:
            st = self._versions.get(version)
            if st is None:
                st = Store()
                self._versions[version] = st
                while len(self._versions) > self._window:
                    self._versions.popitem(last=False)
            st.save(name, blob, copy=copy)

    def get(self, name: str, version: Optional[str] = None):
        with self._lock:
            if version is not None and version != "":
                st = self._versions.get(version)
                return st.get(name) if st else None
            # latest version containing the name
            for st in reversed(self._versions.values()):
                blob = st.get(name)
                if blob is not None:
                    return blob
            return None

    def versions(self) -> List[str]:
        with self._lock:
            return list(self._versions)


_local: Optional[VersionedStore] = None
_local_lock = threading.Lock()


def get_local_store() -> VersionedStore:
    global _local
    with _local_lock:
        if _local is None:
            _local = VersionedStore()
        return _local


def reset_local_store() -> None:
    global _local
    with _local_lock:
        _local = None
