"""P2P blob request/response over the host channel.

Parity with the reference's PeerToPeerEndpoint round trip
(``rchannel/handler/p2p.go:36-47,102-120``): the requester names a blob
(+ optional version), the responder streams it back, or flags failure
(the ``RequestFailed`` flag → here an explicit status byte).  Used by
PairAveraging gossip to pull a random peer's model.
"""

from __future__ import annotations

import itertools
import json
import threading
from typing import Optional

from kungfu_tpu.comm.host import ConnType, HostChannel
from kungfu_tpu.plan.peer import PeerID, parse_peer_id
from kungfu_tpu.store.store import get_local_store
from kungfu_tpu.utils.log import get_logger

_log = get_logger("p2p-store")
_req_counter = itertools.count()
_OK = b"\x01"
_FAIL = b"\x00"


def install_p2p_handler(channel: HostChannel, store=None,
                        control_store=None) -> None:
    """Make this endpoint answer blob requests from ``store`` (default: the
    process-global store).  Names under the reserved ``kf.`` prefix are
    served from ``control_store`` instead — control-plane blobs (e.g. the
    device-strategy epoch record) must not share an eviction window with
    gossip model traffic, whose per-step versions would push them out."""

    def handle(name: str, payload: bytes, src: str):
        # name = "req.<id>"; payload = json {"name":..., "version":...}
        req_id = name[len("req."):]
        try:
            req = json.loads(payload.decode())
            blob_name = req["name"]
            st = (control_store
                  if control_store is not None and blob_name.startswith("kf.")
                  else (store or get_local_store()))
            blob = st.get(blob_name, req.get("version") or None)
        except (ValueError, KeyError) as e:
            _log.warning("bad p2p request from %s: %s", src, e)
            blob = None
        status, body = (_OK, blob) if blob is not None else (_FAIL, b"")
        try:
            channel.send(
                parse_peer_id(src),
                f"rsp.{req_id}",
                status + body,
                ConnType.PEER_TO_PEER,
                retries=5,
            )
        except ConnectionError as e:
            _log.warning("cannot answer %s: %s", src, e)

    channel.on_p2p_request(handle)


def remote_request(
    peer, target: PeerID, name: str, version: Optional[str] = None,
    timeout: float = 60.0,
) -> Optional[bytes]:
    """Pull blob ``name`` from ``target``'s store; None when unavailable."""
    channel = peer.channel
    own_store = getattr(peer, "store", None)
    if name.startswith("kf."):
        own_store = getattr(peer, "_ctrl_store", None) or own_store
    if channel is None or target == peer.config.self_id:
        # single-process mode / self-request: serve from the own store
        st = own_store if own_store is not None else get_local_store()
        return st.get(name, version)
    req_id = f"{peer.config.self_id.port}-{next(_req_counter)}"
    body = json.dumps({"name": name, "version": version or ""}).encode()
    channel.send(target, f"req.{req_id}", body, ConnType.PEER_TO_PEER)
    rsp = channel.recv(target, f"rsp.{req_id}", ConnType.PEER_TO_PEER, timeout=timeout)
    if rsp[:1] != _OK:
        return None
    return rsp[1:]
