"""P2P blob request/response over the host channel.

Parity with the reference's PeerToPeerEndpoint round trip
(``rchannel/handler/p2p.go:36-47,102-120``): the requester names a blob
(+ optional version), the responder streams it back, or flags failure
(the ``RequestFailed`` flag → here an explicit status byte).  Used by
PairAveraging gossip to pull a random peer's model.
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import threading
from typing import Optional

from kungfu_tpu.comm.host import SERVE_NAME_PREFIX, ConnType, HostChannel
from kungfu_tpu.monitor import timeline
from kungfu_tpu.plan.peer import PeerID, parse_peer_id
from kungfu_tpu.store.store import get_local_store
from kungfu_tpu.utils.log import get_logger

_log = get_logger("p2p-store")
_req_counter = itertools.count()
_OK = b"\x01"
_FAIL = b"\x00"


def install_p2p_handler(channel: HostChannel, store=None,
                        control_store=None, n_peers: Optional[int] = None):
    """Make this endpoint answer blob requests from ``store`` (default: the
    process-global store).  Names under the reserved ``kf.`` prefix are
    served from ``control_store`` instead — control-plane blobs (e.g. the
    device-strategy epoch record) must not share an eviction window with
    gossip model traffic, whose per-step versions would push them out.

    Serving happens on a small responder pool, NEVER on the channel's
    receive path: a ~100 MiB model reply blocks on TCP backpressure, and
    if the stream thread is the one writing it, it stops draining its
    own socket — with two peers pulling from each other continuously
    (async gossip), that deadlocks both directions until a timeout.
    The reference answers each ``Request`` from its own goroutine, not
    the connection reader (``rchannel/handler/p2p.go:36-47``)."""

    serve_q: "queue.Queue" = queue.Queue()

    def serve(name: str, payload: bytes, src: str):
        # name = "req.<id>"; payload = json {"name":..., "version":...,
        # "raw": 0|1, "tc": optional kf-xray trace context}
        req_id = name[len("req."):]
        raw = False
        try:
            req = json.loads(payload.decode())
            blob_name = req["name"]
            if timeline.enabled():
                # the requester's trace context rides the request meta:
                # this mark links the responder side into the same
                # distributed trace (docs/xray.md)
                tr, parent = timeline.parse_trace_context(req.get("tc"))
                timeline.event("mark", "p2p.serve", req=req_id,
                               blob=str(blob_name),
                               **timeline.context_attrs(tr, parent))
            raw = bool(req.get("raw"))
            st = (control_store
                  if control_store is not None and blob_name.startswith("kf.")
                  else (store or get_local_store()))
            blob = st.get(blob_name, req.get("version") or None)
        except (ValueError, KeyError) as e:
            _log.warning("bad p2p request from %s: %s", src, e)
            blob = None
        if raw:
            # zero-copy reply: the blob buffer itself is the payload (the
            # requester recv_intos it straight off the socket); a miss is
            # the empty payload — gossip blobs are never 0 bytes
            body = blob if blob is not None else b""
        else:
            # legacy framing: 1 status byte + body in one message (pays a
            # concat copy; fine for the small control-plane blobs).  The
            # store may hold non-bytes buffers (copy=False saves).
            body = (_OK + bytes(blob)) if blob is not None else _FAIL
        try:
            channel.send(
                parse_peer_id(src),
                f"rsp.{req_id}",
                body,
                ConnType.PEER_TO_PEER,
                retries=5,
            )
        except ConnectionError as e:
            _log.warning("cannot answer %s: %s", src, e)

    def responder():
        while True:
            # sentinel-terminated worker loop: stop() enqueues one None
            # per thread, so the forever-block is the shutdown protocol
            item = serve_q.get()  # kflint: allow(blocking-io)
            if item is None:
                return
            try:
                serve(*item)
            except Exception as e:  # noqa: BLE001 — keep serving
                _log.warning("p2p serve failed: %s", e)

    # a pool, not one thread: the reference answers each request on its
    # own goroutine, and with several peers pulling concurrently a
    # single responder would serialize ~100 MiB serves behind the
    # slowest receiver.  The size SCALES with the peer count
    # (host_pool_size: floor 2, capped by KF_CONFIG_HOST_POOL_MAX,
    # exported as the kf_host_pool_size gauge); an explicit
    # KF_CONFIG_P2P_RESPONDERS pins it instead.
    from kungfu_tpu.comm.host import host_pool_size
    from kungfu_tpu.utils import envs

    override = os.environ.get(envs.P2P_RESPONDERS, "").strip()
    if override:
        n_threads = max(1, int(override))
        # the gauge must reflect the PINNED size too, or the one surface
        # meant to confirm the pool's size goes silent exactly when an
        # operator overrides it
        from kungfu_tpu.monitor.registry import REGISTRY

        REGISTRY.gauge("kf_host_pool_size", pool="p2p").set(n_threads)
    else:
        n_threads = host_pool_size(
            n_peers if n_peers is not None else 2, pool="p2p")
    threads = [threading.Thread(target=responder,
                                name=f"kf-p2p-responder-{i}", daemon=True)
               for i in range(n_threads)]
    for t in threads:
        t.start()

    def handle(name: str, payload: bytes, src: str):
        # runs on the channel's receive path — hand off and return so the
        # stream keeps draining.  Names under the reserved serve prefix
        # are the serving plane's (kf-serve request/progress/completion
        # frames, serve/router.py): its own responder pool answers them,
        # and the blob store must not race a _FAIL reply onto the same id.
        if name.startswith(SERVE_NAME_PREFIX):
            return
        serve_q.put((name, payload, src))

    channel.on_p2p_request(handle)

    def stop(join_timeout: float = 5.0):
        for _ in threads:
            serve_q.put(None)
        for t in threads:
            t.join(join_timeout)

    return stop


def _req_meta(name: str, version: Optional[str], **extra) -> dict:
    """The request-frame JSON meta.  An ambient kf-xray trace context on
    the calling thread rides along as the compact ``tc`` field, so the
    responder's handling joins the requester's trace — the HeaderCodec
    wire header carries nothing new."""
    meta = {"name": name, "version": version or "", **extra}
    tc = timeline.format_trace_context(*timeline.current_trace())
    if tc is not None:
        meta["tc"] = tc
    return meta


def _serve_locally(peer, target: PeerID, name: str, version: Optional[str]):
    """Single-process mode / self-request: answer from the own store.
    Returns ``(True, blob)`` when the request never needs the wire."""
    own_store = getattr(peer, "store", None)
    if name.startswith("kf."):
        own_store = getattr(peer, "_ctrl_store", None) or own_store
    if peer.channel is None or target == peer.config.self_id:
        st = own_store if own_store is not None else get_local_store()
        return True, st.get(name, version)
    return False, None


def remote_request(
    peer, target: PeerID, name: str, version: Optional[str] = None,
    timeout: float = 60.0,
) -> Optional[bytes]:
    """Pull blob ``name`` from ``target``'s store; None when unavailable."""
    channel = peer.channel
    local, blob = _serve_locally(peer, target, name, version)
    if local:
        # honor the bytes contract even when the store holds a
        # copy=False buffer (small legacy/control-plane callers only —
        # the gossip hot path uses remote_request_into)
        return blob if blob is None or isinstance(blob, bytes) else bytes(blob)
    req_id = f"{peer.config.self_id.port}-{next(_req_counter)}"
    body = json.dumps(_req_meta(name, version)).encode()
    channel.send(target, f"req.{req_id}", body, ConnType.PEER_TO_PEER)
    rsp = channel.recv(target, f"rsp.{req_id}", ConnType.PEER_TO_PEER, timeout=timeout)
    if rsp[:1] != _OK:
        return None
    return rsp[1:]


def remote_request_into(
    peer, target: PeerID, name: str, buf,
    version: Optional[str] = None, timeout: float = 60.0,
    send_retries: Optional[int] = None,
):
    """Pull blob ``name`` from ``target`` INTO ``buf`` (writable
    contiguous buffer sized to the expected blob) — the gossip hot path.
    On the native backend the payload goes socket→``buf`` with no copy
    (registered receive) and the responder writevs straight from its
    store buffer, so a ~100 MiB model pull costs the wire, not four
    memcpys (reference fused ``ModelBuffer``,
    ``tensorflow/ops/cpu/peer_to_peer.cpp:72-424``).

    Returns ``buf`` when filled; the raw bytes when the blob exists but
    its size does not match ``buf``; ``None`` when the target does not
    have the blob.
    """
    channel = peer.channel
    local, blob = _serve_locally(peer, target, name, version)
    if local:
        if blob is None:
            return None
        # honor the 'buf when filled' contract on the local path too: the
        # store may hold a copy=False non-bytes view whose owner keeps
        # mutating it — callers must get their own buffer, not an alias
        src = memoryview(blob)
        dst = memoryview(buf)
        if src.nbytes == dst.nbytes:
            dst.cast("B")[:] = src.cast("B")
            return buf
        return bytes(src)  # size mismatch: raw bytes, like the wire path
    req_id = f"{peer.config.self_id.port}-{next(_req_counter)}"
    body = json.dumps(_req_meta(name, version, raw=1)).encode()
    # register the destination BEFORE the request leaves: the responder's
    # writev then streams socket→buf with no queue detour even when it
    # answers faster than we can turn around
    posted = channel.post_recv(target, f"rsp.{req_id}", buf,
                               ConnType.PEER_TO_PEER)
    # gossip pulls tolerate misses by design — a bounded send_retries
    # makes a dead target fail in seconds instead of riding the full
    # 500x200 ms connect ladder while the step (or teardown) waits
    kw = {} if send_retries is None else {"retries": send_retries}
    try:
        channel.send(target, f"req.{req_id}", body, ConnType.PEER_TO_PEER,
                     **kw)
    except BaseException:
        posted.abort()
        raise
    if posted.wait(timeout=timeout):
        return buf
    # size mismatch: the payload stayed queued — either the miss marker
    # (empty) or a blob of an unexpected size
    rsp = channel.recv(target, f"rsp.{req_id}", ConnType.PEER_TO_PEER,
                       timeout=timeout)
    return rsp if rsp else None
