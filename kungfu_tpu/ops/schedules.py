"""Device-plane allreduce *schedules* — strategy choice, TPU-style.

The reference adapts its allreduce by swapping per-message routing graphs
(8 named topologies, ``base/strategy.go:10-22``, swapped at runtime with
barrier+consensus, ``session/adaptation.go:8-28``).  On TPU the compiler
owns message routing, so "strategy" becomes **which collective
decomposition gets compiled** (SURVEY §7 step 9): the same allreduce can
lower as

* ``psum`` — one HLO all-reduce; XLA picks the algorithm (default).
* ``two_stage`` — explicit reduce-scatter + all-gather
  (``lax.psum_scatter`` + tiled ``all_gather``): the bandwidth-optimal
  decomposition materialized in the program, which lets XLA schedule the
  two phases independently around neighboring compute.
* ``ring`` — a manual ``ppermute`` ring (n-1 reduce-scatter steps +
  n-1 all-gather steps): every hop is an explicit program point, the
  shape that overlap experiments and the scaling-book recipes reason
  about.

All three produce the same values (sum/mean/min/max; see per-schedule
notes), verified against ``lax.psum`` in ``tests/test_schedules.py``.
Swapping = re-jitting with a different ``schedule=`` — the moral
equivalent of the reference's ``SetGlobalStrategy``, with consensus
handled by the same driver machinery as the host plane
(:mod:`kungfu_tpu.monitor.adaptive`).
"""

from __future__ import annotations

import math
from typing import Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from kungfu_tpu.utils.jaxcompat import axis_size

Axis = Union[str, Tuple[str, ...]]

#: selectable device-plane allreduce schedules
ALLREDUCE_SCHEDULES = ("psum", "two_stage", "ring")

_OPS = {
    "sum": jnp.add,
    "mean": jnp.add,  # sum then divide at the end
    "min": jnp.minimum,
    "max": jnp.maximum,
}
def _pad_identity(op: str, dtype):
    """Identity element for the fold — op- and dtype-aware (an inf pad
    in an int buffer would overflow; a zero pad would corrupt min/max;
    bool has neither iinfo nor inf)."""
    if op in ("sum", "mean"):
        return 0
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if op == "min" else -jnp.inf
    if dtype == jnp.bool_:
        return op == "min"  # True is min's identity, False is max's
    info = jnp.iinfo(dtype)
    return info.max if op == "min" else info.min




def _flatten_pad(a, n: int, op: str):
    """Flatten to [n, chunk] with an op-identity pad (zeros would corrupt
    min/max tails)."""
    flat = a.reshape(-1)
    chunk = max(1, math.ceil(flat.size / n))
    pad = n * chunk - flat.size
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.full((pad,), _pad_identity(op, flat.dtype), flat.dtype)]
        )
    return flat.reshape(n, chunk), flat.size - pad


def _ring_all_reduce_leaf(a, axis_name: str, op: str):
    """ppermute ring: n-1 reduce-scatter hops, n-1 all-gather hops.

    Step s of reduce-scatter: rank r sends chunk (r-s) mod n, receives
    chunk (r-s-1) mod n from rank r-1 and folds it in; after n-1 steps
    rank r owns the fully reduced chunk (r+1) mod n, which then travels
    the ring unreduced for n-1 more steps.
    """
    n = axis_size(axis_name)
    if n == 1:
        return a
    idx = lax.axis_index(axis_name)
    fold = _OPS[op]
    parts, size = _flatten_pad(a, n, op)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def rs_step(s, parts):
        send_i = (idx - s) % n
        recv_i = (idx - s - 1) % n
        buf = lax.dynamic_index_in_dim(parts, send_i, axis=0, keepdims=False)
        got = lax.ppermute(buf, axis_name, perm)
        cur = lax.dynamic_index_in_dim(parts, recv_i, axis=0, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            parts, fold(cur, got), recv_i, axis=0
        )

    parts = lax.fori_loop(0, n - 1, rs_step, parts)

    def ag_step(s, parts):
        send_i = (idx + 1 - s) % n
        recv_i = (idx - s) % n
        buf = lax.dynamic_index_in_dim(parts, send_i, axis=0, keepdims=False)
        got = lax.ppermute(buf, axis_name, perm)
        return lax.dynamic_update_index_in_dim(parts, got, recv_i, axis=0)

    parts = lax.fori_loop(0, n - 1, ag_step, parts)
    return parts.reshape(-1)[:size].reshape(a.shape)


def _two_stage_all_reduce_leaf(a, axis_name: str, op: str):
    """Explicit reduce-scatter + all-gather.  ``psum_scatter`` is
    sum-only; min/max fall back to the ring schedule (same explicit
    two-phase shape, correct op)."""
    n = axis_size(axis_name)
    if n == 1:
        return a
    if op in ("min", "max"):
        return _ring_all_reduce_leaf(a, axis_name, op)
    parts, size = _flatten_pad(a, n, op)
    flat = parts.reshape(-1)
    mine = lax.psum_scatter(flat, axis_name, scatter_dimension=0, tiled=True)
    out = lax.all_gather(mine, axis_name, axis=0, tiled=True)
    return out[:size].reshape(a.shape)


_PSUM_FOLD = {"sum": lax.psum, "min": lax.pmin, "max": lax.pmax}


def all_reduce_scheduled(x, axis: Axis, op: str = "sum",
                         schedule: str = "psum"):
    """Allreduce a tensor/pytree across ``axis`` with an explicit
    schedule.  ``schedule='psum'`` is :func:`kungfu_tpu.ops.all_reduce`;
    the others decompose the collective in-program (docstring above).
    Jit/shard_map-composable; every schedule returns the same values.

    ``axis`` may be a tuple of mesh axis names in outer-to-inner order
    (e.g. a hierarchical communicator's ``(host, local)``): the schedule
    applies to the FIRST non-trivial axis — the cross-host stage — after
    the inner axes reduce with one-hop psum over ICI, the reference's
    local/cross split (``session/strategy.go:176-210``).
    """
    if op not in _OPS:
        raise ValueError(f"unsupported op {op!r}")
    if schedule not in ALLREDUCE_SCHEDULES:
        raise ValueError(
            f"unknown schedule {schedule!r}; one of {ALLREDUCE_SCHEDULES}"
        )
    if schedule == "psum":
        from kungfu_tpu.ops.collective import all_reduce

        return all_reduce(x, axis, op=op)
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    sched_leaf = (_ring_all_reduce_leaf if schedule == "ring"
                  else _two_stage_all_reduce_leaf)
    base = "sum" if op == "mean" else op

    def leaf(a):
        sizes = [axis_size(ax) for ax in axes]
        real = [ax for ax, s in zip(axes, sizes) if s > 1] or [axes[0]]
        for ax in real[1:]:  # inner (intra-host) stages: one-hop psum
            a = _PSUM_FOLD[base](a, ax)
        a = sched_leaf(a, axis_name=real[0], op=base)
        if op == "mean":
            a = a / math.prod(sizes)
        return a

    return jax.tree_util.tree_map(leaf, x)
