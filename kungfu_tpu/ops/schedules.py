"""Device-plane allreduce *schedules* — strategy choice, TPU-style.

The reference adapts its allreduce by swapping per-message routing graphs
(8 named topologies, ``base/strategy.go:10-22``, swapped at runtime with
barrier+consensus, ``session/adaptation.go:8-28``).  On TPU the compiler
owns message routing, so "strategy" becomes **which collective
decomposition gets compiled** (SURVEY §7 step 9): the same allreduce can
lower as

* ``psum`` — one HLO all-reduce; XLA picks the algorithm (default).
* ``two_stage`` — explicit reduce-scatter + all-gather
  (``lax.psum_scatter`` + tiled ``all_gather``): the bandwidth-optimal
  decomposition materialized in the program, which lets XLA schedule the
  two phases independently around neighboring compute.
* ``ring`` — a manual ``ppermute`` ring (n-1 reduce-scatter steps +
  n-1 all-gather steps): every hop is an explicit program point, the
  shape that overlap experiments and the scaling-book recipes reason
  about.
* ``pallas_ring`` — the ring written BELOW XLA: the Pallas ICI kernels
  of :mod:`kungfu_tpu.ops.pallas.collectives`, whose RDMA hops overlap
  the fold math inside one kernel (double-buffered working slots) —
  compiled on TPU, the bitwise-identical lax emulation elsewhere.

All four produce the same values (sum/mean/min/max; see per-schedule
notes), verified against ``lax.psum`` in ``tests/test_schedules.py``.
Swapping = re-jitting with a different ``schedule=`` — the moral
equivalent of the reference's ``SetGlobalStrategy``, with consensus
handled by the same driver machinery as the host plane
(:mod:`kungfu_tpu.monitor.adaptive`).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kungfu_tpu.utils.jaxcompat import axis_size

Axis = Union[str, Tuple[str, ...]]

#: selectable device-plane allreduce schedules (also the device bandit's
#: arm set — kungfu_tpu.monitor.adapt_device learns a winner per payload
#: bucket and installs it with Communicator.set_bucket_strategy)
ALLREDUCE_SCHEDULES = ("psum", "two_stage", "ring", "pallas_ring")

#: schedules selectable for the flat reduce-scatter / all-gather pair
#: below ("lax" = the psum_scatter/all_gather primitives XLA lowers;
#: "pallas_ring" = the in-kernel-overlap ring of ops/pallas/collectives)
FLAT_SCHEDULES = ("lax", "pallas_ring")

#: payload-size buckets for the per-bucket schedule table
#: (:meth:`kungfu_tpu.comm.device.Communicator.set_bucket_strategy`): the
#: best decomposition shifts with payload size — small control tensors
#: are latency-bound (one fused HLO all-reduce wins), large fused
#: gradient buckets are bandwidth-bound (the explicit two-stage/ring
#: decompositions win; PAPERS.md 2011.03641) — so each bucket learns its
#: own winner.  Edges are upper bounds in bytes; the last bucket is
#: unbounded.
SIZE_BUCKETS = ("small", "large")
SIZE_BUCKET_EDGES = (256 << 10,)  # small: < 256 KiB; large: the rest


def size_bucket(nbytes: int) -> int:
    """Bucket index for a payload of ``nbytes`` (0-based, ascending)."""
    for i, edge in enumerate(SIZE_BUCKET_EDGES):
        if nbytes < edge:
            return i
    return len(SIZE_BUCKET_EDGES)

_OPS = {
    "sum": jnp.add,
    "mean": jnp.add,  # sum then divide at the end
    "min": jnp.minimum,
    "max": jnp.maximum,
}
def _pad_identity(op: str, dtype):
    """Identity element for the fold — op- and dtype-aware (an inf pad
    in an int buffer would overflow; a zero pad would corrupt min/max;
    bool has neither iinfo nor inf)."""
    if op in ("sum", "mean"):
        return 0
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if op == "min" else -jnp.inf
    if dtype == jnp.bool_:
        return op == "min"  # True is min's identity, False is max's
    info = jnp.iinfo(dtype)
    return info.max if op == "min" else info.min




def _flatten_pad(a, n: int, op: str):
    """Flatten to [n, chunk] with an op-identity pad (zeros would corrupt
    min/max tails)."""
    flat = a.reshape(-1)
    chunk = max(1, math.ceil(flat.size / n))
    pad = n * chunk - flat.size
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.full((pad,), _pad_identity(op, flat.dtype), flat.dtype)]
        )
    return flat.reshape(n, chunk), flat.size - pad


def _ring_all_reduce_leaf(a, axis_name: str, op: str):
    """ppermute ring: n-1 reduce-scatter hops, n-1 all-gather hops.

    Step s of reduce-scatter: rank r sends chunk (r-s) mod n, receives
    chunk (r-s-1) mod n from rank r-1 and folds it in; after n-1 steps
    rank r owns the fully reduced chunk (r+1) mod n, which then travels
    the ring unreduced for n-1 more steps.
    """
    n = axis_size(axis_name)
    if n == 1:
        return a
    idx = lax.axis_index(axis_name)
    fold = _OPS[op]
    parts, size = _flatten_pad(a, n, op)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def rs_step(s, parts):
        send_i = (idx - s) % n
        recv_i = (idx - s - 1) % n
        buf = lax.dynamic_index_in_dim(parts, send_i, axis=0, keepdims=False)
        got = lax.ppermute(buf, axis_name, perm)
        cur = lax.dynamic_index_in_dim(parts, recv_i, axis=0, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            parts, fold(cur, got), recv_i, axis=0
        )

    parts = lax.fori_loop(0, n - 1, rs_step, parts)

    def ag_step(s, parts):
        send_i = (idx + 1 - s) % n
        recv_i = (idx - s) % n
        buf = lax.dynamic_index_in_dim(parts, send_i, axis=0, keepdims=False)
        got = lax.ppermute(buf, axis_name, perm)
        return lax.dynamic_update_index_in_dim(parts, got, recv_i, axis=0)

    parts = lax.fori_loop(0, n - 1, ag_step, parts)
    return parts.reshape(-1)[:size].reshape(a.shape)


def _two_stage_all_reduce_leaf(a, axis_name: str, op: str):
    """Explicit reduce-scatter + all-gather.  ``psum_scatter`` is
    sum-only; min/max fall back to the ring schedule (same explicit
    two-phase shape, correct op)."""
    n = axis_size(axis_name)
    if n == 1:
        return a
    if op in ("min", "max"):
        return _ring_all_reduce_leaf(a, axis_name, op)
    parts, size = _flatten_pad(a, n, op)
    flat = parts.reshape(-1)
    mine = lax.psum_scatter(flat, axis_name, scatter_dimension=0, tiled=True)
    out = lax.all_gather(mine, axis_name, axis=0, tiled=True)
    return out[:size].reshape(a.shape)


def _pallas_ring_all_reduce_leaf(a, axis_name: str, op: str):
    """The ``pallas_ring`` schedule: ring reduce-scatter + ring
    all-gather through the ICI kernels of
    :mod:`kungfu_tpu.ops.pallas.collectives` (compiled on TPU, the
    bitwise-identical lax emulation elsewhere).  Sum-only like the
    kernels; min/max fall back to the lax ring schedule."""
    if op in ("min", "max"):
        return _ring_all_reduce_leaf(a, axis_name, op)
    from kungfu_tpu.ops.pallas.collectives import ring_all_reduce

    return ring_all_reduce(a, axis_name)


_PSUM_FOLD = {"sum": lax.psum, "min": lax.pmin, "max": lax.pmax}


# -- bucketed reduce-scatter / all-gather (ZeRO weight-update sharding) ----
#
# The gradient-bucket fusion above (one flat buffer, one collective) folded
# into reduce-scatter-sized pieces: the flat [n*chunk] buffer is viewed as
# [n, chunk] in mesh-major device order and bucketed along the CHUNK
# dimension, so every bucket's scatter lands each device a contiguous slice
# of its own chunk and the concatenation over buckets reproduces the
# exact contiguous per-device chunk layout of the un-bucketed scatter.
# That invariant is what keeps the ZeRO optimizer-state geometry (and its
# elastic re-shard/snapshot machinery) identical whether the step ran one
# collective or B of them.  B explicit collectives in the program also give
# XLA independent program points to overlap with neighboring compute — the
# same reason `two_stage` exists (docstring above).

def _dep_fence(pair):
    """Value-identity scheduling fence: ``(a, b) -> (a, b)`` bitwise
    unchanged, but the compiler may not start computing the outputs
    before BOTH inputs exist.  This is how the bucket loops express a
    depth-k window *inside the traced program*: fencing bucket i's
    operand on bucket i-k's result bounds how many bucket collectives
    XLA can hold in flight (and therefore how much gathered live range
    it can accumulate) without changing a single output bit.

    ``lax.optimization_barrier`` has no differentiation rule on current
    jax, so the fence is a custom_vjp identity whose backward applies
    the same barrier to the cotangents — the ZeRO-3 gradient path (an
    all-gather whose transpose IS the reduce-scatter) gets the same
    window on the backward collectives for free.  Falls back to a plain
    identity where the primitive is unavailable (older jax): the values
    are identical either way, only the scheduling hint is lost."""
    bar = getattr(lax, "optimization_barrier", None)
    if bar is None:
        return pair
    return _dep_fence_vjp(pair)


@jax.custom_vjp
def _dep_fence_vjp(pair):
    return lax.optimization_barrier(pair)


def _dep_fence_fwd(pair):
    return lax.optimization_barrier(pair), None


def _dep_fence_bwd(_, ct):
    return (lax.optimization_barrier(ct),)


if hasattr(lax, "optimization_barrier"):
    _dep_fence_vjp.defvjp(_dep_fence_fwd, _dep_fence_bwd)


def bucket_widths(chunk: int, n: int, itemsize: int,
                  bucket_bytes: int) -> List[int]:
    """Per-bucket column widths partitioning ``chunk`` so each bucket's
    collective operand ([n, width] flattened) is ~``bucket_bytes``.
    Always at least one bucket; the last takes the remainder."""
    if chunk <= 0:
        return [chunk] if chunk else []
    per_bucket = max(1, bucket_bytes // max(1, n * itemsize))
    widths = []
    off = 0
    while off < chunk:
        w = min(per_bucket, chunk - off)
        widths.append(w)
        off += w
    return widths


def _check_flat_schedule(schedule: str) -> None:
    if schedule not in FLAT_SCHEDULES:
        raise ValueError(
            f"unknown flat schedule {schedule!r}; one of {FLAT_SCHEDULES}")


def reduce_scatter_flat(g, axes: Sequence[str], chunk: int,
                        widths: Optional[Sequence[int]] = None,
                        serial: bool = False, schedule: str = "lax"):
    """Bucketed reduce-scatter of a flat mesh-major buffer.

    ``g``: per-device ``[n*chunk]`` (the full fused gradient, VMA-varying
    inside shard_map); returns this device's reduced ``[chunk]`` slice,
    where the device's flat index is mesh-major over ``axes`` (outer axis
    first — the same order :mod:`kungfu_tpu.parallel.zero` scatters in).
    ``axes`` must already be filtered to the non-trivial mesh axes; empty
    ``axes`` means a 1-device world and the buffer IS the chunk.

    The default (pipelined) form leaves every bucket's collective
    data-independent, so XLA may overlap them with each other and with
    neighboring compute.  ``serial=True`` is the reference shape — each
    bucket's operand is fenced on the previous bucket's result, forcing
    one collective in flight at a time.  The two forms are **bitwise
    identical** for every bucket count, including the 1-bucket and
    padded-tail degenerate cases (pinned in ``tests/test_schedules.py``):
    the fence is a value identity, and each bucket's reduction order is
    fixed by its own collective either way.  ``serial`` exists as the
    regression control the overlap bench diffs against — never as a
    production path.

    ``schedule="pallas_ring"`` scatters each bucket over the OUTER mesh
    axis through the in-kernel-overlap ring kernel
    (:func:`kungfu_tpu.ops.pallas.collectives.ring_reduce_scatter`;
    inner axes keep the lax primitive) — same mesh-major bucket
    geometry, so the ZeRO shard layout is byte-identical; the reduction
    ORDER is the ring's (docs/pallas_collectives.md), so cross-schedule
    comparisons are allclose, not bitwise."""
    _check_flat_schedule(schedule)
    if not axes:
        return g[:chunk]
    n = 1
    for ax in axes:
        n *= axis_size(ax)
    widths = list(widths) if widths else [chunk]
    g2 = g.reshape(n, chunk)
    if schedule == "pallas_ring":
        from kungfu_tpu.ops.pallas.collectives import ring_reduce_scatter
    parts = []
    off = 0
    for w in widths:
        slab = g2[:, off:off + w].reshape(-1)
        if serial and parts:
            slab, _ = _dep_fence((slab, parts[-1]))
        for i, ax in enumerate(axes):
            if schedule == "pallas_ring" and i == 0:
                slab = ring_reduce_scatter(slab, ax)
            else:
                slab = lax.psum_scatter(
                    slab, ax, scatter_dimension=0, tiled=True)
        parts.append(slab)
        off += w
    out = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return out


def all_gather_flat(shard, axes: Sequence[str],
                    widths: Optional[Sequence[int]] = None,
                    prefetch: bool = False, schedule: str = "lax"):
    """Bucketed all-gather: inverse layout of :func:`reduce_scatter_flat`.

    ``shard``: this device's ``[chunk]`` slice; returns the mesh-major
    ``[n*chunk]`` full buffer on every device.  Differentiable — the
    transpose of each bucket's tiled all-gather is the matching tiled
    psum-scatter, so ``grad(loss(all_gather_flat(p)))`` arrives already
    reduce-scattered (the ZeRO-3 gradient path costs no extra collective).

    ``prefetch=True`` double-buffers the bucket gathers: bucket i's
    operand is fenced on bucket i-2's gathered result, so at most two
    gathers are in flight — the next bucket prefetches while the current
    one retires, but XLA cannot widen the window to all B buckets and
    hold B gathered slabs (n× their shard size each) live at once.  The
    fence is a value identity (bitwise-pinned against ``prefetch=False``)
    and its custom backward applies the same window to the transposed
    reduce-scatters, so the ZeRO-3 gradient path is double-buffered in
    both directions.

    ``schedule="pallas_ring"`` gathers each bucket over the OUTER mesh
    axis through the in-kernel-overlap ring kernel
    (:func:`kungfu_tpu.ops.pallas.collectives.ring_all_gather`; inner
    axes keep the lax primitive).  Gathering is pure data movement, so
    the result is bitwise-identical to the lax schedule; the kernel's
    custom vjp IS the ring reduce-scatter, so the ZeRO-3 gradient path
    keeps its transpose shape."""
    _check_flat_schedule(schedule)
    if not axes:
        return shard
    n = 1
    for ax in axes:
        n *= axis_size(ax)
    chunk = shard.shape[0]
    widths = list(widths) if widths else [chunk]
    if schedule == "pallas_ring":
        from kungfu_tpu.ops.pallas.collectives import ring_all_gather
    slabs = []
    off = 0
    for w in widths:
        piece = shard[off:off + w]
        if prefetch and len(slabs) >= 2:
            piece, _ = _dep_fence((piece, slabs[-2]))
        rev = tuple(reversed(axes))
        for i, ax in enumerate(rev):
            if schedule == "pallas_ring" and i == len(rev) - 1:
                piece = ring_all_gather(piece, ax)
            else:
                piece = lax.all_gather(piece, ax, axis=0, tiled=True)
        slabs.append(piece.reshape(n, w))
        off += w
    full = slabs[0] if len(slabs) == 1 else jnp.concatenate(slabs, axis=1)
    return full.reshape(-1)


#: jaxpr primitives that move bytes between devices, with the per-rank
#: ring-convention wire cost as a multiple of the per-device operand size
#: (s = operand bytes, k = axis size): all-reduce moves 2(k-1)/k*s, a
#: scatter/gather half of that, a permute exactly s.
_COLLECTIVE_COST = {
    "psum": lambda s, k: 2.0 * (k - 1) / k * s,
    "pmin": lambda s, k: 2.0 * (k - 1) / k * s,
    "pmax": lambda s, k: 2.0 * (k - 1) / k * s,
    "reduce_scatter": lambda s, k: (k - 1) / k * s,
    "all_gather": lambda s, k: (k - 1) * s,  # s = the shard being gathered
    "ppermute": lambda s, k: float(s),
    "all_to_all": lambda s, k: (k - 1) / k * s,
}


def traced_collective_bytes(fn, *args, axis_sizes: Dict[str, int]):
    """Per-rank wire bytes per call of ``fn``, measured from its traced
    jaxpr: every cross-device collective primitive actually present in
    the program is costed with the standard ring convention (table
    above).  This is a measurement of the *program XLA compiles* — not an
    estimate from a formula about what the program ought to do — so a
    step that silently all-reduces where it claims to reduce-scatter
    shows up as 2x in the bench row.  ``axis_sizes`` maps mesh axis names
    to sizes (``dict(zip(mesh.axis_names, mesh.devices.shape))``) — the
    walk runs outside any trace, where ``lax.axis_size`` is unavailable.
    Partitioner-inserted transfers (the all-gather a replicated
    ``with_sharding_constraint`` compiles to) happen after tracing and
    are NOT counted; account those analytically
    (:func:`kungfu_tpu.parallel.zero.zero_comm_bytes`).

    Returns ``{primitive_name: bytes}`` (floats, summed over every call
    site reached; scan/fori bodies count once per trace occurrence, not
    per trip)."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    out: Dict[str, float] = {}

    def axis_total(axis_name) -> int:
        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        k = 1
        for ax in axes:
            k *= int(axis_sizes.get(ax, 1))
        return max(k, 1)

    def walk(jp):
        for eqn in jp.eqns:
            prim = eqn.primitive.name
            cost = _COLLECTIVE_COST.get(prim)
            if cost is not None:
                k = axis_total(eqn.params.get("axes")
                               or eqn.params.get("axis_name") or ())
                if k > 1:
                    s = sum(
                        int(np.prod(v.aval.shape)) * v.aval.dtype.itemsize
                        for v in eqn.invars if hasattr(v, "aval")
                        and hasattr(v.aval, "shape")
                    )
                    out[prim] = out.get(prim, 0.0) + cost(s, k)
            for sub in eqn.params.values():
                if hasattr(sub, "eqns"):
                    walk(sub)
                elif hasattr(sub, "jaxpr") and hasattr(sub.jaxpr, "eqns"):
                    walk(sub.jaxpr)
                elif isinstance(sub, (list, tuple)):
                    for s2 in sub:
                        if hasattr(s2, "eqns"):
                            walk(s2)
                        elif hasattr(s2, "jaxpr") and hasattr(s2.jaxpr, "eqns"):
                            walk(s2.jaxpr)

    walk(jaxpr.jaxpr)
    return out


def all_reduce_scheduled(x, axis: Axis, op: str = "sum",
                         schedule: str = "psum"):
    """Allreduce a tensor/pytree across ``axis`` with an explicit
    schedule.  ``schedule='psum'`` is :func:`kungfu_tpu.ops.all_reduce`;
    the others decompose the collective in-program (docstring above).
    Jit/shard_map-composable; every schedule returns the same values.

    ``axis`` may be a tuple of mesh axis names in outer-to-inner order
    (e.g. a hierarchical communicator's ``(host, local)``): the schedule
    applies to the FIRST non-trivial axis — the cross-host stage — after
    the inner axes reduce with one-hop psum over ICI, the reference's
    local/cross split (``session/strategy.go:176-210``).
    """
    if op not in _OPS:
        raise ValueError(f"unsupported op {op!r}")
    if schedule not in ALLREDUCE_SCHEDULES:
        raise ValueError(
            f"unknown schedule {schedule!r}; one of {ALLREDUCE_SCHEDULES}"
        )
    if schedule == "psum":
        from kungfu_tpu.ops.collective import all_reduce

        return all_reduce(x, axis, op=op)
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    sched_leaf = {
        "ring": _ring_all_reduce_leaf,
        "two_stage": _two_stage_all_reduce_leaf,
        "pallas_ring": _pallas_ring_all_reduce_leaf,
    }[schedule]
    base = "sum" if op == "mean" else op

    def leaf(a):
        sizes = [axis_size(ax) for ax in axes]
        real = [ax for ax, s in zip(axes, sizes) if s > 1] or [axes[0]]
        for ax in real[1:]:  # inner (intra-host) stages: one-hop psum
            a = _PSUM_FOLD[base](a, ax)
        a = sched_leaf(a, axis_name=real[0], op=base)
        if op == "mean":
            a = a / math.prod(sizes)
        return a

    return jax.tree_util.tree_map(leaf, x)
