"""Pallas TPU kernels for the hot ops.

The compute data plane is mostly plain XLA (which fuses elementwise work
into the MXU matmuls on its own); these kernels cover the places where
hand-tiling beats the compiler — attention above all, where the fused
online-softmax loop avoids materializing the [S, S] score matrix in HBM.

Kernels run compiled on TPU and in interpreter mode on CPU (tests), so
the CPU multi-process test cluster exercises the same code path.
"""

from kungfu_tpu.ops.pallas.attention import (
    flash_attention,
    flash_attention_with_lse,
    make_flash_attn,
)
from kungfu_tpu.ops.pallas.collectives import (
    ring_all_gather,
    ring_all_reduce,
    ring_reduce_scatter,
)
from kungfu_tpu.ops.pallas.lm_head import lm_head_nll
from kungfu_tpu.ops.pallas.xent import softmax_cross_entropy, token_nll

__all__ = [
    "flash_attention",
    "flash_attention_with_lse",
    "make_flash_attn",
    "lm_head_nll",
    "ring_all_gather",
    "ring_all_reduce",
    "ring_reduce_scatter",
    "softmax_cross_entropy",
    "token_nll",
]
