"""Varying-manual-axes (vma) helpers for jax>=0.9 shard_map typing.

Under ``shard_map`` every value carries the set of mesh axes it varies
over; pallas ``out_shape`` structs must declare it, and scan carries /
switch branches must type-match their varying counterparts.  One shared
implementation so the workaround changes in one place when jax's typing
evolves.
"""

from __future__ import annotations

import inspect

import jax

from kungfu_tpu.utils.jaxcompat import pcast_varying, typeof

#: whether this jax's ShapeDtypeStruct takes the ``vma`` kwarg (0.4.x
#: predates vma typing entirely)
_SDS_HAS_VMA = "vma" in inspect.signature(jax.ShapeDtypeStruct.__init__).parameters


def sds(shape, dtype, vma=frozenset()):
    """``jax.ShapeDtypeStruct`` declaring varying manual axes where the
    running jax supports them; the plain struct otherwise (pre-vma jax
    has no varying types for the out_shape to disagree with)."""
    if _SDS_HAS_VMA and vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def vma_of(*operands) -> frozenset:
    """Union of the operands' varying manual axes (empty outside
    ``shard_map``, and always empty on pre-vma jax)."""
    vs = set()
    for o in operands:
        vs |= set(getattr(typeof(o), "vma", ()) or ())
    return frozenset(vs)


def match_vma(t, vma: frozenset):
    """Mark ``t`` varying over any axes in ``vma`` it doesn't carry yet
    (no-op for axes already varying — pcast rejects varying→varying)."""
    cur = set(getattr(typeof(t), "vma", ()) or ())
    missing = tuple(a for a in vma if a not in cur)
    return pcast_varying(t, missing)
