"""Varying-manual-axes (vma) helpers for jax>=0.9 shard_map typing.

Under ``shard_map`` every value carries the set of mesh axes it varies
over; pallas ``out_shape`` structs must declare it, and scan carries /
switch branches must type-match their varying counterparts.  One shared
implementation so the workaround changes in one place when jax's typing
evolves.
"""

from __future__ import annotations

import jax


def vma_of(*operands) -> frozenset:
    """Union of the operands' varying manual axes (empty outside
    ``shard_map``)."""
    vs = set()
    for o in operands:
        vs |= set(getattr(jax.typeof(o), "vma", ()) or ())
    return frozenset(vs)


def match_vma(t, vma: frozenset):
    """Mark ``t`` varying over any axes in ``vma`` it doesn't carry yet
    (no-op for axes already varying — pcast rejects varying→varying)."""
    cur = set(getattr(jax.typeof(t), "vma", ()) or ())
    missing = tuple(a for a in vma if a not in cur)
    return jax.lax.pcast(t, missing, to="varying") if missing else t
