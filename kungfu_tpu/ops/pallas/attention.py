"""Flash attention as a Pallas TPU kernel.

Forward: a (batch*head, q-block, kv-block) grid; each step consumes ONE
[block_k, D] K/V tile, so VMEM residency is O(block) regardless of
sequence length (round-1 advisor finding: whole-sequence K/V BlockSpecs
spilled VMEM at long S, defeating the kernel's purpose).  The
online-softmax state (m/l running max/sum and the f32 output
accumulator) lives in VMEM scratch carried across the innermost grid
dimension; scores live one [block_q, block_k] tile at a time, feeding
the MXU via ``jnp.dot(..., preferred_element_type=f32)``.  Causal
masking skips all-masked kv blocks twice over: ``pl.when`` skips their
compute, and the K/V index maps clamp to the last needed block so
Pallas's revisit-elision skips their HBM→VMEM copies too — causal
attention does ~half the FLOPs *and* ~half the K/V traffic.

Backward (round 3): two Pallas kernels using the saved logsumexp rows —
the standard flash-attention recomputation

    P  = exp(Q K^T * scale - L)        (recomputed per tile)
    dV = P^T dO
    dP = dO V^T
    dS = P * (dP - rowsum(dO * O))
    dQ = dS K * scale ;  dK = dS^T Q * scale

split the way TPU memory wants it: a **dQ kernel** on a (bh, q-block,
kv-block) grid accumulating dQ in VMEM scratch while K/V tiles stream,
and a **dK/dV kernel** on a (bh, kv-block, q-block) grid accumulating
dK/dV while Q/dO/L/delta tiles stream — both O(block) VMEM, both with
the same causal skip + index-clamp revisit-elision as the forward (a
causal backward does ~half the FLOPs and ~half the tile traffic).  The
blocked-jnp backward is kept as the non-TPU fallback and as the
reference implementation the kernel tests compare against.  The whole
op is a ``custom_vjp`` — autodiff through the Pallas forward would
instead save every tile.

The reference framework has no attention at all (SURVEY §2.4/§5.7 — it
moves gradient buffers only); this kernel is part of the TPU build's
long-context subsystem together with :mod:`kungfu_tpu.parallel.ring`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: caps for the adaptive default block shape (see :func:`_default_blocks`).
#: A round-3 interleaved min-of-8 sweep on v5e (benchmarks/flash_sweep.py,
#: B4 H8 S2048 D128 causal) is monotonic in block_k: (128,128) 2.60 ms →
#: (256,1024) 0.34 ms fwd (7.7x, 101 TFLOP/s).  Large K/V tiles amortize
#: the per-grid-step overhead and keep the MXU fed; 16 MB VMEM fits
#: (256,1024) at D=128 with ~2.7 MB to spare.
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30
#: per-row scalars (lse, delta) cross the pallas_call boundary replicated
#: across one full lane width — Mosaic's tiling only accepts (8k, 128)
#: tiles, so a bare row vector is not a legal block shape on TPU
_LANES = 128


from kungfu_tpu.ops.pallas._sharding import match_vma as _match_vma
from kungfu_tpu.ops.pallas._sharding import vma_of as _vma
from kungfu_tpu.ops.pallas._sharding import sds as _sds
from kungfu_tpu.utils.jaxcompat import tpu_compiler_params


def _causal_hi(qi, block_q, block_k):
    """Index of the LAST kv block a causal q-block ``qi`` attends to."""
    return jax.lax.div((qi + 1) * block_q + block_k - 1, block_k) - 1


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, m_s, l_s, acc_s, *,
                scale, causal, seq_len, block_q, block_k):
    """One (batch*head, q-block, kv-block) grid step; m/l/acc scratch
    carries online-softmax state across the kv dimension."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)

    if causal:
        j_hi = jnp.minimum(_causal_hi(qi, block_q, block_k), n_k - 1)
    else:
        j_hi = n_k - 1

    @pl.when(kj == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    @pl.when(kj <= j_hi)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
        kb = k_ref[0]  # [block_k, D]
        vb = v_ref[0]
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_len  # tail padding
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, _NEG_INF)
        m = m_s[:]  # [block_q, 1] (keepdims — Mosaic wants 2D)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        # fully-masked rows (can only happen on padded tails) contribute 0
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_s[:] = l_s[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * corr + jnp.dot(
            p.astype(v_ref.dtype), vb, preferred_element_type=jnp.float32
        )
        m_s[:] = m_new

    @pl.when(kj == j_hi)
    def _():
        l_safe = jnp.maximum(l_s[:], 1e-30)
        o_ref[0] = (acc_s[:] / l_safe).astype(o_ref.dtype)
        # logsumexp rows, saved for the backward recomputation.  Stored
        # lane-replicated [block_q, LANES]: Mosaic requires output tiles
        # whose last two dims are (8k, 128) — a [block_q] row vector is
        # not a legal tile, a lane-broadcast one is
        l_ref[0] = jnp.broadcast_to(
            m_s[:] + jnp.log(l_safe), (l_ref.shape[1], l_ref.shape[2])
        )


def _fwd_call(q, k, v, causal, block_q, block_k, interpret):
    """q,k,v: [BH, S, D] → (out [BH, S, D], lse [BH, S])."""
    bh, s, d = q.shape
    s_pad = ((s + block_q - 1) // block_q) * block_q
    s_pad = ((s_pad + block_k - 1) // block_k) * block_k
    if s_pad != s:
        pad = [(0, 0), (0, s_pad - s), (0, 0)]
        q, k, v = (jnp.pad(t, pad) for t in (q, k, v))
    n_k = s_pad // block_k
    grid = (bh, s_pad // block_q, n_k)
    kernel = functools.partial(
        _fwd_kernel,
        scale=1.0 / (d ** 0.5),
        causal=causal,
        seq_len=s,
        block_q=block_q,
        block_k=block_k,
    )

    if causal:
        # clamp the kv index for all-masked steps: the block index then
        # repeats, so Pallas elides the HBM→VMEM copy for skipped blocks
        def kv_index(b, i, j):
            return (b, jnp.minimum(j, _causal_hi(i, block_q, block_k)), 0)
    else:
        def kv_index(b, i, j):
            return (b, j, 0)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _sds((bh, s_pad, d), q.dtype, vma=_vma(q, k, v)),
            _sds((bh, s_pad, _LANES), jnp.float32, vma=_vma(q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),   # running sum l
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out[:, :s], lse[:, :s, 0]


def _bwd_blocked(q, k, v, out, lse, dout, causal, block_k, delta=None):
    """Blocked flash backward in jnp; [BH, S, D] operands.  ``delta``
    defaults to rowsum(dO·O); callers with an lse cotangent pass the
    shifted value (see ``_flash_pair_bwd``)."""
    bh, s, d = q.shape
    scale = 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)
    of = out.astype(jnp.float32)
    dof = dout.astype(jnp.float32)
    if delta is None:
        delta = jnp.sum(dof * of, axis=-1)  # [BH, S]

    s_pad = ((s + block_k - 1) // block_k) * block_k
    if s_pad != s:
        pad3 = [(0, 0), (0, s_pad - s), (0, 0)]
        k = jnp.pad(k, pad3)
        v = jnp.pad(v, pad3)
    n_blk = s_pad // block_k
    kf = k.astype(jnp.float32).reshape(bh, n_blk, block_k, d)
    vf = v.astype(jnp.float32).reshape(bh, n_blk, block_k, d)

    q_pos = jnp.arange(s)

    def fold(dq, blk):
        j, kb, vb = blk  # kb/vb: [BH, block_k, D]
        s_blk = jnp.einsum("bqd,bkd->bqk", qf, kb) * scale
        k_pos = j * block_k + jnp.arange(block_k)
        mask = k_pos[None, :] < s
        if causal:
            mask = jnp.logical_and(mask, q_pos[:, None] >= k_pos[None, :])
        p = jnp.where(mask, jnp.exp(s_blk - lse[..., None]), 0.0)  # [BH,S,bk]
        dp = jnp.einsum("bqd,bkd->bqk", dof, vb)
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, kb) * scale
        dk_b = jnp.einsum("bqk,bqd->bkd", ds, qf) * scale
        dv_b = jnp.einsum("bqk,bqd->bkd", p, dof)
        return dq, (dk_b, dv_b)

    dq0 = _match_vma(jnp.zeros((bh, s, d), jnp.float32), _vma(q, k, v, dout))
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        fold, dq0, (jnp.arange(n_blk), kf.transpose(1, 0, 2, 3), vf.transpose(1, 0, 2, 3))
    )
    dk = dk_blocks.transpose(1, 0, 2, 3).reshape(bh, s_pad, d)[:, :s]
    dv = dv_blocks.transpose(1, 0, 2, 3).reshape(bh, s_pad, d)[:, :s]
    return dq.astype(q.dtype), dk.astype(q.dtype), dv.astype(q.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_s, *, scale, causal, seq_len, block_q, block_k):
    """dQ on a (bh, q-block, kv-block) grid; K/V stream along the inner
    dim, dQ accumulates in VMEM scratch (mirror of the forward)."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    if causal:
        j_hi = jnp.minimum(_causal_hi(qi, block_q, block_k), n_k - 1)
    else:
        j_hi = n_k - 1

    @pl.when(kj == 0)
    def _():
        acc_s[:] = jnp.zeros_like(acc_s)

    @pl.when(kj <= j_hi)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale      # [bq, D]
        kb = k_ref[0]                                  # [bk, D]
        vb = v_ref[0]
        do = do_ref[0].astype(jnp.float32)             # [bq, D]
        lse = lse_ref[0][:, :1]                        # [bq, 1] (lane 0)
        delta = delta_ref[0][:, :1]                    # [bq, 1]
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_len
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)     # [bq, bk]
        dp = jnp.dot(do.astype(vb.dtype), vb.T,
                     preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_s[:] = acc_s[:] + jnp.dot(
            ds.astype(kb.dtype), kb, preferred_element_type=jnp.float32
        ) * scale

    @pl.when(kj == j_hi)
    def _():
        dq_ref[0] = acc_s[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_s, dv_s, *, scale, causal, seq_len,
                    block_q, block_k):
    """dK/dV on a (bh, kv-block, q-block) grid; Q/dO/L/delta stream along
    the inner dim, dK/dV accumulate in VMEM scratch."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)
    if causal:
        # first q block that attends to kv block kj
        i_lo = jax.lax.div(kj * block_k, block_q)
    else:
        i_lo = 0

    @pl.when(qi == 0)
    def _():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    @pl.when(qi >= i_lo)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale       # [bq, D]
        kb = k_ref[0]                                   # [bk, D]
        vb = v_ref[0]
        do = do_ref[0].astype(jnp.float32)              # [bq, D]
        lse = lse_ref[0][:, :1]                         # [bq, 1] (lane 0)
        delta = delta_ref[0][:, :1]
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_len
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)      # [bq, bk]
        dv_s[:] = dv_s[:] + jnp.dot(
            p.astype(do_ref.dtype).T, do.astype(do_ref.dtype),
            preferred_element_type=jnp.float32,
        )
        dp = jnp.dot(do.astype(vb.dtype), vb.T,
                     preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # q already carries `scale`, so dS^T (q*scale) == dK
        dk_s[:] = dk_s[:] + jnp.dot(
            ds.astype(q_ref.dtype).T, q.astype(q_ref.dtype),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_q - 1)
    def _():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, out, lse, dout, causal, block_q, block_k, interpret,
                delta=None):
    """Pallas backward: dq via a kv-streaming kernel, dk/dv via a
    q-streaming kernel; [BH, S, D] operands.  ``delta`` as in
    :func:`_bwd_blocked`."""
    bh, s, d = q.shape
    scale = 1.0 / (d ** 0.5)
    if delta is None:
        delta = jnp.sum(
            dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
        )  # [BH, S]

    s_pad = ((s + block_q - 1) // block_q) * block_q
    s_pad = ((s_pad + block_k - 1) // block_k) * block_k
    if s_pad != s:
        pad3 = [(0, 0), (0, s_pad - s), (0, 0)]
        q, k, v, dout = (jnp.pad(t, pad3) for t in (q, k, v, dout))
        # padded q rows: lse=+inf makes their P rows exp(s - inf) = 0
        lse = jnp.pad(lse, [(0, 0), (0, s_pad - s)], constant_values=1e30)
        delta = jnp.pad(delta, [(0, 0), (0, s_pad - s)])
    n_q = s_pad // block_q
    n_k = s_pad // block_k

    # per-row scalars enter the kernels lane-replicated (see _LANES)
    lse = jnp.broadcast_to(lse[..., None], (bh, s_pad, _LANES))
    delta = jnp.broadcast_to(delta[..., None], (bh, s_pad, _LANES))

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))
    if causal:
        def kv_index(b, i, j):
            return (b, jnp.minimum(j, _causal_hi(i, block_q, block_k)), 0)
    else:
        def kv_index(b, i, j):
            return (b, j, 0)
    kv_spec = pl.BlockSpec((1, block_k, d), kv_index)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, seq_len=s,
            block_q=block_q, block_k=block_k,
        ),
        grid=(bh, n_q, n_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[_sds((bh, s_pad, d), q.dtype,
                                        vma=_vma(q, k, v, dout))],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, dout, lse, delta)[0]

    # dk/dv grid: (bh, kv-block, q-block); clamp the q index upward for
    # causal so all-masked q blocks repeat their predecessor's tile and
    # Pallas elides the copies
    if causal:
        def q_index(b, j, i):
            return (b, jnp.maximum(i, jax.lax.div(j * block_k, block_q)), 0)
    else:
        def q_index(b, j, i):
            return (b, i, 0)
    qrow_index = q_index

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, seq_len=s,
            block_q=block_q, block_k=block_k,
        ),
        grid=(bh, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_q, _LANES), qrow_index),
            pl.BlockSpec((1, block_q, _LANES), qrow_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _sds((bh, s_pad, d), q.dtype, vma=_vma(q, k, v, dout)),
            _sds((bh, s_pad, d), q.dtype, vma=_vma(q, k, v, dout)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, dout, lse, delta)
    return dq[:, :s], dk[:, :s], dv[:, :s]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _fwd_call(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fwd_call(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, dout):
    q, k, v, out, lse = res
    import os

    # compiled path (TPU): the Pallas backward kernels.  Interpret mode
    # (CPU test clusters) defaults to the blocked-jnp reference backward
    # — much faster than interpreting the kernels — unless KF_PALLAS_BWD
    # =pallas forces them (how the kernel numerics tests run off-TPU).
    if interpret and os.environ.get("KF_PALLAS_BWD", "") != "pallas":
        return _bwd_blocked(q, k, v, out, lse, dout, causal, block_k)
    return _bwd_pallas(
        q, k, v, out, lse, dout, causal, block_q, block_k, interpret
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_pair(q, k, v, causal, block_q, block_k, interpret):
    """Like :func:`_flash` but returns ``(out, lse)`` — the pair a
    cross-block online-softmax merge needs (ring attention folds each
    rotating K/V block via its lse)."""
    return _fwd_call(q, k, v, causal, block_q, block_k, interpret)


def _flash_pair_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fwd_call(q, k, v, causal, block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _flash_pair_bwd(causal, block_q, block_k, interpret, res, cts):
    """The lse cotangent needs no extra kernel: ∂lse_i/∂s_ij = p_ij, so
    its contribution to dS is ``p * dlse`` — and the backward kernels
    compute ``dS = p * (dp - delta)``, so shifting ``delta -= dlse``
    carries it through both the Pallas and the blocked-jnp paths."""
    q, k, v, out, lse = res
    dout, dlse = cts
    import os

    if interpret and os.environ.get("KF_PALLAS_BWD", "") != "pallas":
        bwd = _bwd_blocked_delta
    else:
        bwd = functools.partial(_bwd_pallas_delta, block_q=block_q,
                                interpret=interpret)
    delta = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ) - dlse.astype(jnp.float32)
    return bwd(q, k, v, out, lse, dout, delta, causal, block_k)


def _bwd_blocked_delta(q, k, v, out, lse, dout, delta, causal, block_k):
    return _bwd_blocked(q, k, v, out, lse, dout, causal, block_k, delta=delta)


def _bwd_pallas_delta(q, k, v, out, lse, dout, delta, causal, block_k, *,
                      block_q, interpret):
    return _bwd_pallas(q, k, v, out, lse, dout, causal, block_q, block_k,
                       interpret, delta=delta)


_flash_pair.defvjp(_flash_pair_fwd, _flash_pair_bwd)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _default_blocks(s: int, block_q, block_k):
    """Resolve ``None`` block sizes: the largest power-of-two tile up to
    the capped default whose sequence padding stays proportionate — a big
    tile only pays off when it isn't mostly padding (S=1152 with a 1024
    block would pad to 2048 and nearly double the tile traffic; it gets
    256 → pad 1280).  Power-of-two choices keep block_q | block_k (or
    vice versa), so the pad length is just max(block_q, block_k)-aligned.
    """
    n = ((max(s, 1) + 127) // 128) * 128
    # tolerate up to ~25% padded rows (and never a whole extra 128-tile
    # on short sequences — the 127 keeps n=128 at a 128 block)
    allowance = max(n // 4, 127)

    def pick(cap):
        for opt in (1024, 512, 256, 128):
            if opt <= cap and ((n + opt - 1) // opt) * opt - n <= allowance:
                return opt
        return 128

    if block_q is None:
        block_q = pick(DEFAULT_BLOCK_Q)
    if block_k is None:
        block_k = pick(DEFAULT_BLOCK_K)
    return block_q, block_k


def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Fused attention for [B, H, S, D] (or [BH, S, D]) operands.

    Differentiable; numerically matches
    :func:`kungfu_tpu.models.transformer.default_attention` (softmax in
    f32).  ``interpret=None`` auto-selects interpreter mode off-TPU so
    the same call works on the CPU test cluster.  ``block_q``/``block_k``
    default to the swept TPU tiles (:func:`_default_blocks`).
    """
    if interpret is None:
        interpret = _use_interpret()
    block_q, block_k = _default_blocks(q.shape[-2], block_q, block_k)
    if q.ndim == 3:
        return _flash(q, k, v, causal, block_q, block_k, interpret)
    if q.ndim != 4:
        raise ValueError(f"expected [B,H,S,D] or [BH,S,D], got {q.shape}")
    b, h, s, d = q.shape
    out = _flash(
        q.reshape(b * h, s, d),
        k.reshape(b * h, s, d),
        v.reshape(b * h, s, d),
        causal, block_q, block_k, interpret,
    )
    return out.reshape(b, h, s, d)


def flash_attention_with_lse(
    q,
    k,
    v,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Fused attention returning ``(out, lse)`` for [BH, S, D] operands.

    Differentiable in both outputs (the lse cotangent folds into the
    backward's delta shift).  The lse rows let a caller merge multiple
    attention calls over disjoint K/V blocks with the standard
    online-softmax combine — :mod:`kungfu_tpu.parallel.ring` uses this
    as its per-round block primitive."""
    if interpret is None:
        interpret = _use_interpret()
    if q.ndim != 3:
        raise ValueError(f"expected [BH, S, D], got {q.shape}")
    block_q, block_k = _default_blocks(q.shape[-2], block_q, block_k)
    return _flash_pair(q, k, v, causal, block_q, block_k, interpret)


def make_flash_attn(block_q: Optional[int] = None, block_k: Optional[int] = None):
    """Adapter for the ``attn_fn(q, k, v, causal)`` slot of
    :meth:`kungfu_tpu.models.transformer.Transformer.apply`."""

    def attn(q, k, v, causal):
        return flash_attention(q, k, v, causal=causal, block_q=block_q, block_k=block_k)

    return attn
