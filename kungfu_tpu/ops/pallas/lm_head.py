"""Fused LM-head + softmax-cross-entropy — logits never touch HBM.

The round-4 analysis (docs/perf.md "disposition of the 0.49× row")
identified the only honest way to beat XLA's fused xent backward: fuse
the *consumers* of dlogits — the LM-head matmuls dW = hᵀ·dlogits and
dh = dlogits·Wᵀ — so the [N, V] dlogits (and the [N, V] logits) never
materialize.  This module is that kernel pair, flash-attention-shaped:

* **forward** — grid (row blocks, vocab blocks): the logits tile is
  computed ON THE MXU (h_blk @ W_blk) into VMEM, fed straight to the
  online-softmax accumulators (max / sumexp / target-logit scratch, as
  in :mod:`kungfu_tpu.ops.pallas.xent`), and discarded.  Residuals:
  ``(h, W, targets, lse)`` — O(N·D + D·V), not O(N·V).
* **backward** — two sweeps, each recomputing the logits tile from the
  residuals (the flash trade: FLOPs for HBM):
  - dh kernel, vocab innermost: ``dh += dlogits_tile @ Wᵀ`` accumulated
    in VMEM scratch across the vocab sweep;
  - dW kernel, rows innermost: ``dW += hᵀ @ dlogits_tile`` accumulated
    across the row sweep.
  ``dlogits_tile = (exp(logits_tile − lse) − onehot)·g`` lives only in
  VMEM.

Roofline (docs/perf.md carries the signed-off version): per logits
element the fusion saves ~12 HBM bytes (bf16 logits write+read, f32
log-probs write+read, bf16 dlogits write+read) and pays 2·D recompute
MACs — at v5e ratios (197 TFLOP/s : 819 GB/s ≈ 240 FLOP/byte) the
wall-clock crossover sits near D ≈ 740, so GPT-2-small dims are
break-even on time and the capacity win (no O(N·V) residual set) is
the real prize: batch sizes that OOM the XLA path outright run here.

Interpret mode on CPU for exactness tests; compiled on TPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kungfu_tpu.ops.pallas._sharding import vma_of as _vma
from kungfu_tpu.ops.pallas._sharding import sds as _sds
from kungfu_tpu.utils.jaxcompat import tpu_compiler_params

DEFAULT_BLOCK_N = 256
DEFAULT_BLOCK_V = 1024
_NEG_INF = -1e30
_LANES = 128


def _dot(a, b):
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _fwd_kernel(h_ref, w_ref, targets_ref, loss_ref, lse_ref,
                m_ref, l_ref, t_ref, *, vocab, block_v, masked):
    """Grid = (row blocks, vocab blocks), vocab innermost; the logits
    tile is an MXU product consumed in VMEM (cf. xent._fwd_kernel for
    the online-softmax scheme and the in-sweep target accumulation)."""
    j = pl.program_id(1)
    n_v = pl.num_programs(1)
    blk = _dot(h_ref[...], w_ref[...])  # [block_n, block_v] f32
    n = blk.shape[0]
    tgt = targets_ref[...][:, :1]

    @pl.when(j == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        t_ref[...] = jnp.zeros_like(t_ref)

    k_pos = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (n, block_v), 1)
    if masked:
        blk = jnp.where(k_pos < vocab, blk, _NEG_INF)
    m = m_ref[...]
    m_new = jnp.maximum(m, jnp.max(blk, axis=-1, keepdims=True))
    corr = jnp.exp(m - m_new)
    l_new = l_ref[...] * corr + jnp.sum(
        jnp.exp(blk - m_new), axis=-1, keepdims=True
    )
    is_tgt = k_pos == tgt
    t_new = t_ref[...] + jnp.sum(jnp.where(is_tgt, blk, 0.0), axis=-1,
                                 keepdims=True)
    m_ref[...] = m_new
    l_ref[...] = l_new
    t_ref[...] = t_new

    @pl.when(j == n_v - 1)
    def _():
        lse = m_new + jnp.log(jnp.maximum(l_new, 1e-30))
        lanes = loss_ref.shape
        loss_ref[...] = jnp.broadcast_to(lse - t_new, lanes)
        lse_ref[...] = jnp.broadcast_to(lse, lanes)


def _dlogits_tile(h_blk, w_blk, targets, lse, g, j, vocab, block_v, masked):
    """Recompute one logits tile and form its dlogits in VMEM."""
    blk = _dot(h_blk, w_blk)
    n = blk.shape[0]
    k_pos = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (n, block_v), 1)
    p = jnp.exp(blk - lse)
    if masked:
        p = jnp.where(k_pos < vocab, p, 0.0)
    onehot = (k_pos == targets).astype(jnp.float32)
    return (p - onehot) * g


def _bwd_dh_kernel(h_ref, w_ref, targets_ref, lse_ref, g_ref, dh_ref,
                   acc_ref, *, vocab, block_v, masked):
    """Grid = (row blocks, vocab blocks), vocab innermost: dh accumulates
    in VMEM across the vocab sweep, written once at the end."""
    j = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    dlog = _dlogits_tile(
        h_ref[...], w_ref[...], targets_ref[...][:, :1], lse_ref[...][:, :1],
        g_ref[...][:, :1], j, vocab, block_v, masked,
    )
    # [bn, bv] @ [bv, D] on the MXU
    acc_ref[...] += jax.lax.dot_general(
        dlog, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == n_v - 1)
    def _():
        dh_ref[...] = acc_ref[...].astype(dh_ref.dtype)


def _bwd_dw_kernel(h_ref, w_ref, targets_ref, lse_ref, g_ref, dw_ref,
                   acc_ref, *, vocab, block_v, masked):
    """Grid = (vocab blocks, row blocks), rows innermost: dW accumulates
    in VMEM across the row sweep."""
    j = pl.program_id(0)
    i = pl.program_id(1)
    n_n = pl.num_programs(1)

    @pl.when(i == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    h_blk = h_ref[...]
    dlog = _dlogits_tile(
        h_blk, w_ref[...], targets_ref[...][:, :1], lse_ref[...][:, :1],
        g_ref[...][:, :1], j, vocab, block_v, masked,
    )
    # [D, bn] @ [bn, bv] on the MXU
    acc_ref[...] += jax.lax.dot_general(
        h_blk, dlog, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == n_n - 1)
    def _():
        dw_ref[...] = acc_ref[...].astype(dw_ref.dtype)


def _pad_nd(h, w, targets, block_n, block_v):
    n, d = h.shape
    v = w.shape[1]
    n_pad = ((n + block_n - 1) // block_n) * block_n
    v_pad = ((v + block_v - 1) // block_v) * block_v
    d_pad = ((d + _LANES - 1) // _LANES) * _LANES
    if n_pad != n or d_pad != d:
        h = jnp.pad(h, [(0, n_pad - n), (0, d_pad - d)])
        targets = jnp.pad(targets, [(0, n_pad - n)])
    if v_pad != v or d_pad != d:
        w = jnp.pad(w, [(0, d_pad - d), (0, v_pad - v)])
    return h, w, targets, n_pad, v_pad, d_pad


def _fwd_call(h, w, targets, block_n, block_v, interpret):
    n, _ = h.shape
    v = w.shape[1]
    h, w, targets, n_pad, v_pad, d_pad = _pad_nd(h, w, targets,
                                                 block_n, block_v)
    row = pl.BlockSpec((block_n, _LANES), lambda i, j: (i, 0))
    kernel = functools.partial(_fwd_kernel, vocab=v, block_v=block_v,
                               masked=v_pad != v)
    loss, lse = pl.pallas_call(
        kernel,
        grid=(n_pad // block_n, v_pad // block_v),
        in_specs=[
            pl.BlockSpec((block_n, d_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((d_pad, block_v), lambda i, j: (0, j)),
            row,
        ],
        out_specs=[row, row],
        out_shape=[
            _sds((n_pad, _LANES), jnp.float32,
                                 vma=_vma(h, w, targets)),
            _sds((n_pad, _LANES), jnp.float32,
                                 vma=_vma(h, w, targets)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(h, w, jnp.broadcast_to(targets[:, None], (n_pad, _LANES)))
    return loss[:n, 0], lse[:n, 0]


def _bwd_call(h, w, targets, lse, g, block_n, block_v, interpret):
    n, d = h.shape
    v = w.shape[1]
    h, w, targets, n_pad, v_pad, d_pad = _pad_nd(h, w, targets,
                                                 block_n, block_v)
    if n_pad != n:
        # padded rows: lse=+inf zeroes their softmax, g=0 their gradient
        lse = jnp.pad(lse, [(0, n_pad - n)], constant_values=1e30)
        g = jnp.pad(g, [(0, n_pad - n)])
    row = pl.BlockSpec((block_n, _LANES), lambda i, j: (i, 0))
    lanes = lambda t: jnp.broadcast_to(t[:, None], (n_pad, _LANES))  # noqa: E731
    masked = v_pad != v

    dh = pl.pallas_call(
        functools.partial(_bwd_dh_kernel, vocab=v, block_v=block_v,
                          masked=masked),
        grid=(n_pad // block_n, v_pad // block_v),
        in_specs=[
            pl.BlockSpec((block_n, d_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((d_pad, block_v), lambda i, j: (0, j)),
            row, row, row,
        ],
        out_specs=pl.BlockSpec((block_n, d_pad), lambda i, j: (i, 0)),
        out_shape=_sds((n_pad, d_pad), h.dtype,
                                       vma=_vma(h, w, targets, lse, g)),
        scratch_shapes=[pltpu.VMEM((block_n, d_pad), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(h, w, lanes(targets), lanes(lse), lanes(g))

    row_dw = pl.BlockSpec((block_n, _LANES), lambda j, i: (i, 0))
    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, vocab=v, block_v=block_v,
                          masked=masked),
        grid=(v_pad // block_v, n_pad // block_n),
        in_specs=[
            pl.BlockSpec((block_n, d_pad), lambda j, i: (i, 0)),
            pl.BlockSpec((d_pad, block_v), lambda j, i: (0, j)),
            row_dw, row_dw, row_dw,
        ],
        out_specs=pl.BlockSpec((d_pad, block_v), lambda j, i: (0, j)),
        out_shape=_sds((d_pad, v_pad), w.dtype,
                                       vma=_vma(h, w, targets, lse, g)),
        scratch_shapes=[pltpu.VMEM((d_pad, block_v), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(h, w, lanes(targets), lanes(lse), lanes(g))
    return dh[:n, :d], dw[:d, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _lmh(h, w, targets, block_n, block_v, interpret):
    loss, _ = _fwd_call(h, w, targets, block_n, block_v, interpret)
    return loss


def _lmh_fwd(h, w, targets, block_n, block_v, interpret):
    loss, lse = _fwd_call(h, w, targets, block_n, block_v, interpret)
    return loss, (h, w, targets, lse)


def _lmh_bwd(block_n, block_v, interpret, res, g):
    h, w, targets, lse = res
    dh, dw = _bwd_call(h, w, targets, lse, g, block_n, block_v, interpret)
    return dh.astype(h.dtype), dw.astype(w.dtype), None


_lmh.defvjp(_lmh_fwd, _lmh_bwd)


def lm_head_nll(
    h,
    w,
    targets,
    block_n: Optional[int] = None,
    block_v: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Per-token NLL of ``softmax(h @ w)`` vs int ``targets`` with the
    LM-head matmul fused into both the xent forward and backward —
    neither logits nor dlogits ever reach HBM.

    ``h``: [..., D] features (post-final-norm), ``w``: [D, V] head
    weights, ``targets``: [...] int.  Differentiable w.r.t. ``h`` and
    ``w``.  Matches ``-log_softmax(h @ w)[target]`` (f32 accumulation
    on the MXU) to float tolerance."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v = w.shape[-1]
    if block_v is None:
        block_v = min(DEFAULT_BLOCK_V, ((max(v, 1) + 127) // 128) * 128)
    if block_n is None:
        block_n = DEFAULT_BLOCK_N
    lead = h.shape[:-1]
    out = _lmh(
        h.reshape(-1, h.shape[-1]),
        w,
        targets.reshape(-1).astype(jnp.int32),
        block_n, block_v, interpret,
    )
    return out.reshape(lead)
