"""Fused softmax-cross-entropy as a Pallas TPU kernel.

For an LM head the logits tensor [B*S, V] (V ~ 32k) is the largest
activation in the model.  ``jax.nn.log_softmax`` + gather materializes a
second [B*S, V] tensor and autodiff saves more; this kernel streams the
vocab once per row block, producing only per-token ``loss`` and
``logsumexp`` — O(N) extra memory instead of O(N*V).

Backward (round 3): a Pallas kernel over the same (row, vocab) grid
recomputes the softmax per tile from the logits and the saved logsumexp
(``dlogits = (softmax - onehot(target)) * g``) — purely elementwise per
tile, no cross-tile state, so it is a single fused read(logits) →
write(dlogits) sweep.  The blocked-jnp backward is kept as the non-TPU
fallback and as the reference the kernel tests compare against.  (The
[N, V] dlogits output itself is required by the head matmul backward
and is unavoidable.)

Interpret mode on CPU for tests; compiled on TPU.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kungfu_tpu.ops.pallas._sharding import vma_of as _vma
from kungfu_tpu.ops.pallas._sharding import sds as _sds
from kungfu_tpu.utils.envs import LaunchKnobs
from kungfu_tpu.utils.jaxcompat import tpu_compiler_params

#: measured on TPU v5e (docs/perf.md): (256, 2048) tiles run the fwd+bwd
#: sweep ~1.5x faster than the round-3 (128, 512) defaults — big enough
#: to pipeline HBM reads, small enough for VMEM double-buffering
DEFAULT_BLOCK_N = 256
DEFAULT_BLOCK_V = 2048
_NEG_INF = -1e30
#: per-row values (targets, loss, lse, g) cross the pallas_call boundary
#: replicated across one full lane width — Mosaic's tiling only accepts
#: (8k, 128) tiles, so a bare row vector is not a legal block shape on TPU
_LANES = 128


def _fwd_kernel(logits_ref, targets_ref, loss_ref, lse_ref, m_ref, l_ref,
                t_ref, *, vocab, block_v, masked):
    """Grid = (row blocks, vocab blocks), vocab innermost.  One [block_n,
    block_v] logits tile lives in VMEM at a time; the online max/sumexp/
    target accumulators persist in scratch across the vocab sweep.

    ``masked`` is a compile-time flag, False whenever block_v divides the
    vocab — the tail-mask compare/selects then vanish from the hot loop.
    The target logit is accumulated IN the sweep: a round-3 experiment
    moved it to an XLA gather outside the kernel and lost 2x — a
    take_along_axis over [8k, 32k] costs 1.7-4.3 ms on v5e (TPU gathers
    serialize), dwarfing the per-element compare it saved."""
    j = pl.program_id(1)
    n_v = pl.num_programs(1)
    blk = logits_ref[...].astype(jnp.float32)  # [block_n, block_v]
    n = blk.shape[0]
    tgt = targets_ref[...][:, :1]  # [block_n, 1] (lane 0)

    @pl.when(j == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        t_ref[...] = jnp.zeros_like(t_ref)

    k_pos = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (n, block_v), 1)
    if masked:
        # one select suffices: exp(_NEG_INF - m_new) underflows to exactly
        # 0, so the sum needs no second mask
        blk = jnp.where(k_pos < vocab, blk, _NEG_INF)
    m = m_ref[...]
    m_new = jnp.maximum(m, jnp.max(blk, axis=-1, keepdims=True))
    corr = jnp.exp(m - m_new)
    l_new = l_ref[...] * corr + jnp.sum(
        jnp.exp(blk - m_new), axis=-1, keepdims=True
    )
    # the target logit lives in exactly one vocab block
    is_tgt = k_pos == tgt
    t_new = t_ref[...] + jnp.sum(jnp.where(is_tgt, blk, 0.0), axis=-1, keepdims=True)
    m_ref[...] = m_new
    l_ref[...] = l_new
    t_ref[...] = t_new

    @pl.when(j == n_v - 1)
    def _():
        lse = m_new + jnp.log(jnp.maximum(l_new, 1e-30))
        lanes = loss_ref.shape
        loss_ref[...] = jnp.broadcast_to(lse - t_new, lanes)
        lse_ref[...] = jnp.broadcast_to(lse, lanes)


def _fwd_call(logits, targets, block_n, block_v, interpret):
    """logits [N, V], targets [N] → (loss [N], lse [N])."""
    n, v = logits.shape
    n_pad = ((n + block_n - 1) // block_n) * block_n
    v_pad = ((v + block_v - 1) // block_v) * block_v
    if n_pad != n or v_pad != v:
        logits = jnp.pad(logits, [(0, n_pad - n), (0, v_pad - v)])
        targets = jnp.pad(targets, [(0, n_pad - n)])
    kernel = functools.partial(
        _fwd_kernel, vocab=v, block_v=block_v, masked=v_pad != v
    )
    row = pl.BlockSpec((block_n, _LANES), lambda i, j: (i, 0))
    loss, lse = pl.pallas_call(
        kernel,
        grid=(n_pad // block_n, v_pad // block_v),
        in_specs=[
            pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
            row,
        ],
        out_specs=[row, row],
        out_shape=[
            _sds((n_pad, _LANES), jnp.float32, vma=_vma(logits, targets)),
            _sds((n_pad, _LANES), jnp.float32, vma=_vma(logits, targets)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(logits, jnp.broadcast_to(targets[:, None], (n_pad, _LANES)))
    return loss[:n, 0], lse[:n, 0]


def _bwd_blocked(logits, targets, lse, g, block_v):
    """dlogits = (softmax - onehot) * g, computed vocab-block-wise.

    Blocks are sliced from the (possibly bf16) logits INSIDE the scan
    body and the result is cast back to the logits dtype per block, so
    live f32 memory stays one [N, block_v] tile — the only full-size
    tensor is the unavoidable dlogits output itself."""
    n, v = logits.shape
    v_pad = ((v + block_v - 1) // block_v) * block_v
    if v_pad != v:
        logits = jnp.pad(logits, [(0, 0), (0, v_pad - v)])
    n_blk = v_pad // block_v

    def fold(_, j):
        x_blk = jax.lax.dynamic_slice_in_dim(
            logits, j * block_v, block_v, axis=1
        ).astype(jnp.float32)
        k_pos = j * block_v + jnp.arange(block_v)
        p = jnp.where(k_pos[None, :] < v, jnp.exp(x_blk - lse[:, None]), 0.0)
        onehot = (k_pos[None, :] == targets[:, None]).astype(jnp.float32)
        d_blk = (p - onehot) * g[:, None]
        return None, d_blk.astype(logits.dtype)

    _, dblocks = jax.lax.scan(fold, None, jnp.arange(n_blk))
    return dblocks.transpose(1, 0, 2).reshape(n, v_pad)[:, :v]


def _bwd_kernel(logits_ref, targets_ref, lse_ref, g_ref, dl_ref, *,
                vocab, block_v, masked):
    """dlogits tile = (softmax - onehot) * g; stateless per grid step.
    ``masked`` as in :func:`_fwd_kernel` (the onehot iota is needed
    either way, but the tail-mask select is skipped when block_v divides
    the vocab)."""
    j = pl.program_id(1)
    blk = logits_ref[...].astype(jnp.float32)  # [block_n, block_v]
    n = blk.shape[0]
    k_pos = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (n, block_v), 1)
    lse = lse_ref[...][:, :1]  # [block_n, 1] (lane 0)
    g = g_ref[...][:, :1]
    p = jnp.exp(blk - lse)
    if masked:
        p = jnp.where(k_pos < vocab, p, 0.0)
    onehot = (k_pos == targets_ref[...][:, :1]).astype(jnp.float32)
    dl_ref[...] = ((p - onehot) * g).astype(dl_ref.dtype)


def _bwd_pallas(logits, targets, lse, g, block_n, block_v, interpret):
    n, v = logits.shape
    n_pad = ((n + block_n - 1) // block_n) * block_n
    v_pad = ((v + block_v - 1) // block_v) * block_v
    if n_pad != n or v_pad != v:
        logits = jnp.pad(logits, [(0, n_pad - n), (0, v_pad - v)])
        targets = jnp.pad(targets, [(0, n_pad - n)])
        # padded rows: lse=+inf zeroes their softmax, g=0 their gradient
        lse = jnp.pad(lse, [(0, n_pad - n)], constant_values=1e30)
        g = jnp.pad(g, [(0, n_pad - n)])
    row = pl.BlockSpec((block_n, _LANES), lambda i, j: (i, 0))
    lanes = lambda t: jnp.broadcast_to(t[:, None], (n_pad, _LANES))  # noqa: E731
    dlogits = pl.pallas_call(
        functools.partial(_bwd_kernel, vocab=v, block_v=block_v,
                          masked=v_pad != v),
        grid=(n_pad // block_n, v_pad // block_v),
        in_specs=[
            pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
            row, row, row,
        ],
        out_specs=pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
        out_shape=_sds((n_pad, v_pad), logits.dtype,
                                       vma=_vma(logits, targets, lse, g)),
        compiler_params=tpu_compiler_params(
            # stateless per tile: both grid dims are parallel
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(logits, lanes(targets), lanes(lse), lanes(g))
    return dlogits[:n, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _xent(logits, targets, block_n, block_v, interpret):
    loss, _ = _fwd_call(logits, targets, block_n, block_v, interpret)
    return loss


def _xent_fwd(logits, targets, block_n, block_v, interpret):
    loss, lse = _fwd_call(logits, targets, block_n, block_v, interpret)
    return loss, (logits, targets, lse)


def _xent_bwd(block_n, block_v, interpret, res, g):
    logits, targets, lse = res
    import os

    # compiled path (TPU): the Pallas backward kernel; interpret mode
    # falls back to blocked jnp unless KF_PALLAS_BWD=pallas forces the
    # kernel (how the numerics tests run off-TPU)
    if interpret and os.environ.get("KF_PALLAS_BWD", "") != "pallas":
        dlogits = _bwd_blocked(logits, targets, lse, g, block_v)
    else:
        dlogits = _bwd_pallas(
            logits, targets, lse, g, block_n, block_v, interpret
        )
    return dlogits.astype(logits.dtype), None


_xent.defvjp(_xent_fwd, _xent_bwd)


#: Per-shape kernel-vs-XLA routing thresholds, seeded from the settled
#: v5e measurements (BENCH_extra.json tpu_kernels; docs/perf.md):
#:
#: * fwd-only: the kernel streams the logits once and beats XLA's
#:   materialized log-softmax ~2x at HBM scale (2.49 vs 5.00 ms at
#:   N=8192, V=32768).  Below ~4M logits elements both are microseconds
#:   and the pallas call overhead can lose — route XLA there.
#: * fwd+bwd (training): XLA fuses the dlogits-consumer epilogue into
#:   its backward sweep and wins ~2x (4.69 vs 2.30 ms at the same
#:   shape) — UNLESS its O(N*V) log-prob + residual set does not fit,
#:   where the kernel is the only variant that runs at all (the batch-8
#:   LM OOMs only the XLA path on 16 GiB).  The byte estimate is
#:   logits + f32 log-probs per element; re-measure the crossover with
#:   ``benchmarks/xent_sweep.py --crossover`` and adjust via env.
XENT_FWD_MIN_ELEMENTS = 1 << 22
XENT_TRAIN_XLA_BUDGET_MB = 2048


class _Knobs(LaunchKnobs):
    """The ``KF_TPU_XENT`` / ``KF_XENT_XLA_BUDGET_MB`` /
    ``KF_XENT_FWD_MIN_ELEMENTS`` routing knobs.

    These were always documented as launch-set (they pick which kernel
    gets traced for a shape and carry no cluster-size state), but the
    reads used to execute AT TRACE TIME inside jitted callers, each
    carrying a ``kflint: allow(recompile-hazard)`` waiver.  Hoisting the
    reads into the launch-knob base makes the documented semantics
    real — a mid-run env mutation never silently changes what the next
    trace compiles — and retires the waivers.  Tests and tools that
    mutate the environment call ``XENT_ENV.reload()`` afterwards (fresh
    processes, the normal launcher path, pick the values up at
    import)."""

    def _read(self) -> None:
        mode = os.environ.get("KF_TPU_XENT", "auto").lower()
        if mode == "xla":
            mode = "plain"  # long-standing alias
        if mode not in ("fused", "plain", "auto"):
            # fail loudly AT LOAD: a typo silently auto-routing (or
            # silently going plain, as pre-round-4 code did) hides the
            # misconfiguration
            raise ValueError(
                f"KF_TPU_XENT={mode!r}: one of fused | plain | xla | auto"
            )
        self.mode = mode
        self.budget_mb = int(os.environ.get(
            "KF_XENT_XLA_BUDGET_MB", str(XENT_TRAIN_XLA_BUDGET_MB)))
        self.fwd_min_elements = int(os.environ.get(
            "KF_XENT_FWD_MIN_ELEMENTS", str(XENT_FWD_MIN_ELEMENTS)))


XENT_ENV = _Knobs()


def _route_fused(n: int, v: int, itemsize: int, training: bool) -> bool:
    """True = take the Pallas kernel for this (shape, dtype, phase)."""
    if training:
        resid_bytes = n * v * (itemsize + 4)
        return resid_bytes > (XENT_ENV.budget_mb << 20)
    return n * v >= XENT_ENV.fwd_min_elements


def route_fused_lm_head(n_tokens: int, vocab: int) -> bool:
    """Should a training loss skip materializing logits entirely and take
    the fused LM-head kernel (:mod:`kungfu_tpu.ops.pallas.lm_head`)?

    Owns the one assumption callers kept duplicating: the plain path's
    logits are f32 (``Transformer.apply`` casts), so the residual bound
    is the training branch of :func:`_route_fused` at itemsize 4 — the
    same budget that routes :func:`token_nll` to the xent kernel."""
    return _route_fused(n_tokens, vocab, 4, training=True)


def token_nll(logits, targets, training: bool = True):
    """Mean next-token NLL with the fused/plain dispatch.

    The single owner of the ``KF_TPU_XENT`` switch (``fused`` | ``plain``
    | ``auto``): both the standalone
    :meth:`~kungfu_tpu.models.transformer.Transformer.loss` head and the
    sharded trainer's pipeline head route through here, so the mode
    semantics can't drift between the two loss paths.  Fused keeps the
    O(N·V) log-prob tensor and its autodiff residuals out of HBM.

    ``auto`` (the default) routes per shape on TPU via
    :func:`_route_fused` — the round-3 always-fused policy sent every
    caller to the kernel, including training shapes where XLA's fused
    backward is ~2x faster.  ``training=False`` lets eval-only callers
    opt into the fwd-only crossover (the kernel wins much earlier
    there); the default assumes gradients will flow.

    The mode is the launch-set :data:`XENT_ENV` knob — read at import,
    not at trace time; mutate the env then call ``XENT_ENV.reload()``
    to re-route (tests)."""
    mode = XENT_ENV.mode
    if mode == "fused":
        fused = True
    elif mode == "plain" or jax.default_backend() != "tpu":
        fused = False
    else:  # auto on TPU: per-shape routing
        v = logits.shape[-1]
        n = 1
        for d in logits.shape[:-1]:
            n *= d
        fused = _route_fused(n, v, jnp.dtype(logits.dtype).itemsize,
                             training)
    if fused:
        return jnp.mean(softmax_cross_entropy(logits, targets))
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1).squeeze(-1)
    return jnp.mean(nll)


def softmax_cross_entropy(
    logits,
    targets,
    block_n: Optional[int] = None,
    block_v: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Per-token NLL for ``logits`` [..., V] and int targets [...].

    Matches ``-log_softmax(logits)[target]`` numerically; differentiable
    w.r.t. logits.  ``block_v=None`` shrinks the default tile to the
    128-rounded vocab so small vocabs (tests, toy models) don't pad up
    to a whole 2048-wide block."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v = logits.shape[-1]
    if block_v is None:
        block_v = min(DEFAULT_BLOCK_V, ((max(v, 1) + 127) // 128) * 128)
    if block_n is None:
        block_n = DEFAULT_BLOCK_N
    lead = logits.shape[:-1]
    out = _xent(
        logits.reshape(-1, v),
        targets.reshape(-1).astype(jnp.int32),
        block_n, block_v, interpret,
    )
    return out.reshape(lead)
