"""Pallas ICI collectives: ring reduce-scatter / all-gather kernels.

The overlap plane (kf-overlap) hides wire time on the *host* plane and
leans on XLA's default double-buffering on the device plane; this module
writes the device collectives themselves — the 1810.11112
communication/computation-overlap design space pushed below XLA, at the
pod-scale regime 1909.09756 identifies (collectives inside ICI).  Two
kernel families, each in a unidirectional and a bidirectional form:

* **ring reduce-scatter** — the per-device ``[n*chunk]`` mesh-major flat
  buffer is carved into ``n`` chunks; partial sums travel the ring and
  each device ends with its own fully reduced chunk.  Inside ONE
  ``pallas_call``, each step's RDMA (``make_async_remote_copy``) is
  started, the *local* HBM→VMEM chunk prefetch rides the same window,
  and the fold (``recv + local``) executes while the send DMA is still
  draining — chunk *i*'s reduction runs while chunk *i±1*'s copy is in
  flight, double-buffered working slots throughout.
* **ring all-gather** — the inverse movement: each device's ``[chunk]``
  shard travels the ring; the VMEM→HBM output drain of the chunk
  received at step *s* overlaps the step-*s+1* forward RDMA.

The bidirectional forms split the chunk's sublane rows into two bands
that travel clockwise and counter-clockwise at once, halving per-link
bytes on the (full-duplex) ICI ring.

Geometry contract — identical to :mod:`kungfu_tpu.ops.schedules`: the
flat buffer is viewed ``[n, chunk]`` in mesh-major device order, device
``r`` owns chunk ``r``, and bucket concatenation reproduces the exact
un-bucketed per-device layout (the ZeRO-2/3 invariant).  That is what
lets ``reduce_scatter_flat``/``all_gather_flat`` swap these kernels in
per bucket without moving a single optimizer-state byte.

Implementation routing (``impl`` argument, default from the launch-set
``KF_PALLAS_COLLECTIVES`` env — read ONCE at import, never in traced
code):

* ``pallas`` — the kernels; compiled on TPU, ``interpret=True``
  elsewhere (the bitwise test/bench mode — the interpreter is a
  correctness tool, not a transport);
* ``lax`` — a pure ``lax.ppermute`` ring with the IDENTICAL hop order
  and fold-operand order, so its results are **bitwise-identical** to
  the kernels (pinned in ``tests/test_pallas_collectives.py``);
* ``auto`` (default) — ``pallas`` on TPU, ``lax`` elsewhere (same
  policy as :func:`kungfu_tpu.parallel.ring.ring_attention`'s
  ``block_impl="auto"``: interpret-mode Pallas is far too slow for the
  CPU test cluster, and the emulation computes the same bits).

Reduction-order contract: a ring reduce-scatter's chunk ``c`` folds
contributions in ring order starting at device ``c±1`` —
``((x[c+1] + x[c+2]) + ...) + x[c]`` for the clockwise direction — which
for floats differs bitwise from XLA's ``lax.psum_scatter`` association
in general.  The kernels are therefore pinned bitwise against the
order-matched lax emulation on arbitrary floats, and against
``lax.psum_scatter`` itself on order-exact data (ints, and
integer-valued floats whose sums are exactly representable); all-gather
is pure data movement and is pinned bitwise against ``lax.all_gather``
unconditionally.  See docs/pallas_collectives.md.

Both collectives are differentiable as a custom-vjp pair: the backward
of the all-gather IS the ring reduce-scatter of the cotangent (and vice
versa), so the ZeRO-3 gradient path keeps its "transpose of the gather
is the scatter" shape when it rides ``schedule="pallas_ring"``.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kungfu_tpu.ops.pallas._sharding import match_vma as _match_vma
from kungfu_tpu.ops.pallas._sharding import sds as _sds
from kungfu_tpu.ops.pallas._sharding import vma_of as _vma
from kungfu_tpu.utils.envs import LaunchKnobs
from kungfu_tpu.utils.jaxcompat import axis_size, tpu_compiler_params

_LANE = 128

#: selectable implementations (module docstring)
IMPLS = ("auto", "pallas", "lax")


class _Knobs(LaunchKnobs):
    """``KF_PALLAS_COLLECTIVES`` — the default ``impl`` for every ring
    collective call that does not pass one explicitly.  Launch-set by
    design (it selects which program gets traced; no cluster-size state
    to go stale): read at import / :meth:`reload`, never in traced
    code."""

    def _read(self) -> None:
        impl = os.environ.get("KF_PALLAS_COLLECTIVES", "auto").lower()
        if impl not in IMPLS:
            raise ValueError(
                f"KF_PALLAS_COLLECTIVES={impl!r}: one of {IMPLS}")
        self.impl = impl


ENV = _Knobs()


def _use_pallas(impl) -> bool:
    impl = impl if impl is not None else ENV.impl
    if impl not in IMPLS:
        raise ValueError(f"impl {impl!r}: one of {IMPLS} (or None)")
    if impl == "pallas":
        return True
    if impl == "lax":
        return False
    return jax.default_backend() == "tpu"


# -- geometry --------------------------------------------------------------

def _sublane(dtype) -> int:
    """Minimum second-to-last tile dim for ``dtype`` (f32 8, bf16 16,
    int8/fp8 32 — the Mosaic tiling table)."""
    size = jnp.dtype(dtype).itemsize
    if size >= 4:
        return 8
    if size == 2:
        return 16
    return 32


def _tile_rows(chunk: int, dtype) -> int:
    """Rows of the padded ``[rows, 128]`` chunk tile."""
    sub = _sublane(dtype)
    rows = -(-chunk // _LANE)
    return max(sub, -(-rows // sub) * sub)


def _band_rows(rows: int, dtype) -> int:
    """Clockwise band height of the bidirectional row split (0 = the
    chunk is too short to split; callers fall back to unidirectional).
    Shared by kernel and emulation so the per-band fold orders — and
    therefore the bits — agree."""
    sub = _sublane(dtype)
    if rows < 2 * sub:
        return 0
    return -(-(rows // 2) // sub) * sub


def _chunk_view(flat, n: int, chunk: int):
    """``[n*chunk]`` flat → padded ``[n, rows, 128]`` mesh-major view."""
    rows = _tile_rows(chunk, flat.dtype)
    pad = rows * _LANE - chunk
    g = flat.reshape(n, chunk)
    if pad:
        g = jnp.concatenate([g, jnp.zeros((n, pad), g.dtype)], axis=-1)
    return g.reshape(n, rows, _LANE)


def _shard_view(shard, chunk: int):
    """``[chunk]`` shard → padded ``[rows, 128]`` tile."""
    rows = _tile_rows(chunk, shard.dtype)
    pad = rows * _LANE - chunk
    if pad:
        shard = jnp.concatenate(
            [shard, jnp.zeros((pad,), shard.dtype)])
    return shard.reshape(rows, _LANE)


def ring_wire_bytes(nbytes: int, n: int, kind: str = "reduce_scatter") -> float:
    """Analytic per-rank ICI wire bytes of one ring collective over a
    per-device payload of ``nbytes`` (the ring convention of
    :data:`kungfu_tpu.ops.schedules._COLLECTIVE_COST`): a reduce-scatter
    moves ``(n-1)/n * nbytes``, an all-gather ``(n-1) * nbytes`` (its
    payload being the shard), an all-reduce the sum of both.  Direction
    count does not change the BYTES — the bidirectional forms move the
    same total over twice the links in half the steps."""
    if kind == "reduce_scatter":
        return (n - 1) / n * nbytes
    if kind == "all_gather":
        return (n - 1) * nbytes
    if kind == "all_reduce":
        return 2.0 * (n - 1) / n * nbytes
    raise ValueError(f"unknown kind {kind!r}")


# -- the order-matched lax emulation ---------------------------------------
#
# One hop = one lax.ppermute; the fold is `received + local` with the
# receive operand FIRST — the exact operand order the kernels use, so
# emulation and kernel are bitwise-identical on every input (pinned in
# tests/test_pallas_collectives.py).  Chunk c's partial starts at device
# c+sign, hops in `sign` direction, and lands fully reduced on its owner
# after n-1 hops.

def _take(parts, idx):
    return lax.dynamic_index_in_dim(parts, idx, axis=0, keepdims=False)


def _rs_dir_emul(parts, axis: str, sign: int):
    """parts: [n, rows, 128]; returns this device's reduced [rows, 128]."""
    n = axis_size(axis)
    me = lax.axis_index(axis)
    perm = [(i, (i + sign) % n) for i in range(n)]
    acc = _take(parts, (me - sign) % n)
    for s in range(n - 1):
        got = lax.ppermute(acc, axis, perm)
        acc = got + _take(parts, (me - sign * (s + 2)) % n)
    return acc


def _ag_dir_emul(tile, axis: str, sign: int):
    """tile: [rows, 128]; returns the gathered [n, rows, 128]."""
    n = axis_size(axis)
    me = lax.axis_index(axis)
    perm = [(i, (i + sign) % n) for i in range(n)]
    # match the tile's varying manual axes up front (vma-typed jax): the
    # zeros are unvarying but every update writes varying data
    out = _match_vma(jnp.zeros((n,) + tile.shape, tile.dtype),
                     _vma(tile) | frozenset({axis}))
    out = lax.dynamic_update_index_in_dim(out, tile, me, axis=0)
    buf = tile
    for s in range(n - 1):
        buf = lax.ppermute(buf, axis, perm)
        out = lax.dynamic_update_index_in_dim(
            out, buf, (me - sign * (s + 1)) % n, axis=0)
    return out


def _rs_emul(parts, axis: str, bidirectional: bool):
    rows = parts.shape[1]
    band = _band_rows(rows, parts.dtype) if bidirectional else 0
    if not band:
        return _rs_dir_emul(parts, axis, +1)
    return jnp.concatenate(
        [_rs_dir_emul(parts[:, :band], axis, +1),
         _rs_dir_emul(parts[:, band:], axis, -1)], axis=0)


def _ag_emul(tile, axis: str, bidirectional: bool):
    rows = tile.shape[0]
    band = _band_rows(rows, tile.dtype) if bidirectional else 0
    if not band:
        return _ag_dir_emul(tile, axis, +1)
    return jnp.concatenate(
        [_ag_dir_emul(tile[:band], axis, +1),
         _ag_dir_emul(tile[band:], axis, -1)], axis=1)


# -- the kernels -----------------------------------------------------------
#
# Protocol per direction (sign = +1 clockwise / -1 counter-clockwise),
# device `me`, neighbors dst = me+sign (where our RDMA lands) and
# src = me-sign (who lands in ours):
#
#   reduce-scatter: acc slots [2], recv slots [2], local-prefetch slots
#   [2].  Step s: start the RDMA of the current partial (acc[s%2] →
#   dst's recv[s%2]); start the HBM→VMEM prefetch of the local chunk the
#   fold needs; wait_recv; fold `recv + local` into acc[(s+1)%2] (or the
#   output on the last step) WHILE the send DMA drains; wait_send.  The
#   fold-while-sending is the in-kernel overlap; the slot alternation
#   plus the per-step wait_send/ack make the 2-deep buffers safe.
#
#   all-gather: working slots [2] double as send source and landing
#   zone.  Step s: forward slot s%2; wait_recv of slot (s+1)%2; start
#   the VMEM→HBM output drain of the received chunk — it overlaps the
#   forward's send drain — wait_send, wait the drain.
#
# Flow control (compiled only; the interpreter executes DMAs in program
# order and does not implement remote semaphore_signal): a REGULAR ack
# semaphore — after consuming the slot our upstream neighbor wrote, we
# signal it; a sender re-uses a remote slot (step s+2) only after that
# ack.  Kernel entry is fenced by the standard neighbor barrier
# (get_barrier_semaphore + collective_id) so no RDMA lands before its
# target kernel is live.

_LOGICAL = pltpu.DeviceIdType.LOGICAL


def _neighbor_barrier(left, right):
    bar = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(bar, inc=1, device_id=left,
                           device_id_type=_LOGICAL)
    pltpu.semaphore_signal(bar, inc=1, device_id=right,
                           device_id_type=_LOGICAL)
    pltpu.semaphore_wait(bar, 2)


def _rs_kernel(x_ref, o_ref, acc_ref, recv_ref, loc_ref, send_sem,
               recv_sem, copy_sem, ack_sem, *, axis, n, band, rows,
               interpret):
    """Ring reduce-scatter over ``axis``.  x_ref: [n, rows, 128] (ANY);
    o_ref: [rows, 128] (VMEM).  ``band`` > 0 splits rows into a
    clockwise band [0:band] and a counter-clockwise band [band:]."""
    me = lax.axis_index(axis)
    dirs = ((+1, 0, band if band else rows),) if not band else (
        (+1, 0, band), (-1, band, rows))
    nbr = {+1: lax.rem(me + 1, n), -1: lax.rem(me + n - 1, n)}
    if not interpret:
        _neighbor_barrier(nbr[-1], nbr[+1])

    # seed: the step-0 partial is the local chunk owned by the device
    # one hop upstream (chunk me-sign)
    for d, (sign, lo, hi) in enumerate(dirs):
        seed = pltpu.make_async_copy(
            x_ref.at[lax.rem(me - sign + n, n), pl.ds(lo, hi - lo)],
            acc_ref.at[d, 0, pl.ds(0, hi - lo)],
            copy_sem.at[d, 0])
        seed.start()
        seed.wait()

    for s in range(n - 1):
        slot, nslot = s % 2, (s + 1) % 2
        rdmas, locals_ = [], []
        for d, (sign, lo, hi) in enumerate(dirs):
            if not interpret and s >= 2:
                # downstream consumed the slot we are about to overwrite
                pltpu.semaphore_wait(ack_sem.at[d], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[d, slot, pl.ds(0, hi - lo)],
                dst_ref=recv_ref.at[d, slot, pl.ds(0, hi - lo)],
                send_sem=send_sem.at[d, slot],
                recv_sem=recv_sem.at[d, slot],
                device_id=nbr[sign],
                device_id_type=_LOGICAL)
            rdma.start()
            # overlap 1: the local-chunk prefetch rides the RDMA window
            lcp = pltpu.make_async_copy(
                x_ref.at[lax.rem(me - sign * (s + 2) + 2 * n * n, n),
                         pl.ds(lo, hi - lo)],
                loc_ref.at[d, slot, pl.ds(0, hi - lo)],
                copy_sem.at[d, slot])
            lcp.start()
            rdmas.append(rdma)
            locals_.append(lcp)
        for d, (sign, lo, hi) in enumerate(dirs):
            rdmas[d].wait_recv()
            locals_[d].wait()
            span = pl.ds(0, hi - lo)
            # overlap 2: the fold executes while the send DMA drains
            # (wait_send comes after); operand order `recv + local` is
            # the emulation's — bitwise contract
            folded = recv_ref[d, slot, span] + loc_ref[d, slot, span]
            if s + 1 < n - 1:
                acc_ref[d, nslot, span] = folded
            else:
                o_ref[pl.ds(lo, hi - lo)] = folded
            rdmas[d].wait_send()
            if not interpret and s <= n - 4:
                # tell upstream its step-s write is consumed.  Signaled
                # ONLY when a wait will consume it — upstream waits at
                # its steps 2..n-2 for our folds of steps 0..n-4 — so
                # the ack semaphore drains to exactly zero at kernel end
                # (a trailing signal would strand a nonzero count into
                # the next invocation and break the slot-reuse fence)
                pltpu.semaphore_signal(
                    ack_sem.at[d], inc=1,
                    device_id=nbr[-sign], device_id_type=_LOGICAL)


def _ag_kernel(x_ref, o_ref, buf_ref, send_sem, recv_sem, copy_sem,
               ack_sem, *, axis, n, band, rows, interpret):
    """Ring all-gather over ``axis``.  x_ref: [rows, 128] (ANY);
    o_ref: [n, rows, 128] (ANY)."""
    me = lax.axis_index(axis)
    dirs = ((+1, 0, band if band else rows),) if not band else (
        (+1, 0, band), (-1, band, rows))
    nbr = {+1: lax.rem(me + 1, n), -1: lax.rem(me + n - 1, n)}

    # own chunk: into working slot 0 and output row `me`
    own_out = pltpu.make_async_copy(
        x_ref, o_ref.at[me], copy_sem.at[0, 0])
    own_out.start()
    for d, (sign, lo, hi) in enumerate(dirs):
        seed = pltpu.make_async_copy(
            x_ref.at[pl.ds(lo, hi - lo)],
            buf_ref.at[d, 0, pl.ds(0, hi - lo)],
            copy_sem.at[d, 1])
        seed.start()
        seed.wait()
    own_out.wait()
    if not interpret:
        _neighbor_barrier(nbr[-1], nbr[+1])

    for s in range(n - 1):
        slot, nslot = s % 2, (s + 1) % 2
        rdmas, drains = [], []
        for d, (sign, lo, hi) in enumerate(dirs):
            if not interpret and s >= 2:
                pltpu.semaphore_wait(ack_sem.at[d], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=buf_ref.at[d, slot, pl.ds(0, hi - lo)],
                dst_ref=buf_ref.at[d, nslot, pl.ds(0, hi - lo)],
                send_sem=send_sem.at[d, slot],
                recv_sem=recv_sem.at[d, nslot],
                device_id=nbr[sign],
                device_id_type=_LOGICAL)
            rdma.start()
            rdmas.append(rdma)
        for d, (sign, lo, hi) in enumerate(dirs):
            rdmas[d].wait_recv()
            # overlap: the VMEM→HBM output drain of the received chunk
            # runs while this step's forward send is still draining
            drain = pltpu.make_async_copy(
                buf_ref.at[d, nslot, pl.ds(0, hi - lo)],
                o_ref.at[lax.rem(me - sign * (s + 1) + 2 * n * n, n),
                         pl.ds(lo, hi - lo)],
                copy_sem.at[d, slot])
            drain.start()
            drains.append(drain)
        for d, (sign, lo, hi) in enumerate(dirs):
            rdmas[d].wait_send()
            drains[d].wait()
            if not interpret and 1 <= s <= n - 3:
                # the slot our upstream wrote at step s-1 is now fully
                # consumed (forwarded at step s, drained at step s-1).
                # Signaled only for writes a future wait guards (upstream
                # waits at its steps 2..n-2 for writes 0..n-4, i.e. our
                # signals at steps 1..n-3): the semaphore drains to zero
                # at kernel end
                pltpu.semaphore_signal(
                    ack_sem.at[d], inc=1,
                    device_id=nbr[-sign], device_id_type=_LOGICAL)


def _any_space():
    space = getattr(pltpu, "ANY", None)
    if space is None:
        space = pltpu.TPUMemorySpace.ANY
    return space


def _rs_pallas(parts, axis: str, n: int, bidirectional: bool,
               interpret: bool):
    rows = parts.shape[1]
    band = _band_rows(rows, parts.dtype) if bidirectional else 0
    ndir = 2 if band else 1
    kernel = functools.partial(
        _rs_kernel, axis=axis, n=n, band=band, rows=rows,
        interpret=interpret)
    return pl.pallas_call(
        kernel,
        out_shape=_sds((rows, _LANE), parts.dtype,
                       vma=_vma(parts) | frozenset({axis})),
        in_specs=[pl.BlockSpec(memory_space=_any_space())],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((ndir, 2, rows, _LANE), parts.dtype),  # acc
            pltpu.VMEM((ndir, 2, rows, _LANE), parts.dtype),  # recv
            pltpu.VMEM((ndir, 2, rows, _LANE), parts.dtype),  # local
            pltpu.SemaphoreType.DMA((ndir, 2)),               # send
            pltpu.SemaphoreType.DMA((ndir, 2)),               # recv
            pltpu.SemaphoreType.DMA((ndir, 2)),               # copies
            pltpu.SemaphoreType.REGULAR((ndir,)),             # acks
        ],
        compiler_params=tpu_compiler_params(collective_id=1),
        interpret=interpret,
    )(parts)


def _ag_pallas(tile, axis: str, n: int, bidirectional: bool,
               interpret: bool):
    rows = tile.shape[0]
    band = _band_rows(rows, tile.dtype) if bidirectional else 0
    ndir = 2 if band else 1
    kernel = functools.partial(
        _ag_kernel, axis=axis, n=n, band=band, rows=rows,
        interpret=interpret)
    return pl.pallas_call(
        kernel,
        out_shape=_sds((n, rows, _LANE), tile.dtype,
                       vma=_vma(tile) | frozenset({axis})),
        in_specs=[pl.BlockSpec(memory_space=_any_space())],
        out_specs=pl.BlockSpec(memory_space=_any_space()),
        scratch_shapes=[
            pltpu.VMEM((ndir, 2, rows, _LANE), tile.dtype),   # slots
            pltpu.SemaphoreType.DMA((ndir, 2)),               # send
            pltpu.SemaphoreType.DMA((ndir, 2)),               # recv
            pltpu.SemaphoreType.DMA((ndir, 2)),               # copies
            pltpu.SemaphoreType.REGULAR((ndir,)),             # acks
        ],
        compiler_params=tpu_compiler_params(collective_id=2),
        interpret=interpret,
    )(tile)


# -- differentiable cores (custom-vjp pair) --------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _rs_core(flat, axis, bidirectional, use_pallas, interpret):
    n = axis_size(axis)
    chunk = flat.shape[0] // n
    parts = _chunk_view(flat, n, chunk)
    if use_pallas:
        tile = _rs_pallas(parts, axis, n, bidirectional, interpret)
    else:
        tile = _rs_emul(parts, axis, bidirectional)
    return tile.reshape(-1)[:chunk]


def _rs_fwd(flat, axis, bidirectional, use_pallas, interpret):
    return _rs_core(flat, axis, bidirectional, use_pallas, interpret), None


def _rs_bwd(axis, bidirectional, use_pallas, interpret, _, ct):
    # transpose of the tiled reduce-scatter is the tiled all-gather
    return (_ag_core(ct, axis, bidirectional, use_pallas, interpret),)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _ag_core(shard, axis, bidirectional, use_pallas, interpret):
    n = axis_size(axis)
    chunk = shard.shape[0]
    tile = _shard_view(shard, chunk)
    if use_pallas:
        full = _ag_pallas(tile, axis, n, bidirectional, interpret)
    else:
        full = _ag_emul(tile, axis, bidirectional)
    return full.reshape(n, -1)[:, :chunk].reshape(-1)


def _ag_fwd(shard, axis, bidirectional, use_pallas, interpret):
    return _ag_core(shard, axis, bidirectional, use_pallas, interpret), None


def _ag_bwd(axis, bidirectional, use_pallas, interpret, _, ct):
    # transpose of the tiled all-gather is the reduce-scatter — the
    # ZeRO-3 gradient arrives already scattered, ring order
    return (_rs_core(ct, axis, bidirectional, use_pallas, interpret),)


_rs_core.defvjp(_rs_fwd, _rs_bwd)
_ag_core.defvjp(_ag_fwd, _ag_bwd)


# -- public API ------------------------------------------------------------

def _resolve(impl, interpret):
    use_pallas = _use_pallas(impl)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return use_pallas, bool(interpret)


def ring_reduce_scatter(flat, axis: str, *, bidirectional: bool = False,
                        impl=None, interpret=None):
    """Ring reduce-scatter (sum) of a per-device mesh-major ``[n*chunk]``
    flat buffer over mesh ``axis``; returns this device's reduced
    ``[chunk]`` slice (device ``r`` owns chunk ``r`` — the
    :func:`kungfu_tpu.ops.schedules.reduce_scatter_flat` geometry).
    Must run inside ``shard_map`` with ``axis`` a live mesh axis; the
    buffer length must divide by the axis size (callers pad — the
    schedule layer's bucket geometry already does).  Differentiable:
    the vjp is the matching ring all-gather."""
    n = axis_size(axis)
    if n == 1:
        return flat
    if flat.ndim != 1 or flat.shape[0] % n:
        raise ValueError(
            f"ring_reduce_scatter wants a flat [n*chunk] buffer over "
            f"n={n}, got shape {flat.shape}")
    use_pallas, interp = _resolve(impl, interpret)
    return _rs_core(flat, axis, bool(bidirectional), use_pallas, interp)


def ring_all_gather(shard, axis: str, *, bidirectional: bool = False,
                    impl=None, interpret=None):
    """Ring all-gather of a per-device ``[chunk]`` shard over mesh
    ``axis``; returns the mesh-major ``[n*chunk]`` concatenation (the
    :func:`kungfu_tpu.ops.schedules.all_gather_flat` geometry, bitwise —
    gathering is pure data movement).  Differentiable: the vjp is the
    matching ring reduce-scatter, so a ZeRO-3-style loss-of-gathered-
    params arrives already scattered."""
    n = axis_size(axis)
    if n == 1:
        return shard
    if shard.ndim != 1:
        raise ValueError(
            f"ring_all_gather wants a flat [chunk] shard, got {shard.shape}")
    use_pallas, interp = _resolve(impl, interpret)
    return _ag_core(shard, axis, bool(bidirectional), use_pallas, interp)


def ring_all_reduce(x, axis: str, *, bidirectional: bool = False,
                    impl=None, interpret=None):
    """Ring all-reduce (sum) of an arbitrary-shaped per-device tensor:
    reduce-scatter then all-gather through the same kernels — the
    ``pallas_ring`` arm of :func:`kungfu_tpu.ops.schedules.
    all_reduce_scheduled`.  Sum only (``psum_scatter`` parity); min/max
    ride the lax ring schedule instead."""
    n = axis_size(axis)
    if n == 1:
        return x
    from kungfu_tpu.ops.schedules import _flatten_pad

    parts, size = _flatten_pad(x, n, "sum")
    flat = parts.reshape(-1)
    shard = ring_reduce_scatter(flat, axis, bidirectional=bidirectional,
                                impl=impl, interpret=interpret)
    full = ring_all_gather(shard, axis, bidirectional=bidirectional,
                           impl=impl, interpret=interpret)
    return full[:size].reshape(x.shape)
