"""In-jit collective ops — the hot-path API.

Parity with the reference's TF custom ops
(``srcs/cpp/src/tensorflow/ops/cpu/collective.cpp``,
``srcs/python/kungfu/tensorflow/ops/collective.py``), re-designed for XLA:
these are plain functions used **inside** ``jit``/``shard_map`` code with
the communicator's axis names; XLA lowers them to ICI collectives.  There
is no async op machinery (the reference needed AsyncOpKernels + done
callbacks; XLA overlaps collectives with compute automatically).

Example (inside a training step shard-mapped over ``comm.axis``)::

    grads = ops.group_all_reduce(grads, axis=comm.axis, mean=True)
"""

from kungfu_tpu.ops.collective import (
    all_reduce,
    group_all_reduce,
    all_gather,
    broadcast,
    barrier_value,
    peer_rank,
    peer_size,
)
from kungfu_tpu.ops.fuse import fuse, defuse
from kungfu_tpu.ops.schedules import ALLREDUCE_SCHEDULES, all_reduce_scheduled
from kungfu_tpu.ops.monitor import global_noise_scale, group_all_reduce_with_variance
from kungfu_tpu.ops.state import counter, exponential_moving_average

__all__ = [
    "all_reduce",
    "group_all_reduce",
    "all_gather",
    "broadcast",
    "barrier_value",
    "peer_rank",
    "peer_size",
    "ALLREDUCE_SCHEDULES",
    "all_reduce_scheduled",
    "fuse",
    "defuse",
    "global_noise_scale",
    "group_all_reduce_with_variance",
    "counter",
    "exponential_moving_average",
]
