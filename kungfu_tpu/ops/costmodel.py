"""kf-xray cost model: analytic FLOPs/bytes for the flagship transformer.

MFU is a ratio of two numbers this repo previously had neither of: the
model FLOPs a step *must* execute (analytic, below — NOT a profiler
count, so recompute/fusion choices cannot inflate it) and the chip's
peak FLOP/s (detected from the TPU device kind, or pinned by the
``KF_XRAY_PEAK_FLOPS`` launch env).  On the CPU mesh there is no
meaningful peak, so :func:`chip_peak_flops` returns ``None`` and every
consumer reports the **model-FLOPs rate** row instead of an MFU — the
same tunnel-proof discipline as every other CPU-mesh bench row.

Three model surfaces (docs/xray.md derives each):

* :func:`train_step_flops` — fwd+bwd(+head) for one training step, the
  standard 3x-forward accounting (backward re-does both matmul operands);
* :func:`serve_prefill_flops` / :func:`serve_decode_flops` — the serving
  plane's phases (prefill computes ``tokens`` positions attending into a
  growing context; decode computes one position over the full context);
* bytes: :func:`param_bytes` and :func:`kv_bytes_per_token` — the
  roofline denominators next to the ``kf_opt_state_bytes`` /
  ``kf_kv_cache_bytes`` gauges.

The live surface is :class:`MFUMeter`: one object per training loop (or
serving engine) that turns per-step wall clock + the analytic FLOPs into
the ``kf_mfu`` / ``kf_model_flops_s`` gauges and the per-phase
``kf_step_phase_seconds{phase=...}`` gauges, all riding the existing
snapshot → aggregator → ``/cluster`` → kftop flow.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

from kungfu_tpu.monitor import timeline
from kungfu_tpu.monitor.registry import REGISTRY

#: launch env pinning the per-chip peak FLOP/s (overrides detection;
#: registered in utils/envs.py like every KF_* knob)
PEAK_ENV = "KF_XRAY_PEAK_FLOPS"

#: per-chip bf16 peak FLOP/s by jax ``device_kind`` prefix (public
#: figures; one chip = what one jax device reports).  Longest prefix
#: wins so "TPU v5p" is not swallowed by "TPU v5".
CHIP_PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}


# -- parameter / bytes accounting ------------------------------------------
def transformer_param_count(cfg) -> int:
    """Exact parameter count of :class:`~kungfu_tpu.models.transformer.
    Transformer` under ``cfg`` — pinned against a real ``init()`` tree in
    tests so the analytic model cannot drift from the code."""
    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
    per_layer = (
        4 * (d * d + d)      # wq/wk/wv/wo (+bias)
        + (d * f + f) + (f * d + d)  # ffn_in/ffn_out (+bias)
        + 2 * 2 * d          # ln1/ln2 scale+bias
    )
    total = v * d + cfg.n_layers * per_layer + 2 * d  # embed + layers + ln_f
    if cfg.pos == "learned":
        total += cfg.max_seq * d
    total += d * v  # untied head, no bias
    return total


def matmul_param_count(cfg) -> int:
    """Parameters that participate in matmuls (the ``2 * P * tokens``
    denominator of the classic FLOPs estimate): everything except the
    embedding lookup table, positions, layernorms, and biases."""
    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
    return cfg.n_layers * (4 * d * d + 2 * d * f) + d * v


def param_bytes(cfg, dtype_bytes: int = 4) -> int:
    """Model parameter footprint (f32 master params by default)."""
    return transformer_param_count(cfg) * dtype_bytes


def kv_bytes_per_token(cfg, dtype_bytes: int = 2) -> int:
    """KV-cache bytes one token pins: K+V per layer in compute dtype —
    the per-token slope of the ``kf_kv_cache_bytes`` gauge."""
    return 2 * cfg.n_layers * cfg.n_heads * cfg.head_dim * dtype_bytes


# -- FLOPs model ------------------------------------------------------------
def forward_flops(cfg, batch: int, seq: int, lm_head: bool = True) -> int:
    """Forward-pass FLOPs for ``[batch, seq]`` tokens: the matmul term
    (``2 * P_matmul`` per token), the quadratic attention term
    (``4 * d * S`` per token per layer for QK^T + PV), and optionally
    the LM head."""
    d = cfg.d_model
    tokens = batch * seq
    matmul = 2 * tokens * cfg.n_layers * (4 * d * d + 2 * d * cfg.d_ff)
    attn = 4 * tokens * seq * d * cfg.n_layers
    head = 2 * tokens * d * cfg.vocab_size if lm_head else 0
    return matmul + attn + head


def train_step_flops(cfg, batch: int, seq: int) -> int:
    """Fwd + bwd for one step: the standard 3x-forward accounting (the
    backward pass re-computes both operands of every matmul)."""
    return 3 * forward_flops(cfg, batch, seq)


def serve_prefill_flops(cfg, tokens: int, start: int = 0) -> int:
    """Prefill of ``tokens`` new positions on top of ``start`` cached
    ones (prefix reuse skips the cached positions' FLOPs — exactly the
    saving ``bench.py --serve`` measures in computed tokens): matmul +
    attention into the growing ``[0, start+tokens)`` context, plus ONE
    logits row (prefill emits only the last position's token)."""
    if tokens <= 0:
        return 0
    d = cfg.d_model
    matmul = 2 * tokens * cfg.n_layers * (4 * d * d + 2 * d * cfg.d_ff)
    # position start+i attends over start+i+1 keys; sum_i ~ t*(start + (t+1)/2)
    attended = tokens * start + tokens * (tokens + 1) // 2
    attn = 4 * d * cfg.n_layers * attended
    head = 2 * d * cfg.vocab_size
    return matmul + attn + head


def serve_decode_flops(cfg, context: int) -> int:
    """One decode position of one sequence attending over ``context``
    keys (its own included)."""
    d = cfg.d_model
    matmul = 2 * cfg.n_layers * (4 * d * d + 2 * d * cfg.d_ff)
    attn = 4 * d * cfg.n_layers * max(1, context)
    head = 2 * d * cfg.vocab_size
    return matmul + attn + head


# -- chip peak --------------------------------------------------------------
def chip_peak_flops(device=None) -> Optional[float]:
    """Per-chip peak FLOP/s: the ``KF_XRAY_PEAK_FLOPS`` env wins, else
    the detected TPU device kind's table entry; ``None`` on CPU/unknown
    backends (there is no honest peak to divide by — consumers report
    the model-FLOPs rate instead)."""
    pinned = os.environ.get(PEAK_ENV, "").strip()
    if pinned:
        try:
            v = float(pinned)
            return v if v > 0 else None
        except ValueError:
            pass
    try:
        if device is None:
            import jax

            devices = jax.devices()
            if not devices:
                return None
            device = devices[0]
        kind = str(getattr(device, "device_kind", "") or "")
    except Exception:  # noqa: BLE001 — detection must never break a loop
        return None
    best = None
    for prefix, peak in CHIP_PEAK_FLOPS.items():
        if kind.startswith(prefix) and (best is None or len(prefix) > best[0]):
            best = (len(prefix), peak)
    return best[1] if best else None


# -- live meter -------------------------------------------------------------
def record_phases(phases: Dict[str, float]) -> None:
    """Export a per-step phase split as the
    ``kf_step_phase_seconds{phase=...}`` gauges (the continuous
    decomposition kftop's XRAY section renders cluster-wide)."""
    for phase, seconds in phases.items():
        REGISTRY.gauge("kf_step_phase_seconds", phase=phase).set(
            float(seconds))


class MFUMeter:
    """Continuous MFU / model-FLOPs-rate accounting for one loop.

    ``step_flops`` may be a constant (training: one analytic number per
    step) or accumulated via :meth:`add_flops` (serving: prefill/decode
    FLOPs vary per iteration).  Each :meth:`step` turns the window into
    the ``kf_model_flops_s`` gauge, the ``kf_mfu`` gauge when a chip
    peak is known, and — when a phase split is supplied — the per-phase
    gauges plus an ``xray`` timeline mark so offline dumps carry the
    same sample the live plane exports."""

    def __init__(self, step_flops: int = 0,
                 peak_flops: Optional[float] = None,
                 detect_peak: bool = True,
                 ema_alpha: float = 0.2,
                 rank: Optional[int] = None):
        self.step_flops = int(step_flops)
        self.peak_flops = (peak_flops if peak_flops is not None
                           else (chip_peak_flops() if detect_peak else None))
        self._alpha = float(ema_alpha)
        self._pending_flops = 0
        self._last = None  # perf_counter of the previous step boundary
        self._rate_ema: Optional[float] = None
        self.rank = rank
        self.mfu: Optional[float] = None

    def add_flops(self, flops: int) -> None:
        """Accumulate FLOPs executed since the last :meth:`step` (the
        serving engine's per-prefill/per-decode contributions)."""
        self._pending_flops += int(flops)

    def step(self, wall_s: Optional[float] = None,
             phases: Optional[Dict[str, float]] = None) -> Optional[float]:
        """One step boundary.  ``wall_s`` pins the step duration; without
        it the meter uses the time since its previous call.  Returns the
        smoothed model-FLOPs rate (FLOP/s), ``None`` until measurable."""
        now = time.perf_counter()
        if wall_s is None:
            wall_s = (now - self._last) if self._last is not None else None
        self._last = now
        flops = self.step_flops + self._pending_flops
        self._pending_flops = 0
        if wall_s is None or wall_s <= 0 or flops <= 0:
            return self._rate_ema
        rate = flops / wall_s
        self._rate_ema = (rate if self._rate_ema is None
                          else (1 - self._alpha) * self._rate_ema
                          + self._alpha * rate)
        REGISTRY.gauge("kf_model_flops_s").set(self._rate_ema)
        if self.peak_flops:
            self.mfu = self._rate_ema / self.peak_flops
            REGISTRY.gauge("kf_mfu").set(self.mfu)
        if phases:
            record_phases(phases)
        if timeline.enabled():
            timeline.event(
                "xray", "mfu-sample", rank=self.rank,
                flops=flops, wall_s=round(wall_s, 6),
                flops_s=round(self._rate_ema, 3),
                mfu=(round(self.mfu, 5) if self.mfu is not None else None),
                **{f"phase_{k}": round(v, 6)
                   for k, v in (phases or {}).items()})
        return self._rate_ema
