"""Tensor fusion: flatten a pytree into one contiguous buffer and back.

Parity with reference ``kungfu/tensorflow/ops/__init__.py:29-46`` (fuse /
defuse) and the fused ``ModelBuffer`` (``model_buffer.hpp:13-53``): small
tensors are packed into one buffer so a collective or a gossip transfer is
one launch instead of hundreds.

``batch_axes`` preserves leading stacked axes (the eager communicator's
per-peer axis) outside the flattening.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class FuseTreeDef(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    sizes: Tuple[int, ...]
    fused_dtype: Any


def fuse(tree, batch_axes: int = 0, dtype=None):
    """Flatten every leaf (beyond ``batch_axes`` leading dims) and concat.

    Returns ``(buffer, FuseTreeDef)``.  All leaves are cast to a common
    ``dtype`` (default: result dtype promotion across leaves)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        raise ValueError("fuse of empty tree")
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    if dtype is None:
        dtype = jnp.result_type(*dtypes)
    flat = [
        jnp.reshape(l, l.shape[:batch_axes] + (-1,)).astype(dtype) for l in leaves
    ]
    sizes = tuple(f.shape[-1] for f in flat)
    buf = jnp.concatenate(flat, axis=-1)
    return buf, FuseTreeDef(treedef, shapes, dtypes, sizes, dtype)


def defuse(buf, spec: FuseTreeDef, batch_axes: int = 0):
    """Inverse of :func:`fuse`."""
    offsets = np.cumsum([0] + list(spec.sizes))
    leaves = []
    for i, (shape, dt) in enumerate(zip(spec.shapes, spec.dtypes)):
        piece = jax.lax.slice_in_dim(
            buf, offsets[i], offsets[i + 1], axis=buf.ndim - 1
        )
        leaves.append(jnp.reshape(piece, shape).astype(dt))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)
