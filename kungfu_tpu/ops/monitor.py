"""In-graph training-statistics monitors.

Parity with the reference's monitoring ops: gradient noise scale
(``NoiseScale`` op, ``tensorflow/ops/cpu/collective.cpp:212-260``;
estimator from the OpenAI GNS paper, used by
``optimizers/grad_noise_scale.py``) and gradient variance
(``optimizers/grad_variance.py``).  Pure JAX — on TPU these are a few
fused reductions piggybacking on the allreduce, essentially free.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from kungfu_tpu.monitor import pulse
from kungfu_tpu.ops.collective import all_reduce, peer_size


def _sq_norm(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)


def host_noise_scale(engine, local_flat, avg_flat, local_batch_size):
    """Gradient-noise-scale estimate over the HOST collective plane (the
    multi-process analog of :func:`global_noise_scale` — same OpenAI
    estimator, with the cross-peer mean of the local square norms running
    on the :class:`~kungfu_tpu.comm.engine.CollectiveEngine`).

    ``local_flat``: this worker's fused local gradient (numpy);
    ``avg_flat``: the allreduced MEAN gradient the step just applied.
    Every worker must call this at the same step point — the inner mean
    is a collective.  Returns the raw per-step estimate (the scalar
    math is ONE shared implementation, :func:`kungfu_tpu.monitor.pulse.
    noise_scale`), or ``None`` on a single worker where the two-batch
    estimator is undefined — same no-signal contract as the in-graph
    estimator.  Smooth with an EMA before acting on it (reference
    ``grad_noise_scale.py:41-88``)."""
    import numpy as np

    n = len(engine.peers)
    g_local_sq = float(np.sum(np.square(np.asarray(local_flat, np.float64))))
    g_local_sq = float(
        engine.all_reduce(
            np.array([g_local_sq], np.float64), op="mean", record=False
        )[0]
    )
    g_global_sq = float(np.sum(np.square(np.asarray(avg_flat, np.float64))))
    return pulse.noise_scale(g_local_sq, g_global_sq, local_batch_size, n)


def global_noise_scale(local_grads, avg_grads, local_batch_size, axis):
    """Gradient noise scale estimate from one step.

    ``local_grads``: this peer's gradients (batch ``b_small``);
    ``avg_grads``: the allreduced mean gradients (batch ``b_big = n*b_small``).

    Returns the raw (noisy) per-step estimate ``S / |G|^2``; smooth it with
    :func:`kungfu_tpu.ops.state.exponential_moving_average` as the reference
    does (``grad_noise_scale.py:41-88``).  ``None`` (a trace-time Python
    value — the axis size is static) on a single peer, matching
    :func:`host_noise_scale`: with ``b_small == b_big`` the estimator
    divides by zero, and any number it returned would be a lie."""
    n = peer_size(axis)
    if int(n) <= 1:
        return None
    b_small = jnp.asarray(local_batch_size, jnp.float32)
    b_big = b_small * n
    g_local_sq = _sq_norm(local_grads)
    # average the local square norms so the estimate is symmetric across peers
    g_local_sq = all_reduce(g_local_sq, axis, op="mean")
    g_global_sq = _sq_norm(avg_grads)
    g2 = (b_big * g_global_sq - b_small * g_local_sq) / (b_big - b_small)
    s = (g_local_sq - g_global_sq) / (1.0 / b_small - 1.0 / b_big)
    return s / (jnp.abs(g2) + pulse.GNS_EPS)


def group_all_reduce_with_variance(grads, axis) -> Tuple:
    """Mean-allreduce gradients and simultaneously estimate the cross-peer
    gradient variance  E_i |g_i - gـavg|^2  (one extra psum of squares).

    Returns ``(avg_grads, variance_scalar)``."""
    avg = all_reduce(grads, axis, op="mean")
    local_sq = _sq_norm(grads)
    mean_sq = all_reduce(local_sq, axis, op="mean")
    var = mean_sq - _sq_norm(avg)
    return avg, jnp.maximum(var, 0.0)
