"""In-graph training-statistics monitors.

Parity with the reference's monitoring ops: gradient noise scale
(``NoiseScale`` op, ``tensorflow/ops/cpu/collective.cpp:212-260``;
estimator from the OpenAI GNS paper, used by
``optimizers/grad_noise_scale.py``) and gradient variance
(``optimizers/grad_variance.py``).  Pure JAX — on TPU these are a few
fused reductions piggybacking on the allreduce, essentially free.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from kungfu_tpu.ops.collective import all_reduce, peer_size


def _sq_norm(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)


def global_noise_scale(local_grads, avg_grads, local_batch_size, axis):
    """Gradient noise scale estimate from one step.

    ``local_grads``: this peer's gradients (batch ``b_small``);
    ``avg_grads``: the allreduced mean gradients (batch ``b_big = n*b_small``).

    Returns the raw (noisy) per-step estimate ``S / |G|^2``; smooth it with
    :func:`kungfu_tpu.ops.state.exponential_moving_average` as the reference
    does (``grad_noise_scale.py:41-88``)."""
    n = peer_size(axis)
    b_small = jnp.asarray(local_batch_size, jnp.float32)
    b_big = b_small * n
    g_local_sq = _sq_norm(local_grads)
    # average the local square norms so the estimate is symmetric across peers
    g_local_sq = all_reduce(g_local_sq, axis, op="mean")
    g_global_sq = _sq_norm(avg_grads)
    g2 = (b_big * g_global_sq - b_small * g_local_sq) / (b_big - b_small)
    s = (g_local_sq - g_global_sq) / (1.0 / b_small - 1.0 / b_big)
    return s / (jnp.abs(g2) + 1e-30)


def group_all_reduce_with_variance(grads, axis) -> Tuple:
    """Mean-allreduce gradients and simultaneously estimate the cross-peer
    gradient variance  E_i |g_i - gـavg|^2  (one extra psum of squares).

    Returns ``(avg_grads, variance_scalar)``."""
    avg = all_reduce(grads, axis, op="mean")
    local_sq = _sq_norm(grads)
    mean_sq = all_reduce(local_sq, axis, op="mean")
    var = mean_sq - _sq_norm(avg)
    return avg, jnp.maximum(var, 0.0)
