"""Collective ops for use inside jit/shard_map code.

Each takes ``axis`` — one name or tuple of mesh axis names (use
``Communicator.axis`` for the global world).  These lower to single XLA HLO
collectives; no chunking/strategy machinery is needed on TPU (the compiler
tiles transfers over the ICI torus; cf. reference
``session/session.go:292-321`` which hand-chunks into 1 MiB pieces).
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from kungfu_tpu.utils.jaxcompat import axis_size

Axis = Union[str, Tuple[str, ...]]


def peer_rank(axis: Axis):
    """Global index along ``axis`` (reference `Rank` op, topology.cpp)."""
    if isinstance(axis, str):
        return jax.lax.axis_index(axis)
    idx = jnp.int32(0)
    for a in axis:
        idx = idx * axis_size(a) + jax.lax.axis_index(a)
    return idx


def peer_size(axis: Axis) -> int:
    if isinstance(axis, str):
        return axis_size(axis)
    n = 1
    for a in axis:
        n *= axis_size(a)
    return n


def all_reduce(x, axis: Axis, op: str = "sum"):
    """Allreduce one tensor or pytree across ``axis``."""
    if op == "sum":
        f = lambda a: jax.lax.psum(a, axis)
    elif op == "mean":
        f = lambda a: jax.lax.pmean(a, axis)
    elif op == "min":
        f = lambda a: jax.lax.pmin(a, axis)
    elif op == "max":
        f = lambda a: jax.lax.pmax(a, axis)
    else:
        raise ValueError(f"unsupported op {op!r}")
    return jax.tree_util.tree_map(f, x)


def group_all_reduce(tensors, axis: Axis, op: str = "sum"):
    """Allreduce a pytree of gradients in one logical group
    (reference ``group_all_reduce``, collective.py:67-69).  XLA fuses the
    resulting psums; no manual bucketing required."""
    return all_reduce(tensors, axis, op)


def all_gather(x, axis: Axis, tiled: bool = False):
    return jax.tree_util.tree_map(
        lambda a: jax.lax.all_gather(a, axis, axis=0, tiled=tiled), x
    )


def broadcast(x, axis: Axis, root: int = 0):
    """Every peer gets peer ``root``'s value."""

    def leaf(a):
        # where() not mask-multiply: a NaN/Inf on a non-root peer must not
        # poison the psum (0*NaN == NaN) — broadcast exists precisely to
        # recover diverged replicas from root's good copy.
        contrib = jnp.where(peer_rank(axis) == root, a, jnp.zeros_like(a))
        return jax.lax.psum(contrib, axis)

    return jax.tree_util.tree_map(leaf, x)


def barrier_value(axis: Axis):
    """A data dependency that forces cross-peer synchronization."""
    return jax.lax.psum(jnp.int32(1), axis)
