"""Stateful scalar ops, functional style.

Parity with the reference's stateful TF kernels ``Counter`` and
``ExponentialMovingAverage`` (``tensorflow/ops/cpu/state.cpp:6-78``).  In
JAX state is explicit: each op is ``new_state, value = f(state, ...)`` and
the state rides in the optimizer/train state pytree.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp


class CounterState(NamedTuple):
    step: jnp.ndarray  # int32


def counter(state: Optional[CounterState] = None, incr: int = 1):
    """Returns ``(new_state, value_before_increment)`` — matches the
    reference op which emits the pre-increment count."""
    if state is None:
        return CounterState(jnp.asarray(incr, jnp.int32)), jnp.asarray(0, jnp.int32)
    return CounterState(state.step + incr), state.step


class EMAState(NamedTuple):
    value: jnp.ndarray
    initialized: jnp.ndarray  # bool


def ema_init(shape=(), dtype=jnp.float32) -> EMAState:
    return EMAState(jnp.zeros(shape, dtype), jnp.asarray(False))


def exponential_moving_average(state: EMAState, x, alpha: float = 0.01):
    """``v <- (1-alpha)*v + alpha*x``; first sample initializes v=x
    (reference ``state.cpp`` EMA semantics).  Returns ``(state, value)``."""
    x = jnp.asarray(x, state.value.dtype)
    new = jnp.where(state.initialized, (1 - alpha) * state.value + alpha * x, x)
    st = EMAState(new, jnp.asarray(True))
    return st, new
