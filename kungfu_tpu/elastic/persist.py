"""kf-persist: the durable state plane — async sharded checkpoints,
manifest selection, and checkpoint-shape-agnostic cold restore.

Every recovery rung below this one (shrink, slice loss, stage re-carve)
assumes *some* rank survives with its in-memory boundary.  A whole-job
preemption — the dominant failure mode for real TPU capacity — loses
every process at once, so the last line of defense must be durable
storage.  This module makes that cheap enough to run continuously and
exact enough to restore onto a *different* world:

* **Async off the step path** (the kf-overlap handle pattern): each rank
  streams its committed :class:`~kungfu_tpu.elastic.reshard.ZeroBoundary`
  shard to the manifest directory on a single ordered writer thread.
  :meth:`PersistPlane.persist_async` issues and returns a
  :class:`PersistHandle`; :meth:`PersistPlane.persist_fence` settles
  every in-flight write at the next boundary.  A persist handle may not
  straddle ``elastic_step``, a shrink, or a re-carve — the
  ``handle-discipline`` lint enforces it like any other async handle.
* **Ring-buddy de-duplication for free**: in chunk mode the boundary's
  ``_vec`` holds exactly this rank's own ``ceil(total/n)`` chunk — the
  buddy mirror lives separately and is *never* written, because its
  owner writes the same bytes under its own rank file.  Total manifest
  bytes are ``O(total)``, not ``O(total * replication)``.
* **Torn writes are detectable, never restorable**: each rank's segment
  file is written atomically (tempfile + ``os.replace``) and then
  *committed* by an adjacent ``rank<r>.ok.json`` carrying its byte count
  and blake2b content digest.  A manifest is **complete** iff its
  ``meta.json`` and every old rank's (segment, commit record) pair are
  present and the digests verify — :func:`newest_complete_manifest`
  skips a newer partial/torn manifest in favor of an older complete one.
* **Shape-agnostic restore**: :func:`restore_from_manifest` re-carves
  the persisted old-geometry chunks into any new world size through the
  same pure :func:`~kungfu_tpu.parallel.zero.reshard_plan` the live
  re-carve uses — file reads replace wire segments, the math is
  identical, so a cold restart onto a larger or smaller world is
  bitwise what a fixed-world replay would have produced.  Stage
  (pipeline) geometry re-carves the same way through
  :func:`stage_restore_plan` (the pure ``stage_recarve_plan``).
* **Restore-time agreement**: after a cold restart every rank must
  restore the SAME manifest — concurrent GC or a manifest completing
  mid-scan could split the vote.  :meth:`PersistPlane.agree_manifest`
  is the one restore-time wire exchange: rank 0 picks and fans out, the
  rest block on it.  Registered in
  ``analysis/commgraph.py::ENTRYPOINTS`` and proto-verified over every
  geometry ≤ 16 ranks like every other protocol (docs/lint.md).

Durability-before-report (checkpoint.py doctrine) applies: anything
that advertises progress past a manifest must ``persist_fence()``
first.  Observability: ``kf_ckpt_last_step`` / ``kf_ckpt_age_seconds``
/ ``kf_ckpt_bytes_total`` / ``kf_ckpt_period_seconds`` gauges flow
through the aggregator to ``/cluster`` and kftop's ``CKPT STALE``
alarm; ``ckpt`` timeline events mark issue/done/restore.

See docs/persistence.md for the manifest format and the goodput
methodology (``bench.py --persist``).
"""

from __future__ import annotations

import hashlib
import io
import json
import math
import os
import re
import shutil
import tempfile
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from kungfu_tpu.elastic.reshard import ZeroBoundary, _recv_or_fail
from kungfu_tpu.monitor import timeline
from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.utils import envs
from kungfu_tpu.utils.log import get_logger

_log = get_logger("persist")

#: manifest directory name: ``step_<NNNNNNNN>.v<cluster_version>``
MANIFEST_RE = re.compile(r"^step_(\d{8})\.v(\d+)$")
META_NAME = "meta.json"
#: manifest format version (meta.json "format"); bump on layout changes
FORMAT = 1

#: persist-plane gauges (monitor/registry.py METRIC_HELP documents them)
G_LAST_STEP = "kf_ckpt_last_step"
G_AGE = "kf_ckpt_age_seconds"
G_BYTES = "kf_ckpt_bytes_total"
G_PERIOD = "kf_ckpt_period_seconds"


class ManifestError(RuntimeError):
    """A manifest failed verification: torn segment, digest mismatch,
    or missing commit record.  Callers restore an OLDER complete
    manifest instead — a partial write must never become state."""


def _npz_safe(arr: np.ndarray) -> np.ndarray:
    """bfloat16 (ml_dtypes) does not survive ``.npz`` — widen to f32
    (lossless; the recorded dtype name casts it back on restore)."""
    if arr.dtype.name == "bfloat16" or arr.dtype.kind == "V":
        return arr.astype(np.float32)
    return arr


def _np_dtype(name: str) -> np.dtype:
    """dtype from its ``.name`` including ml_dtypes extension types."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _atomic_write(path: str, data: bytes) -> None:
    """Write-then-rename so a reader never observes a half-written
    file (the checkpoint.py pattern); a crash leaves only a ``.tmp``."""
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _digest_file(path: str) -> Tuple[str, int]:
    """(blake2b hexdigest, byte count) of a file's current content."""
    h = hashlib.blake2b(digest_size=16)
    n = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            h.update(block)
            n += len(block)
    return h.hexdigest(), n


def manifest_name(step: int, cluster_version: int) -> str:
    return f"step_{int(step):08d}.v{int(cluster_version)}"


def _seg_path(mdir: str, rank: int) -> str:
    return os.path.join(mdir, f"rank{rank}.seg.npz")


def _ok_path(mdir: str, rank: int) -> str:
    return os.path.join(mdir, f"rank{rank}.ok.json")


def load_manifest_meta(mdir: str) -> dict:
    with open(os.path.join(mdir, META_NAME), "rb") as f:
        return json.loads(f.read().decode())


def verify_rank_file(mdir: str, rank: int, *, digest: bool = True) -> dict:
    """Verify old rank ``rank``'s (segment, commit record) pair; returns
    the parsed commit record.  Raises :class:`ManifestError` on a torn
    or tampered segment — the digest is the commit.  ``digest=False``
    checks the recorded byte count against the file size only (an
    atomic-rename filesystem can't leave a right-sized wrong-content
    segment short of corruption): the cheap mode for GC's am-I-allowed-
    to-delete scans, never for choosing a restore source."""
    okp, segp = _ok_path(mdir, rank), _seg_path(mdir, rank)
    if not os.path.isfile(okp):
        raise ManifestError(f"{mdir}: rank {rank} has no commit record")
    with open(okp, "rb") as f:
        ok = json.loads(f.read().decode())
    if not os.path.isfile(segp):
        raise ManifestError(f"{mdir}: rank {rank} segment file missing")
    if not digest:
        nbytes = os.stat(segp).st_size
        if nbytes != ok.get("nbytes"):
            raise ManifestError(
                f"{mdir}: rank {rank} segment is short "
                f"({nbytes} != committed {ok.get('nbytes')} bytes)")
        return ok
    hexd, nbytes = _digest_file(segp)
    if nbytes != ok.get("nbytes") or hexd != ok.get("blake2b"):
        raise ManifestError(
            f"{mdir}: rank {rank} segment is torn/corrupt "
            f"({nbytes} bytes, digest {hexd[:12]}… != committed "
            f"{ok.get('nbytes')} bytes, {str(ok.get('blake2b'))[:12]}…)")
    return ok


def manifest_complete(mdir: str, *, digest: bool = True) -> bool:
    """A manifest is restorable iff its meta and EVERY old rank's
    digest-verified segment landed.  Anything less is a partial write
    in progress or a preemption mid-persist — never restored."""
    try:
        meta = load_manifest_meta(mdir)
    except (OSError, ValueError):
        return False
    try:
        for r in range(int(meta["old_n"])):
            verify_rank_file(mdir, r, digest=digest)
    except (ManifestError, KeyError, ValueError):
        return False
    return True


def manifest_dirs(root: str) -> List[Tuple[int, int, str]]:
    """Every manifest directory under ``root`` as sorted
    ``[(step, cluster_version, path)]`` (oldest first)."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in sorted(os.listdir(root)):
        m = MANIFEST_RE.match(name)
        if m:
            out.append((int(m.group(1)), int(m.group(2)),
                        os.path.join(root, name)))
    out.sort()
    return out


def newest_complete_manifest(root: str) -> Optional[str]:
    """The restore source: the newest manifest that verifies complete.
    A newer partial one (preempted mid-persist) is skipped — restoring
    it would blend a torn write into training state."""
    for _, _, path in reversed(manifest_dirs(root)):
        if manifest_complete(path):
            return path
    return None


def gc_manifests(root: str, keep: int) -> List[str]:
    """Keep the newest ``keep`` (≥ 1) COMPLETE manifests; drop older
    complete ones and any partial older than the newest complete (a
    partial newer than it may still be landing and is left alone).  The
    only complete manifest is never deleted — it is the last restore
    point.  Returns the removed paths."""
    keep = max(1, int(keep))
    entries = manifest_dirs(root)
    # size-only completeness: GC runs on the writer thread after EVERY
    # persist, and digest-verifying keep+ manifests x old_n segments
    # there would put O(state bytes) of hashing on a 1-core host's step
    # path; deciding what to KEEP needs only will-this-restore-attempt-
    # consider-it, and restore itself still full-verifies
    complete = [(s, v, p) for (s, v, p) in entries
                if manifest_complete(p, digest=False)]
    if not complete:
        return []
    survivors = {p for _, _, p in complete[-keep:]}
    newest_key = complete[-1][:2]
    removed = []
    for s, v, p in entries:
        if p in survivors or (s, v) > newest_key:
            continue
        try:
            shutil.rmtree(p)
            removed.append(p)
        except OSError:
            pass  # concurrent GC: someone else removed it first
    return removed


# -- restore -----------------------------------------------------------------
@dataclass
class RestoredState:
    """One rank's re-carved view of a manifest: the geometry it was
    restored INTO, the ZeRO vector chunks for that geometry, the
    replicated scalar optimizer leaves, and the named replicated
    arrays (params, counters, KV snapshots — whatever the trainer
    persisted)."""

    step: int
    cluster_version: int
    total: int
    new_n: int
    my_new: int
    chunk: int
    vec: Dict[int, np.ndarray] = field(default_factory=dict)
    scal: Dict[int, np.ndarray] = field(default_factory=dict)
    replicated: Dict[str, np.ndarray] = field(default_factory=dict)
    meta: dict = field(default_factory=dict)

    def install_into_boundary(self, boundary: ZeroBoundary) -> None:
        """Seed a :class:`ZeroBoundary` with the restored carve so the
        live elastic machinery (buddy mirrors, re-carve on the next
        membership change) continues from the restored step.  Leaf
        classification is by ndim (the boundary contract), so the
        scalar leaves ride along in the same tree."""
        tree = {f"s{i}": a for i, a in sorted(self.scal.items())}
        tree.update({f"v{i}": a for i, a in sorted(self.vec.items())})
        boundary.commit_local(self.step, tree, self.total, self.new_n,
                              self.my_new)


def restore_from_manifest(mdir: str, my_new: int, new_n: int
                          ) -> RestoredState:
    """Checkpoint-shape-agnostic restore: assemble new rank ``my_new``'s
    chunk of a ``new_n``-rank world from a manifest written under ANY
    old geometry, by slicing the persisted old chunks along the same
    pure :func:`~kungfu_tpu.parallel.zero.reshard_plan` the live
    re-carve exchanges over the wire.  Purely file-driven — every new
    rank computes the identical plan and reads only the old rank files
    its segments live in.  Every touched file is digest-verified first
    (:class:`ManifestError` on a torn segment)."""
    from kungfu_tpu.parallel.zero import reshard_plan

    if new_n < 1 or not 0 <= my_new < new_n:
        raise ValueError(f"bad restore geometry rank {my_new} of {new_n}")
    meta = load_manifest_meta(mdir)
    if int(meta.get("format", 0)) != FORMAT:
        raise ManifestError(
            f"{mdir}: manifest format {meta.get('format')!r} != {FORMAT}")
    step = int(meta["step"])
    total, old_n, oc = int(meta["total"]), int(meta["old_n"]), \
        int(meta["chunk"])
    new_chunk = math.ceil(total / new_n) if total else 0
    plan = reshard_plan(total, old_n, new_n) if total else []
    lo = my_new * new_chunk

    loaded: Dict[int, Tuple[dict, dict]] = {}

    def rank_file(r: int) -> Tuple[dict, dict]:
        if r not in loaded:
            ok = verify_rank_file(mdir, r)
            with np.load(_seg_path(mdir, r), allow_pickle=False) as z:
                loaded[r] = ({k: z[k] for k in z.files}, ok)
        return loaded[r]

    # replicated + scalar leaves live in the lowest rank's file (they
    # have no owner: any copy is THE copy — rank 0 writes it once)
    z0, ok0 = rank_file(0)
    repl = {
        k[2:]: np.asarray(z0[k],
                          dtype=_np_dtype(ok0["repl_dtypes"][k[2:]]))
        for k in z0 if k.startswith("r_")
    }
    scal = {
        int(k[2:]): np.asarray(z0[k],
                               dtype=_np_dtype(ok0["scal_dtypes"][k[2:]]))
        for k in z0 if k.startswith("s_")
    }
    vec_dtypes = {int(i): _np_dtype(name)
                  for i, name in ok0.get("vec_dtypes", {}).items()}
    vec: Dict[int, np.ndarray] = {}
    if old_n == 1 and new_n == 1:
        # degenerate round-trip: pass the stored leaves through as-is.
        # This is also the only restorable geometry for full-mode
        # (device-plane) manifests whose leaves keep their own shapes —
        # the flat re-carve below is defined for the host-plane ZeRO
        # representation (every vector leaf a length-``total`` vector).
        for i, dt in vec_dtypes.items():
            vec[i] = np.asarray(z0[f"v{i}"], dtype=dt)
    else:
        vec = {i: np.zeros((new_chunk,), dt)
               for i, dt in vec_dtypes.items()}
        for (o, r, s, ln) in plan:
            if r != my_new:
                continue
            z, _ = rank_file(o)
            off = o * oc
            for i in vec:
                src = np.asarray(z[f"v{i}"], dtype=vec[i].dtype)
                if src.ndim != 1:
                    raise ManifestError(
                        f"{mdir}: leaf {i} has shape {src.shape}; only "
                        "flat (host-plane ZeRO) manifests re-carve onto "
                        "a different world size")
                got = src[s - off:s - off + ln]
                if got.shape[0] != ln:
                    raise ManifestError(
                        f"{mdir}: rank {o} chunk of leaf {i} is short — "
                        f"segment [{s},{s + ln}) falls outside it")
                vec[i][s - lo:s - lo + ln] = got
    timeline.event("ckpt", "restore", step=step, old_n=old_n, new_n=new_n,
                   rank=my_new, manifest=os.path.basename(mdir))
    _log.info("restored manifest %s (step %d, %d->%d ranks) as rank %d",
              mdir, step, old_n, new_n, my_new)
    return RestoredState(
        step=step, cluster_version=int(meta.get("cluster_version", 0)),
        total=total, new_n=new_n, my_new=my_new, chunk=new_chunk,
        vec=vec, scal=scal, replicated=repl, meta=meta)


def stage_restore_plan(n_layers: int, old_stages: int, new_stages: int
                       ) -> List[Tuple[int, int, int]]:
    """The pipeline-stage analog of the restore re-carve:
    ``[(unit, old_stage, new_stage)]`` telling a new stage which layer
    units (and the embed/final blocks, units -1/-2) to load from which
    OLD stage's persisted file — the pure
    :func:`~kungfu_tpu.parallel.pp.stage_recarve_plan`, so restoring a
    checkpoint written under S stages onto S' stages moves exactly the
    units the live elastic stage re-carve would have."""
    from kungfu_tpu.parallel.pp import stage_recarve_plan

    return stage_recarve_plan(n_layers, old_stages, new_stages)


# -- the async persist plane -------------------------------------------------
class PersistHandle:
    """One in-flight durable write (the kf-overlap handle shape):
    :meth:`wait` blocks until the manifest segment is durable and
    returns the manifest path, re-raising any write failure."""

    def __init__(self, fut: "Future[str]", step: int, mdir: str):
        self._fut = fut
        self.step = int(step)
        self.manifest = mdir

    def wait(self, timeout: Optional[float] = None) -> str:
        try:
            return self._fut.result(timeout)
        except _FutureTimeout:
            raise TimeoutError(
                f"persist of step {self.step} still in flight after "
                f"{timeout}s") from None

    def done(self) -> bool:
        return self._fut.done()


class PersistPlane:
    """Per-rank durable state plane over one manifest root.

    ``rank`` is this worker's rank in the CURRENT world; in chunk mode
    it must equal the boundary's ``my_old`` (one process per rank — the
    host-plane training contract).  Knobs default from the
    persist env registry (:func:`kungfu_tpu.utils.envs.
    persist_knobs`): ``period_s`` seconds between issued persists (0 =
    every commit), ``depth`` bound on in-flight handles, ``keep``
    complete manifests retained by GC (rank 0 runs GC after each
    durable write)."""

    def __init__(self, root: str, rank: int, *,
                 cluster_version: int = 0,
                 period_s: Optional[float] = None,
                 depth: Optional[int] = None,
                 keep: Optional[int] = None):
        knobs = envs.persist_knobs()
        self.root = root
        self.rank = int(rank)
        self.cluster_version = int(cluster_version)
        self.period_s = float(knobs["period_s"] if period_s is None
                              else period_s)
        self.depth = max(1, int(knobs["depth"] if depth is None else depth))
        self.keep = max(1, int(knobs["keep"] if keep is None else keep))
        self._lock = threading.Lock()
        self._inflight: "deque[PersistHandle]" = deque()
        self._writer = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kf-persist")
        self._last_issue_t: Optional[float] = None
        self._last_done_t = time.monotonic()
        os.makedirs(root, exist_ok=True)
        REGISTRY.gauge(G_PERIOD).set(float(self.period_s))
        self.touch_age()

    # -- gauges -----------------------------------------------------------
    def touch_age(self) -> None:
        """Refresh ``kf_ckpt_age_seconds`` = seconds since the last
        DURABLE write.  Called on every commit/fence so the gauge keeps
        growing while training runs with a wedged writer — the signal
        kftop's CKPT STALE alarm fires on."""
        with self._lock:
            age = time.monotonic() - self._last_done_t
        REGISTRY.gauge(G_AGE).set(float(age))

    # -- issue ------------------------------------------------------------
    def due(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            last = self._last_issue_t
        return last is None or (now - last) >= self.period_s

    def commit(self, step: int, boundary: Optional[ZeroBoundary] = None,
               replicated: Optional[Dict[str, np.ndarray]] = None
               ) -> Optional[PersistHandle]:
        """Period-gated persist at a committed step boundary: issues a
        durable write when the persist period has elapsed (always, when
        ``period_s`` is 0) and returns its handle, else ``None``.  The
        returned handle is also tracked internally — a plain
        :meth:`persist_fence` at the next boundary settles it."""
        self.touch_age()
        if not self.due():
            return None
        return self.persist_async(step, boundary, replicated)

    def persist_async(self, step: int,
                      boundary: Optional[ZeroBoundary] = None,
                      replicated: Optional[Dict[str, np.ndarray]] = None
                      ) -> PersistHandle:
        """Issue one durable write of this rank's shard of step
        ``step``: the boundary's OWN vector chunks (the ring-buddy
        mirror is skipped — its owner writes those bytes), plus — on
        rank 0 only — the replicated scalar leaves and every named
        ``replicated`` array.  Snapshot copies are taken HERE,
        synchronously (donated-buffer discipline); serialization and
        the atomic writes run on the single ordered writer thread.
        Issuing past the depth bound blocks on the oldest handle
        (local backpressure, the kf-overlap window)."""
        writes_repl = self.rank == 0
        noop = False
        if boundary is not None:
            (bstep, total, old_n, my_old, chunk, full_mode, vec, scal) = \
                boundary.export_carve()
            if bstep is None:
                raise ValueError("persist before any boundary commit")
            if full_mode:
                # every rank holds the full vectors — rank 0 writes them
                # once under a 1-rank geometry; the rest add nothing
                old_n, my_old, chunk = 1, 0, int(total)
                noop = self.rank != 0
        else:
            # single-writer mode (serve workers, driver-side state):
            # only plane rank 0 persists; the manifest is 1-rank shaped
            total, old_n, my_old, chunk = 0, 1, 0, 0
            vec, scal = {}, {}
            noop = self.rank != 0
        mdir = os.path.join(self.root,
                            manifest_name(step, self.cluster_version))
        with self._lock:
            self._last_issue_t = time.monotonic()
        if noop:
            done: "Future[str]" = Future()
            done.set_result(mdir)
            return PersistHandle(done, step, mdir)
        # dtype names are recorded BEFORE the npz-safe widening so a
        # bfloat16 carve casts back bitwise on restore
        vec_dtypes = {str(i): np.asarray(a).dtype.name
                      for i, a in vec.items()}
        vec_snap = {i: np.array(_npz_safe(np.asarray(a)))
                    for i, a in vec.items()}
        scal_dtypes = {str(i): np.asarray(a).dtype.name
                       for i, a in scal.items()} if writes_repl else {}
        scal_snap = {str(i): np.array(_npz_safe(np.asarray(a)))
                     for i, a in scal.items()} if writes_repl else {}
        repl_snap: Dict[str, np.ndarray] = {}
        repl_dtypes: Dict[str, str] = {}
        if writes_repl and replicated:
            for name, a in replicated.items():
                a = np.asarray(a)
                repl_dtypes[name] = a.dtype.name
                repl_snap[name] = np.array(_npz_safe(a))
        meta = {
            "format": FORMAT, "step": int(step),
            "cluster_version": self.cluster_version,
            "total": int(total), "old_n": int(old_n), "chunk": int(chunk),
        }
        while True:
            with self._lock:
                if len(self._inflight) < self.depth:
                    break
                oldest = self._inflight.popleft()
            oldest.wait()
        timeline.event("ckpt", "persist-issue", step=int(step),
                       rank=self.rank, leaves=len(vec_snap))
        fut = self._writer.submit(
            self._write, int(step), mdir, meta, self.rank == 0, my_old,
            vec_snap, vec_dtypes, scal_snap, scal_dtypes, repl_snap,
            repl_dtypes)
        h = PersistHandle(fut, step, mdir)
        with self._lock:
            self._inflight.append(h)
        return h

    def _write(self, step: int, mdir: str, meta: dict, writes_meta: bool,
               my_old: int, vec, vec_dtypes, scal, scal_dtypes,
               repl, repl_dtypes) -> str:
        os.makedirs(mdir, exist_ok=True)
        if writes_meta:
            _atomic_write(os.path.join(mdir, META_NAME),
                          json.dumps(meta, sort_keys=True).encode())
        bio = io.BytesIO()
        arrays = {f"v{i}": a for i, a in vec.items()}
        arrays.update({f"s_{k}": a for k, a in scal.items()})
        arrays.update({f"r_{k}": a for k, a in repl.items()})
        np.savez(bio, **arrays)
        segp = _seg_path(mdir, my_old)
        payload = bio.getvalue()
        # digest the buffer we are about to fsync, not a re-read of the
        # file: same commit semantics (the rename only lands after the
        # bytes), half the hashing on the 1-writer-thread host
        digest = hashlib.blake2b(payload, digest_size=16).hexdigest()
        nbytes = len(payload)
        _atomic_write(segp, payload)
        ok = {
            "rank": my_old, "nbytes": nbytes, "blake2b": digest,
            "vec_dtypes": vec_dtypes, "scal_dtypes": scal_dtypes,
            "repl_dtypes": repl_dtypes,
        }
        # the ok record is the commit: it lands only after the segment
        # bytes are durable, so a torn segment can never verify
        _atomic_write(_ok_path(mdir, my_old),
                      json.dumps(ok, sort_keys=True).encode())
        with self._lock:
            self._last_done_t = time.monotonic()
        REGISTRY.gauge(G_LAST_STEP).set(float(step))
        REGISTRY.gauge(G_AGE).set(0.0)
        g = REGISTRY.gauge(G_BYTES)
        g.set(float(g.value) + float(nbytes))
        timeline.event("ckpt", "persist-done", step=step, rank=self.rank,
                       nbytes=nbytes, manifest=os.path.basename(mdir))
        if self.rank == 0:
            gc_manifests(self.root, self.keep)
        return mdir

    # -- fence ------------------------------------------------------------
    def persist_fence(self, timeout: Optional[float] = None) -> int:
        """Settle every in-flight persist handle (re-raising the first
        write failure); returns how many were waited.  This is the
        boundary fence of the handle pattern: call it before
        ``elastic_step``, a shrink/re-carve, or reporting progress that
        relies on the newest manifest being durable."""
        n = 0
        while True:
            with self._lock:
                if not self._inflight:
                    break
                h = self._inflight.popleft()
            h.wait(timeout)
            n += 1
        self.touch_age()
        return n

    def close(self) -> None:
        self.persist_fence()
        self._writer.shutdown(wait=True)

    # -- restore-time agreement (proto-verified; ENTRYPOINTS) -------------
    def agree_manifest(self, chan, workers, my_rank: int,
                       step: int = -1, version: int = -1
                       ) -> Tuple[int, int]:
        """Restore-time manifest agreement: rank 0 has scanned the
        manifest root (:func:`choose_manifest`) and fans its choice
        ``(step, version)`` out to every other rank; everyone else
        blocks on rank 0's frame before touching the directory.
        ``(-1, -1)`` = fresh start (no complete manifest) — agreed the
        same way, so no rank restores what another ignores."""
        n = len(workers)
        name = f"kf.persist.agree.v{self.cluster_version}"
        if my_rank == 0:
            payload = json.dumps(
                {"step": int(step), "version": int(version)}).encode()
            for r in range(1, n):
                chan.send(workers[r], name, payload)
            return int(step), int(version)
        blob = _recv_or_fail(chan, workers[0], 0, "persist-agree", name)
        got = json.loads(bytes(blob).decode())
        return int(got["step"]), int(got["version"])


def agreed_manifest_path(root: str, step: int, version: int
                         ) -> Optional[str]:
    """Path of the agreed manifest (``None`` for the fresh-start
    sentinel ``(-1, -1)``)."""
    if step < 0:
        return None
    return os.path.join(root, manifest_name(step, version))


def choose_manifest(root: str) -> Tuple[int, int]:
    """Rank 0's scan for :meth:`PersistPlane.agree_manifest`:
    ``(step, cluster_version)`` of the newest complete manifest, or the
    fresh-start sentinel ``(-1, -1)``."""
    path = newest_complete_manifest(root)
    if path is None:
        return -1, -1
    m = MANIFEST_RE.match(os.path.basename(path))
    assert m is not None
    return int(m.group(1)), int(m.group(2))
