"""Elastic re-sharding of ZeRO weight-update state from the step boundary.

ZeRO-sharded optimizer state (``parallel/zero.py``) has *geometry*: each
rank holds the contiguous ``ceil(total/n)`` chunk of every flat state
vector that the mesh-major scatter assigned it.  An elastic membership
change (schedule-driven resize, or shrink-to-survivors after a peer
death) changes ``n`` — the state must be **re-carved**, and the existing
machinery had two ways to do it, both wrong for the in-flight case:

* ``zero1_snapshot``/``zero1_restore`` funnels the full state through
  rank 0's host RAM (a leader gather — exactly what a shrink cannot
  rely on, and O(state_bytes) on one host);
* ``zero1_reshard`` re-places *live* arrays — but after a peer death the
  live arrays of the dead rank are gone.

This module generalizes the repad logic to **arbitrary old→new world
sizes without gathering to a leader**, working directly from the
committed step boundary (the same boundary
:class:`kungfu_tpu.checkpoint.StepSnapshot` replays params from):

* :class:`ZeroBoundary` — per-rank host copy of the ZeRO state at the
  last committed step: the full flat vectors when they are locally
  addressable (single-controller worlds, the CPU-mesh harness), or this
  rank's chunk when the state is distributed (multi-controller), plus
  the replicated scalar leaves and the geometry ``(step, total, old_n)``.
* :meth:`ZeroBoundary.replicate_ring` — optional ring-buddy redundancy
  for chunk-mode worlds: each rank mirrors its successor's chunk
  (``O(total/n)`` wire bytes, off the step path), so a *single* dead
  rank's chunk survives on its predecessor and an unplanned shrink can
  still re-carve without any global snapshot.
* :func:`recarve` — the segment-exchange itself, driven by the pure
  :func:`kungfu_tpu.parallel.zero.reshard_plan` every rank computes
  identically: each surviving old rank serves exactly the segments of
  its chunk (or its dead successor's buddy copy) that the new geometry
  assigns elsewhere; each new rank assembles its chunk from those
  segments.  Per-rank traffic is ``O(total/old_n + total/new_n)``; no
  rank ever holds more than a buddy's worth beyond its own shard.

The re-carve is **bitwise**: segments move untouched (numpy slices on
the host plane), padding is zeros by construction on both sides, so
training after the re-carve continues exactly as a fixed-size world
restored from the same boundary would — the property the tier-1 tests
pin against a non-elastic run.
"""

from __future__ import annotations

import io
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kungfu_tpu.monitor import timeline
from kungfu_tpu.utils.log import get_logger

_log = get_logger("reshard")


def _vector_indices(leaves) -> List[int]:
    return [i for i, l in enumerate(leaves) if getattr(l, "ndim", 0) >= 1]


def _recv_or_fail(chan, addr, old_rank: int, op: str, name: str) -> bytes:
    """Receive one reshard frame, converting a raw channel timeout into
    the typed :class:`~kungfu_tpu.comm.faults.PeerFailureError` the
    recovery contract promises.  The engine's ``_recv`` does exactly
    this for step collectives; the reshard exchange runs INSIDE the
    recovery path, where callers catch ``PeerFailureError`` to re-enter
    recovery — a leaked ``TimeoutError`` (a second death mid-exchange)
    would crash the survivor instead."""
    from kungfu_tpu.comm.faults import PeerFailureError

    try:
        return chan.recv(addr, name)
    except PeerFailureError:
        raise
    except (TimeoutError, OSError) as e:
        raise PeerFailureError(old_rank, peer=addr, op=op,
                               phase=f"recv {name!r}", cause=e) from e


class ZeroBoundary:
    """Host-side committed boundary of a ZeRO-sharded optimizer state.

    Commit once per applied step (cheap: a host copy of this rank's
    shard — the ``StepSnapshot`` discipline applied to sharded state).
    After a membership change, :meth:`recarve` rebuilds the state for
    the new world size and :meth:`place` puts it back on the new mesh
    epoch.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._step: Optional[int] = None
        self._treedef = None
        self._total: Optional[int] = None
        self._old_n: Optional[int] = None
        self._my_old: Optional[int] = None
        self._chunk: Optional[int] = None
        #: vector leaves: {leaf_index: np chunk-or-full}
        self._vec: Dict[int, np.ndarray] = {}
        self._full_mode = True
        #: scalar (replicated) leaves: {leaf_index: np}
        self._scal: Dict[int, np.ndarray] = {}
        #: ring-buddy mirror of the successor's chunks (chunk mode)
        self._buddy: Dict[int, np.ndarray] = {}
        self._buddy_of: Optional[int] = None
        #: ring distance of the buddy exchange (1 = adjacent successor;
        #: multislice runs use ranks_per_slice so every mirror lands in
        #: a DIFFERENT slice and a whole-slice death stays recoverable)
        self._buddy_stride: int = 1
        #: vector leaf dtypes (survives even when a joiner holds no data)
        self._vec_dtypes: Dict[int, np.dtype] = {}

    # -- commit -----------------------------------------------------------
    def commit(self, step: int, opt_shard, params) -> None:
        """Record the ZeRO state as of completed step ``step``.

        ``params`` supplies the true (unpadded) parameter count — the
        re-carve must not move old-geometry padding into a smaller new
        padded total.  Leaves are host-copied: ``np.array`` (a real
        copy) so later donated-buffer reuse cannot corrupt the boundary.
        """
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(opt_shard)
        total = int(
            sum(int(np.prod(l.shape)) for l in
                jax.tree_util.tree_leaves(params))
        )
        vec_idx = _vector_indices(leaves)
        full_mode = True
        old_n = 1
        my_old = 0
        chunk = total
        for i in vec_idx:
            leaf = leaves[i]
            if hasattr(leaf, "is_fully_addressable") \
                    and not leaf.is_fully_addressable:
                full_mode = False
            if hasattr(leaf, "sharding"):
                old_n = max(old_n, len(leaf.sharding.device_set))
        vec: Dict[int, np.ndarray] = {}
        scal: Dict[int, np.ndarray] = {}
        if full_mode:
            chunk = math.ceil(total / old_n) if old_n else total
            for i, l in enumerate(leaves):
                (vec if i in vec_idx else scal)[i] = np.array(l)
        else:
            for i, l in enumerate(leaves):
                if i not in vec_idx:
                    scal[i] = np.array(l)
                    continue
                shards = l.addressable_shards
                if len(shards) != 1:
                    raise NotImplementedError(
                        "ZeroBoundary chunk mode assumes one device per "
                        f"process; this process holds {len(shards)} shards")
                s = shards[0]
                off = int(s.index[0].start or 0)
                data = np.array(s.data)
                chunk = data.shape[0]
                my_old = off // chunk if chunk else 0
                vec[i] = data
        with self._lock:
            self._step = int(step)
            self._treedef = treedef
            self._total = total
            self._old_n = old_n
            self._my_old = my_old
            self._chunk = chunk
            self._vec = vec
            self._scal = scal
            self._full_mode = full_mode
            self._vec_dtypes = {i: a.dtype for i, a in vec.items()}
            # a fresh commit invalidates any buddy mirror of older state
            self._buddy = {}
            self._buddy_of = None
            self._buddy_stride = 1

    def commit_local(self, step: int, opt_chunk_tree, total: int,
                     old_n: int, my_old: int) -> None:
        """Chunk-mode commit for host-plane ZeRO workers: each process
        holds its optimizer state over its OWN ``ceil(total/old_n)``
        chunk as host arrays (the ``engine.reduce_scatter`` training
        path — one process per rank, no shared mesh).  Vector leaves
        must be exactly one chunk long; scalar leaves are replicated."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(opt_chunk_tree)
        chunk = math.ceil(total / old_n) if old_n else int(total)
        vec_idx = set(_vector_indices(leaves))
        vec, scal = {}, {}
        for i, l in enumerate(leaves):
            a = np.array(l)
            if i in vec_idx:
                if a.shape != (chunk,):
                    raise ValueError(
                        f"state leaf {i} has shape {a.shape}, expected one "
                        f"({chunk},) chunk of total={total} over "
                        f"{old_n} ranks")
                vec[i] = a
            else:
                scal[i] = a
        with self._lock:
            self._step = int(step)
            self._treedef = treedef
            self._total = int(total)
            self._old_n = int(old_n)
            self._my_old = int(my_old)
            self._chunk = chunk
            self._vec = vec
            self._scal = scal
            self._full_mode = False
            self._vec_dtypes = {i: a.dtype for i, a in vec.items()}
            self._buddy = {}
            self._buddy_of = None
            self._buddy_stride = 1

    def chunks(self) -> Tuple[int, Dict[int, np.ndarray], Dict[int, np.ndarray]]:
        """(step, vector chunks, scalars) of the current carve — the
        host-plane worker reads its re-carved state back through this
        after :meth:`recarve` (mesh-less worlds have no :meth:`place`)."""
        with self._lock:
            return self._step, dict(self._vec), dict(self._scal)

    def export_carve(self):
        """One-lock snapshot for the durable persist plane
        (``elastic/persist.py``): ``(step, total, old_n, my_old, chunk,
        full_mode, vec, scal)`` — this rank's OWN committed carve only.
        The buddy mirror is deliberately excluded: its owner persists
        those bytes under its own rank file, which is what de-duplicates
        the manifest down to one copy of every chunk."""
        with self._lock:
            return (self._step, self._total, self._old_n, self._my_old,
                    self._chunk, self._full_mode, dict(self._vec),
                    dict(self._scal))

    def join(self, fresh_opt_shard, params, old_n: int) -> None:
        """Joiner bootstrap: a worker entering an existing world holds no
        committed chunk, but must still participate in the next
        :meth:`recarve` as a pure receiver.  ``fresh_opt_shard`` (its own
        ``init_opt(params)``) supplies the state STRUCTURE and leaf
        dtypes; ``old_n`` is the incumbent world size the exchange will
        re-carve from."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(fresh_opt_shard)
        total = int(
            sum(int(np.prod(l.shape)) for l in
                jax.tree_util.tree_leaves(params))
        )
        vec_idx = set(_vector_indices(leaves))
        with self._lock:
            self._step = -1  # no local progress; adopted from the serve side
            self._treedef = treedef
            self._total = total
            self._old_n = int(old_n)
            self._my_old = None
            self._chunk = None
            self._vec = {}
            self._scal = {i: np.array(l) for i, l in enumerate(leaves)
                          if i not in vec_idx}
            self._full_mode = False
            self._vec_dtypes = {i: np.dtype(leaves[i].dtype)
                                for i in vec_idx}
            self._buddy = {}
            self._buddy_of = None
            self._buddy_stride = 1

    def step(self) -> Optional[int]:
        with self._lock:
            return self._step

    @property
    def old_n(self) -> Optional[int]:
        with self._lock:
            return self._old_n

    # -- ring-buddy redundancy (chunk mode) -------------------------------
    def replicate_ring(self, chan, workers, tag: str = "0",
                       stride: int = 1) -> None:
        """Mirror this rank's committed chunks onto the rank ``stride``
        ring positions behind it (``(r - stride) % n``) and adopt the
        chunks of the rank ``stride`` ahead — after this, any single
        dead rank's chunk survives ``stride`` positions away and
        :func:`recarve` can serve it.  ``O(total/n)`` bytes each way,
        run at a committed step boundary (off the hot path).  ``tag``
        must be identical on every rank (step number or cluster
        version), and so must ``stride`` — it is part of the exchange
        geometry.

        ``stride=1`` is the classic adjacent-successor ring.  Multislice
        jobs pass ``stride = ranks_per_slice``: every mirror then lands
        in the NEXT slice, so a whole slice dying at once (the
        multislice failure grain) leaves every one of its chunks alive
        on the predecessor slice — adjacent same-slice mirrors would all
        die together."""
        with self._lock:
            if self._step is None:
                raise ValueError("replicate_ring before any commit")
            if self._full_mode:
                return  # full vectors held locally: nothing can be lost
            vec = dict(self._vec)
            my_old, n = self._my_old, self._old_n
        if n is None or n < 2:
            return
        stride = int(stride)
        if not 1 <= stride < n:
            raise ValueError(
                f"buddy stride {stride} must be in [1, {n}) — a stride "
                "of the whole ring mirrors a rank onto itself")
        pred = workers[(my_old - stride) % n]
        succ = workers[(my_old + stride) % n]
        bio = io.BytesIO()
        np.savez(bio, **{f"v{i}": a for i, a in vec.items()})
        name = f"kf.zbuddy.{tag}"
        timeline.event("shrink", "buddy-replicate", rank=my_old,
                       nbytes=bio.getbuffer().nbytes, stride=stride)
        chan.send(pred, name, bio.getvalue())
        with np.load(io.BytesIO(_recv_or_fail(
                chan, succ, (my_old + stride) % n, "zero-buddy", name))) as z:
            buddy = {int(k[1:]): z[k] for k in z.files}
        with self._lock:
            self._buddy = buddy
            self._buddy_of = (my_old + stride) % n
            self._buddy_stride = stride

    # -- re-carve ---------------------------------------------------------
    def recarve(self, new_n: int, peer=None, old_workers=None,
                new_workers=None, tag: str = "0",
                dead: Optional[Sequence[int]] = None,
                expect_step: Optional[int] = None) -> None:
        """Re-shard the committed state in place for a ``new_n``-rank
        world.  Leaderless: every participant computes the same
        :func:`~kungfu_tpu.parallel.zero.reshard_plan` and moves only
        the ``O(total/n)`` segments it owns or will own.

        Full mode (every vector locally addressable) needs no peers at
        all.  Chunk mode exchanges segments over ``peer``'s host channel
        between ``old_workers`` (the pre-change membership this boundary
        was committed under) and ``new_workers`` (the agreed new
        membership).  ``dead`` is the set of OLD ranks that provably
        cannot serve (shrink-to-survivors passes its confirmed dead
        set); their segments are served from the ring-buddy mirror on
        their predecessor (see :meth:`replicate_ring`) — without a
        mirror, a dead rank's chunk is unrecoverable and this raises.
        Old ranks absent from ``new_workers`` but NOT in ``dead`` are
        *leavers* of a planned resize: still alive, they serve their
        own segments before detaching (every leaver must call
        ``recarve`` too — :func:`kungfu_tpu.elastic.hooks.elastic_step`
        does this before honoring the detach).  Every participant must
        pass the same ``dead`` set: it is part of the plan.

        ``expect_step`` is the cluster-AGREED committed step (the shrink
        path passes the leader-agreed replay boundary).  Committed steps
        can diverge by one across survivors — the dead peer may have fed
        some of them before dying — and a chunk committed one step ahead
        is not restorable state for a step-behind replay (its previous
        value is gone, as is its buddy mirror's): segments from mixed
        steps would silently blend two optimizer states.  A mismatch
        therefore raises — escalate to the checkpoint restart, the same
        policy as an unrecoverable dead chunk.
        """
        from kungfu_tpu.parallel.zero import reshard_plan

        with self._lock:
            if self._step is None:
                raise ValueError("recarve before any commit")
            total, old_n = self._total, self._old_n
            full_mode = self._full_mode
            step = self._step
        if (expect_step is not None and step >= 0
                and step != int(expect_step)):
            raise ValueError(
                f"boundary committed at step {step} but the cluster agreed "
                f"to replay from step {expect_step} — a re-carve would "
                "blend optimizer states from different steps; escalate to "
                "the checkpoint restart")
        if new_n < 1:
            raise ValueError(f"new_n must be >= 1, got {new_n}")
        plan = reshard_plan(total, old_n, new_n)
        new_chunk = math.ceil(total / new_n)
        timeline.event("shrink", "zero-recarve", old_n=old_n, new_n=new_n,
                       total=total, segments=len(plan))
        if full_mode:
            # local slicing only: zero the padding, keep [0, total)
            with self._lock:
                for i, full in self._vec.items():
                    if full.shape[0] < total:
                        raise ValueError(
                            f"state vector {i} has {full.shape[0]} elements "
                            f"but params fuse to {total} — boundary was "
                            "committed against a different param tree")
                    buf = np.zeros((new_chunk * new_n,), full.dtype)
                    buf[:total] = full[:total]
                    self._vec[i] = buf
                self._old_n = new_n
                self._my_old = 0
                self._chunk = new_chunk
            return
        self._recarve_channel(plan, new_n, new_chunk, peer,
                              old_workers, new_workers, tag, dead)

    def _recarve_channel(self, plan, new_n, new_chunk, peer,
                         old_workers, new_workers, tag, dead=None):
        if peer is None or old_workers is None or new_workers is None:
            raise ValueError(
                "chunk-mode recarve needs peer + old_workers + new_workers")
        chan = peer.channel
        with self._lock:
            my_old, old_n = self._my_old, self._old_n
            chunk = self._chunk
            step = self._step
            vec = dict(self._vec)
            dtypes = dict(self._vec_dtypes)
            buddy, buddy_of = dict(self._buddy), self._buddy_of
            stride = self._buddy_stride
        me = peer.config.self_id
        # the plan is computed from the boundary's recorded epoch
        # (old_n, my_old) while addressing uses the caller's old_workers;
        # a stale boundary (missed commit, standby leftovers) would serve
        # wrong bytes under matching segment names — fail upfront instead
        if len(old_workers) != old_n:
            raise ValueError(
                f"boundary was committed under {old_n} ranks but "
                f"old_workers has {len(old_workers)} members — stale "
                "boundary or wrong membership epoch")
        if my_old is not None and old_workers.rank(me) != my_old:
            raise ValueError(
                f"boundary records this rank as old rank {my_old} but "
                f"old_workers places it at {old_workers.rank(me)} — stale "
                "boundary or wrong membership epoch")
        my_new = new_workers.rank(me)
        dead = {int(d) for d in (dead or ())}
        # serving = every old rank still able to answer: survivors AND
        # planned-resize leavers (alive, detaching only after this)
        alive = {r for r in range(old_n) if r not in dead}

        def server_of(o: int) -> Optional[int]:
            """Old rank whose host serves old rank ``o``'s segments."""
            if o in alive:
                return o
            pred = (o - stride) % old_n
            if pred in alive:
                return pred  # serves from its buddy mirror
            return None

        for o in dead:
            serv = server_of(o)
            if serv is None:
                raise ValueError(
                    f"old rank {o} is dead and so is its buddy predecessor "
                    f"{(o - stride) % old_n} (stride {stride}) — chunk "
                    "unrecoverable (buddy redundancy covers one failure "
                    "domain; escalate to the checkpoint restart)")
            if serv == my_old and buddy_of != o:
                raise ValueError(
                    f"old rank {o} is dead and this rank holds no buddy "
                    "mirror of its chunk (replicate_ring was never run on "
                    "this boundary) — chunk unrecoverable")

        def seg_name(i: int, s: int) -> str:
            return f"kf.zrc.{tag}.l{i}.o{s}"

        def local_source(o: int) -> Optional[Dict[int, np.ndarray]]:
            if o == my_old:
                return vec
            if o == buddy_of and buddy:
                return buddy
            return None

        # 1) serve every segment THIS host is responsible for
        offs = {}
        if my_old is not None:
            offs[my_old] = my_old * chunk
        if buddy_of is not None:
            offs[buddy_of] = buddy_of * chunk
        for (o, r, s, ln) in plan:
            if my_old is None or server_of(o) != my_old:
                continue
            src = local_source(o)
            if src is None:
                raise AssertionError(
                    f"server {my_old} has no data for old rank {o}")
            dst = new_workers[r]
            if dst == me:
                continue
            off = offs[o]
            for i, data in src.items():
                chan.send(dst, seg_name(i, s),
                          np.ascontiguousarray(data[s - off:s - off + ln]))
        # replicated scalars (and the boundary step) for pure joiners,
        # served by the lowest surviving old rank (replicated leaves have
        # no owner: any surviving copy is THE copy)
        serving_scal = min(alive) if alive else None
        if my_old is not None and my_old == serving_scal:
            with self._lock:
                scal = dict(self._scal)
            bio = io.BytesIO()
            np.savez(bio, __step__=np.int64(step),
                     **{f"s{i}": a for i, a in scal.items()})
            for w in new_workers:
                if old_workers.rank(w) is None:
                    chan.send(w, f"kf.zrc.{tag}.scalars", bio.getvalue())

        if my_new is None:
            # leaver: served its segments; drop the now-stale shard
            with self._lock:
                self._vec = {}
            return

        # 2) assemble my new chunk
        if my_old is None:
            if serving_scal is None:
                raise ValueError("no surviving old member to receive from")
            with np.load(io.BytesIO(_recv_or_fail(
                    chan, old_workers[serving_scal], serving_scal,
                    "zero-recarve", f"kf.zrc.{tag}.scalars"))) as z:
                with self._lock:
                    self._scal = {int(k[1:]): z[k] for k in z.files
                                  if k != "__step__"}
                    self._step = step = int(z["__step__"])
        lo = my_new * new_chunk
        new_vec = {i: np.zeros((new_chunk,), dt) for i, dt in dtypes.items()}
        for (o, r, s, ln) in plan:
            if r != my_new:
                continue
            src = (local_source(o)
                   if my_old is not None and server_of(o) == my_old
                   else None)
            if src is not None:
                off = offs[o]
                for i, data in src.items():
                    new_vec[i][s - lo:s - lo + ln] = \
                        data[s - off:s - off + ln]
                continue
            serv = server_of(o)
            for i in new_vec:
                got = np.frombuffer(
                    _recv_or_fail(chan, old_workers[serv], serv,
                                  "zero-recarve", seg_name(i, s)),
                    dtype=new_vec[i].dtype)
                if got.shape[0] != ln:
                    raise ValueError(
                        f"recarve segment {seg_name(i, s)}: expected {ln} "
                        f"elements, got {got.shape[0]}")
                new_vec[i][s - lo:s - lo + ln] = got
        with self._lock:
            self._vec = new_vec
            self._old_n = new_n
            self._my_old = my_new
            self._chunk = new_chunk
            self._buddy = {}
            self._buddy_of = None
            self._buddy_stride = 1

    # -- placement --------------------------------------------------------
    def place(self, new_comm):
        """Rebuild the optimizer-state pytree on ``new_comm``'s mesh from
        the (re-carved) boundary: vector leaves sharded ``P(axes)``,
        scalars replicated.  Call after :meth:`recarve` with
        ``new_comm.size == new_n``."""
        import jax
        import jax.numpy as jnp

        from kungfu_tpu.parallel.zero import _place_sharded

        with self._lock:
            if self._treedef is None:
                raise ValueError("place before any commit")
            if self._old_n != new_comm.size:
                raise ValueError(
                    f"boundary is carved for {self._old_n} ranks but the "
                    f"communicator has {new_comm.size} — recarve first")
            n_leaves = self._treedef.num_leaves
            leaves = []
            for i in range(n_leaves):
                if i in self._vec:
                    v = self._vec[i]
                    if self._full_mode:
                        leaves.append(_place_sharded(new_comm, full_np=v))
                    else:
                        leaves.append(_place_sharded(new_comm, my_chunk=v))
                else:
                    leaves.append(jax.device_put(
                        jnp.asarray(self._scal[i]),
                        new_comm.replicated_sharding()))
            return jax.tree_util.tree_unflatten(self._treedef, leaves)


#: default boundary for the one-trainer-per-process case (mirrors
#: ``checkpoint.step_snapshot``)
zero_boundary = ZeroBoundary()


def recarve_after_shrink(peer, boundary: ZeroBoundary, old_workers,
                         expect_step: Optional[int] = None) -> None:
    """Shrink-recovery hook: re-carve ``boundary`` across the survivors.

    Call AFTER :func:`kungfu_tpu.elastic.shrink.shrink_to_survivors`
    succeeded (``peer.cluster.workers`` is already the shrunk list);
    ``old_workers`` is the pre-shrink membership the boundary was
    committed under.  ``expect_step`` is the leader-agreed replay step
    (``recover_from_peer_failure`` passes it when a params snapshot was
    synced) — a survivor whose boundary committed a different step
    raises rather than blending optimizer states.  The subsequent mesh
    epoch then restores sharded state via :meth:`ZeroBoundary.place`.
    """
    new_workers = peer.cluster.workers
    # survivors ARE the new membership: every old rank absent from it is
    # confirmed dead (ping-confirmed by the exclusion consensus), not a
    # leaver — its chunks must come from ring-buddy mirrors
    dead = [r for r, w in enumerate(old_workers)
            if new_workers.rank(w) is None]
    boundary.recarve(
        len(new_workers), peer=peer, old_workers=old_workers,
        new_workers=new_workers, tag=f"v{peer.cluster_version}",
        dead=dead, expect_step=expect_step,
    )
