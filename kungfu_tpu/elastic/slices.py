"""Slice topology: the worker-rank ↔ TPU-slice mapping for multislice jobs.

A multislice pod is a two-level world: chips within a slice talk over
ICI, slices talk over DCN — and *failures* follow the same grain.  A
slice that loses its DCN link, its coordinator, or power loses **all**
its hosts at once, and a slice that loses *some* of them cannot keep
training (its within-slice mesh is broken even though the surviving
hosts answer pings).  The elastic layer therefore needs a stable notion
of "which slice does worker rank r belong to", kept consistent across
membership changes:

* **Contract**: workers are slice-major contiguous — rank ``r`` lives in
  slice ``r // ranks_per_slice``.  This mirrors the mesh layout
  (:func:`kungfu_tpu.platforms.tpu_pod.slice_mesh_layout` flattens
  slice-major) and the launcher's spawn order (``kfrun`` assigns
  ``MEGASCALE_SLICE_ID = rank // ranks_per_slice`` in emulation; on a
  real pod each host's env already carries its slice id).
* **ranks_per_slice** is pinned by the launcher (``KF_SLICE_RANKS``) or
  derived once from the bootstrap membership (bootstrap size /
  ``MEGASCALE_NUM_SLICES``).  It never changes: elastic grow/shrink
  moves whole slices, so the CURRENT topology for an n-worker membership
  is simply ``n / ranks_per_slice`` slices (and a membership that does
  not divide is a bug the topology refuses to paper over).

Everything here is pure (no sockets, no jax): the shrink protocol, the
resize alignment, the chaos layer, and the tests all share it.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from kungfu_tpu.utils import envs

__all__ = [
    "SliceTopology",
    "bootstrap_topology",
    "align_to_slices",
    "slice_verdict",
    "slice_quorum_ok",
]


@dataclass(frozen=True)
class SliceTopology:
    """Rank→slice mapping for ONE membership epoch (``num_slices``
    slices of ``ranks_per_slice`` workers, slice-major contiguous)."""

    num_slices: int
    ranks_per_slice: int

    def __post_init__(self):
        if self.num_slices < 1 or self.ranks_per_slice < 1:
            raise ValueError(f"degenerate slice topology {self!r}")

    @property
    def size(self) -> int:
        return self.num_slices * self.ranks_per_slice

    def slice_of(self, rank: int) -> int:
        if not 0 <= rank < self.size:
            raise ValueError(f"rank {rank} outside the {self.size}-rank world")
        return rank // self.ranks_per_slice

    def ranks_in(self, slice_id: int) -> List[int]:
        if not 0 <= slice_id < self.num_slices:
            raise ValueError(
                f"slice {slice_id} outside the {self.num_slices}-slice world")
        lo = slice_id * self.ranks_per_slice
        return list(range(lo, lo + self.ranks_per_slice))

    def leader_of(self, slice_id: int) -> int:
        """The slice's representative on the DCN control plane: its
        lowest rank (every member of a surviving slice is alive — a
        slice with any dead member is excluded whole, so the lowest
        rank is always available to lead)."""
        return self.ranks_in(slice_id)[0]

    def for_size(self, n: int) -> "SliceTopology":
        """The topology of an ``n``-worker membership under the SAME
        ranks-per-slice.  Raises when ``n`` is not whole slices — the
        elastic layer aligns every resize, so a misaligned membership
        means the alignment was bypassed."""
        if n % self.ranks_per_slice:
            raise ValueError(
                f"membership of {n} workers is not whole slices "
                f"({self.ranks_per_slice} ranks/slice) — slice-aligned "
                "elasticity was bypassed")
        return SliceTopology(n // self.ranks_per_slice, self.ranks_per_slice)


def bootstrap_topology(bootstrap_size: int,
                       env=None) -> Optional[SliceTopology]:
    """The job's slice topology from the launch contract, or ``None``
    for single-slice jobs (``MEGASCALE_NUM_SLICES`` unset/<=1) — the
    None path is the byte-identical today's-behavior path.

    ``ranks_per_slice`` comes from ``KF_SLICE_RANKS`` when the launcher
    pinned it (it must: late joiners' bootstrap worker list is the
    *current* cluster, not the original one) and otherwise derives from
    ``bootstrap_size / num_slices`` — failing loudly when the worker
    count does not tile the slices."""
    env = env if env is not None else os.environ
    num_slices = int(env.get(envs.MEGASCALE_NUM_SLICES, "0") or 0)
    if num_slices <= 1:
        return None
    rps_s = (env.get(envs.SLICE_RANKS, "") or "").strip()
    if rps_s:
        rps = int(rps_s)
        if rps < 1:
            raise ValueError(f"{envs.SLICE_RANKS}={rps} must be >= 1")
        return SliceTopology(num_slices, rps)
    if bootstrap_size % num_slices:
        raise ValueError(
            f"{envs.MEGASCALE_NUM_SLICES}={num_slices} does not tile the "
            f"{bootstrap_size}-worker bootstrap world — set "
            f"{envs.SLICE_RANKS} or fix the worker count")
    return SliceTopology(num_slices, bootstrap_size // num_slices)


def align_to_slices(new_size: int, topo: SliceTopology) -> int:
    """Clamp a proposed worker count to whole slices (nearest multiple
    of ``ranks_per_slice``, never below one slice).  Planned elasticity
    on a multislice pod grows and shrinks by slices: a fractional slice
    has no mesh to join (its chips cannot form the within-slice axis)."""
    rps = topo.ranks_per_slice
    # nearest multiple, ties rounding UP (a half-slice ask leans toward
    # capacity) — int arithmetic, not round(): banker's rounding would
    # make 5 workers on 2-rank slices align DOWN, surprising schedules
    aligned = max(rps, ((new_size + rps // 2) // rps) * rps)
    return int(aligned)


def slice_verdict(dead_ranks: Iterable[int],
                  topo: SliceTopology) -> Tuple[Set[int], Set[int]]:
    """``(dead_slices, degraded_slices)`` from a ping-confirmed dead
    rank set: ``dead_slices`` lost every member, ``degraded_slices``
    lost some but not all.  The shrink protocol excludes BOTH whole —
    a half-dead slice has live hosts but no within-slice mesh, and
    letting it "keep training" on a broken ICI domain is silent
    corruption, not fault tolerance."""
    dead_by_slice: dict = {}
    for r in dead_ranks:
        dead_by_slice.setdefault(topo.slice_of(r), set()).add(r)
    dead_slices, degraded = set(), set()
    for s, dr in dead_by_slice.items():
        if len(dr) >= topo.ranks_per_slice:
            dead_slices.add(s)
        else:
            degraded.add(s)
    return dead_slices, degraded


def slice_quorum_ok(surviving_slices: Sequence[int],
                    topo: SliceTopology) -> bool:
    """Quorum at slice granularity: a strict majority of slices must
    survive — OR exactly half, provided the survivors include the
    lowest slice id.  The tie-break is the piece rank-granular quorum
    cannot have: a partition splits the slice set into disjoint halves,
    and only ONE half can contain slice 0, so both sides deciding by
    this rule can never both continue (the split-brain strict majority
    exists to prevent).  It is what makes the canonical 2-slice pod's
    slice loss survivable at all — rank-granular strict majority would
    refuse exactly-half survivors and relaunch the world."""
    alive = set(surviving_slices)
    if 2 * len(alive) > topo.num_slices:
        return True
    return 2 * len(alive) == topo.num_slices and 0 in alive
