"""Shrink-to-survivors: in-flight peer-failure recovery.

The detector-driven relaunch (``runner/monitored.py``) recovers from any
failure, but a whole-job restart throws away every surviving worker's
warm XLA caches and in-memory state — on TPU that means re-paying the
multi-ten-second compile that ``monitor/detector.py`` has to special-case
with ``DEFAULT_COMPILE_GRACE_S``.  This module makes the restart the
*last resort* instead of the only mechanism:

1. a collective primitive exhausts its per-peer deadline and raises
   :class:`~kungfu_tpu.comm.faults.PeerFailureError` (``comm/engine.py``);
2. each survivor **confirms** the dead set by pinging every current
   worker (the exception's rank is only a suspect — a peer blocked on
   the true victim times out toward an innocent neighbor);
3. the survivors run an **exclusion consensus** over the survivor peer
   list (the same ``consensus_bytes`` collective the resize protocol
   uses): everyone must propose the identical shrunk cluster + version;
4. quorum check — the survivors must be a strict majority of the
   current membership, otherwise :class:`QuorumLostError` (the caller
   escalates to the detector restart via
   :func:`~kungfu_tpu.monitor.signals.monitor_report_down`);
5. the agreed cluster is applied through the **existing elastic propose
   path** (``Peer._propose``: runner notify, token fence, connection
   reset, mesh-epoch retirement), published to the config server so
   standby peers and watch runners observe it, and the caller replays
   from the last committed step boundary
   (:class:`kungfu_tpu.checkpoint.StepSnapshot`).

Survivors that were blocked on the victim converge here within one
per-peer deadline of each other, so the consensus collective rendezvouses
without extra coordination.

**Multislice pods** (``MEGASCALE_NUM_SLICES`` > 1) run the same ladder at
*slice* granularity (docs/multislice.md): the ping-confirmed dead set is
widened to whole slices (a partially-dead slice is excluded whole — its
live members get :class:`~kungfu_tpu.comm.faults.SliceExcludedError`),
quorum is counted in slices with a lowest-slice tie-break at exactly
half, and the exclusion consensus runs over the surviving slices'
leaders with an ICI-local relay to their members.  Single-slice jobs
never touch any of it.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

from kungfu_tpu.comm.faults import (PeerFailureError, QuorumLostError,
                                    SliceExcludedError)
from kungfu_tpu.monitor import ledger, timeline
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.utils.log import get_logger, log_event

_log = get_logger("shrink")

#: ping-confirm budget per peer when probing the dead set
PROBE_TIMEOUT_S = 3.0

#: connect-ladder length for recovery-path sends (consensus / replay
#: broadcast): short, because these run exactly when peers are dying
_RECOVERY_SEND_RETRIES = 5


def find_dead_ranks(peer, suspects: Iterable[int] = (),
                    timeout: float = PROBE_TIMEOUT_S) -> List[int]:
    """Ranks of current workers whose endpoint no longer answers a ping.
    ``suspects`` (the blame carried by a ``PeerFailureError``) get a
    second confirming ping if the sweep found them alive — a victim can
    die between the collective failure and the sweep reaching it.

    One ping thread per peer: dead SYN-dropping hosts burn the full
    ``timeout``, and at pod scale a sequential sweep would serialize
    recovery latency behind each of them — the sweep is bounded at
    ~``timeout`` total, not ``timeout * n_dead`` (same head-of-line
    reasoning as the detector's parallel fan-out)."""
    import threading

    workers = peer.cluster.workers
    me = workers.rank(peer.config.self_id)

    def sweep(ranks: List[int]) -> List[int]:
        alive = [False] * len(ranks)

        def one(i, r):
            alive[i] = peer.channel.ping(workers[r], timeout=timeout)

        ts = [threading.Thread(target=one, args=(i, r), daemon=True)
              for i, r in enumerate(ranks)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout + 2.0)
        return [r for i, r in enumerate(ranks) if not alive[i]]

    # materialize ONCE: `suspects` may be a generator, and it is read
    # twice below (the timeline mark and the recheck filter) — iterating
    # a one-shot iterator twice would silently skip the confirming ping
    suspects = [s for s in suspects if s is not None]
    timeline.event("shrink", "ping-confirm", rank=me, suspects=suspects)
    dead = sweep([r for r in range(len(workers)) if r != me])
    recheck = [
        s for s in suspects
        if s != me and s not in dead and 0 <= s < len(workers)
    ]
    dead += sweep(recheck)
    return sorted(set(dead))


def _peer_slice_topology(peer):
    """The peer's current slice topology (None = single slice).  Guarded
    with ``getattr`` so hand-rolled peer doubles in tests — and any
    driver predating the multislice wiring — keep the rank-granular
    path unchanged."""
    fn = getattr(peer, "slice_topology", None)
    return fn() if callable(fn) else None


def expand_dead_to_slices(peer, topo, dead: Sequence[int]) -> List[int]:
    """Slice-granular death verdict: widen a ping-confirmed dead rank
    set to WHOLE slices.  A slice with every member dead is dead; a
    slice with some members dead is *degraded* — its survivors answer
    ping but have no within-slice mesh left, so the protocol excludes
    the whole slice rather than let a half-dead slice silently keep
    training.  Raises :class:`SliceExcludedError` when THIS peer's own
    slice is among them (the caller is alive but must stand down)."""
    from kungfu_tpu.elastic.slices import slice_verdict

    workers = peer.cluster.workers
    me = workers.rank(peer.config.self_id)
    dead_slices, degraded = slice_verdict(dead, topo)
    excluded = dead_slices | degraded
    timeline.event("slice", "verdict", rank=me,
                   dead_slices=sorted(dead_slices),
                   degraded=sorted(degraded))
    if not excluded:
        return sorted(set(dead))
    if degraded:
        _log.warning(
            "slice(s) %s are PARTIALLY dead — degrading to excluded "
            "(a half-dead slice must not keep training)", sorted(degraded),
        )
    my_slice = topo.slice_of(me)
    if my_slice in excluded:
        timeline.event("slice", "self-excluded", rank=me, slice=my_slice)
        raise SliceExcludedError(
            my_slice, [r for r in dead if topo.slice_of(r) == my_slice])
    return sorted({r for s in excluded for r in topo.ranks_in(s)})


def _slice_consensus(peer, topo, payload: bytes, digest: str,
                     survivor_ranks: Sequence[int]) -> bool:
    """Exclusion consensus at slice granularity: one vote among the
    surviving slices' LEADERS over the DCN control plane, then each
    leader relays the verdict to its own slice members (ICI-local).
    Slice members of a surviving slice are all alive by construction
    (any death degrades the slice to excluded), so the leader is always
    the slice's lowest rank."""
    workers = peer.cluster.workers
    me = workers.rank(peer.config.self_id)
    my_slice = topo.slice_of(me)
    surv_slices = sorted({topo.slice_of(r) for r in survivor_ranks})
    leader_ranks = [topo.leader_of(s) for s in surv_slices]
    leaders = workers.select(leader_ranks)
    timeline.event("slice", "leader-consensus", rank=me,
                   slices=surv_slices, digest=digest)
    ok = False
    if me in leader_ranks:
        try:
            # subgroup collective, not SPMD divergence: the participant
            # list IS `leaders`, and the guard admits exactly its
            # members — non-leaders rendezvous on the relay below
            ok = peer.channel.consensus_bytes(  # kflint: allow(collective-consistency)
                payload, leaders, name=f"kf.slice.{digest}",
                send_retries=_RECOVERY_SEND_RETRIES,
            )
        except (TimeoutError, ConnectionError, OSError) as e:
            _log.warning("slice-leader consensus did not converge: %s", e)
            ok = False
    if topo.ranks_per_slice == 1:
        return ok
    # relay: the leader broadcasts (verdict, payload) to its slice; a
    # member checks the payload against its OWN computed proposal so a
    # leader that agreed to a DIFFERENT shrunk cluster cannot drag its
    # slice along silently.  Name is digest- and slice-keyed: divergent
    # proposals and neighboring slices cannot cross-talk.
    members = workers.select(topo.ranks_in(my_slice))
    name = f"kf.slice.{digest}.s{my_slice}"
    verdict = (b"\x01" if ok else b"\x00") + payload
    try:
        if me == topo.leader_of(my_slice):
            peer.channel.broadcast_bytes(
                verdict, members, name,
                send_retries=_RECOVERY_SEND_RETRIES,
            )
            return ok
        blob = peer.channel.broadcast_bytes(None, members, name)
        return bool(blob) and blob[:1] == b"\x01" and blob[1:] == payload
    except (TimeoutError, ConnectionError, OSError) as e:
        _log.warning("slice verdict relay failed: %s", e)
        return False


def shrink_to_survivors(peer, dead_ranks: Sequence[int]) -> bool:
    """Evict ``dead_ranks`` by exclusion consensus among the survivors
    and apply the shrunk membership through the elastic propose path.

    Returns ``True`` on success (the peer's next ``engine()`` /
    ``communicator()`` call builds the shrunk epoch).  Returns ``False``
    when the survivors could not agree (divergent dead sets — e.g. a
    partition where each side sees the other down); the caller should
    escalate.  Raises :class:`QuorumLostError` when the survivors are
    not a strict majority of the current membership.
    """
    workers = peer.cluster.workers
    dead = sorted({r for r in dead_ranks if 0 <= r < len(workers)})
    if not dead:
        return False
    me = workers.rank(peer.config.self_id)
    if me is None or me in dead:
        raise ValueError("shrink_to_survivors must run on a surviving member")
    # kf-overlap fence, BEFORE exclusion consensus: every issued async
    # handle must settle first — handles toward the dead complete with
    # their typed PeerFailureError via the per-peer deadline (bounded,
    # cannot hang), and a handle left in flight would otherwise tangle
    # its old-epoch recvs with the consensus traffic and the rebuilt
    # engine.  _propose drains again, but by then the consensus has run;
    # the window must be empty before the first shrink collective.
    eng = getattr(peer, "_engine", None)
    if eng is not None:
        drained = eng.drain_async()
        if drained:
            timeline.event("shrink", "drain", rank=me, drained=drained)
    topo = _peer_slice_topology(peer)
    if topo is not None and topo.num_slices <= 1:
        # a job shrunk down to ONE surviving slice has its failure grain
        # back at ranks (there is no cross-slice mesh left to protect,
        # and treating the lone slice as excludable-whole would turn any
        # single death into a full stop) — run the classic rank ladder
        topo = None
    if topo is not None:
        # slice-granular: whole slices die together (partial death
        # degrades the slice to excluded; raises SliceExcludedError on
        # a surviving member of a degraded slice)
        dead = expand_dead_to_slices(peer, topo, dead)
    survivor_ranks = [r for r in range(len(workers)) if r not in dead]
    if topo is not None:
        # quorum is counted in SLICES: strict majority, or exactly half
        # holding the lowest slice id (the deterministic tie-break only
        # one partition side can satisfy) — the rule that makes the
        # canonical 2-slice pod's slice loss survivable at all
        from kungfu_tpu.elastic.slices import slice_quorum_ok

        surv_slices = sorted({topo.slice_of(r) for r in survivor_ranks})
        if not slice_quorum_ok(surv_slices, topo):
            timeline.event("slice", "quorum-lost", rank=me,
                           survivors=len(surv_slices),
                           total=topo.num_slices)
            if me == min(survivor_ranks):
                from kungfu_tpu.monitor.aggregator import \
                    post_control_if_enabled

                post_control_if_enabled(peer, "quorum-lost", dead=dead,
                                        survivors=len(surv_slices))
            raise QuorumLostError(len(surv_slices), topo.num_slices)
    # strict majority: a minority partition must NOT shrink-and-continue
    # (two half-clusters training independently is silent divergence,
    # worse than a restart) — it falls back to the detector instead
    elif 2 * len(survivor_ranks) <= len(workers):
        timeline.event("shrink", "quorum-lost", rank=me,
                       survivors=len(survivor_ranks), total=len(workers))
        if me == min(survivor_ranks):
            from kungfu_tpu.monitor.aggregator import post_control_if_enabled

            # the operator's "full restart incoming" signal on kftop
            post_control_if_enabled(peer, "quorum-lost", dead=dead,
                                    survivors=len(survivor_ranks))
        raise QuorumLostError(len(survivor_ranks), len(workers))

    survivors = workers.select(survivor_ranks)
    new_cluster = Cluster(peer.cluster.runners, survivors)
    version = peer.cluster_version + 1
    payload = new_cluster.digest() + version.to_bytes(8, "little")
    # consensus over the SURVIVOR list: the gather root is the lowest
    # surviving rank, so a dead rank 0 cannot wedge the vote.  Divergent
    # dead sets mean divergent survivor lists — the vote then either
    # disagrees on the payload or never rendezvouses at all (recv
    # timeout); both are "no agreement", not a crash.
    #
    # The rendezvous name is keyed by the PAYLOAD DIGEST, not just the
    # version: a failed round can leave its messages queued (the version
    # only bumps on success), and a version-keyed retry would consume
    # that stale round's bytes.  Digest-keying makes divergent proposals
    # miss each other entirely (timeout → contained below) and makes any
    # leftover same-name message byte-identical to the live one — stale
    # equals fresh, so it cannot poison the vote.
    import hashlib

    digest = hashlib.blake2b(payload, digest_size=8).hexdigest()
    timeline.event("shrink", "consensus", rank=me, dead=dead,
                   version=version, digest=digest)
    if topo is not None:
        # cross-slice agreement runs over slice LEADERS only (one DCN
        # round-trip per surviving slice), relayed ICI-locally
        ok = _slice_consensus(peer, topo, payload, digest, survivor_ranks)
    else:
        try:
            # send_retries is SHORT: this collective runs exactly when
            # peers are dying, and a consensus root that died after the
            # ping sweep must surface as ConnectionError in seconds, not
            # after the channel's 500-rung bring-up ladder
            ok = peer.channel.consensus_bytes(
                payload, survivors, name=f"kf.shrink.{digest}",
                send_retries=_RECOVERY_SEND_RETRIES,
            )
        except (TimeoutError, ConnectionError, OSError) as e:
            _log.warning("exclusion consensus did not converge: %s", e)
            ok = False
    if not ok:
        _log.warning(
            "survivors disagree on the dead set (mine: %s) — not shrinking",
            dead,
        )
        return False
    _log.warning(
        "excluding dead rank(s) %s: %d -> %d workers (v%d)",
        dead, len(workers), len(survivors), version,
    )
    timeline.event("shrink", "propose", rank=me, dead=dead,
                   version=version, survivors=len(survivors))
    # kf-ledger: a shrink is the most consequential "decision" the
    # cluster makes — the consensus version is the agreement round
    ledger.record_decision(
        "shrink", "world", len(workers), len(survivors),
        consensus_seq=version, evidence={"dead": list(dead)})
    if topo is not None:
        timeline.event("slice", "propose", rank=me,
                       dead_slices=sorted({topo.slice_of(r) for r in dead}),
                       version=version)
    _publish_shrunk_cluster(peer, new_cluster, survivors)
    peer._propose(new_cluster, version)
    log_event(f"shrunk-to-survivors-v{version}-n{len(survivors)}")
    # control event for the live plane, AFTER _propose: the propose path
    # posts its own generic "resize" event, and kftop's cluster-health
    # line shows only the newest control — the shrink (which names the
    # dead set, the thing the operator needs) must be the one that sticks
    if survivors.rank(peer.config.self_id) == 0:
        from kungfu_tpu.monitor.aggregator import post_control_if_enabled

        extra = {}
        if topo is not None:
            extra["slices"] = sorted({topo.slice_of(r) for r in dead})
        post_control_if_enabled(peer, "shrink", dead=dead, version=version,
                                survivors=len(survivors), **extra)
    return True


def _publish_shrunk_cluster(peer, new_cluster: Cluster, survivors) -> None:
    """Lowest surviving rank PUTs the shrunk cluster to the config server
    (best effort): standby peers, watch runners, and late joiners must
    observe the post-failure membership, and the next schedule-driven
    resize must diff against it rather than the pre-failure list."""
    if not peer.config.config_server:
        return
    if survivors.rank(peer.config.self_id) != 0:
        return
    import urllib.request

    req = urllib.request.Request(
        peer.config.config_server,
        data=new_cluster.to_json().encode(),
        method="PUT",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()
    except OSError as e:
        _log.warning("cannot publish shrunk cluster: %s", e)


def recover_from_peer_failure(
    peer,
    failure: Optional[BaseException] = None,
    snapshot=None,
    zero_boundary=None,
    stage_boundary=None,
) -> Tuple[bool, Optional[Tuple[int, object, dict]]]:
    """The full survivor-side driver: confirm the dead set, shrink, and
    hand back the replay point.

    Returns ``(shrunk, replay)`` where ``replay`` is the **agreed**
    ``(step, tree, meta)`` boundary — the shrink leader's (new rank 0's)
    snapshot, broadcast to every survivor — or ``None`` without one.
    The agreement matters: the dead peer may have fed some survivors
    before dying, so committed steps can diverge by one across
    survivors, and replaying from per-peer snapshots would rendezvous
    collectives under mismatched step names forever.  Pass ``snapshot``
    on every surviving rank or on none (the broadcast must be
    symmetric).

    ``zero_boundary`` (a :class:`kungfu_tpu.elastic.reshard.ZeroBoundary`,
    same all-or-none symmetry) carries ZeRO-sharded optimizer state,
    which cannot ride the leader-broadcast ``snapshot`` (each rank holds
    only its 1/n chunk): after the shrink it is re-carved **leaderlessly**
    across the survivors — each rank exchanging only the O(total/n)
    segments the new geometry moves, dead ranks' chunks served from
    their ring-buddy mirrors — and the caller restores the sharded state
    for the shrunk epoch with ``zero_boundary.place(new_comm)``.

    ``stage_boundary`` (a :class:`kungfu_tpu.parallel.pp.StageBoundary`,
    same all-or-none symmetry AND the same snapshot requirement) carries
    a pipeline stage's params + ZeRO-2 optimizer chunks through the
    shrink: after the membership is applied, the surviving stages
    re-balance the LAYERS over themselves via the pure stage re-carve
    plan — a whole dead stage (= a dead slice under the PP-across-DCN
    mapping) is restored from the ring-buddy mirror on its predecessor
    stage instead of aborting the job.  Recovery-ladder rung 10
    (docs/fault_tolerance.md, docs/pipeline.md).

    ``shrunk=False`` means nothing provably died (a transient — the
    caller may simply retry the collective).  On quorum loss this
    signals the failure detector (``otherdown`` → the MonitoredRun
    relaunch, the pre-existing last resort) and re-raises
    :class:`QuorumLostError`.
    """
    if stage_boundary is not None and snapshot is None:
        raise ValueError(
            "stage_boundary needs a StepSnapshot alongside it — the "
            "leader-agreed replay step gates the stage re-carve against "
            "survivors whose boundaries committed different steps")
    if zero_boundary is not None and snapshot is None:
        # checked before anything destructive: the recarve must be gated
        # on the leader-agreed replay step (survivors' boundaries can
        # diverge by one), and that step only exists via the snapshot
        raise ValueError(
            "zero_boundary needs a StepSnapshot alongside it — the "
            "leader-agreed replay step gates the re-carve against "
            "survivors whose boundaries committed different steps")
    suspects = []
    if isinstance(failure, PeerFailureError) and failure.rank is not None:
        suspects.append(failure.rank)
    dead = find_dead_ranks(peer, suspects)
    if not dead:
        _log.info(
            "peer failure (%s) but every worker answers ping — transient, "
            "not shrinking", failure,
        )
        return False, None
    old_workers = peer.cluster.workers  # pre-shrink membership, for recarve
    try:
        shrunk = shrink_to_survivors(peer, dead)
    except QuorumLostError:
        from kungfu_tpu.monitor.signals import monitor_report_down

        _log.error(
            "quorum lost (%d dead of %d): escalating to detector-driven "
            "restart", len(dead), peer.size(),
        )
        monitor_report_down()
        raise
    replay = None
    if shrunk and snapshot is not None:
        replay = _sync_replay_point(peer, snapshot)
    if shrunk and zero_boundary is not None:
        from kungfu_tpu.elastic.reshard import recarve_after_shrink

        # the leader-agreed replay step gates the recarve: a survivor
        # whose boundary committed one step ahead (the dead peer fed it
        # before dying) holds state the step-behind replay cannot use —
        # recarve raises loudly instead of blending two steps.  A
        # snapshot was passed (entry check) but the replay sync itself
        # can degrade (broadcast timeout, nothing committed yet): with
        # no agreed step there is nothing to gate on, and an ungated
        # exchange would blend divergent boundaries SILENTLY — fail the
        # recovery toward the checkpoint restart instead.
        if replay is None:
            raise RuntimeError(
                "replay-point sync yielded no agreed step (broadcast "
                "failed or no boundary was committed): the zero_boundary "
                "re-carve cannot be step-gated and survivors' boundaries "
                "may diverge — escalate to the checkpoint restart")
        recarve_after_shrink(peer, zero_boundary, old_workers,
                             expect_step=replay[0])
    if shrunk and stage_boundary is not None:
        from kungfu_tpu.parallel.pp import recarve_stages_after_shrink

        # rung 10: re-balance pipeline stages over the survivors — the
        # same step gate as the ZeRO re-carve, for the same reason
        if replay is None:
            raise RuntimeError(
                "replay-point sync yielded no agreed step (broadcast "
                "failed or no boundary was committed): the stage "
                "re-carve cannot be step-gated and survivors' boundaries "
                "may diverge — escalate to the checkpoint restart")
        recarve_stages_after_shrink(peer, stage_boundary, old_workers,
                                    expect_step=replay[0])
    return shrunk, replay


def _sync_replay_point(peer, snapshot):
    """All survivors adopt the leader's committed boundary: the lowest
    surviving rank broadcasts its :class:`StepSnapshot` wire form over
    the (already-shrunk) worker list; everyone else adopts it.  A
    survivor one committed step ahead of the leader deliberately steps
    back — consistency of the replayed step beats that one step of
    progress (the alternative is a cluster-wide rendezvous livelock)."""
    survivors = peer.cluster.workers
    version = peer.cluster_version
    name = f"kf.shrink.replay.v{version}"
    # rank=None → the module default (the process's stable identity set
    # at Peer.start) stamps the event; the POST-shrink rank would alias
    # a dead peer's id in the merged timeline
    timeline.event("shrink", "replay", version=version,
                   new_rank=survivors.rank(peer.config.self_id))
    try:
        if survivors.rank(peer.config.self_id) == 0:
            peer.channel.broadcast_bytes(
                snapshot.serialize(), survivors, name,
                send_retries=_RECOVERY_SEND_RETRIES,
            )
            return snapshot.last()
        blob = peer.channel.broadcast_bytes(None, survivors, name)
        return snapshot.adopt(blob)
    except (TimeoutError, ConnectionError, OSError, ValueError) as e:
        _log.warning(
            "no agreed replay point (%s); continuing without replay", e
        )
        return None
