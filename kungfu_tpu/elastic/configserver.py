"""HTTP cluster-config store (+ the mounted live-monitoring plane).

REST parity with reference ``elastic/configserver/configserver.go:24-112``:

* ``GET  /get``   → ``{"version": N, "cluster": {...}}`` (404 when cleared)
* ``PUT  /put``   → body = cluster JSON; validated; version++
* ``POST /reset`` → body = cluster JSON; reset to version 0
* ``DELETE /``    → clear
* ``GET  /stop``  → shut the server down

When a :class:`~kungfu_tpu.monitor.aggregator.ClusterAggregator` is
mounted (``kfrun -monitor`` / ``kf-config-server -monitor``), three more
routes serve the live cluster plane — co-hosted here because this is the
one process every peer already knows the address of, and it survives a
shrink:

* ``POST /push``    → rank snapshot / control-event intake
* ``GET  /cluster`` → the rolling cluster view (JSON; ``kftop`` renders it)
* ``GET  /metrics`` → cluster-plane Prometheus text
* ``GET  /alerts``  → the kf-sentinel alert state (active rules, fired
  alerts, detector verdicts) — 404 unless a Sentinel is attached to the
  mounted aggregator (``kfrun -sentinel`` / ``KF_SENTINEL_DIR``)
* ``GET  /decisions`` → the kf-ledger view (recent decision records
  joined to their measured effects, plus the summary) — same 404
  contract as ``/alerts``
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.utils.log import get_logger

_log = get_logger("config-server")


class ConfigServer:
    def __init__(self, port: int = 9100, cluster: Optional[Cluster] = None,
                 host: str = "0.0.0.0", aggregator=None):
        self.port = port
        self._lock = threading.Lock()
        self._cluster = cluster
        self._version = 0
        self._thread: Optional[threading.Thread] = None
        #: mounted live-monitoring plane (None = routes answer 404)
        self.aggregator = aggregator
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                _log.debug(fmt, *args)

            def _reply(self, code: int, body: bytes = b"",
                       content_type: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", "0"))
                return self.rfile.read(n)

            def do_GET(self):
                if self.path.startswith("/stop"):
                    self._reply(200, b"{}")
                    threading.Thread(target=srv.stop, daemon=True).start()
                    return
                if self.path.startswith("/cluster"):
                    agg = srv.aggregator
                    if agg is None:
                        self._reply(404, b'{"error": "no aggregator"}')
                        return
                    view = agg.cluster_view(srv._cluster_info())
                    self._reply(200, json.dumps(view).encode())
                    return
                if self.path.startswith("/alerts"):
                    agg = srv.aggregator
                    sentinel = getattr(agg, "_sentinel", None)
                    if agg is None or sentinel is None:
                        self._reply(404, b'{"error": "no sentinel"}')
                        return
                    self._reply(200,
                                json.dumps(sentinel.alerts_view()).encode())
                    return
                if self.path.startswith("/decisions"):
                    agg = srv.aggregator
                    sentinel = getattr(agg, "_sentinel", None)
                    if agg is None or sentinel is None:
                        self._reply(404, b'{"error": "no sentinel"}')
                        return
                    self._reply(
                        200, json.dumps(sentinel.ledger.view()).encode())
                    return
                if self.path.startswith("/metrics"):
                    agg = srv.aggregator
                    if agg is None:
                        self._reply(404, b'{"error": "no aggregator"}')
                        return
                    from kungfu_tpu.monitor.registry import REGISTRY

                    # cluster view + this process's own registry (the
                    # aggregator ticks kf_cluster_control_events_total
                    # there — it must be scrapeable somewhere)
                    text = (agg.render_prometheus(srv._cluster_info())
                            + REGISTRY.render_prometheus())
                    self._reply(200, text.encode(),
                                content_type="text/plain; version=0.0.4")
                    return
                with srv._lock:
                    if srv._cluster is None:
                        self._reply(404, b'{"error": "no cluster"}')
                        return
                    body = json.dumps(
                        {"version": srv._version, "cluster": json.loads(srv._cluster.to_json())}
                    ).encode()
                self._reply(200, body)

            def do_PUT(self):
                try:
                    cluster = Cluster.from_json(self._body().decode())
                except (ValueError, KeyError) as e:
                    self._reply(400, json.dumps({"error": str(e)}).encode())
                    return
                with srv._lock:
                    srv._cluster = cluster
                    srv._version += 1
                    v = srv._version
                _log.info("cluster updated to version %d (n=%d)", v, cluster.size())
                self._reply(200, json.dumps({"version": v}).encode())

            def do_POST(self):
                if self.path.startswith("/push"):
                    agg = srv.aggregator
                    if agg is None:
                        self._reply(404, b'{"error": "no aggregator"}')
                        return
                    try:
                        agg.ingest(json.loads(self._body().decode()))
                    except (ValueError, KeyError) as e:
                        self._reply(400, json.dumps({"error": str(e)}).encode())
                        return
                    self._reply(200, b"{}")
                    return
                try:
                    cluster = Cluster.from_json(self._body().decode())
                except (ValueError, KeyError) as e:
                    self._reply(400, json.dumps({"error": str(e)}).encode())
                    return
                with srv._lock:
                    srv._cluster = cluster
                    srv._version = 0
                self._reply(200, b'{"version": 0}')

            def do_DELETE(self):
                with srv._lock:
                    srv._cluster = None
                    srv._version = 0
                self._reply(200, b"{}")

        self._server = ThreadingHTTPServer((host, port), Handler)
        # port=0 asks the kernel for an ephemeral port — reflect the
        # actual binding so .url works
        self.port = self._server.server_address[1]
        self._server.daemon_threads = True

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/get"

    def start(self) -> "ConfigServer":
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def snapshot(self):
        with self._lock:
            return self._version, self._cluster

    def _cluster_info(self) -> Optional[dict]:
        """``{version, size, workers}`` for the aggregator's cluster
        health, or None when no cluster is stored.  Takes and releases
        the config lock BEFORE the aggregator's own lock is touched —
        the two must never nest (pylockorder)."""
        version, cluster = self.snapshot()
        if cluster is None:
            return None
        return {
            "version": version,
            "size": cluster.size(),
            "workers": [str(w) for w in cluster.workers],
        }


def main(argv=None) -> int:
    """Standalone elastic config server (reference
    ``cmd/kungfu-config-server/kungfu-config-server.go:19-30``)."""
    import argparse
    import time

    p = argparse.ArgumentParser(prog="kf-config-server")
    p.add_argument("-port", type=int, default=9100)
    p.add_argument("-host", default="0.0.0.0")
    p.add_argument("-monitor", action="store_true",
                   help="mount the live cluster aggregator "
                        "(/push, /cluster, /metrics; view with kftop)")
    ns = p.parse_args(argv)
    aggregator = None
    if ns.monitor:
        from kungfu_tpu.monitor.aggregator import ClusterAggregator

        aggregator = ClusterAggregator()
        # KF_SENTINEL_DIR in the environment attaches the judging
        # plane (history + detectors + /alerts); unset = no sentinel,
        # byte-identical aggregator (monitor/sentinel.py cost contract)
        from kungfu_tpu.monitor.sentinel import Sentinel

        sentinel = Sentinel.from_env()
        if sentinel is not None:
            aggregator.attach_sentinel(sentinel)
    srv = ConfigServer(port=ns.port, host=ns.host,
                       aggregator=aggregator).start()
    _log.info("config server listening on %s:%d", ns.host, ns.port)
    try:
        while srv._thread is not None and srv._thread.is_alive():
            time.sleep(0.5)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
