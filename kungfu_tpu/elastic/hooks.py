"""Elastic train-loop driver.

Parity with reference ``KungFuElasticTrainHook`` (``hooks/elastic.py:14-87``)
and the policy hooks: once per training step the loop (1) re-syncs the
global step by allreduce-MAX, (2) proposes the scheduled cluster size,
(3) runs the resize protocol, and (4) after a membership change
re-broadcasts params from rank 0 and re-syncs the step — or stops if this
worker was detached.

New workers spawned mid-job by the watch runner join at the new cluster
version; their *initial* ``broadcast_parameters`` call (named by cluster
version) rendezvouses with the survivors' *re*-broadcast, so state flows
to them without a checkpoint.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

import numpy as np

from kungfu_tpu.chaos import note_step as _chaos_note_step
from kungfu_tpu.elastic.schedule import step_based_schedule
from kungfu_tpu.initializer import broadcast_parameters
from kungfu_tpu.monitor import timeline
from kungfu_tpu.monitor.signals import monitor_compile_grace
from kungfu_tpu.utils.log import get_logger, log_event

_log = get_logger("elastic")


@dataclass
class ElasticState:
    step: int = 0
    detached: bool = False
    resized: int = 0  # number of membership changes survived


def sync_step(peer, step: int) -> int:
    """Cluster-wide step = MAX over workers (reference
    ``hooks/elastic.py:33,50-52``) — new joiners jump to the global step."""
    engine = peer.engine()
    if engine is None:
        return step
    # auto-named (engine sequence numbers): a joiner's first sync must
    # rendezvous with the survivors' Nth — names must not embed the step
    out = engine.all_reduce(np.array([step], np.int64), op="max")
    return int(out[0])


def elastic_step(
    peer,
    state: ElasticState,
    schedule: Optional[str],
    params,
    zero_boundary=None,
    bandit=None,
) -> Tuple[ElasticState, object, bool]:
    """Run once per completed training step.

    Returns ``(new_state, params, should_stop)``; ``params`` are re-broadcast
    when membership changed.

    ``bandit`` (a kf-adapt driver, :mod:`kungfu_tpu.monitor.adapt_device`)
    gets ``on_membership_change()`` after a resize: bandit state survives
    the resize by *re-exploring* — a 4-rank arm table says nothing about
    the 2-rank regime, so the measured winners are re-learned on the new
    membership instead of carried stale.

    Call order per training step is: local grads → gradient allreduce →
    apply → ``elastic_step``.  The step re-sync happens *first* here so a
    newly-joined worker (local step 0) jumps to the global step before the
    schedule is consulted — otherwise it would propose the schedule's
    step-0 size and shrink the cluster it just joined."""
    # fault injection rendezvous: `die:step=N` clauses fire here, at the
    # same step boundary on every rank (no-op unless KF_CHAOS_SPEC).
    # chaos_rank, not rank(): clause targeting survives rank reshuffles
    _chaos_note_step(peer.chaos_rank(), state.step)
    # note_step above already stamped the flight recorder's step counter;
    # the mark makes the step boundary itself visible in merged timelines
    timeline.event("step", f"step{state.step}", rank=peer.chaos_rank())
    step = sync_step(peer, state.step)
    target = step_based_schedule(schedule, step) if schedule else peer.size()
    changed = False
    old_workers = peer.cluster.workers  # pre-resize membership (recarve)
    if target != peer.size():
        log_event(f"proposing-resize-{peer.size()}->{target}-at-step-{step}")
        if peer.config.config_server:
            peer.propose_new_size(target)
            changed = peer.resize_cluster_from_url()
        else:
            _log.warning("no config server; cannot resize to %d", target)
    if changed:
        if zero_boundary is not None:
            # ZeRO-sharded optimizer state does not ride the params
            # broadcast (each rank holds 1/n): re-carve the committed
            # boundary leaderlessly for the new membership.  This runs
            # BEFORE the detach check — a planned resize's leavers are
            # alive and must serve their segments (nobody died, so no
            # ``dead`` set); survivors then restore the sharded state
            # with ``zero_boundary.place(new communicator)``.
            #
            # The exchange is symmetric: every NEW rank must be running
            # the same recarve.  elastic_step cannot arrange that for a
            # pure joiner (a fresh process sees `changed=False` here; a
            # rejoining standby adopted the cluster in await_rejoin) —
            # its side of the wiring is ZeroBoundary.join() + recarve
            # with the same memberships and tag, which only the
            # application can place in the joiner's startup path.
            # Proceeding would strand the joiner's segments in its
            # channel queue and leave it training on init_opt zeros, so
            # grows with unwired joiners fail loudly instead.
            joiners = [w for w in peer.cluster.workers
                       if old_workers.rank(w) is None]
            if joiners:
                raise ValueError(
                    f"elastic_step cannot re-carve ZeRO state through a "
                    f"grow with pure joiners ({len(joiners)} new "
                    "worker(s)): joiners must symmetrically run "
                    "ZeroBoundary.join() + recarve in their startup path "
                    "(see docs/zero.md), or restore from a checkpoint")
            zero_boundary.recarve(
                peer.size(), peer=peer, old_workers=old_workers,
                new_workers=peer.cluster.workers,
                tag=f"v{peer.cluster_version}",
            )
        if peer.detached:
            log_event("detached-stopping")
            return replace(state, detached=True), params, True
        if bandit is not None:
            # survivors re-explore: the engines/communicators are rebuilt
            # for the new membership, so the measured arm tables reset
            # BEFORE any new-epoch window can be charged to a stale
            # winner.  After the detach check — a detached peer has no
            # engine in the new membership to re-anchor on
            bandit.on_membership_change(peer.cluster_version)
        log_event(f"resynced-after-resize-v{peer.cluster_version}")
        # the new cluster shape re-jits the training step (new mesh ⇒
        # fresh XLA compile, multi-ten-second on TPU); tell the failure
        # detector so the next batch's stall allowance is compile-sized,
        # not heartbeat-sized (no-op when monitoring is off)
        monitor_compile_grace(peer.rank())
        # re-broadcast runs on the host channel (safe while the new engine
        # is cold).  Do NOT run an engine collective here: a joiner's first
        # engine op is its step's gradient allreduce, so the survivors'
        # first new-epoch engine op must be the same — alignment happens at
        # the top of the next elastic_step via sync_step.
        params = broadcast_parameters(params, peer)
        return (
            ElasticState(step=step + 1, resized=state.resized + 1),
            params,
            False,
        )
    return replace(state, step=step + 1), params, False
