"""Worker-side resize protocol: fetch + consensus.

Parity with reference ``peer/peer.go:236-276``: loop — GET the cluster
JSON from the config server, run a bytes-consensus over its digest among
the *current* workers until every peer observed the same config, then hand
the agreed (cluster, version) to ``Peer._propose``.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Tuple

from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.utils.log import get_logger

_log = get_logger("resize")

FETCH_RETRY_PERIOD_S = 0.2
DEFAULT_TIMEOUT_S = 120.0


def fetch_cluster(url: str) -> Tuple[Cluster, int]:
    with urllib.request.urlopen(url, timeout=10) as resp:
        doc = json.loads(resp.read().decode())
    cluster = Cluster.from_json(json.dumps(doc["cluster"]))
    return cluster, int(doc["version"])


def fetch_cluster_with_consensus(peer, timeout: float = DEFAULT_TIMEOUT_S) -> Tuple[Cluster, int]:
    """All current workers converge on one (cluster, version) snapshot."""
    url = peer.config.config_server
    deadline = time.time() + timeout
    attempt = 0
    while True:
        if time.time() > deadline:
            raise TimeoutError(f"no consensus on cluster config after {timeout}s")
        try:
            cluster, version = fetch_cluster(url)
        except (urllib.error.URLError, OSError, KeyError, ValueError) as e:
            _log.debug("config fetch failed: %s", e)
            time.sleep(FETCH_RETRY_PERIOD_S)
            continue
        payload = cluster.digest() + version.to_bytes(8, "little")
        if peer.consensus_bytes(payload, name=f"resize.{attempt}"):
            return cluster, version
        attempt += 1
        time.sleep(FETCH_RETRY_PERIOD_S)
