"""Worker-side resize protocol: fetch + consensus.

Parity with reference ``peer/peer.go:236-276``: loop — GET the cluster
JSON from the config server, run a bytes-consensus over its digest among
the *current* workers until every peer observed the same config, then hand
the agreed (cluster, version) to ``Peer._propose``.

Retry discipline: every worker runs this loop at once, so a constant
retry period turns a config-server hiccup into a synchronized thundering
herd the instant it comes back — fetch failures back off exponentially
(jittered, capped) instead.  The *consensus* retry keeps a short mean
delay (peers genuinely racing one PUT converge within a round or two)
but jitters it so N workers don't re-gather in lockstep.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Tuple

from kungfu_tpu.chaos import controller_for as _chaos_controller_for
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.utils.log import get_logger
from kungfu_tpu.utils.retry import jittered, sleep_backoff

_log = get_logger("resize")

FETCH_RETRY_PERIOD_S = 0.2
FETCH_RETRY_CAP_S = 2.0
DEFAULT_TIMEOUT_S = 120.0


def slice_aligned_size(peer, new_size: int) -> int:
    """Clamp a proposed worker count to whole slices on a multislice
    pod (``Peer.propose_new_size`` calls this before the PUT): planned
    elasticity grows/shrinks by slices — a worker count that splits a
    slice would leave chips with no within-slice mesh.  Single-slice
    jobs pass through untouched."""
    topo = peer.slice_topology()
    if topo is None:
        return new_size
    from kungfu_tpu.elastic.slices import align_to_slices

    aligned = align_to_slices(new_size, topo)
    if aligned != new_size:
        _log.warning(
            "proposed size %d is not whole slices (%d ranks/slice) — "
            "aligning to %d", new_size, topo.ranks_per_slice, aligned,
        )
    return aligned


def fetch_cluster(url: str, chaos=None) -> Tuple[Cluster, int]:
    if chaos is not None and chaos.config_unavailable():
        raise urllib.error.URLError("chaos: config-server unavailability window")
    with urllib.request.urlopen(url, timeout=10) as resp:
        doc = json.loads(resp.read().decode())
    cluster = Cluster.from_json(json.dumps(doc["cluster"]))
    return cluster, int(doc["version"])


def fetch_cluster_with_consensus(peer, timeout: float = DEFAULT_TIMEOUT_S) -> Tuple[Cluster, int]:
    """All current workers converge on one (cluster, version) snapshot."""
    url = peer.config.config_server
    # chaos_rank (the stable bootstrap identity), NOT the current rank:
    # a shrink promotes survivor ranks, and a rank-scoped config_down
    # clause must not re-fire on the promoted survivor
    chaos = _chaos_controller_for(peer.chaos_rank())
    deadline = time.time() + timeout
    attempt = 0
    failures = 0
    while True:
        if time.time() > deadline:
            raise TimeoutError(f"no consensus on cluster config after {timeout}s")
        try:
            cluster, version = fetch_cluster(url, chaos)
        except (urllib.error.URLError, OSError, KeyError, ValueError) as e:
            _log.debug("config fetch failed: %s", e)
            sleep_backoff(failures, base=FETCH_RETRY_PERIOD_S,
                          cap=FETCH_RETRY_CAP_S)
            failures += 1
            continue
        failures = 0
        payload = cluster.digest() + version.to_bytes(8, "little")
        # the consensus round index is part of the rendezvous name, so it
        # MUST advance identically on every peer — only the local sleep
        # between rounds is jittered, never the attempt counter
        if peer.consensus_bytes(payload, name=f"resize.{attempt}"):
            return cluster, version
        attempt += 1
        time.sleep(jittered(FETCH_RETRY_PERIOD_S))
