"""Step-based resize schedules.

Parity with reference ``StepBasedSchedule`` (``tensorflow/ops/cpu/
elastic.cpp:16-82`` + ``ops/adapt.py step_based_schedule``): a config
string ``"size:steps,size:steps,..."`` mapping training-step ranges to
cluster sizes, e.g. ``"1:100,2:100,4:200"`` = 100 steps at 1 worker, 100
at 2, 200 at 4.  After the schedule ends, the last size holds.
"""

from __future__ import annotations

from typing import List, Tuple


def parse_schedule(config: str) -> List[Tuple[int, int]]:
    """→ list of (size, steps); validates positivity."""
    out = []
    for part in config.split(","):
        part = part.strip()
        if not part:
            continue
        size_s, steps_s = part.split(":")
        size, steps = int(size_s), int(steps_s)
        if size <= 0 or steps <= 0:
            raise ValueError(f"invalid schedule entry {part!r}")
        out.append((size, steps))
    if not out:
        raise ValueError(f"empty schedule {config!r}")
    return out


def step_based_schedule(config: str, step: int) -> int:
    """Cluster size scheduled for ``step``."""
    sched = parse_schedule(config)
    off = 0
    for size, steps in sched:
        off += steps
        if step < off:
            return size
    return sched[-1][0]


def total_steps(config: str) -> int:
    return sum(steps for _, steps in parse_schedule(config))
