"""Elasticity — online cluster resize without restarting the job.

Parity with the reference's headline capability (``resize_cluster``):

* :mod:`kungfu_tpu.elastic.configserver` — HTTP cluster-config store
  (reference ``srcs/go/kungfu/elastic/configserver``);
* :mod:`kungfu_tpu.elastic.resize` — worker-side fetch + consensus
  protocol (reference ``peer/peer.go:227-276``);
* :mod:`kungfu_tpu.elastic.schedule` — ``step_based_schedule`` config
  parsing (reference ``tensorflow/ops/cpu/elastic.cpp:16-82``);
* :mod:`kungfu_tpu.elastic.hooks` — the elastic train loop driver
  (reference ``hooks/elastic.py`` KungFuElasticTrainHook);
* :mod:`kungfu_tpu.elastic.shrink` — in-flight peer-failure recovery:
  exclusion consensus among the survivors, shrunk mesh epoch, replay
  from the last committed step (no reference analog — the reference's
  only recovery is the whole-job relaunch this makes the last resort);
* :mod:`kungfu_tpu.elastic.persist` — the durable state plane: async
  sharded checkpoints under digest-verified manifests and
  checkpoint-shape-agnostic cold restore onto any world size (the
  recovery rung below shrink — survives a whole-job preemption; see
  docs/persistence.md).

On TPU a resize is a **mesh-epoch swap**: membership changes on the host
plane (consensus + runner notify), then the next ``communicator()`` /
``engine()`` call builds the new epoch and state is re-broadcast from rank
0 — the analog of the reference's new Session + ``ResetNcclHelper``.
"""

from kungfu_tpu.elastic.configserver import ConfigServer
from kungfu_tpu.elastic.schedule import step_based_schedule, parse_schedule
from kungfu_tpu.elastic.hooks import ElasticState, elastic_step
from kungfu_tpu.elastic.shrink import (
    find_dead_ranks,
    recover_from_peer_failure,
    shrink_to_survivors,
)
from kungfu_tpu.elastic.persist import (
    PersistPlane,
    RestoredState,
    newest_complete_manifest,
    restore_from_manifest,
)

__all__ = [
    "ConfigServer",
    "step_based_schedule",
    "parse_schedule",
    "ElasticState",
    "elastic_step",
    "find_dead_ranks",
    "recover_from_peer_failure",
    "shrink_to_survivors",
    "PersistPlane",
    "RestoredState",
    "newest_complete_manifest",
    "restore_from_manifest",
]
