"""Device prefetch: overlap host→device transfer with compute.

The training loops in this framework consume numpy batches
(:class:`~kungfu_tpu.datasets.adaptor.ElasticDataset`, the loader
helpers); every ``step(params, opt, batch)`` call then pays the
host→device copy on the critical path.  ``prefetch_to_device`` wraps any
batch iterator and keeps ``size`` batches already resident on device: a
background thread stages batch N+k while the step computes on batch N —
the standard TPU input-pipeline overlap (flax's ``jax_utils.prefetch``
shape, re-homed here so the elastic loaders get it too).

The transfer thread only calls ``jax.device_put`` (safe off-thread);
iterator exhaustion and worker exceptions propagate to the consumer.
On resize, drop the prefetcher with the rest of the mesh epoch and wrap
the (re-sharded) iterator again — staged batches belong to a device
layout that no longer exists.

Consumption accounting caveat: the SOURCE iterator runs up to ``size``
batches ahead of what the training loop has actually used.  A loader
that tracks consumed samples (:class:`ElasticDataset`) will therefore
have over-counted by the staged batches at the moment of a resize;
either rewind with ``skip(actually_consumed)`` before re-wrapping, or
prefetch only within resize-free spans (e.g. re-wrap per epoch, resize
at epoch boundaries — the shape every elastic example here uses).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

import jax

_SENTINEL = object()


def prefetch_to_device(it: Iterable, size: int = 2,
                       device=None) -> Iterator:
    """Yield items of ``it`` with up to ``size`` of them pre-staged on
    ``device`` (default: the default device).  Each item is passed
    through ``jax.device_put`` as a pytree.

    A plain function (not a generator): validation and the transfer
    thread start EAGERLY at the call, so staging overlaps any setup the
    caller does before its loop.  Closing/abandoning the returned
    iterator (including ``break`` and the per-resize re-wrap this module
    recommends) stops the worker and releases the staged device buffers
    — a blocked producer must not pin HBM forever.
    """
    if size < 1:
        raise ValueError("size must be >= 1")
    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = threading.Event()

    def offer(item) -> bool:
        """put() that a consumer shutdown can always unblock."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not offer(jax.device_put(item, device)):
                    return
            offer(_SENTINEL)
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            offer(e)

    t = threading.Thread(target=worker, daemon=True, name="kf-prefetch")
    t.start()

    def gen():
        from kungfu_tpu.monitor import timeline

        try:
            while True:
                # consumer-side wait: the worker always terminates the
                # stream (sentinel or exception object), so an unbounded
                # block here ends exactly when the producer does.  The
                # kf-xray `input` span times this block — the
                # input-pipeline stall the step-time attribution charges
                # to `input_stall` (docs/xray.md); a warm queue records
                # ~0, an empty one records exactly the stall
                with timeline.span("input", "prefetch.next"):
                    item = q.get()  # kflint: allow(blocking-io)
                if item is _SENTINEL:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            try:  # unblock a producer waiting on a full queue
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(5)

    return gen()
