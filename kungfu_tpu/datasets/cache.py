"""Shared dataset cache-base resolution (one policy for every helper)."""

from __future__ import annotations

import os

DATA_DIR_ENV = "KF_DATA_DIR"


def cache_dir(name: str) -> str:
    """``$KF_DATA_DIR`` (default ``~/.cache/kungfu_tpu``) ``/<name>``."""
    base = os.environ.get(DATA_DIR_ENV) or os.path.join(
        os.path.expanduser("~"), ".cache", "kungfu_tpu"
    )
    return os.path.join(base, name)
