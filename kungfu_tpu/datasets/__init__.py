from kungfu_tpu.datasets.adaptor import ElasticDataset  # noqa: F401
