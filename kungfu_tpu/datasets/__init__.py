from kungfu_tpu.datasets.adaptor import ElasticDataset  # noqa: F401
from kungfu_tpu.datasets.cifar import load_cifar10  # noqa: F401
from kungfu_tpu.datasets.imagenet import ImageNetFolder  # noqa: F401
from kungfu_tpu.datasets.mnist import load_mnist, synthetic_mnist  # noqa: F401
from kungfu_tpu.datasets.prefetch import prefetch_to_device  # noqa: F401
