"""Elastic dataset adaptor — shard/batch/skip that survives resizes.

Parity with reference ``kungfu/tensorflow/v1/datasets/adaptor.py:4-45``,
which rebuilds a tf.data pipeline from mutable shard/offset variables so a
worker joining (or surviving) a resize continues from the global sample
offset instead of restarting the epoch.  Here the adaptor is an indexable-
array pipeline (numpy in, device batch out):

* a deterministic per-epoch global permutation (all ranks agree on it by
  seed — no coordination needed);
* the global stream is cut into *global batches* of
  ``batch_size × cluster_size``; each rank takes its ``rank``-th slice;
* progress is tracked in **samples consumed**, so after ``set_cluster``
  (resize) or a restart, ``skip(consumed)`` resumes exactly where the old
  cluster stopped, under the new shape.

Short final batches are dropped (every rank must see the same batch count
per epoch or collectives deadlock — same invariant as the reference).
"""

from __future__ import annotations

from typing import Iterator, Sequence, Tuple

import numpy as np


class ElasticDataset:
    def __init__(
        self,
        arrays: Sequence[np.ndarray],
        batch_size: int,
        rank: int = 0,
        size: int = 1,
        seed: int = 0,
        shuffle: bool = True,
    ):
        arrays = [np.asarray(a) for a in arrays]
        if not arrays:
            raise ValueError("need at least one array")
        n = len(arrays[0])
        for a in arrays:
            if len(a) != n:
                raise ValueError("arrays must share the leading dimension")
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.arrays = arrays
        self.n = n
        self.batch_size = batch_size
        self.seed = seed
        self.shuffle = shuffle
        self.consumed = 0  # global samples consumed across the cluster
        self.set_cluster(rank, size)

    # -- elasticity -------------------------------------------------------
    def set_cluster(self, rank: int, size: int) -> None:
        """Re-shard after a membership change (the reference's mutable
        shard variables).  ``consumed`` is kept: the stream continues."""
        if not (0 <= rank < size):
            raise ValueError(f"rank {rank} outside size {size}")
        self.rank = rank
        self.size = size

    def skip(self, consumed_samples: int) -> None:
        """Fast-forward the global stream (restart/recovery resume)."""
        if consumed_samples < 0:
            raise ValueError("consumed_samples must be >= 0")
        self.consumed = consumed_samples

    # -- iteration --------------------------------------------------------
    @property
    def global_batch(self) -> int:
        return self.batch_size * self.size

    def batches_per_epoch(self) -> int:
        return self.n // self.global_batch

    def _epoch_perm(self, epoch: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(self.n)
        return np.random.default_rng((self.seed, epoch)).permutation(self.n)

    def next_batch(self) -> Tuple[np.ndarray, ...]:
        """The next per-rank batch at the current global offset."""
        gb = self.global_batch
        per_epoch = self.batches_per_epoch() * gb
        if per_epoch == 0:
            raise ValueError(
                f"dataset of {self.n} samples smaller than one global batch {gb}"
            )
        # realign to a global-batch boundary: after a resize mid-epoch the
        # consumed count may not divide the new global batch
        offset = ((self.consumed + gb - 1) // gb) * gb
        epoch, pos = divmod(offset, per_epoch)
        perm = self._epoch_perm(epoch)
        sl = perm[pos + self.rank * self.batch_size:
                  pos + (self.rank + 1) * self.batch_size]
        self.consumed = offset + gb
        return tuple(a[sl] for a in self.arrays)

    def sync_consumed(self, peer) -> int:
        """Adopt the cluster-wide MAX consumed-samples offset (host-plane
        allreduce).  A worker that just joined (or restarted without a
        local checkpoint) holds offset 0 while survivors are mid-stream;
        without this sync each rank would slice a DIFFERENT global batch
        and the data-parallel step would silently mix sample windows.

        Call it at the same engine-op sequence point on every member:
        right after ``broadcast_parameters`` at startup, and right after
        ``set_cluster`` in the resize branch (see
        ``examples/cifar_elastic.py``)."""
        engine = peer.engine()
        if engine is not None:
            # control-plane traffic: record=False keeps the rendezvous
            # wait at resize boundaries out of the strategy-adaptation
            # throughput windows
            out = engine.all_reduce(
                np.array([self.consumed], np.int64), op="max", record=False
            )
            self.skip(int(out[0]))
        return self.consumed

    def epochs(self, n_epochs: int) -> Iterator[Tuple[np.ndarray, ...]]:
        """Iterate whole epochs from the current offset."""
        gb = self.global_batch
        per_epoch = self.batches_per_epoch() * gb
        if per_epoch == 0:
            raise ValueError(
                f"dataset of {self.n} samples smaller than one global batch {gb}"
            )
        end = (self.consumed // per_epoch + n_epochs) * per_epoch
        while self.consumed < end:
            yield self.next_batch()
