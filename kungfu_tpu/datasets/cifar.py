"""Real CIFAR-10 loader: cache-or-download, hash-pinned, synthetic fallback.

Parity with the reference's dataset helpers
(``srcs/python/kungfu/tensorflow/v1/helpers/cifar.py`` — downloads the
CIFAR archive and feeds it to the examples/benchmarks).  Same TPU-build
hardening as :mod:`kungfu_tpu.datasets.mnist`:

* the archive is verified against a pinned SHA-256 before use;
* air-gapped environments fall back to a deterministic synthetic set with
  a loud warning (``synthetic_fallback=False`` restores strict behavior).

Cache layout: ``$KF_DATA_DIR`` (default ``~/.cache/kungfu_tpu``)
``/cifar10/cifar-10-python.tar.gz``.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tarfile
import urllib.request
from typing import Tuple

import numpy as np

from kungfu_tpu.utils.log import get_logger

_log = get_logger("cifar")

from kungfu_tpu.datasets.cache import DATA_DIR_ENV  # noqa: F401

ARCHIVE = "cifar-10-python.tar.gz"
#: canonical archive digest (stable since 2009)
ARCHIVE_SHA256 = "6d958be074577803d12ecdefd02955f39262c83c16fe9348329d7fe0b5c001ce"

MIRRORS = (
    "https://www.cs.toronto.edu/~kriz/",
    "https://ossci-datasets.s3.amazonaws.com/cifar/",
)

NUM_CLASSES = 10
IMAGE_SHAPE = (32, 32, 3)


def data_dir() -> str:
    from kungfu_tpu.datasets.cache import cache_dir

    return cache_dir("cifar10")


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _fetch(dest: str, timeout: float) -> bool:
    for mirror in MIRRORS:
        try:
            tmp = dest + ".part"
            with urllib.request.urlopen(mirror + ARCHIVE, timeout=timeout) as r, open(
                tmp, "wb"
            ) as f:
                for block in iter(lambda: r.read(1 << 20), b""):
                    f.write(block)
            os.replace(tmp, dest)
            return True
        except OSError as e:
            _log.debug("mirror %s failed: %s", mirror, e)
    return False


def _read_batches(archive_path: str):
    """Extract (train_x, train_y, test_x, test_y) uint8 arrays from the
    tar without unpacking it to disk."""
    train_x, train_y = [], []
    test_x = test_y = None
    with tarfile.open(archive_path, "r:gz") as tf:
        for member in tf.getmembers():
            name = os.path.basename(member.name)
            if not (name.startswith("data_batch_") or name == "test_batch"):
                continue
            f = tf.extractfile(member)
            if f is None:
                continue
            d = pickle.load(f, encoding="bytes")
            x = np.asarray(d[b"data"], np.uint8).reshape(-1, 3, 32, 32)
            x = x.transpose(0, 2, 3, 1)  # NCHW on disk -> NHWC for TPU convs
            y = np.asarray(d[b"labels"], np.int32)
            if name == "test_batch":
                test_x, test_y = x, y
            else:
                train_x.append((name, x))
                train_y.append((name, y))
    if len(train_x) != 5 or test_x is None:
        raise ValueError(f"{archive_path}: incomplete CIFAR-10 archive")
    train_x.sort()
    train_y.sort()
    return (
        np.concatenate([x for _, x in train_x]),
        np.concatenate([y for _, y in train_y]),
        test_x,
        test_y,
    )


def _synthetic(n_train: int, n_test: int, seed: int = 0):
    """Deterministic class-conditioned blobs: each class gets a fixed
    random color/texture template plus noise — linearly separable enough
    for convergence tests, shaped exactly like the real set."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(size=(NUM_CLASSES,) + IMAGE_SHAPE).astype(np.float32)

    def make(n, salt):
        r = np.random.default_rng((seed, salt))
        y = r.integers(0, NUM_CLASSES, size=n).astype(np.int32)
        x = templates[y] * 0.35 + r.normal(size=(n,) + IMAGE_SHAPE).astype(np.float32) * 0.25
        x = np.clip(x * 0.5 + 0.5, 0.0, 1.0).astype(np.float32)
        return x, y

    return make(n_train, 1), make(n_test, 2)


def load_cifar10(
    verify: bool = True,
    synthetic_fallback: bool = True,
    timeout: float = 30.0,
    n_synthetic_train: int = 4096,
    n_synthetic_test: int = 512,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """Returns ``((x_train, y_train), (x_test, y_test))``; images are
    float32 NHWC in [0, 1], labels int32."""
    directory = data_dir()
    path = os.path.join(directory, ARCHIVE)
    if not os.path.exists(path):
        os.makedirs(directory, exist_ok=True)
        if not _fetch(path, timeout):
            if not synthetic_fallback:
                raise OSError(
                    f"cannot download {ARCHIVE} and no cache at {path}"
                )
            _log.warning(
                "CIFAR-10 unavailable (no egress?) — using a deterministic "
                "SYNTHETIC set; results are not comparable to real CIFAR"
            )
            return _synthetic(n_synthetic_train, n_synthetic_test)
    if verify:
        digest = _sha256(path)
        if digest != ARCHIVE_SHA256:
            raise ValueError(
                f"{path}: sha256 {digest} does not match the pinned digest "
                f"{ARCHIVE_SHA256} — delete the file and re-fetch"
            )
    train_x, train_y, test_x, test_y = _read_batches(path)
    to_f = lambda a: (a.astype(np.float32) / 255.0)
    return (to_f(train_x), train_y), (to_f(test_x), test_y)
