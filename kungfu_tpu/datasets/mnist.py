"""Real MNIST loader: cache-or-download, hash-pinned, synthetic fallback.

Parity with the reference's dataset helpers
(``srcs/python/kungfu/tensorflow/v1/helpers/mnist.py`` — it downloads the
IDX files and feeds them to the examples).  TPU-build differences:

* files are verified against pinned SHA-256 digests before use (a
  corrupted or swapped cache must not silently train garbage);
* air-gapped environments (no egress) fall back to a deterministic
  synthetic set with a loud warning instead of crashing, so the examples
  and convergence tests run everywhere (``synthetic_fallback=False``
  restores strict behavior).

Cache layout: ``$KF_DATA_DIR`` (default ``~/.cache/kungfu_tpu``)
``/mnist/<idx file>`` — either the raw IDX files or their ``.gz``
originals are accepted.
"""

from __future__ import annotations

import gzip
import hashlib
import os
import struct
import urllib.request
from typing import Optional, Tuple

import numpy as np

from kungfu_tpu.utils.log import get_logger

_log = get_logger("mnist")

from kungfu_tpu.datasets.cache import DATA_DIR_ENV  # noqa: F401

# canonical gzipped IDX files and their SHA-256 digests (stable since 1998)
FILES = {
    "train-images-idx3-ubyte.gz": "440fcabf73cc546fa21475e81ea370265605f56be210a4024d2ca8f203523609",
    "train-labels-idx1-ubyte.gz": "3552534a0a558bbed6aed32b30c495cca23d567ec52cac8be1a0730e8010255c",
    "t10k-images-idx3-ubyte.gz": "8d422c7b0a1c1c79245a5bcf07fe86e33eeafee792b84584aec276f5a2dbc4e6",
    "t10k-labels-idx1-ubyte.gz": "f7ae60f92e00ec6debd23a6088c31dbd2371eca3ffa0defaefb259924204aec6",
}

MIRRORS = (
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def data_dir() -> str:
    from kungfu_tpu.datasets.cache import cache_dir

    return cache_dir("mnist")


def _fetch(name: str, dest: str, timeout: float) -> bool:
    for mirror in MIRRORS:
        try:
            tmp = dest + ".part"
            with urllib.request.urlopen(mirror + name, timeout=timeout) as r, open(
                tmp, "wb"
            ) as f:
                f.write(r.read())
            os.replace(tmp, dest)
            return True
        except OSError as e:
            _log.debug("mirror %s failed for %s: %s", mirror, name, e)
    return False


def _read_idx(raw: bytes) -> np.ndarray:
    """Parse the IDX format (magic 0x801 labels / 0x803 images)."""
    magic, = struct.unpack(">I", raw[:4])
    ndim = magic & 0xFF
    if (magic >> 8) != 0x08 or ndim not in (1, 3):
        raise ValueError(f"not an MNIST IDX file (magic {magic:#x})")
    dims = struct.unpack(f">{ndim}I", raw[4 : 4 + 4 * ndim])
    data = np.frombuffer(raw, dtype=np.uint8, offset=4 + 4 * ndim)
    return data.reshape(dims)


def _load_file(directory: str, gz_name: str, verify: bool, timeout: float) -> Optional[np.ndarray]:
    gz_path = os.path.join(directory, gz_name)
    raw_path = gz_path[: -len(".gz")]
    if not os.path.exists(gz_path) and not os.path.exists(raw_path):
        os.makedirs(directory, exist_ok=True)
        if not _fetch(gz_name, gz_path, timeout):
            return None
    if os.path.exists(gz_path):
        if verify:
            digest = _sha256(gz_path)
            if digest != FILES[gz_name]:
                raise ValueError(
                    f"{gz_path}: sha256 {digest} does not match the pinned "
                    f"digest {FILES[gz_name]} — delete the file and re-fetch"
                )
        with gzip.open(gz_path, "rb") as f:
            return _read_idx(f.read())
    # pre-extracted raw IDX: there is no pin for the extracted form, so a
    # verified load cannot accept it (a swapped raw file would silently
    # train garbage — the exact thing the pins exist to stop); pass
    # verify=False to opt in to an unverified local cache
    if verify:
        raise ValueError(
            f"{raw_path} is an unverifiable raw cache (only the .gz "
            "originals are hash-pinned) — keep the .gz alongside it or "
            "load with verify=False"
        )
    with open(raw_path, "rb") as f:
        return _read_idx(f.read())


def synthetic_mnist(n: int = 4096, seed: int = 42) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic linearly-separable stand-in with MNIST shapes."""
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 28 * 28).astype(np.float32)
    w_true = rng.randn(28 * 28, 10).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1).astype(np.int32)
    return x, y


def load_mnist(
    split: str = "train",
    cache_dir: Optional[str] = None,
    normalize: bool = True,
    verify: bool = True,
    synthetic_fallback: bool = True,
    timeout: float = 20.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Return ``(images [N, 784] float32, labels [N] int32)``.

    Looks in the cache, then the download mirrors; with
    ``synthetic_fallback`` (default) an unreachable network degrades to
    :func:`synthetic_mnist` with a warning instead of failing — so the
    same example code runs on an air-gapped TPU pod and a laptop."""
    if split not in ("train", "test"):
        raise ValueError(f"split {split!r}")
    directory = cache_dir or data_dir()
    prefix = "train" if split == "train" else "t10k"
    try:
        images = _load_file(directory, f"{prefix}-images-idx3-ubyte.gz", verify, timeout)
        labels = _load_file(directory, f"{prefix}-labels-idx1-ubyte.gz", verify, timeout)
    except (ValueError, OSError):
        if not synthetic_fallback:
            raise
        images = labels = None
    if images is None or labels is None:
        if not synthetic_fallback:
            raise RuntimeError(
                f"MNIST {split} files unavailable in {directory} and no "
                "mirror reachable; place the IDX .gz files there"
            )
        _log.warning(
            "MNIST unavailable (no cache in %s, no egress) — using the "
            "deterministic synthetic stand-in", directory,
        )
        return synthetic_mnist()
    if len(images) != len(labels):
        raise ValueError(f"images/labels length mismatch {len(images)}/{len(labels)}")
    x = images.reshape(len(images), -1).astype(np.float32)
    if normalize:
        x /= 255.0
    return x, labels.astype(np.int32)
