"""ImageNet folder pipeline: lazy JPEG decode, standard augmentation,
elastic sharding.

Parity with the reference's ImageNet helper
(``srcs/python/kungfu/tensorflow/v1/helpers/imagenet.py`` — TFRecord
parse + random-crop/flip train pipeline feeding ResNet).  TPU-build
shape: the input is the standard ImageNet directory layout
(``<root>/<split>/<wnid>/*.JPEG``), decoding is lazy (per batch, PIL),
and the shard/offset machinery is COMPOSED from
:class:`~kungfu_tpu.datasets.adaptor.ElasticDataset` over the sample
indices — so the pipeline inherits resize-surviving elastic semantics
(``set_cluster``/``skip``/``sync_consumed``) instead of reimplementing
them.

No download: ImageNet is license-gated, so there is nothing to pin or
fetch.  Without a dataset directory the loader falls back to a
deterministic synthetic set (loudly), like the MNIST/CIFAR helpers.

Transforms (the standard ResNet recipe):

* train: random-resized crop (scale 0.08–1, ratio 3/4–4/3) → ``size²``,
  random horizontal flip;
* eval: resize short side by 256/224 (256 for size 224), center crop.

Both return float32 NHWC normalized with the ImageNet mean/std.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from kungfu_tpu.datasets.adaptor import ElasticDataset
from kungfu_tpu.utils.log import get_logger

_log = get_logger("imagenet")

from kungfu_tpu.datasets.cache import DATA_DIR_ENV, cache_dir  # noqa: F401

MEAN = np.array([0.485, 0.456, 0.406], np.float32)
STD = np.array([0.229, 0.224, 0.225], np.float32)


def default_root() -> str:
    return cache_dir("imagenet")


def _scan(split_dir: str) -> Tuple[List[str], np.ndarray, List[str]]:
    """(paths, labels, class_names) from ``<split_dir>/<class>/<img>``."""
    classes = sorted(
        d for d in os.listdir(split_dir)
        if os.path.isdir(os.path.join(split_dir, d))
    )
    paths: List[str] = []
    labels: List[int] = []
    exts = (".jpeg", ".jpg", ".png")
    for li, c in enumerate(classes):
        cdir = os.path.join(split_dir, c)
        for f in sorted(os.listdir(cdir)):
            if f.lower().endswith(exts):
                paths.append(os.path.join(cdir, f))
                labels.append(li)
    return paths, np.asarray(labels, np.int32), classes


class ImageNetFolder:
    """Elastic, lazily-decoded image-folder dataset.

    The same surface the examples use on :class:`ElasticDataset` —
    ``next_batch()``, ``set_cluster(rank, size)``, ``skip(consumed)``,
    ``sync_consumed(peer)`` — with decode+augment happening per batch.
    """

    def __init__(
        self,
        root: Optional[str] = None,
        split: str = "train",
        image_size: int = 224,
        batch_size: int = 32,
        rank: int = 0,
        size: int = 1,
        seed: int = 0,
        train_transform: Optional[bool] = None,
        synthetic_fallback: bool = True,
        n_synthetic: int = 1024,
        synthetic_classes: int = 1000,
    ):
        self.image_size = image_size
        self.train_transform = (
            split == "train" if train_transform is None else train_transform
        )
        self.seed = seed
        root = root or default_root()
        split_dir = os.path.join(root, split)
        self._synthetic = None
        if os.path.isdir(split_dir):
            self.paths, self.labels, self.classes = _scan(split_dir)
            if not self.paths:
                raise ValueError(f"no images under {split_dir}")
        elif synthetic_fallback:
            _log.warning(
                "no ImageNet at %s — using a deterministic SYNTHETIC set; "
                "results are not comparable to real ImageNet", split_dir,
            )
            rng = np.random.default_rng(seed)
            self._synthetic = rng.normal(
                size=(synthetic_classes, 8, 8, 3)
            ).astype(np.float32)  # low-res class templates, upsampled on read
            self.paths = [f"synthetic://{i}" for i in range(n_synthetic)]
            split_salt = sum(ord(c) for c in split)
            self.labels = np.random.default_rng((seed, split_salt)).integers(
                0, synthetic_classes, n_synthetic
            ).astype(np.int32)
            self.classes = [f"class{i}" for i in range(synthetic_classes)]
        else:
            raise OSError(f"no ImageNet directory at {split_dir}")
        # sharding/offset machinery: ElasticDataset over the INDICES
        self._index = ElasticDataset(
            [np.arange(len(self.paths), dtype=np.int64)],
            batch_size, rank=rank, size=size, seed=seed,
        )

    # -- elastic surface (delegated) --------------------------------------
    def set_cluster(self, rank: int, size: int) -> None:
        self._index.set_cluster(rank, size)

    def skip(self, consumed: int) -> None:
        self._index.skip(consumed)

    def sync_consumed(self, peer) -> int:
        return self._index.sync_consumed(peer)

    @property
    def consumed(self) -> int:
        return self._index.consumed

    def batches_per_epoch(self) -> int:
        return self._index.batches_per_epoch()

    def __len__(self) -> int:
        return len(self.paths)

    # -- decode + transform ------------------------------------------------
    def _load(self, path: str, rng: np.random.Generator) -> np.ndarray:
        s = self.image_size
        if self._synthetic is not None:
            idx = int(path.split("://")[1])
            t = self._synthetic[self.labels[idx] % len(self._synthetic)]
            # nearest-neighbor upsample to EXACTLY s x s for any s (kron
            # with s//8 tiles silently truncated non-multiples of 8)
            ix = (np.arange(s) * t.shape[0]) // s
            img = t[ix][:, ix]
            img = img * 0.3 + rng.normal(size=img.shape).astype(np.float32) * 0.1
            return np.clip(img * 0.5 + 0.5, 0.0, 1.0)
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("RGB")
            w, h = im.size
            if self.train_transform:
                # random-resized crop: standard scale/ratio jitter
                for _ in range(10):
                    area = w * h * rng.uniform(0.08, 1.0)
                    ratio = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
                    cw = int(round(np.sqrt(area * ratio)))
                    ch = int(round(np.sqrt(area / ratio)))
                    if 0 < cw <= w and 0 < ch <= h:
                        x0 = int(rng.integers(0, w - cw + 1))
                        y0 = int(rng.integers(0, h - ch + 1))
                        im = im.resize((s, s), Image.BILINEAR,
                                       box=(x0, y0, x0 + cw, y0 + ch))
                        break
                else:
                    im = im.resize((s, s), Image.BILINEAR)
                if rng.random() < 0.5:
                    im = im.transpose(Image.FLIP_LEFT_RIGHT)
            else:
                short = int(round(s * 256 / 224))  # the standard 224->256 ratio
                scale = short / min(w, h)
                im = im.resize(
                    (max(s, int(round(w * scale))), max(s, int(round(h * scale)))),
                    Image.BILINEAR,
                )
                w2, h2 = im.size
                x0, y0 = (w2 - s) // 2, (h2 - s) // 2
                im = im.crop((x0, y0, x0 + s, y0 + s))
            return np.asarray(im, np.float32) / 255.0

    def next_batch(self) -> Tuple[np.ndarray, np.ndarray]:
        """(images [b, s, s, 3] float32 normalized, labels [b] int32)."""
        offset = self._index.consumed
        (idxs,) = self._index.next_batch()
        # per-batch rng: deterministic given (seed, global offset) so
        # restarts replay identical augmentations
        rng = np.random.default_rng((self.seed, offset))
        imgs = np.stack([self._load(self.paths[int(i)], rng) for i in idxs])
        imgs = (imgs - MEAN) / STD
        return imgs.astype(np.float32), self.labels[idxs]
