from kungfu_tpu.torch.ops.collective import (  # noqa: F401
    all_gather,
    all_reduce,
    all_reduce_async,
    broadcast,
    broadcast_parameters,
    wait_all_handles,
)
