"""Dtype-keyed dispatch between torch tensors and the host engine.

Parity with reference ``kungfu/torch/ops/clib.py:10-35`` — a per-dtype op
dispatch table.  Each supported torch dtype maps to a ``(to_np, from_np)``
converter pair; dtypes without a numpy representation (bfloat16) stage
through float32 on the host, which is exact for the reduce ops we support
(bf16 is a truncated f32).
"""

from __future__ import annotations

import numpy as np
import torch


def _identity_pair():
    def to_np(t: "torch.Tensor") -> np.ndarray:
        return np.ascontiguousarray(t.detach().cpu().numpy())

    def from_np(a: np.ndarray, like: "torch.Tensor") -> "torch.Tensor":
        return torch.from_numpy(np.ascontiguousarray(a)).to(like.dtype)

    return to_np, from_np


def _via_f32_pair():
    def to_np(t: "torch.Tensor") -> np.ndarray:
        return np.ascontiguousarray(t.detach().float().cpu().numpy())

    def from_np(a: np.ndarray, like: "torch.Tensor") -> "torch.Tensor":
        return torch.from_numpy(np.ascontiguousarray(a)).to(like.dtype)

    return to_np, from_np


#: torch dtype -> (tensor->ndarray, ndarray->tensor) converters.
#: numpy mirrors these dtypes 1:1 (torch->numpy is exact via .numpy());
#: only bfloat16 lacks a numpy type and stages through float32.
CONVERTERS = {
    torch.bfloat16: _via_f32_pair(),
    **{
        dt: _identity_pair()
        for dt in (
            torch.float16, torch.float32, torch.float64,
            torch.uint8, torch.int8, torch.int32, torch.int64,
        )
    },
}

SUPPORTED_DTYPES = frozenset(CONVERTERS)


def to_numpy(t: "torch.Tensor") -> np.ndarray:
    try:
        to_np, _ = CONVERTERS[t.dtype]
    except KeyError:
        raise TypeError(f"unsupported torch dtype {t.dtype}") from None
    return to_np(t)


def from_numpy(a: np.ndarray, like: "torch.Tensor") -> "torch.Tensor":
    _, from_np = CONVERTERS[like.dtype]
    return from_np(a, like)
