"""Torch collective ops over the host graph-collective engine.

Parity with reference ``kungfu/torch/ops/collective.py`` (all_reduce,
broadcast_parameters, ``collective.py:40-45``) and the async-handle flow of
``srcs/cpp/src/torch/ops/cuda/collective.cpp:20-90`` (launch → handle →
``wait_all_handles``), here staged through a thread pool instead of CUDA
streams.

Scope (set expectations before reaching for this module): **torch rides
the HOST plane** — CPU tensors over the TCP/unix-socket engine, matching
the reference's CPU path and suitable for CPU clusters and tests.  The
TPU device plane (ICI/XLA collectives) is the jax path
(:mod:`kungfu_tpu.ops` / :mod:`kungfu_tpu.comm.device`); there is no
torch-on-TPU data path here.

All functions take an optional ``engine``; by default they use the global
peer's engine (``kungfu_tpu.python``).  In single-process mode (no engine)
every collective is the identity, so scripts run unchanged under
``python`` and ``kfrun -np N``.

Naming: collectives rendezvous by name across ranks, so async submissions
must be named at *call* time (thread-pool execution order is not
deterministic).  Each op gets ``torch.<round>.<seq>`` — callers must issue
the same op sequence on every rank, the same contract as the reference.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Iterable, List, Optional, Tuple, Union

import torch

from kungfu_tpu.torch.ops import clib

_seq_lock = threading.Lock()
_seq = [0]


def _next_name(kind: str) -> str:
    with _seq_lock:
        n = _seq[0]
        _seq[0] += 1
    return f"torch.{kind}.{n}"




def _default_engine():
    from kungfu_tpu import python

    try:
        peer = python._peer()
    except RuntimeError:
        return None
    return peer.engine()


def _check_op_dtype(t: "torch.Tensor", op: str) -> None:
    if op == "mean" and not t.dtype.is_floating_point:
        raise TypeError(
            f"op='mean' on {t.dtype} would silently truncate; use op='sum'"
        )


def all_reduce(
    t: "torch.Tensor", op: str = "mean", engine=None, name: str = ""
) -> "torch.Tensor":
    """Synchronous allreduce; returns a new tensor of the same dtype."""
    engine = engine if engine is not None else _default_engine()
    _check_op_dtype(t, op)
    if engine is None:
        return t.clone()
    a = clib.to_numpy(t)
    out = engine.all_reduce(a, op=op, name=name or _next_name("ar"))
    return clib.from_numpy(out, t).reshape(t.shape)


Handle = Tuple[Future, "torch.Tensor"]


def all_reduce_async(
    t: "torch.Tensor", op: str = "mean", engine=None, name: str = ""
) -> Handle:
    """Launch an allreduce; returns a handle for :func:`wait_all_handles`.

    The result is copied **into** ``t`` when awaited (in-place semantics,
    matching the reference's gradient sync)."""
    engine = engine if engine is not None else _default_engine()
    nm = name or _next_name("ar")
    _check_op_dtype(t, op)
    if engine is None:
        f: Future = Future()
        f.set_result(None)
        return (f, t)
    a = clib.to_numpy(t)
    # the engine's per-engine async pool: reused threads, and never shared
    # across in-process engines (a bounded pool shared by several engines
    # can fill with waiters and starve the rank they wait for)
    fut = engine.async_pool().submit(engine.all_reduce, a, op, nm)
    return (fut, t)


def wait_all_handles(handles: Iterable[Handle]) -> None:
    """Await async collectives, copying each result into its tensor
    (reference ``wait_all_handles``, ops/cuda/helper.cpp)."""
    for fut, t in handles:
        out = fut.result()
        if out is not None:
            with torch.no_grad():
                t.copy_(clib.from_numpy(out, t).reshape(t.shape))


def broadcast(
    t: "torch.Tensor", root: int = 0, engine=None, name: str = ""
) -> "torch.Tensor":
    engine = engine if engine is not None else _default_engine()
    if engine is None:
        return t.clone()
    a = clib.to_numpy(t)
    out = engine.broadcast(a, root=root, name=name or _next_name("bc"))
    return clib.from_numpy(out, t).reshape(t.shape)


def all_gather(t: "torch.Tensor", engine=None, name: str = "") -> "torch.Tensor":
    """Stack every rank's tensor on a new leading axis (reference
    ``torch/ops/collective.py:48-52``): returns shape ``[np, *t.shape]``."""
    engine = engine if engine is not None else _default_engine()
    if engine is None:
        return t.clone().unsqueeze(0)
    a = clib.to_numpy(t)
    out = engine.all_gather(a, name=name or _next_name("ag"))
    return clib.from_numpy(out, t).reshape((-1,) + tuple(t.shape))


def broadcast_parameters(
    params: Union[dict, Iterable["torch.Tensor"]], root: int = 0, engine=None
) -> None:
    """Broadcast rank ``root``'s parameters into every rank's tensors
    in place (reference ``torch/ops/collective.py:40-45``).

    ``params`` may be a ``state_dict``-style mapping or an iterable of
    tensors; iteration order must agree across ranks."""
    engine = engine if engine is not None else _default_engine()
    if engine is None:
        return
    items: List[Tuple[str, "torch.Tensor"]]
    if isinstance(params, dict):
        items = [(str(k), v) for k, v in params.items()]
    else:
        items = [(str(i), p) for i, p in enumerate(params)]
    # deterministic per-key names (reference keys collectives by tensor
    # name); per-(src,name) FIFO queues make cross-round reuse safe
    for key, t in items:
        if not torch.is_tensor(t):
            continue
        a = clib.to_numpy(t)
        out = engine.broadcast(a, root=root, name=f"torch.bp.{key}")
        with torch.no_grad():
            t.copy_(clib.from_numpy(out, t).reshape(t.shape))
