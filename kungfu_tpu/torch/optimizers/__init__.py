from kungfu_tpu.torch.optimizers.sync_sgd import (  # noqa: F401
    SynchronousSGDOptimizer,
)
