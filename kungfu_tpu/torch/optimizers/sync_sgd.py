"""Synchronous-SGD torch optimizer wrapper.

Parity with reference ``kungfu/torch/optimizers/sync_sgd.py:6-32``: a
dynamic subclass of the user's optimizer whose ``step()`` first syncs
every parameter's gradient across the cluster (allreduce-mean), then runs
the wrapped update.  Gradient syncs are launched asynchronously per
parameter and awaited together, mirroring the reference's async-CUDA path
(launch all → ``wait_all_handles``), which overlaps the per-tensor
transfers.
"""

from __future__ import annotations

from typing import Optional

import torch

from kungfu_tpu.torch.ops import collective


def _sync_gradients(optimizer: "torch.optim.Optimizer", op: str, engine) -> None:
    # deterministic per-parameter names (the reference keys collectives by
    # tensor name): ranks rendezvous by name, and wait_all_handles below
    # completes before the next step so cross-step reuse cannot overlap
    handles = []
    idx = 0
    for group in optimizer.param_groups:
        for p in group["params"]:
            if p.grad is None:
                continue
            handles.append(
                collective.all_reduce_async(
                    p.grad, op=op, engine=engine, name=f"torch.grad.{idx}"
                )
            )
            idx += 1
    collective.wait_all_handles(handles)


def SynchronousSGDOptimizer(
    optimizer: "torch.optim.Optimizer",
    op: str = "mean",
    engine=None,
) -> "torch.optim.Optimizer":
    """Wrap any ``torch.optim.Optimizer`` so that ``step()`` synchronizes
    gradients first.  Mutates ``optimizer``'s class in place (the
    reference's dynamic-subclass pattern) and returns it.

    ``op='mean'`` averages gradients (the S-SGD grad/np); ``op='sum'``
    leaves scaling to the caller's learning rate."""
    base = optimizer.__class__

    class _KungFuSynchronousSGD(base):  # type: ignore[valid-type, misc]
        def step(self, closure=None):
            _sync_gradients(self, self._kf_op, self._kf_engine)
            return super().step(closure)

    _KungFuSynchronousSGD.__name__ = "KungFu" + base.__name__
    optimizer.__class__ = _KungFuSynchronousSGD
    optimizer._kf_op = op
    optimizer._kf_engine = engine
    return optimizer
