"""PyTorch binding — parity with reference ``srcs/python/kungfu/torch``.

The reference exposes a small torch surface (``kungfu/torch/__init__.py``,
``torch/optimizers/sync_sgd.py:6-32``, ``torch/ops/{collective,clib}.py``):
a ``SynchronousSGDOptimizer`` that dynamically subclasses any torch
optimizer to allreduce gradients before ``step()``, ``broadcast_parameters``
for rank-0 initialization, and a dtype-keyed op dispatch table.

Here the collectives run over the framework's host-side graph-collective
engine (:mod:`kungfu_tpu.comm.engine` — the multi-process CPU data path;
torch tensors never touch the TPU mesh, exactly as the reference's torch
path never touches TF).  Async variants stage through a thread pool and
return handles awaited by :func:`wait_all_handles`, mirroring the
reference's CUDA ``HandlerManager`` (``ops/cuda/collective.cpp:20-90``).
"""

from kungfu_tpu.torch.ops.collective import (  # noqa: F401
    all_gather,
    all_reduce,
    all_reduce_async,
    broadcast,
    broadcast_parameters,
    wait_all_handles,
)
from kungfu_tpu.torch.optimizers.sync_sgd import (  # noqa: F401
    SynchronousSGDOptimizer,
)
