"""PairAveraging — AD-PSGD asynchronous gossip.

Reference ``async_sgd.py:71-142`` + ``peer_to_peer.cpp``: each step a
worker (1) pulls a random peer's model from that peer's in-memory
versioned store, (2) averages it 0.5/0.5 into its own weights, (3) applies
its local gradients, (4) publishes the new model.  No collectives, no
global synchronization — by design.  On TPU this runs on the **host
channel** (CPU NICs), not the ICI: gossip is deliberately not a collective,
and pulling a ~100MB model is control-plane-scale traffic that overlaps
with device compute.

The model travels as one fused bf16/f32 buffer (reference fuses into a
``ModelBuffer`` too, ``model_buffer.hpp:13-53``).
"""

from __future__ import annotations

import random
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from kungfu_tpu.ops.fuse import defuse, fuse
from kungfu_tpu.utils.log import get_logger

_log = get_logger("pair-avg")


class PairAveragingOptimizer:
    """Host-driven gossip optimizer.

    Usage::

        opt = PairAveragingOptimizer(optax.sgd(0.1), peer)
        state = opt.init(params)            # publishes + barrier
        params, state = opt.step(params, grads, state)
    """

    def __init__(
        self,
        inner: optax.GradientTransformation,
        peer=None,
        name: str = "model",
        selector: str = "random",
        fuse_dtype=jnp.float32,
        seed: int = 0,
    ):
        if peer is None:
            from kungfu_tpu.python import init as _init

            peer = _init()
        self.inner = inner
        self.peer = peer
        self.name = name
        self.selector = selector
        self.fuse_dtype = fuse_dtype
        self._rng = random.Random(seed + peer.rank())
        self._rr_next = 0
        self._step_count = 0
        self._recv_buf = None  # reused registered-receive buffer
        #: cumulative wall seconds / bytes spent inside blob pulls —
        #: benchmarks/gossip.py derives the measured pull bandwidth
        self.pull_seconds = 0.0
        self.pull_bytes = 0

        # ONE compiled program per step flavor: average with the pulled
        # model (when a pull landed), apply local gradients, and return
        # the updated params together with their fused buffer — so the
        # publish is a zero-copy view of jit output, not a re-fuse +
        # tobytes (two full-model copies per step gone)
        def _step(params, grads, state, other_buf):
            if other_buf is not None:
                mine, spec = fuse(params, dtype=self.fuse_dtype)
                params = defuse(0.5 * mine + 0.5 * other_buf, spec)
            updates, state = self.inner.update(grads, state, params)
            params = optax.apply_updates(params, updates)
            out_buf, _ = fuse(params, dtype=self.fuse_dtype)
            return params, state, out_buf

        self._step_avg_jit = jax.jit(_step)
        self._step_local_jit = jax.jit(
            lambda params, grads, state: _step(params, grads, state, None)
        )

    # -- store IO --------------------------------------------------------
    def _serialize(self, params):
        buf, _ = fuse(params, dtype=self.fuse_dtype)
        # np.asarray of a CPU-resident jax array is a zero-copy readonly
        # view; the store takes it without snapshotting (copy=False) —
        # jax arrays are immutable, so the handover is safe
        return np.asarray(buf)

    def _deserialize_buf(self, blob):
        return jnp.asarray(
            np.frombuffer(blob, dtype=np.dtype(self.fuse_dtype))
        )

    def _publish(self, params) -> None:
        self.peer.save(self.name, self._serialize(params),
                       version=str(self._step_count), copy=False)

    def _publish_buf(self, fused) -> None:
        self.peer.save(self.name, np.asarray(fused),
                       version=str(self._step_count), copy=False)

    def _select_peer(self) -> Optional[int]:
        n, me = self.peer.size(), self.peer.rank()
        others = [r for r in range(n) if r != me]
        if not others:
            return None
        if self.selector == "roundrobin":
            target = others[self._rr_next % len(others)]
            self._rr_next += 1
            return target
        return self._rng.choice(others)

    # -- optimizer surface -----------------------------------------------
    def init(self, params) -> optax.OptState:
        """Publish the initial model and barrier so every peer has
        something to serve before the first pull (reference
        ``async_sgd.py:110-120``: save fused model + barrier at step 0)."""
        self._publish(params)
        self.peer.barrier()
        return self.inner.init(params)

    def _pull(self, target):
        """Pull the target's fused model into the reused receive buffer
        (socket→buffer on the native backend).  Returns the filled numpy
        view or None."""
        import time as _time

        if self._recv_buf is None:
            n = int(np.sum([int(np.prod(l.shape)) for l in
                            jax.tree_util.tree_leaves(self._last_params)]))
            self._recv_buf = np.empty(n, np.dtype(self.fuse_dtype))
        t0 = _time.perf_counter()
        got = self.peer.request_into(target, self.name, self._recv_buf)
        dt = _time.perf_counter() - t0
        if got is None:
            return None
        self.pull_seconds += dt
        self.pull_bytes += memoryview(got).nbytes
        return got

    def step(self, params, grads, state):
        """One gossip step; returns ``(new_params, new_state)``."""
        self._last_params = params
        target = self._select_peer()
        other = None
        if target is not None:
            blob = self._pull(target)
            if blob is not None:
                other = self._deserialize_buf(blob)
            else:
                _log.debug("peer %d had no %r yet", target, self.name)
        if other is not None:
            params, state, fused = self._step_avg_jit(params, grads, state, other)
        else:
            params, state, fused = self._step_local_jit(params, grads, state)
        self._step_count += 1
        self._publish_buf(fused)
        return params, state
