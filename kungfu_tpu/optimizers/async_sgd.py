"""PairAveraging — AD-PSGD asynchronous gossip.

Reference ``async_sgd.py:71-142`` + ``peer_to_peer.cpp``: each step a
worker (1) pulls a random peer's model from that peer's in-memory
versioned store, (2) averages it 0.5/0.5 into its own weights, (3) applies
its local gradients, (4) publishes the new model.  No collectives, no
global synchronization — by design.  On TPU this runs on the **host
channel** (CPU NICs), not the ICI: gossip is deliberately not a collective,
and pulling a ~100MB model is control-plane-scale traffic that overlaps
with device compute.

The model travels as one fused bf16/f32 buffer (reference fuses into a
``ModelBuffer`` too, ``model_buffer.hpp:13-53``).
"""

from __future__ import annotations

import random
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from kungfu_tpu.ops.fuse import defuse, fuse
from kungfu_tpu.utils.log import get_logger

_log = get_logger("pair-avg")


class PairAveragingOptimizer:
    """Host-driven gossip optimizer.

    Usage::

        opt = PairAveragingOptimizer(optax.sgd(0.1), peer)
        state = opt.init(params)            # publishes + barrier
        params, state = opt.step(params, grads, state)
    """

    def __init__(
        self,
        inner: optax.GradientTransformation,
        peer=None,
        name: str = "model",
        selector: str = "random",
        fuse_dtype=jnp.float32,
        seed: int = 0,
    ):
        if peer is None:
            from kungfu_tpu.python import init as _init

            peer = _init()
        self.inner = inner
        self.peer = peer
        self.name = name
        self.selector = selector
        self.fuse_dtype = fuse_dtype
        self._rng = random.Random(seed + peer.rank())
        self._rr_next = 0
        self._spec = None
        self._step_count = 0

        def _avg(params, other_buf):
            mine, spec = fuse(params, dtype=self.fuse_dtype)
            merged = 0.5 * mine + 0.5 * other_buf
            return defuse(merged, spec)

        self._avg_jit = jax.jit(_avg)
        self._update_jit = jax.jit(
            lambda g, s, p: self.inner.update(g, s, p)
        )

    # -- store IO --------------------------------------------------------
    def _serialize(self, params) -> bytes:
        buf, self._spec = fuse(params, dtype=self.fuse_dtype)
        return np.asarray(buf).tobytes()

    def _deserialize_buf(self, blob: bytes):
        return jnp.asarray(
            np.frombuffer(blob, dtype=np.dtype(self.fuse_dtype)).copy()
        )

    def _publish(self, params) -> None:
        self.peer.save(self.name, self._serialize(params), version=str(self._step_count))

    def _select_peer(self) -> Optional[int]:
        n, me = self.peer.size(), self.peer.rank()
        others = [r for r in range(n) if r != me]
        if not others:
            return None
        if self.selector == "roundrobin":
            target = others[self._rr_next % len(others)]
            self._rr_next += 1
            return target
        return self._rng.choice(others)

    # -- optimizer surface -----------------------------------------------
    def init(self, params) -> optax.OptState:
        """Publish the initial model and barrier so every peer has
        something to serve before the first pull (reference
        ``async_sgd.py:110-120``: save fused model + barrier at step 0)."""
        self._publish(params)
        self.peer.barrier()
        return self.inner.init(params)

    def step(self, params, grads, state):
        """One gossip step; returns ``(new_params, new_state)``."""
        target = self._select_peer()
        if target is not None:
            blob = self.peer.request(target, self.name)
            if blob is not None:
                params = self._avg_jit(params, self._deserialize_buf(blob))
            else:
                _log.debug("peer %d had no %r yet", target, self.name)
        updates, state = self._update_jit(grads, state, params)
        params = optax.apply_updates(params, updates)
        self._step_count += 1
        self._publish(params)
        return params, state
