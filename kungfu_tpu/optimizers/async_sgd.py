"""PairAveraging — AD-PSGD asynchronous gossip.

Reference ``async_sgd.py:71-142`` + ``peer_to_peer.cpp``: each step a
worker (1) pulls a random peer's model from that peer's in-memory
versioned store, (2) averages it 0.5/0.5 into its own weights, (3) applies
its local gradients, (4) publishes the new model.  No collectives, no
global synchronization — by design.  On TPU this runs on the **host
channel** (CPU NICs), not the ICI: gossip is deliberately not a collective,
and pulling a ~100MB model is control-plane-scale traffic that overlaps
with device compute.

The model travels as one fused bf16/f32 buffer (reference fuses into a
``ModelBuffer`` too, ``model_buffer.hpp:13-53``).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from kungfu_tpu.ops.fuse import defuse, fuse
from kungfu_tpu.utils.log import get_logger

_log = get_logger("pair-avg")


class PairAveragingOptimizer:
    """Host-driven gossip optimizer.

    Usage::

        opt = PairAveragingOptimizer(optax.sgd(0.1), peer)
        state = opt.init(params)            # publishes + barrier
        params, state = opt.step(params, grads, state)
    """

    def __init__(
        self,
        inner: optax.GradientTransformation,
        peer=None,
        name: str = "model",
        selector: str = "random",
        fuse_dtype=jnp.float32,
        seed: int = 0,
    ):
        if peer is None:
            from kungfu_tpu.python import init as _init

            peer = _init()
        self.inner = inner
        self.peer = peer
        self.name = name
        self.selector = selector
        self.fuse_dtype = fuse_dtype
        self._rng = random.Random(seed + peer.rank())
        self._rr_next = 0
        self._step_count = 0
        self._recv_buf = None  # reused registered-receive buffer
        #: cumulative wall seconds / bytes spent inside blob pulls —
        #: benchmarks/gossip.py derives the measured pull bandwidth
        self.pull_seconds = 0.0
        self.pull_bytes = 0
        #: steps that averaged with a pulled model / fell back to local
        self.averaged_steps = 0
        self.local_steps = 0

        # ONE compiled program per step flavor: average with the pulled
        # model (when a pull landed), apply local gradients, and return
        # the updated params together with their fused buffer — so the
        # publish is a zero-copy view of jit output, not a re-fuse +
        # tobytes (two full-model copies per step gone)
        def _step(params, grads, state, other_buf):
            if other_buf is not None:
                mine, spec = fuse(params, dtype=self.fuse_dtype)
                params = defuse(0.5 * mine + 0.5 * other_buf, spec)
            updates, state = self.inner.update(grads, state, params)
            params = optax.apply_updates(params, updates)
            out_buf, _ = fuse(params, dtype=self.fuse_dtype)
            return params, state, out_buf

        self._step_avg_jit = jax.jit(_step)
        self._step_local_jit = jax.jit(
            lambda params, grads, state: _step(params, grads, state, None)
        )

    # -- store IO --------------------------------------------------------
    # The model travels as RAW BYTES (a uint8 view): the store/serve/
    # registered-receive chain rides the buffer protocol, which ml_dtypes
    # extension dtypes (bfloat16 — the fuse_dtype that HALVES gossip wire
    # bytes) do not export.  The view is zero-copy both ways.
    def _serialize(self, params):
        buf, _ = fuse(params, dtype=self.fuse_dtype)
        # np.asarray of a CPU-resident jax array is a zero-copy readonly
        # view; the store takes it without snapshotting (copy=False) —
        # jax arrays are immutable, so the handover is safe
        return np.asarray(buf).view(np.uint8)

    def _deserialize_buf(self, blob):
        raw = (np.frombuffer(blob, np.uint8)
               if isinstance(blob, (bytes, bytearray, memoryview))
               else np.asarray(blob).view(np.uint8))
        return jnp.asarray(raw.view(np.dtype(self.fuse_dtype)))

    def _model_nbytes(self, params) -> int:
        numel = int(np.sum([int(np.prod(l.shape)) for l in
                            jax.tree_util.tree_leaves(params)]))
        return numel * np.dtype(self.fuse_dtype).itemsize

    def _publish(self, params) -> None:
        self.peer.save(self.name, self._serialize(params),
                       version=str(self._step_count), copy=False)

    def _publish_buf(self, fused) -> None:
        self.peer.save(self.name, np.asarray(fused).view(np.uint8),
                       version=str(self._step_count), copy=False)

    def _select_peer(self) -> Optional[int]:
        n, me = self.peer.size(), self.peer.rank()
        others = [r for r in range(n) if r != me]
        if not others:
            return None
        if self.selector == "roundrobin":
            target = others[self._rr_next % len(others)]
            self._rr_next += 1
            return target
        return self._rng.choice(others)

    # -- optimizer surface -----------------------------------------------
    def init(self, params) -> optax.OptState:
        """Publish the initial model and barrier so every peer has
        something to serve before the first pull (reference
        ``async_sgd.py:110-120``: save fused model + barrier at step 0)."""
        self._publish(params)
        self.peer.barrier()
        return self.inner.init(params)

    def _pull(self, target):
        """Pull the target's fused model into the reused receive buffer
        (socket→buffer on the native backend).  Returns the filled numpy
        view or None."""
        import time as _time

        if self._recv_buf is None:
            self._recv_buf = np.empty(self._model_nbytes(self._last_params),
                                      np.uint8)
        t0 = _time.perf_counter()
        try:
            # misses are tolerated by design — bound the connect ladder
            # so a dead target costs seconds, not 500x200 ms on the
            # critical path
            got = self.peer.request_into(target, self.name,
                                         self._recv_buf, send_retries=25)
        except (TimeoutError, ConnectionError, OSError) as e:
            _log.debug("pull from %d failed: %s", target, e)
            return None
        dt = _time.perf_counter() - t0
        if got is None:
            return None
        self.pull_seconds += dt
        self.pull_bytes += memoryview(got).nbytes
        return got

    def step(self, params, grads, state):
        """One gossip step; returns ``(new_params, new_state)``."""
        self._last_params = params
        target = self._select_peer()
        other = None
        if target is not None:
            blob = self._pull(target)
            if blob is not None:
                other = self._deserialize_buf(blob)
            else:
                _log.debug("peer %d had no %r yet", target, self.name)
        if other is not None:
            params, state, fused = self._step_avg_jit(params, grads, state, other)
            self.averaged_steps += 1
        else:
            params, state, fused = self._step_local_jit(params, grads, state)
            self.local_steps += 1
        self._step_count += 1
        self._publish_buf(fused)
        return params, state


class _ModelPuller(threading.Thread):
    """Free-running background model puller with triple-buffered landings.

    The reference keeps the training step off the wire with a
    double-buffered background request plus a memcpy on landing
    (``tensorflow/ops/cpu/peer_to_peer.cpp:156-258``: prefetch_buf →
    model_buf copy under a mutex).  Here three slots rotate ownership so a
    landing is a pointer swap, never a model-sized copy:

    * ``writing`` — the slot the in-flight registered receive fills
      (socket→buffer on the native backend),
    * ``ready`` — the freshest landed model, waiting to be taken,
    * ``read`` — checked out by the consumer's last :meth:`take`.

    With one writer and one consumer, at most one slot is in each state,
    so three suffice and no state ever tears.  The consumer's read slot is
    only recycled by its *next* take — by then the jitted step that
    averaged with it has materialized (the publish synchronizes on the
    fused output), so the puller never overwrites bytes a computation
    might still read.
    """

    def __init__(
        self,
        peer,
        name: str,
        nbytes: int,
        select: Callable[[], Optional[int]],
        pull_timeout: float = 10.0,
        min_interval: float = 0.0,
        paced: bool = False,
    ):
        super().__init__(name=f"kf-gossip-pull-{name}", daemon=True)
        self.peer = peer
        self.blob_name = name
        self._select = select
        # raw byte buffers: the wire rides the buffer protocol, which
        # ml_dtypes fuse dtypes (bfloat16) do not export — the consumer
        # reinterprets on take (PairAveragingOptimizer._deserialize_buf)
        self._slots = [np.empty(nbytes, np.uint8) for _ in range(3)]
        self._free = [0, 1, 2]
        self._ready: Optional[int] = None
        self._read: Optional[int] = None
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self.landed = threading.Event()  #: set on every landing
        self.pull_timeout = pull_timeout
        self.min_interval = min_interval
        #: paced mode: pull only when :meth:`kick`ed, at most one in
        #: flight — the reference's one-prefetch-per-step rate limit
        #: (``AsyncRequestModel``: ``if (!is_requesting_) ...``), which
        #: keeps the wire from starving the step it overlaps with
        self.paced = paced
        self._kick = threading.Event()
        #: landing sequence number (0 = nothing landed yet)
        self.seq = 0
        self._take_seq = 0
        self.pull_seconds = 0.0
        self.pull_bytes = 0
        self.misses = 0

    def kick(self) -> None:
        """Request one pull (paced mode); no-op when one is in flight."""
        self._kick.set()

    # -- puller side ------------------------------------------------------
    def run(self) -> None:  # noqa: D102
        while not self._stop_evt.is_set():
            if self.paced:
                if not self._kick.wait(0.1):
                    continue
                self._kick.clear()
            try:
                target = self._select()
            except Exception as e:  # noqa: BLE001 — elastic churn can
                # momentarily drop self from the worker list (rank()
                # raises); the puller must outlive it
                _log.debug("peer selection failed: %s", e)
                target = None
            if target is None:
                self._stop_evt.wait(0.05)
                continue
            with self._lock:
                w = self._free.pop()
            t0 = time.perf_counter()
            try:
                # bounded connect ladder: a dead target must fail within
                # ~pull_timeout, or close() could not join this thread
                # and the peer teardown would race the in-flight call
                got = self.peer.request_into(
                    target, self.blob_name, self._slots[w],
                    timeout=self.pull_timeout,
                    send_retries=max(1, int(self.pull_timeout / 0.2)),
                )
            except Exception as e:  # noqa: BLE001 — peer churn is normal
                _log.debug("async pull from %d failed: %s", target, e)
                got = None
            dt = time.perf_counter() - t0
            landed = got is not None and memoryview(got).nbytes == \
                self._slots[w].nbytes
            if landed and got is not self._slots[w]:
                # size-matched blob that took the queued path (or the
                # local-serve path): land it via one copy
                self._slots[w][:] = np.frombuffer(got, self._slots[w].dtype)
            with self._lock:
                if landed:
                    if self._ready is not None:
                        self._free.append(self._ready)
                    self._ready = w
                    self.seq += 1
                    self.pull_seconds += dt
                    self.pull_bytes += self._slots[w].nbytes
                else:
                    self._free.append(w)
                    self.misses += 1
            if landed:
                self.landed.set()
            if self.min_interval:
                self._stop_evt.wait(self.min_interval)

    # -- consumer side ----------------------------------------------------
    def take(self):
        """Return ``(buf, seq)`` of the freshest landed model, or ``None``
        when nothing has landed yet.  Reuses the previous landing when no
        new one arrived (reference semantics: the step averages with
        whatever the background request last delivered)."""
        with self._lock:
            if self._ready is not None:
                if self._read is not None:
                    self._free.append(self._read)
                self._read, self._ready = self._ready, None
                self._take_seq = self.seq
            if self._read is None:
                return None
            return self._slots[self._read], self._take_seq

    def wait_landed(self, timeout: float) -> bool:
        """Block until a landing newer than the last take (bounded)."""
        self.landed.clear()
        with self._lock:
            if self._ready is not None:
                return True
        return self.landed.wait(timeout)

    def close(self, timeout: Optional[float] = None) -> None:
        self._stop_evt.set()
        if self.is_alive():
            # worst-case in-flight pull: the bounded connect ladder
            # (~pull_timeout), the registered wait (pull_timeout), and
            # the size-mismatch fallback recv (pull_timeout) in sequence
            waited = (timeout if timeout is not None
                      else 3.0 * self.pull_timeout + 5.0)
            self.join(waited)
            if self.is_alive():
                # teardown proceeding under a live pull would race the
                # channel free (the C++ ApiGuard makes the close wait,
                # but the situation deserves a loud trace)
                _log.warning(
                    "gossip puller still in flight after %.0fs join; "
                    "channel close will drain it", waited)


class AsyncPairAveragingOptimizer(PairAveragingOptimizer):
    """AD-PSGD with the pull **off** the critical path.

    Parity with the reference's ``AsyncModelAveraging`` /
    ``AsyncRequestModel`` pair
    (``tensorflow/ops/cpu/peer_to_peer.cpp:156-258,411-466``): a
    background thread keeps pulling a peer's fused model; ``step()``
    averages with the last *landed* model and never waits on the wire
    (after the blocking first pull, which the reference also does).

    ``max_staleness`` bounds divergence: when the same landed model has
    been consumed that many consecutive steps (the wire has stalled),
    the step blocks — bounded by ``pull_timeout`` — for a fresh landing.
    The reference has no such bound; AD-PSGD's convergence proof assumes
    bounded staleness, so the knob defaults on (16) rather than off.
    """

    def __init__(self, *args, max_staleness: Optional[int] = 16,
                 pull_timeout: float = 10.0, min_interval: float = 0.0,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.max_staleness = max_staleness
        self._pull_timeout = pull_timeout
        self._min_interval = min_interval
        self._puller: Optional[_ModelPuller] = None
        self._consumed_seq = 0
        self._consumed_same = 0

    def _ensure_puller(self, params) -> None:
        if self._puller is not None:
            return
        self._puller = _ModelPuller(
            self.peer, self.name, self._model_nbytes(params),
            self._select_peer, pull_timeout=self._pull_timeout,
            min_interval=self._min_interval, paced=True,
        )
        self._puller.start()
        self._puller.kick()  # first pull starts racing the first step

    def init(self, params) -> optax.OptState:
        state = super().init(params)
        self._ensure_puller(params)
        return state

    def _await_landing(self) -> bool:
        """Kick-and-wait until a landing (bounded by pull_timeout).  The
        paced puller parks after a miss, so the kick must come first and
        must repeat while waiting — a missed pull (target down, blob not
        yet published) otherwise turns every wait into a guaranteed
        timeout with zero chance of success."""
        deadline = time.monotonic() + self._pull_timeout
        while True:
            self._puller.kick()
            left = deadline - time.monotonic()
            if left <= 0:
                return False
            if self._puller.wait_landed(min(0.5, left)):
                return True

    def step(self, params, grads, state):
        self._last_params = params
        self._ensure_puller(params)
        if self._puller.seq == 0:
            # blocking first pull, like the reference's synchronous
            # Request before the prefetch loop starts
            self._await_landing()
        elif (self.max_staleness is not None
              and self._consumed_same >= self.max_staleness):
            _log.debug("staleness bound hit (%d); waiting for a landing",
                       self._consumed_same)
            self._await_landing()
        took = self._puller.take()
        # start the next pull now — it overlaps this step's compute and
        # publish, landing in time for a later step
        self._puller.kick()
        if took is not None:
            buf, seq = took
            self._consumed_same = (self._consumed_same + 1
                                   if seq == self._consumed_seq else 0)
            self._consumed_seq = seq
            other = self._deserialize_buf(buf)
            params, state, fused = self._step_avg_jit(params, grads, state,
                                                      other)
            self.averaged_steps += 1
        else:
            params, state, fused = self._step_local_jit(params, grads, state)
            self.local_steps += 1
        self._step_count += 1
        self._publish_buf(fused)
        # surface the puller's wire accounting through the same fields the
        # blocking optimizer exposes, so benchmarks read one interface
        self.pull_seconds = self._puller.pull_seconds
        self.pull_bytes = self._puller.pull_bytes
        return params, state

    def close(self) -> None:
        """Stop the background puller (idempotent)."""
        if self._puller is not None:
            self._puller.close()
            self._puller = None
