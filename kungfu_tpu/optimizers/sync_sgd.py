"""Synchronous SGD — allreduce gradients, then inner update."""

from __future__ import annotations

import optax

from kungfu_tpu import ops


def synchronous_sgd(
    inner: optax.GradientTransformation,
    axis,
    average: bool = True,
    schedule: str = "psum",
    fuse_grads: bool = False,
) -> optax.GradientTransformation:
    """The S-SGD wrapper (reference ``sync_sgd.py:58-109``: group allreduce
    then grad/np).  ``inner`` is any optax optimizer; ``axis`` the mesh
    axis name(s).  With ``average=False`` gradients are summed (the caller
    scales the LR instead).

    ``schedule`` selects the allreduce decomposition that gets COMPILED
    into the training step (``kungfu_tpu.ops.schedules``; pass
    ``comm.strategy`` to honor a ``set_strategy``/``autotune_strategy``
    choice).  A strategy swap therefore means rebuilding the optimizer
    and re-jitting — on TPU the strategy lives in the program, not in a
    per-message router.

    ``fuse_grads=True`` buckets the whole gradient pytree into ONE flat
    buffer before the collective (reference fuse/defuse,
    ``python/kungfu/ops/__init__.py:29-46``): one psum of N bytes instead
    of one per leaf.  XLA often fuses per-leaf psums on TPU anyway; the
    explicit bucket pins it — and on meshes where each collective carries
    fixed dispatch overhead (many-leaf models, virtual/CPU meshes, ring
    or two-stage schedules whose per-leaf program is long) it is a
    measured win.  Costs one fuse/defuse reshape pass in-program."""

    def init(params):
        return inner.init(params)

    def update(grads, state, params=None):
        op = "mean" if average else "sum"
        if fuse_grads:
            from kungfu_tpu.ops.fuse import defuse, fuse

            buf, spec = fuse(grads)
            buf = ops.all_reduce_scheduled(buf, axis, op=op,
                                           schedule=schedule)
            grads = defuse(buf, spec)
        else:
            # schedule="psum" dispatches to the same all_reduce that
            # group_all_reduce wraps — one call site for every schedule
            grads = ops.all_reduce_scheduled(grads, axis, op=op,
                                             schedule=schedule)
        return inner.update(grads, state, params)

    return optax.GradientTransformation(init, update)
