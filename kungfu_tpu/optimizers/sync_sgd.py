"""Synchronous SGD — allreduce gradients, then inner update."""

from __future__ import annotations

import optax

from kungfu_tpu import ops


def synchronous_sgd(
    inner: optax.GradientTransformation,
    axis,
    average: bool = True,
) -> optax.GradientTransformation:
    """The S-SGD wrapper (reference ``sync_sgd.py:58-109``: group allreduce
    then grad/np).  ``inner`` is any optax optimizer; ``axis`` the mesh
    axis name(s).  With ``average=False`` gradients are summed (the caller
    scales the LR instead)."""

    def init(params):
        return inner.init(params)

    def update(grads, state, params=None):
        grads = ops.group_all_reduce(grads, axis, op="mean" if average else "sum")
        return inner.update(grads, state, params)

    return optax.GradientTransformation(init, update)
