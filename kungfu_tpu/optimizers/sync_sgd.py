"""Synchronous SGD — allreduce gradients, then inner update."""

from __future__ import annotations

import optax

from kungfu_tpu import ops


def synchronous_sgd(
    inner: optax.GradientTransformation,
    axis,
    average: bool = True,
    schedule: str = "psum",
) -> optax.GradientTransformation:
    """The S-SGD wrapper (reference ``sync_sgd.py:58-109``: group allreduce
    then grad/np).  ``inner`` is any optax optimizer; ``axis`` the mesh
    axis name(s).  With ``average=False`` gradients are summed (the caller
    scales the LR instead).

    ``schedule`` selects the allreduce decomposition that gets COMPILED
    into the training step (``kungfu_tpu.ops.schedules``; pass
    ``comm.strategy`` to honor a ``set_strategy``/``autotune_strategy``
    choice).  A strategy swap therefore means rebuilding the optimizer
    and re-jitting — on TPU the strategy lives in the program, not in a
    per-message router."""

    def init(params):
        return inner.init(params)

    def update(grads, state, params=None):
        # schedule="psum" dispatches to the same all_reduce that
        # group_all_reduce wraps — one call site for every schedule
        grads = ops.all_reduce_scheduled(
            grads, axis, op="mean" if average else "sum", schedule=schedule
        )
        return inner.update(grads, state, params)

    return optax.GradientTransformation(init, update)
