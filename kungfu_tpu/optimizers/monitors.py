"""Monitoring optimizers — S-SGD plus in-graph training statistics.

Reference ``grad_noise_scale.py:41-88`` (OpenAI gradient-noise-scale
estimator + EMA, via the C++ ``NoiseScale`` op) and
``grad_variance.py:37-76``.  These statistics are the signals the adaptive
policies use to pick batch/cluster size at runtime.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from kungfu_tpu import ops
from kungfu_tpu.ops.monitor import global_noise_scale, group_all_reduce_with_variance
from kungfu_tpu.ops.state import EMAState, ema_init, exponential_moving_average


class GNSState(NamedTuple):
    inner: optax.OptState
    ema: EMAState
    noise_scale: jnp.ndarray  # smoothed GNS estimate


def monitor_gradient_noise_scale(
    inner: optax.GradientTransformation,
    axis,
    local_batch_size: int,
    ema_alpha: float = 0.01,
) -> optax.GradientTransformation:
    """S-SGD whose state additionally carries a smoothed gradient noise
    scale (``state.noise_scale``)."""

    def init(params):
        return GNSState(inner.init(params), ema_init(), jnp.zeros((), jnp.float32))

    def update(grads, state, params=None):
        avg = ops.group_all_reduce(grads, axis, op="mean")
        raw = global_noise_scale(grads, avg, local_batch_size, axis)
        if raw is None:
            # single worker: the two-batch estimator does not exist —
            # train normally, carry the EMA/estimate untouched
            updates, new_inner = inner.update(avg, state.inner, params)
            return updates, GNSState(new_inner, state.ema,
                                     state.noise_scale)
        new_ema, smoothed = exponential_moving_average(state.ema, raw, ema_alpha)
        updates, new_inner = inner.update(avg, state.inner, params)
        return updates, GNSState(new_inner, new_ema, smoothed)

    return optax.GradientTransformation(init, update)


class GradVarianceState(NamedTuple):
    inner: optax.OptState
    variance: jnp.ndarray


def monitor_gradient_variance(
    inner: optax.GradientTransformation,
    axis,
) -> optax.GradientTransformation:
    """S-SGD whose state carries the cross-replica gradient variance."""

    def init(params):
        return GradVarianceState(inner.init(params), jnp.zeros((), jnp.float32))

    def update(grads, state, params=None):
        avg, var = group_all_reduce_with_variance(grads, axis)
        updates, new_inner = inner.update(avg, state.inner, params)
        return updates, GradVarianceState(new_inner, var)

    return optax.GradientTransformation(init, update)
