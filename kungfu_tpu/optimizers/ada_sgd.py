"""AdaptiveSGD — SMA early, S-SGD late.

Reference ``ada_sgd.py:26-83``: model-averaging while gradients are noisy
(early training / large clusters), switch to synchronous SGD at
``change_step``.  The reference re-broadcasts weights at the switch to
re-synchronize replicas; here the same effect comes from one full-strength
averaging step (alpha=1) at the boundary, keeping the whole schedule inside
the compiled program (no eager hook needed).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from kungfu_tpu import ops
from kungfu_tpu.optimizers.sma_sgd import DEFAULT_ALPHA


class AdaptiveSGDState(NamedTuple):
    step: jnp.ndarray
    inner: optax.OptState


def adaptive_sgd(
    inner: optax.GradientTransformation,
    axis,
    change_step: int,
    alpha: float = DEFAULT_ALPHA,
) -> optax.GradientTransformation:
    def init(params):
        return AdaptiveSGDState(jnp.zeros((), jnp.int32), inner.init(params))

    def update(grads, state, params):
        if params is None:
            raise ValueError("adaptive_sgd requires params")
        step = state.step
        in_sma = step < change_step
        at_switch = step == change_step

        # both phases need the weight average only in SMA / switch steps,
        # but SPMD control flow is uniform across replicas, so compute it
        # unconditionally — XLA overlaps it and it is one psum of params.
        avg = ops.all_reduce(params, axis, op="mean")
        sync_grads = ops.group_all_reduce(grads, axis, op="mean")

        used_grads = jax.tree_util.tree_map(
            lambda g, sg: jnp.where(in_sma, g, sg), grads, sync_grads
        )
        inner_updates, new_inner = inner.update(used_grads, state.inner, params)

        # averaging pull: alpha in SMA phase, 1.0 at the switch (re-sync), 0 after
        pull = jnp.where(in_sma, alpha, jnp.where(at_switch, 1.0, 0.0))
        updates = jax.tree_util.tree_map(
            lambda u, p, a: u + (pull * (a - p)).astype(u.dtype),
            inner_updates, params, avg,
        )
        return updates, AdaptiveSGDState(step + 1, new_inner)

    return optax.GradientTransformation(init, update)
