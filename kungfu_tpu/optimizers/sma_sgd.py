"""Synchronous model averaging (SMA / EA-SGD).

Reference ``sma_sgd.py:45-74``: each step allreduce the *weights*, move
each replica toward the average with rate ``alpha`` (default 0.1), then
apply local gradients.  Tolerant of large clusters where averaging
gradients degrades accuracy (the reference's 16-worker ImageNet result).
"""

from __future__ import annotations

import jax
import optax

from kungfu_tpu import ops

DEFAULT_ALPHA = 0.1  # reference sma_sgd.py


def synchronous_averaging(
    inner: optax.GradientTransformation,
    axis,
    alpha: float = DEFAULT_ALPHA,
) -> optax.GradientTransformation:
    def init(params):
        return inner.init(params)

    def update(grads, state, params):
        if params is None:
            raise ValueError("synchronous_averaging requires params")
        avg = ops.all_reduce(params, axis, op="mean")
        inner_updates, new_state = inner.update(grads, state, params)
        updates = jax.tree_util.tree_map(
            lambda u, p, a: u + alpha * (a - p).astype(u.dtype),
            inner_updates, params, avg,
        )
        return updates, new_state

    return optax.GradientTransformation(init, update)
