"""Distributed optimizer algorithms.

Parity with reference ``srcs/python/kungfu/tensorflow/optimizers``: the same
five algorithm families, re-designed as **optax-style gradient
transformations** that run *inside* the jitted, shard-mapped training step
(the reference instead wrapped ``tf.Optimizer.apply_gradients`` around
async C++ ops — on TPU the collective is part of the compiled program):

* :func:`synchronous_sgd` — S-SGD: allreduce-mean gradients, then inner
  update (reference ``sync_sgd.py:58-109``).
* :func:`synchronous_averaging` — SMA / EA-SGD: average *weights* each
  step, pull each replica toward the average with rate ``alpha`` while
  applying local gradients (reference ``sma_sgd.py:45-74``).
* :func:`adaptive_sgd` — SMA before ``change_step``, S-SGD after
  (reference ``ada_sgd.py:26-83``).
* :class:`PairAveragingOptimizer` — AD-PSGD gossip: pull a random peer's
  model from its versioned store over the host channel, average 0.5/0.5,
  apply local gradients, publish (reference ``async_sgd.py:71-142``).
  Deliberately *not* a collective — host-side p2p.
* :class:`AsyncPairAveragingOptimizer` — same algorithm with the pull
  moved off the critical path: a background thread keeps a
  triple-buffered registered receive in flight; the step averages with
  the last *landed* model (reference ``AsyncModelAveraging`` /
  ``AsyncRequestModel``, ``peer_to_peer.cpp:156-258,411-466``).
* :func:`monitor_gradient_noise_scale` / :func:`monitor_gradient_variance`
  — S-SGD plus in-graph training statistics (reference
  ``grad_noise_scale.py``, ``grad_variance.py``).

All collective-based transforms take ``axis`` = mesh axis name(s)
(``Communicator.axis``) and must be called inside ``shard_map``/``pjit``
over that mesh.
"""

from kungfu_tpu.optimizers.sync_sgd import synchronous_sgd
from kungfu_tpu.optimizers.sma_sgd import synchronous_averaging
from kungfu_tpu.optimizers.ada_sgd import adaptive_sgd
from kungfu_tpu.optimizers.async_sgd import (
    AsyncPairAveragingOptimizer,
    PairAveragingOptimizer,
)
from kungfu_tpu.optimizers.monitors import (
    monitor_gradient_noise_scale,
    monitor_gradient_variance,
)

__all__ = [
    "synchronous_sgd",
    "synchronous_averaging",
    "adaptive_sgd",
    "PairAveragingOptimizer",
    "AsyncPairAveragingOptimizer",
    "monitor_gradient_noise_scale",
    "monitor_gradient_variance",
]
