"""Peer identity.

Parity with reference ``srcs/go/plan/{id,addr}.go``: a peer is identified by
``(host, port)``; colocated peers may exchange host-side messages over a Unix
domain socket.  On TPU one *peer process* typically drives all local TPU
chips of a host (one process per host), but the framework also supports one
process per chip for CPU-backend testing.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

_PEER_RE = re.compile(r"^(?P<host>[^:]+):(?P<port>\d+)$")


@dataclass(frozen=True, order=True)
class PeerID:
    host: str
    port: int

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"

    def sock_file(self) -> str:
        """Unix-socket path used for colocated host-side transport
        (analog of reference ``plan/addr.go:24``)."""
        return f"/tmp/kungfu-tpu-{self.port}.sock"

    def named_addr(self, name: str) -> str:
        return f"{self}#{name}"


def parse_peer_id(s: str) -> PeerID:
    m = _PEER_RE.match(s.strip())
    if not m:
        raise ValueError(f"invalid peer id {s!r}; want host:port")
    return PeerID(m.group("host"), int(m.group("port")))
