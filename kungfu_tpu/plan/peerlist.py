"""Ordered peer lists with rank / local-rank / host partitioning.

Parity with reference ``srcs/go/plan/peerlist.go:39-178``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from kungfu_tpu.plan.peer import PeerID, parse_peer_id


@dataclass(frozen=True)
class PeerList:
    peers: Tuple[PeerID, ...]

    # -- construction ----------------------------------------------------
    @classmethod
    def of(cls, *peers: PeerID) -> "PeerList":
        return cls(tuple(peers))

    @classmethod
    def parse(cls, spec: str) -> "PeerList":
        """Parse ``host:port,host:port,...``."""
        if not spec:
            return cls(())
        return cls(tuple(parse_peer_id(p) for p in spec.split(",")))

    def __str__(self) -> str:
        return ",".join(str(p) for p in self.peers)

    # -- container protocol ----------------------------------------------
    def __len__(self) -> int:
        return len(self.peers)

    def __iter__(self) -> Iterator[PeerID]:
        return iter(self.peers)

    def __getitem__(self, i: int) -> PeerID:
        return self.peers[i]

    def __contains__(self, p: PeerID) -> bool:
        return p in self.peers

    # -- rank queries ----------------------------------------------------
    def rank(self, p: PeerID) -> Optional[int]:
        try:
            return self.peers.index(p)
        except ValueError:
            return None

    def local_rank(self, p: PeerID) -> Optional[int]:
        """Index among peers on the same host (ordered by global rank)."""
        r = 0
        for q in self.peers:
            if q == p:
                return r
            if q.host == p.host:
                r += 1
        return None

    def local_size(self, p: PeerID) -> int:
        return sum(1 for q in self.peers if q.host == p.host)

    def hosts(self) -> List[str]:
        """Distinct hosts in first-appearance order."""
        seen: List[str] = []
        for p in self.peers:
            if p.host not in seen:
                seen.append(p.host)
        return seen

    def partition_by_host(self) -> Dict[str, List[int]]:
        """host → ordered global ranks on that host
        (analog of reference ``peerlist.go:166`` PartitionByHost)."""
        out: Dict[str, List[int]] = {}
        for i, p in enumerate(self.peers):
            out.setdefault(p.host, []).append(i)
        return out

    def local_masters(self) -> List[int]:
        """Global rank of the first peer on each host — the participants of
        the cross-host stage of hierarchical collectives."""
        seen: Dict[str, int] = {}
        for i, p in enumerate(self.peers):
            seen.setdefault(p.host, i)
        return [seen[h] for h in self.hosts()]

    # -- set ops (for elastic diffing) -----------------------------------
    def diff(self, other: "PeerList") -> Tuple[List[PeerID], List[PeerID]]:
        """Returns (added, removed) going from ``self`` to ``other``."""
        a, b = set(self.peers), set(other.peers)
        added = [p for p in other.peers if p not in a]
        removed = [p for p in self.peers if p not in b]
        return added, removed

    def on_host(self, host: str) -> "PeerList":
        return PeerList(tuple(p for p in self.peers if p.host == host))

    def select(self, ranks: Sequence[int]) -> "PeerList":
        return PeerList(tuple(self.peers[r] for r in ranks))
