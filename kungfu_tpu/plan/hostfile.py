"""MPI-style hostfile parsing (parity with reference ``srcs/go/plan/hostfile``).

Format, one host per line::

    192.168.1.10 slots=4
    192.168.1.11 slots=4  # comment

Lines without ``slots=`` default to 1 slot.
"""

from __future__ import annotations

from kungfu_tpu.plan.hostspec import HostList, HostSpec


def parse_hostfile_text(text: str) -> HostList:
    hosts = []
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        ip = parts[0]
        slots = 1
        for p in parts[1:]:
            if p.startswith("slots="):
                slots = int(p[len("slots="):])
        hosts.append(HostSpec(ip, slots))
    return HostList(hosts)


def parse_hostfile(path: str) -> HostList:
    with open(path) as f:
        return parse_hostfile_text(f.read())
