"""Host specifications and host lists.

Parity with reference ``srcs/go/plan/hostspec.go``: a host spec is
``ip:slots[:public_addr]``; a host list generates runner lists and peer
lists capped at a total ``np``.  Default worker port range 10000-11000 and
runner port 38080 mirror the reference (``hostspec.go:121-126``).

On TPU a *slot* is one worker process; in one-process-per-host mode each
host contributes one slot regardless of chip count, while CPU-backend test
clusters use one slot per simulated device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

from kungfu_tpu.plan.peer import PeerID
from kungfu_tpu.plan.peerlist import PeerList

DEFAULT_RUNNER_PORT = 38080
DEFAULT_PORT_RANGE = (10000, 11000)


@dataclass(frozen=True)
class HostSpec:
    ip: str
    slots: int
    public_addr: str = ""

    def __post_init__(self):
        if self.slots < 0:
            raise ValueError(f"negative slots on host {self.ip}")
        if not self.public_addr:
            object.__setattr__(self, "public_addr", self.ip)

    def __str__(self) -> str:
        return f"{self.ip}:{self.slots}:{self.public_addr}"

    @classmethod
    def parse(cls, s: str) -> "HostSpec":
        parts = s.strip().split(":")
        if len(parts) == 1:
            return cls(parts[0], 1)
        if len(parts) == 2:
            return cls(parts[0], int(parts[1]))
        if len(parts) == 3:
            return cls(parts[0], int(parts[1]), parts[2])
        raise ValueError(f"invalid host spec {s!r}; want ip[:slots[:public_addr]]")


class HostList:
    def __init__(self, hosts: List[HostSpec]):
        ips = [h.ip for h in hosts]
        if len(set(ips)) != len(ips):
            raise ValueError("duplicate host ip in host list")
        self.hosts: Tuple[HostSpec, ...] = tuple(hosts)

    @classmethod
    def parse(cls, spec: str) -> "HostList":
        """Parse ``ip:slots[,ip:slots]...``."""
        if not spec:
            return cls([])
        return cls([HostSpec.parse(h) for h in spec.split(",")])

    def __str__(self) -> str:
        return ",".join(str(h) for h in self.hosts)

    def __len__(self) -> int:
        return len(self.hosts)

    def cap(self) -> int:
        return sum(h.slots for h in self.hosts)

    def gen_runner_list(self, port: int = DEFAULT_RUNNER_PORT) -> PeerList:
        return PeerList(tuple(PeerID(h.ip, port) for h in self.hosts))

    def gen_peer_list(self, np: int, port_range: Tuple[int, int] = DEFAULT_PORT_RANGE) -> PeerList:
        """First ``np`` slots filled host-major, worker ``j`` on a host gets
        port ``port_range[0] + j`` (analog of ``hostspec.go:194-210``)."""
        if np > self.cap():
            raise ValueError(f"np={np} exceeds host list capacity {self.cap()}")
        lo, hi = port_range
        peers: List[PeerID] = []
        for h in self.hosts:
            for j in range(h.slots):
                if len(peers) >= np:
                    return PeerList(tuple(peers))
                port = lo + j
                if port >= hi:
                    raise ValueError(f"slot {j} on {h.ip} exceeds port range {port_range}")
                peers.append(PeerID(h.ip, port))
        return PeerList(tuple(peers))

    def lookup(self, ip: str) -> HostSpec:
        for h in self.hosts:
            if h.ip == ip:
                return h
        raise KeyError(ip)


def parse_host_list(spec: str) -> HostList:
    return HostList.parse(spec)
