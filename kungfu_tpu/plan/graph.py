"""Directed communication graphs.

Capability parity with reference ``srcs/go/plan/graph/graph.go``: a digraph
where every node tracks a self-loop flag plus ordered predecessor/successor
lists, a compact forest-array codec (``f[i]`` = father of node ``i``) used to
ship trees between processes, reversal (a broadcast tree reversed is a reduce
tree), and a canonical digest for cross-process consensus.

Implementation is fresh: immutable-ish Python dataclasses over numpy arrays,
hashed with blake2b for digests.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Iterable, List, Sequence


@dataclass
class Node:
    rank: int
    self_loop: bool = False
    prevs: List[int] = field(default_factory=list)
    nexts: List[int] = field(default_factory=list)


class Graph:
    """A digraph over ranks ``0..n-1``."""

    def __init__(self, n: int):
        self.nodes: List[Node] = [Node(i) for i in range(n)]

    def __len__(self) -> int:
        return len(self.nodes)

    # -- construction ----------------------------------------------------
    def add_self_loop(self, i: int) -> None:
        self.nodes[i].self_loop = True

    def add_edge(self, i: int, j: int) -> None:
        """Directed edge i → j."""
        if i == j:
            self.add_self_loop(i)
            return
        self.nodes[i].nexts.append(j)
        self.nodes[j].prevs.append(i)

    # -- queries ---------------------------------------------------------
    def prevs(self, i: int) -> Sequence[int]:
        return tuple(self.nodes[i].prevs)

    def nexts(self, i: int) -> Sequence[int]:
        return tuple(self.nodes[i].nexts)

    def is_self_loop(self, i: int) -> bool:
        return self.nodes[i].self_loop

    def edges(self) -> List[tuple]:
        out = []
        for node in self.nodes:
            for j in node.nexts:
                out.append((node.rank, j))
        return out

    # -- transforms ------------------------------------------------------
    def reverse(self) -> "Graph":
        g = Graph(len(self))
        for node in self.nodes:
            if node.self_loop:
                g.add_self_loop(node.rank)
            for j in node.nexts:
                g.add_edge(j, node.rank)
        return g

    # -- forest-array codec ----------------------------------------------
    def to_forest_array(self) -> List[int]:
        """Encode a tree/forest as ``f[i] = father(i)`` (roots are their own
        father).  Only valid when every node has ≤1 predecessor."""
        f = []
        for node in self.nodes:
            if len(node.prevs) > 1:
                raise ValueError(f"node {node.rank} has {len(node.prevs)} fathers; not a forest")
            f.append(node.prevs[0] if node.prevs else node.rank)
        return f

    @classmethod
    def from_forest_array(cls, f: Sequence[int]) -> "Graph":
        n = len(f)
        g = cls(n)
        roots = 0
        for i, father in enumerate(f):
            if not 0 <= father < n:
                raise ValueError(f"father {father} of node {i} out of range [0,{n})")
            if father == i:
                roots += 1
                g.add_self_loop(i)
            else:
                g.add_edge(father, i)
        if roots == 0:
            raise ValueError("forest array has no root")
        g._assert_acyclic(f)
        return g

    @staticmethod
    def _assert_acyclic(f: Sequence[int]) -> None:
        n = len(f)
        for start in range(n):
            i, hops = start, 0
            while f[i] != i:
                i = f[i]
                hops += 1
                if hops > n:
                    raise ValueError("forest array contains a cycle")

    # -- consensus digest ------------------------------------------------
    def digest_bytes(self) -> bytes:
        """Canonical content hash — equal graphs (same edges, loops, order)
        hash equal across processes."""
        h = hashlib.blake2b(digest_size=16)
        h.update(len(self).to_bytes(4, "little"))
        for node in self.nodes:
            h.update(b"L" if node.self_loop else b"l")
            for j in node.nexts:
                h.update(j.to_bytes(4, "little"))
            h.update(b"|")
        return h.digest()

    def __eq__(self, other) -> bool:
        return isinstance(other, Graph) and self.digest_bytes() == other.digest_bytes()

    def __repr__(self) -> str:
        return f"Graph(n={len(self)}, edges={self.edges()})"


def merge_graphs(graphs: Iterable[Graph]) -> Graph:
    """Union of edge sets (used to combine reduce+broadcast pair views)."""
    graphs = list(graphs)
    n = len(graphs[0])
    out = Graph(n)
    seen = set()
    for g in graphs:
        for i in range(n):
            if g.is_self_loop(i):
                out.nodes[i].self_loop = True
            for j in g.nexts(i):
                if (i, j) not in seen:
                    seen.add((i, j))
                    out.add_edge(i, j)
    return out
