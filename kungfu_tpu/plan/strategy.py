"""Collective strategy names.

Parity with reference ``srcs/go/kungfu/base/strategy.go:10-22``: eight named
strategies plus AUTO (selection rule: :func:`auto_select` — single host →
RING, a measured divergence from the reference; multi-host →
BINARY_TREE_STAR).  The host plane (:mod:`kungfu_tpu.comm.engine`) keeps
the reference's graph semantics — a strategy generates (reduce, bcast)
routing graphs; on the device plane (:mod:`kungfu_tpu.comm.device`) a
strategy instead selects among compiled collective schedules.  Names and
the env/flag surface are preserved either way.
"""

from __future__ import annotations

import enum


class Strategy(enum.Enum):
    STAR = "STAR"
    MULTI_STAR = "MULTI_STAR"
    RING = "RING"
    CLIQUE = "CLIQUE"
    TREE = "TREE"
    BINARY_TREE = "BINARY_TREE"
    BINARY_TREE_STAR = "BINARY_TREE_STAR"
    MULTI_BINARY_TREE_STAR = "MULTI_BINARY_TREE_STAR"
    AUTO = "AUTO"

    def __str__(self) -> str:
        return self.value


DEFAULT_STRATEGY = Strategy.BINARY_TREE_STAR


def parse_strategy(s: str) -> Strategy:
    try:
        return Strategy(s.strip().upper().replace("-", "_"))
    except ValueError:
        names = ", ".join(m.value for m in Strategy)
        raise ValueError(f"unknown strategy {s!r}; one of: {names}") from None


def auto_select(num_hosts: int) -> Strategy:
    """AUTO rule.  The reference picks STAR for one host and
    BINARY_TREE_STAR otherwise (``session/strategy.go:90-99``); this build
    diverges for the single-host case: colocated peers talk over unix
    sockets where RING pipelines chunked transfers ~20% faster than the
    root-bottlenecked STAR (measured at np∈{2,4}, docs/perf.md)."""
    return Strategy.RING if num_hosts <= 1 else Strategy.BINARY_TREE_STAR
