"""Collective strategy names.

Parity with reference ``srcs/go/kungfu/base/strategy.go:10-22``: eight named
strategies plus AUTO.  On TPU a *strategy* selects among compiled collective
schedules (see :mod:`kungfu_tpu.comm.strategies`) rather than per-message
routing graphs, but the names, the env/flag surface, and the AUTO selection
rule (single host → STAR, multi host → BINARY_TREE_STAR) are preserved.
"""

from __future__ import annotations

import enum


class Strategy(enum.Enum):
    STAR = "STAR"
    MULTI_STAR = "MULTI_STAR"
    RING = "RING"
    CLIQUE = "CLIQUE"
    TREE = "TREE"
    BINARY_TREE = "BINARY_TREE"
    BINARY_TREE_STAR = "BINARY_TREE_STAR"
    MULTI_BINARY_TREE_STAR = "MULTI_BINARY_TREE_STAR"
    AUTO = "AUTO"

    def __str__(self) -> str:
        return self.value


DEFAULT_STRATEGY = Strategy.BINARY_TREE_STAR


def parse_strategy(s: str) -> Strategy:
    try:
        return Strategy(s.strip().upper().replace("-", "_"))
    except ValueError:
        names = ", ".join(m.value for m in Strategy)
        raise ValueError(f"unknown strategy {s!r}; one of: {names}") from None


def auto_select(num_hosts: int) -> Strategy:
    """Reference AUTO rule (``session/strategy.go:90-99``): one host → STAR,
    otherwise BINARY_TREE_STAR."""
    return Strategy.STAR if num_hosts <= 1 else Strategy.BINARY_TREE_STAR
