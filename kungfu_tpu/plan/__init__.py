"""Topology / plan layer — pure data structures describing the cluster.

TPU-native analog of reference ``srcs/go/plan``: peer identity, ordered peer
lists, host specs, cluster membership with validated resize, and the
communication graphs used by the host-side (gossip / control) collectives.

On TPU the *device* data plane does not consume these graphs — XLA lowers
collectives onto the ICI torus itself.  The graphs remain load-bearing for:

* host-side control-plane collectives (consensus, barrier across processes);
* the async gossip channel (PairAveraging peer selection);
* strategy benchmarking/adaptation (host plane: routing graphs in
  :mod:`kungfu_tpu.comm.engine`; device plane: compiled collective
  schedules in :mod:`kungfu_tpu.comm.device`).
"""

from kungfu_tpu.plan.graph import Graph, Node
from kungfu_tpu.plan.peer import PeerID, parse_peer_id
from kungfu_tpu.plan.peerlist import PeerList
from kungfu_tpu.plan.hostspec import HostSpec, HostList, parse_host_list, DEFAULT_RUNNER_PORT, DEFAULT_PORT_RANGE
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.plan.topology import (
    gen_star,
    gen_tree,
    gen_binary_tree,
    gen_binary_tree_star,
    gen_multi_binary_tree_star,
    gen_multi_star,
    gen_circular_graph_pair,
    gen_default_reduce_graph,
)
from kungfu_tpu.plan.strategy import Strategy, parse_strategy, auto_select

__all__ = [
    "Graph",
    "Node",
    "PeerID",
    "parse_peer_id",
    "PeerList",
    "HostSpec",
    "HostList",
    "parse_host_list",
    "Cluster",
    "Strategy",
    "parse_strategy",
    "auto_select",
    "gen_star",
    "gen_tree",
    "gen_binary_tree",
    "gen_binary_tree_star",
    "gen_multi_binary_tree_star",
    "gen_multi_star",
    "gen_circular_graph_pair",
    "gen_default_reduce_graph",
    "DEFAULT_RUNNER_PORT",
    "DEFAULT_PORT_RANGE",
]
