"""Cluster = runners + workers, with validation and resize.

Parity with reference ``srcs/go/plan/cluster.go:10-118``: a JSON-serializable
membership document validated on every update, plus the resize rule — shrink
drops the tail of the worker list, grow appends workers round-robin onto
hosts that still have free slots (``cluster.go:75-106`` growOne).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Optional

from kungfu_tpu.plan.hostspec import DEFAULT_PORT_RANGE, DEFAULT_RUNNER_PORT
from kungfu_tpu.plan.peer import PeerID
from kungfu_tpu.plan.peerlist import PeerList


@dataclass(frozen=True)
class Cluster:
    runners: PeerList
    workers: PeerList

    # -- codec -----------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(
            {
                "runners": [str(p) for p in self.runners],
                "workers": [str(p) for p in self.workers],
            },
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, s: str) -> "Cluster":
        d = json.loads(s)
        c = cls(
            runners=PeerList.parse(",".join(d.get("runners", []))),
            workers=PeerList.parse(",".join(d.get("workers", []))),
        )
        c.validate()
        return c

    def digest(self) -> bytes:
        """Canonical bytes for the membership consensus collective."""
        return hashlib.blake2b(self.to_json().encode(), digest_size=16).digest()

    # -- validation ------------------------------------------------------
    def validate(self) -> None:
        runner_hosts = {r.host for r in self.runners}
        for w in self.workers:
            if w.host not in runner_hosts:
                raise ValueError(f"worker {w} has no runner on its host")
        if len(set(self.workers.peers)) != len(self.workers):
            raise ValueError("duplicate workers")

    def size(self) -> int:
        return len(self.workers)

    # -- resize ----------------------------------------------------------
    def resize(self, new_size: int, port_range=DEFAULT_PORT_RANGE) -> "Cluster":
        if new_size < 0:
            raise ValueError("negative cluster size")
        workers = list(self.workers.peers)
        if new_size <= len(workers):
            return Cluster(self.runners, PeerList(tuple(workers[:new_size])))
        while len(workers) < new_size:
            nxt = self._grow_one(workers, port_range)
            if nxt is None:
                raise ValueError(
                    f"cannot grow to {new_size}: all {len(self.runners)} hosts full"
                )
            workers.append(nxt)
        return Cluster(self.runners, PeerList(tuple(workers)))

    def _grow_one(self, workers, port_range) -> Optional[PeerID]:
        """Place one more worker on the least-loaded runner host with a free
        port slot (ports are allocated densely from the range start)."""
        lo, hi = port_range
        load = {r.host: 0 for r in self.runners}
        used = {}
        for w in workers:
            load[w.host] = load.get(w.host, 0) + 1
            used.setdefault(w.host, set()).add(w.port)
        for host in sorted(load, key=lambda h: load[h]):
            for port in range(lo, hi):
                if port not in used.get(host, set()):
                    return PeerID(host, port)
        return None

    @classmethod
    def single_process(cls, host: str = "127.0.0.1") -> "Cluster":
        w = PeerList.of(PeerID(host, DEFAULT_PORT_RANGE[0]))
        r = PeerList.of(PeerID(host, DEFAULT_RUNNER_PORT))
        return cls(r, w)
