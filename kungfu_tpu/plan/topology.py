"""Communication-graph generators.

Parity with reference ``srcs/go/plan/topology.go`` + ``plan/subgraph``:
star, tree, binary tree, binary-tree-star (binary trees within each host,
star across hosts), their multi-root rotated families, and ring pairs.

Every generator returns ``(reduce_graph, broadcast_graph)`` pairs or a
broadcast tree from which the reduce tree is derived by reversal + self
loops (reference ``topology.go:33``).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from kungfu_tpu.plan.graph import Graph

GraphPair = Tuple[Graph, Graph]  # (reduce, broadcast)


def gen_default_reduce_graph(bcast: Graph) -> Graph:
    """Reduce tree = reversed broadcast tree with self-loops on every node
    (each node contributes its own buffer)."""
    g = bcast.reverse()
    for i in range(len(g)):
        g.add_self_loop(i)
    return g


def _pair(bcast: Graph) -> GraphPair:
    return gen_default_reduce_graph(bcast), bcast


def gen_star(n: int, center: int = 0) -> GraphPair:
    """Everyone exchanges with ``center``."""
    b = Graph(n)
    b.add_self_loop(center)
    for i in range(n):
        if i != center:
            b.add_edge(center, i)
    return _pair(b)


def gen_tree(n: int, host_ranks: Sequence[Sequence[int]] = None) -> GraphPair:
    """Host-aware tree (reference ``topology.go:17-31`` GenTree): a star
    from each host's local master to its local ranks, plus a star over the
    masters centered at the first.  Without host info, degenerates to a
    flat star at rank 0 (single-host case)."""
    if not host_ranks:
        host_ranks = [list(range(n))]
    b = Graph(n)
    masters: List[int] = []
    for ranks in host_ranks:
        if not ranks:
            continue
        masters.append(ranks[0])
        for r in ranks[1:]:
            b.add_edge(ranks[0], r)
    if masters:
        b.add_self_loop(masters[0])
        for m in masters[1:]:
            b.add_edge(masters[0], m)
    return _pair(b)


def gen_binary_tree(n: int, ranks: Sequence[int] = None) -> GraphPair:
    """Binary tree over ``ranks`` (default 0..n-1), heap-shaped."""
    if ranks is None:
        ranks = list(range(n))
    b = Graph(n)
    if ranks:
        b.add_self_loop(ranks[0])
    for idx in range(1, len(ranks)):
        b.add_edge(ranks[(idx - 1) // 2], ranks[idx])
    return _pair(b)


def gen_binary_tree_star(n: int, host_ranks: Sequence[Sequence[int]]) -> GraphPair:
    """The reference default strategy (``topology.go:76-105``): a binary tree
    within each host's ranks; local roots form a star across hosts centered
    on the first host's root."""
    b = Graph(n)
    roots: List[int] = []
    for ranks in host_ranks:
        if not ranks:
            continue
        roots.append(ranks[0])
        for idx in range(1, len(ranks)):
            b.add_edge(ranks[(idx - 1) // 2], ranks[idx])
    if roots:
        b.add_self_loop(roots[0])
        for r in roots[1:]:
            b.add_edge(roots[0], r)
    return _pair(b)


def gen_multi_binary_tree_star(n: int, host_ranks: Sequence[Sequence[int]]) -> List[GraphPair]:
    """One binary-tree-star per host, each rotated to center on a different
    host — chunks are spread across the pairs to use all NICs
    (``topology.go:107``)."""
    hosts = [h for h in host_ranks if h]
    k = max(1, len(hosts))
    pairs: List[GraphPair] = []
    for shift in range(k):
        rotated = list(hosts[shift:]) + list(hosts[:shift])
        pairs.append(gen_binary_tree_star(n, rotated))
    return pairs


def gen_multi_star(n: int, host_ranks: Sequence[Sequence[int]] = None) -> List[GraphPair]:
    """Host-aware multi-star (reference ``topology.go:117-125`` GenMultiStar
    + ``genMultiStar``): within each host a star from the local master to
    its ranks; across hosts a star over the masters — one graph pair per
    master rotation, so chunks spread the cross-host load over every
    host's NIC.  Without host info, one host is assumed (pure local star,
    single pair)."""
    if not host_ranks:
        host_ranks = [list(range(n))]
    hosts = [list(h) for h in host_ranks if h]
    masters = [h[0] for h in hosts]
    pairs: List[GraphPair] = []
    for root_idx in range(max(1, len(masters))):
        b = Graph(n)
        for ranks in hosts:
            for r in ranks[1:]:
                b.add_edge(ranks[0], r)
        if masters:
            center = masters[root_idx % len(masters)]
            b.add_self_loop(center)
            for m in masters:
                if m != center:
                    b.add_edge(center, m)
        pairs.append(_pair(b))
    return pairs


def gen_circular_graph_pair(n: int, ranks: Sequence[int] = None, shift: int = 0) -> GraphPair:
    """Ring: reduce flows around the ring accumulating, broadcast flows the
    result back around (``topology.go:149-160``).  ``shift`` rotates the
    ring start so multiple rings spread load."""
    if ranks is None:
        ranks = list(range(n))
    k = len(ranks)
    ring = [ranks[(i + shift) % k] for i in range(k)]
    reduce_g = Graph(n)
    bcast_g = Graph(n)
    for i in range(k):
        reduce_g.add_self_loop(ring[i])
        if i + 1 < k:
            reduce_g.add_edge(ring[i], ring[i + 1])
    # result lands at ring[-1]; broadcast back down the ring
    bcast_g.add_self_loop(ring[-1])
    for i in range(k - 1, 0, -1):
        bcast_g.add_edge(ring[i], ring[i - 1])
    return reduce_g, bcast_g


def gen_clique(n: int) -> List[GraphPair]:
    """All-to-all: n stars, one centered at each rank — the CLIQUE strategy
    (reference ``topology.go:136-147``)."""
    return [gen_star(n, center=c) for c in range(n)]


def gen_cross_ring_pairs(n: int, masters: Sequence[int]) -> List[GraphPair]:
    """Ring rotations over the local-master subset for the cross-host
    stage of hierarchical allreduce (reference
    ``subgraph.go:5-17`` + ``session/strategy.go:188-196``): one ring pair
    per rotation; every non-master node is untouched (no self-loop)."""
    return [
        gen_circular_graph_pair(n, ranks=list(masters), shift=r)
        for r in range(max(1, len(masters)))
    ]


def gen_cross_binary_tree(n: int, masters: Sequence[int]) -> List[GraphPair]:
    """Binary tree over the local-master subset (reference
    ``subgraph.go:19-31`` + ``strategy.go:198-202``), reduce graph with
    self-loops on the masters only so non-participants stay inert."""
    ms = list(masters)
    b = Graph(n)
    for i in range(len(ms)):
        for j in (2 * i + 1, 2 * i + 2):
            if j < len(ms):
                b.add_edge(ms[i], ms[j])
    r = b.reverse()
    for m in ms:
        r.add_self_loop(m)
    return [(r, b)]
