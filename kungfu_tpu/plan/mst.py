"""Minimum spanning tree over measured peer latencies.

Parity with reference ``include/kungfu/mst.hpp:10-57`` (Prim's algorithm
over the symmetrized latency matrix) feeding the ``MinimumSpanningTree``
TF op (``topology.cpp:118``): the resulting tree becomes the broadcast
topology via ``set_tree``.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def minimum_spanning_tree(weights: np.ndarray) -> List[int]:
    """Prim's MST over a symmetric (n, n) weight matrix; returns the
    forest array ``f[i] = father(i)`` rooted at 0."""
    w = np.asarray(weights, dtype=np.float64)
    n = w.shape[0]
    if w.shape != (n, n):
        raise ValueError(f"weights must be square, got {w.shape}")
    w = (w + w.T) / 2.0  # symmetrize (reference does the same)
    father = list(range(n))
    if n <= 1:
        return father
    in_tree = np.zeros(n, dtype=bool)
    in_tree[0] = True
    best_cost = w[0].copy()
    best_from = np.zeros(n, dtype=np.int64)
    for _ in range(n - 1):
        masked = np.where(in_tree, np.inf, best_cost)
        j = int(np.argmin(masked))
        if not np.isfinite(masked[j]):
            raise ValueError("disconnected weight matrix")
        father[j] = int(best_from[j])
        in_tree[j] = True
        improve = w[j] < best_cost
        best_cost = np.where(improve, w[j], best_cost)
        best_from = np.where(improve, j, best_from)
    return father
